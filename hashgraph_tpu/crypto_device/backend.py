"""The device batch-verify pipeline behind the ``verify_batch_submit`` seam.

Orchestrates the three device stages over one signature batch:

1. **decompress** — A and R encodings for every lane, stacked into one
   dispatch (curve.decompress: the shared inverse-sqrt chain);
2. **hash** — vectorized SHA-512 challenge hashes k_i over R||A||M;
3. **msm** — the randomized-linear-combination check, one Straus MSM
   across all lanes (msm._msm_is_identity).

Host work between stages is O(n) bookkeeping: canonical-scalar checks
(s < L), mod-L scalar algebra for the randomizers (Python ints are the
host's native 256-bit ALU), and window decomposition. Lane counts and
SHA block counts pad to power-of-two buckets so the set of compiled
shapes — and therefore XLA compile time, amortized further by the
persistent compile cache — stays tiny.

Failure semantics (the part that makes this a *backend*, not a fork):
the RLC accepting proves every lane verifies under the cofactored
criterion; the RLC failing says only "at least one lane is bad", so the
batch drops to the host verifier (the native pool's own batch path, or
the pure-Python RFC 8032 twin) for **exact per-item blame** — the same
escalation the native runtime performs internally when a chunk's
combination fails. Verdicts are therefore decision-identical to
``signing/_ed25519.py`` on every input, which the fuzz battery asserts.

Every batch increments ``hashgraph_device_verify_{batches,signatures}_
total``; blame escalations increment ``..._fallbacks_total``; verify
work lands in the ``hashgraph_device_verify_seconds`` histogram and the
per-phase split is exported via :func:`last_phase_seconds` for the
bench's BENCH-json timing block. The clocks measure WORK, not the wall
window: ``submit`` is host pack + device dispatch inside
``verify_batch_begin``; ``decompress``/``hash``/``msm``/``fallback``
are time spent inside ``collect``; any overlap gap an async caller
opens between begin and collect is deliberately attributed to NOTHING
(the whole point of the submit/collect seam is that the gap is free).
"""

from __future__ import annotations

import secrets
import time

import numpy as np

from ..obs import (
    DEVICE_VERIFY_BATCHES_TOTAL,
    DEVICE_VERIFY_FALLBACKS_TOTAL,
    DEVICE_VERIFY_SECONDS,
    DEVICE_VERIFY_SIGNATURES_TOTAL,
    registry,
)
from ..signing._ed25519 import L  # ONE home for the group order

# The identity's encoding (y=1): the inert pad for unused lanes.
_PAD_ENC = b"\x01" + b"\x00" * 31

_last_phases: "dict[str, float]" = {}

_jax_state: "dict[str, object]" = {"checked": False, "ok": False}


def available() -> bool:
    """True when JAX (any backend, CPU included) can serve the pipeline."""
    if not _jax_state["checked"]:
        try:
            import jax

            jax.devices()
            _jax_state["ok"] = True
        except Exception:
            _jax_state["ok"] = False
        _jax_state["checked"] = True
    return bool(_jax_state["ok"])


def last_phase_seconds() -> "dict[str, float]":
    """Per-phase wall seconds of the most recent batch (bench hook)."""
    return dict(_last_phases)


def _bucket(n: int, floor: int = 8) -> int:
    b = floor
    while b < n:
        b *= 2
    return b


def _decompress_jit():
    import jax

    from . import curve

    if "decompress" not in _jax_state:
        _jax_state["decompress"] = jax.jit(curve.decompress)
    return _jax_state["decompress"]


def verify_batch_begin(
    identities: "list[bytes]",
    payloads: "list[bytes]",
    signatures: "list[bytes]",
):
    """Start the device pipeline NOW (decompress + challenge hashes are
    in flight when this returns); the returned zero-arg collect yields
    one bool per item. Lengths must be pre-checked by the seam."""
    import jax.numpy as jnp

    from . import curve, msm, sha512

    n = len(identities)
    verdicts = [False] * n
    t0 = time.perf_counter()
    phases = {
        "submit": 0.0, "decompress": 0.0, "hash": 0.0, "msm": 0.0,
        "fallback": 0.0,
    }
    registry.counter(DEVICE_VERIFY_BATCHES_TOTAL).inc()
    registry.counter(DEVICE_VERIFY_SIGNATURES_TOTAL).inc(n)

    # Host precheck: non-canonical scalars (s >= L) are False without
    # touching the device — same short-circuit as the host verifiers.
    live = [
        i for i in range(n)
        if int.from_bytes(signatures[i][32:], "little") < L
    ]
    if not live:
        phases["submit"] = time.perf_counter() - t0
        _finish_phases(phases)
        return lambda: verdicts

    k = len(live)
    lanes = _bucket(2 * k)
    enc = np.zeros((lanes, 32), np.uint8)
    enc[2 * k:] = np.frombuffer(_PAD_ENC, np.uint8)
    for j, i in enumerate(live):
        enc[j] = np.frombuffer(identities[i], np.uint8)
        enc[k + j] = np.frombuffer(signatures[i][:32], np.uint8)
    points_dev, ok_dev = _decompress_jit()(jnp.asarray(enc))

    # Challenge hashes k_i = SHA-512(R || A || M), bucketed on lanes
    # AND block count (two axes of shape variation, both bounded).
    msgs = [
        signatures[i][:32] + identities[i] + payloads[i] for i in live
    ]
    blocks = _bucket(max(sha512.blocks_needed(len(m)) for m in msgs), 1)
    hash_lanes = _bucket(k)
    digests_dev = sha512.sha512_batch_dispatch(
        msgs + [b""] * (hash_lanes - k), blocks
    )
    phases["submit"] = time.perf_counter() - t0

    def _collect() -> "list[bool]":
        tc = time.perf_counter()
        points = np.asarray(points_dev)
        ok = np.asarray(ok_dev)
        phases["decompress"] = time.perf_counter() - tc
        t1 = time.perf_counter()
        digests = sha512.digest_bytes(digests_dev)[:k]
        phases["hash"] = time.perf_counter() - t1
        t2 = time.perf_counter()

        ok_a, ok_r = ok[:k], ok[k:2 * k]
        surv = [j for j in range(k) if ok_a[j] and ok_r[j]]
        if not surv:
            phases["msm"] = time.perf_counter() - t2
            _finish_phases(phases)
            return verdicts

        # Randomized linear combination (fresh nonzero 128-bit z per
        # item per batch): accept iff
        # 8*(S*B + sum -z_i h_i A_i + sum -z_i R_i) == O.
        h = [int.from_bytes(bytes(digests[j]), "little") % L for j in surv]
        z = [1 + secrets.randbelow((1 << 128) - 1) for _ in surv]
        m = len(surv)
        msm_lanes = _bucket(2 * m + 1)
        pts = np.broadcast_to(curve.IDENTITY, (msm_lanes, 4, 16)).copy()
        s_total = 0
        for row, j in enumerate(surv):
            i = live[j]
            s_total = (
                s_total
                + z[row] * int.from_bytes(signatures[i][32:], "little")
            ) % L
            pts[row] = points[j]                  # A_i
            pts[m + row] = points[k + j]          # R_i
        scalars = [(-(z[r] * h[r])) % L for r in range(m)]
        scalars += [(-z[r]) % L for r in range(m)]
        scalars.append(s_total)
        pts[2 * m] = curve.BASE_AFFINE
        nibbles = np.zeros((msm_lanes, msm.WINDOWS), np.int32)
        nibbles[:2 * m + 1] = msm.scalars_to_nibbles(scalars)
        accepted = msm.msm_accepts(jnp.asarray(pts), jnp.asarray(nibbles))
        phases["msm"] = time.perf_counter() - t2

        if accepted:
            for j in surv:
                verdicts[live[j]] = True
        else:
            t3 = time.perf_counter()
            registry.counter(DEVICE_VERIFY_FALLBACKS_TOTAL).inc()
            rows = [live[j] for j in surv]
            host = _host_blame(
                [identities[i] for i in rows],
                [payloads[i] for i in rows],
                [signatures[i] for i in rows],
            )
            for i, verdict in zip(rows, host):
                verdicts[i] = bool(verdict)
            phases["fallback"] = time.perf_counter() - t3
        _finish_phases(phases)
        return verdicts

    return _collect


def _finish_phases(phases: "dict[str, float]") -> None:
    # Work, not wall: total = what begin+collect actually spent, so an
    # async caller's overlap gap never inflates the histogram.
    phases["total"] = sum(phases.values())
    registry.histogram(DEVICE_VERIFY_SECONDS).observe(phases["total"])
    _last_phases.clear()
    _last_phases.update(phases)


def _host_blame(identities, payloads, signatures) -> "list[bool]":
    """Exact per-item verdicts from the host verifier hierarchy (native
    pool batch if present, else the pure-Python twin) — the blame pass
    after a failed linear combination."""
    from .. import native
    from ..signing import _ed25519 as _py

    results = native.ed25519_verify_batch(
        [bytes(i) for i in identities],
        list(payloads),
        [bytes(s) for s in signatures],
    )
    if results is not None:
        return [code == 1 for code in results]
    return [
        _py.verify(bytes(i), p, bytes(s))
        for i, p, s in zip(identities, payloads, signatures)
    ]


def verify_batch(identities, payloads, signatures) -> "list[bool]":
    """Synchronous wrapper: begin + collect."""
    return verify_batch_begin(identities, payloads, signatures)()
