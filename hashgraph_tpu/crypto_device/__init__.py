"""hashgraph_tpu.crypto_device — device-resident Ed25519 batch verify.

The accelerator-side counterpart of ``native/consensus_native.cpp``'s
batch verifier (ROADMAP item 2): the whole randomized-linear-combination
check — batched point decompression, vectorized SHA-512 challenge
hashes, and one Straus multi-scalar multiply across every signature
lane — runs in JAX, so validated ingest stops being bounded by host
cores. The same code compiles for TPU, GPU, and CPU (CI runs it on the
CPU backend); an optional Pallas kernel accelerates the MSM's field
multiply where the backend supports it (:mod:`.pallas_msm`).

Layering:

- :mod:`.field`   — radix-2^16 u32-limb GF(2^255-19) core (lazy carries)
- :mod:`.sha512`  — vectorized SHA-512 in uint32 pairs, ragged batches
- :mod:`.curve`   — extended-Edwards point ops + batched decompression
- :mod:`.msm`     — the Straus MSM + cofactored identity test, one jit
- :mod:`.backend` — pipeline orchestration, buckets, metrics, blame

The public seam is NOT here: engines select the backend through
``Ed25519ConsensusSigner(device_verify=True)`` (or the
``HASHGRAPH_TPU_DEVICE_VERIFY`` env), and every caller keeps speaking
``SignatureScheme.verify_batch_submit`` / ``PendingVerdicts``. This
package only exposes the backend entry points that seam calls, plus
bench/test hooks.

Import note: submodules import JAX; this ``__init__`` defers those
imports so ``hashgraph_tpu.signing`` (and the jax-free obs/WAL layers
under it) can probe availability without initializing a backend.
"""

from __future__ import annotations

__all__ = [
    "available",
    "verify_batch",
    "verify_batch_begin",
    "last_phase_seconds",
]


def available() -> bool:
    from . import backend

    return backend.available()


def verify_batch(identities, payloads, signatures):
    from . import backend

    return backend.verify_batch(identities, payloads, signatures)


def verify_batch_begin(identities, payloads, signatures):
    from . import backend

    return backend.verify_batch_begin(identities, payloads, signatures)


def last_phase_seconds():
    from . import backend

    return backend.last_phase_seconds()
