"""Optional Pallas kernel for the MSM inner loop's field multiply.

The MSM spends ~95% of its time in fe.mul; on TPU the jnp path's
scatter-add into product columns round-trips HBM between the outer
product and the carry chain. This kernel keeps one lane block's columns
resident in VMEM: the 16-limb schoolbook runs as a fori_loop over the
multiplicand limbs accumulating into a (32, block) scratch, then folds
and carries in-register before the single write-back.

Layout: limbs-major ``int32[16, L]`` (lanes on the 128-wide lane axis —
the transpose of the jnp path's ``[..., 16]``) so the VPU sees full
tiles. int32 stands in for uint32 (TPU Pallas int support): 16x16-bit
products may wrap the sign bit, but wrapping is exact mod 2^32 and the
hi/lo split masks through it (`(p >> 16) & 0xffff` after an arithmetic
shift equals the logical result).

Strictly optional: :func:`enabled` is False unless the backend is a
real TPU (or HASHGRAPH_TPU_DEVICE_VERIFY_PALLAS=interpret forces the
interpreter for tests), and any lowering failure latches the jnp path —
CPU CI runs the identical field core either way (ROADMAP item 2's
"pure-jax.numpy path everywhere else").
"""

from __future__ import annotations

import os

import jax
import jax.numpy as jnp

_MASK = (1 << 16) - 1
_FOLD = 38
_LIMBS = 16

_state: "dict[str, bool | None]" = {"enabled": None, "interpret": False}


def _probe() -> bool:
    mode = os.environ.get("HASHGRAPH_TPU_DEVICE_VERIFY_PALLAS", "").lower()
    if mode in ("0", "off"):
        return False
    if mode == "interpret":
        _state["interpret"] = True
        return True
    try:
        backend = jax.default_backend()
    except Exception:
        return False
    if backend != "tpu" and mode not in ("1", "on"):
        return False
    try:  # lowering probe: latch off on any failure
        a = jnp.zeros((_LIMBS, 8), jnp.int32)
        _fe_mul_tl(a, a).block_until_ready()
        return True
    except Exception:
        return False


def enabled() -> bool:
    if _state["enabled"] is None:
        _state["enabled"] = _probe()
    return bool(_state["enabled"])


def _mul_kernel(a_ref, b_ref, out_ref):
    a = a_ref[:]  # [16, L] int32, limbs < 2^16
    b = b_ref[:]
    lanes = a.shape[1]
    cols = jnp.zeros((2 * _LIMBS, lanes), jnp.int32)

    def limb_step(i, cols):
        ai = jax.lax.dynamic_slice_in_dim(a, i, 1, axis=0)  # [1, L]
        prod = ai * b  # wraps int32; exact mod 2^32
        lo = prod & _MASK
        hi = jax.lax.shift_right_logical(prod, 16) & _MASK
        lo_pad = jax.lax.pad(
            lo, jnp.int32(0),
            [(0, 2 * _LIMBS - _LIMBS, 0), (0, 0, 0)],
        )
        hi_pad = jax.lax.pad(
            hi, jnp.int32(0),
            [(0, 2 * _LIMBS - _LIMBS, 0), (0, 0, 0)],
        )
        shifted_lo = _roll_down(lo_pad, i)
        shifted_hi = _roll_down(hi_pad, i + 1)
        return cols + shifted_lo + shifted_hi

    cols = jax.lax.fori_loop(0, _LIMBS, limb_step, cols)
    t = cols[:_LIMBS] + cols[_LIMBS:] * _FOLD
    for _ in range(3):  # the shared three-pass carry (see field.carry)
        out = []
        carry = jnp.zeros((t.shape[1],), jnp.int32)
        for i in range(_LIMBS):
            cur = t[i] + carry
            out.append(cur & _MASK)
            carry = jax.lax.shift_right_logical(cur, 16)
        t = jnp.stack(out)
        t = t.at[0].add(carry * _FOLD)
    out_ref[:] = t


def _roll_down(x, k):
    """Shift rows down by (traced) k, zero-filling the top."""
    n = x.shape[0]
    idx = jnp.arange(n) - k
    gathered = x[jnp.clip(idx, 0, n - 1)]
    return jnp.where((idx >= 0)[:, None], gathered, 0)


@jax.jit
def _fe_mul_tl(a_tl, b_tl):
    from jax.experimental import pallas as pl

    return pl.pallas_call(
        _mul_kernel,
        out_shape=jax.ShapeDtypeStruct(a_tl.shape, jnp.int32),
        interpret=_state["interpret"],
    )(a_tl, b_tl)


def fe_mul(a, b):
    """Drop-in for field._mul_jnp: accepts/returns the jnp layout
    (uint32[..., 16]) and runs the transposed Pallas kernel."""
    shape = a.shape
    a_tl = jnp.moveaxis(a.reshape(-1, _LIMBS), -1, 0).astype(jnp.int32)
    b_tl = jnp.moveaxis(b.reshape(-1, _LIMBS), -1, 0).astype(jnp.int32)
    out = _fe_mul_tl(a_tl, b_tl)
    return jnp.moveaxis(out, 0, -1).astype(jnp.uint32).reshape(shape)


def reset_for_tests() -> None:
    """Re-run the probe (tests flip the env override)."""
    _state["enabled"] = None
    _state["interpret"] = False
    _fe_mul_tl.clear_cache()
