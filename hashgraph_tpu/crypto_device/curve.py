"""Vectorized edwards25519 point arithmetic + batched decompression.

Points are extended twisted-Edwards coordinates stacked as
``uint32[..., 4, 16]`` — (X, Y, Z, T) with x = X/Z, y = Y/Z, T = XY/Z —
the exact coordinate system of the host twin (signing/_ed25519.py) so
the two implementations can be diffed limb for limb in tests. The
addition law is the unified a=-1 formula (complete for d non-square):
one code path adds, doubles, and absorbs the identity, which is what
lets thousands of heterogeneous lanes run in lockstep.

Decompression is the batch headliner: RFC 8032 5.1.3 x-recovery needs
one z^((p-5)/8) exponentiation per point, and here the whole batch's
exponentiations run as ONE 252-squaring chain across all lanes
(field.pow22523) — a Montgomery ladder per point would serialize
exactly the work the vector units should share. Rejections (y >= p,
no square root, x=0 with sign bit) come back as per-lane flags, never
exceptions: on the wire a malformed point is indistinguishable from a
forged signature and must produce a False verdict, not a fault.
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from . import field as fe
from .field import LIMBS

# Base point (RFC 8032) in host ints, carried into device limbs once.
_B_Y = (4 * pow(5, fe.P - 2, fe.P)) % fe.P
_B_X = 15112221349535400772501151409588531511454012693041857206046113283949847762202

BASE_AFFINE = np.stack([
    fe._int_to_limbs(_B_X),
    fe._int_to_limbs(_B_Y),
    fe._int_to_limbs(1),
    fe._int_to_limbs((_B_X * _B_Y) % fe.P),
])

IDENTITY = np.stack([
    fe._int_to_limbs(0),
    fe._int_to_limbs(1),
    fe._int_to_limbs(1),
    fe._int_to_limbs(0),
])


def identity(batch_shape=()):
    return jnp.broadcast_to(jnp.asarray(IDENTITY), (*batch_shape, 4, LIMBS))


def base_point(batch_shape=()):
    return jnp.broadcast_to(
        jnp.asarray(BASE_AFFINE), (*batch_shape, 4, LIMBS)
    )


def add(p, q):
    """Unified extended addition (add-2008-hwcd-3, a=-1): mirrors the
    host twin's _add exactly — same intermediates, same 2d constant."""
    x1, y1, z1, t1 = (p[..., i, :] for i in range(4))
    x2, y2, z2, t2 = (q[..., i, :] for i in range(4))
    a = fe.mul(fe.sub(y1, x1), fe.sub(y2, x2))
    b = fe.mul(fe.add(y1, x1), fe.add(y2, x2))
    c = fe.mul(fe.mul(t1, fe.const(fe.D2, t1.shape[:-1])), t2)
    zz = fe.mul(z1, z2)
    d = fe.add(zz, zz)
    e = fe.sub(b, a)
    f = fe.sub(d, c)
    g = fe.add(d, c)
    h = fe.add(b, a)
    return jnp.stack(
        [fe.mul(e, f), fe.mul(g, h), fe.mul(f, g), fe.mul(e, h)], axis=-2
    )


def dbl(p):
    """Dedicated doubling (dbl-2008-hwcd, a=-1): 4 squarings + 3
    products vs the unified add's 9 — the MSM's window loop is 4 parts
    doubling to 1 part add, so this is most of its runtime. Verified
    against the host twin's _dbl(p) = _add(p, p) in the battery."""
    x1, y1, z1 = p[..., 0, :], p[..., 1, :], p[..., 2, :]
    a = fe.sqr(x1)
    b = fe.sqr(y1)
    zz = fe.sqr(z1)
    c = fe.add(zz, zz)
    e = fe.sub(fe.sub(fe.sqr(fe.add(x1, y1)), a), b)
    g = fe.sub(b, a)                 # a=-1: D + B with D = -A
    f = fe.sub(g, c)
    h = fe.sub(fe.sub(fe.const(fe.ZERO, a.shape[:-1]), a), b)  # -(A+B)
    return jnp.stack(
        [fe.mul(e, f), fe.mul(g, h), fe.mul(f, g), fe.mul(e, h)], axis=-2
    )


def is_identity(p):
    """Projective identity test: X == 0 and Y == Z (exact mod p)."""
    x, y, z = p[..., 0, :], p[..., 1, :], p[..., 2, :]
    return jnp.logical_and(fe.is_zero(x), fe.eq(y, z))


def decompress(enc):
    """RFC 8032 5.1.3 batched point decompression.

    ``enc``: uint8[..., 32] little-endian encodings. Returns
    ``(points, ok)`` where ``ok`` is False for every 5.1.3 rejection:
    non-canonical y (>= p), no square root, or x = 0 with the sign bit
    set. Rejected lanes hold the identity so downstream point math stays
    well-defined regardless of flags."""
    sign = (enc[..., 31] >> 7).astype(jnp.uint32)
    masked = jnp.concatenate(
        [enc[..., :31], (enc[..., 31] & 0x7F)[..., None]], axis=-1
    )
    canonical = fe.is_canonical_fe(masked)
    y = fe.from_bytes(masked)
    batch = y.shape[:-1]
    one = fe.const(fe.ONE, batch)
    yy = fe.sqr(y)
    u = fe.sub(yy, one)                      # y^2 - 1
    v = fe.add(fe.mul(fe.const(fe.D, batch), yy), one)  # d y^2 + 1
    v3 = fe.mul(fe.sqr(v), v)
    v7 = fe.mul(fe.sqr(v3), v)
    x = fe.mul(fe.mul(u, v3), fe.pow22523(fe.mul(u, v7)))
    vxx = fe.mul(v, fe.sqr(x))
    root_ok = fe.eq(vxx, u)
    neg_ok = fe.eq(vxx, fe.sub(fe.const(fe.ZERO, batch), u))
    x = jnp.where(
        root_ok[..., None], x,
        fe.mul(x, fe.const(fe.SQRT_M1, batch)),
    )
    has_root = jnp.logical_or(root_ok, neg_ok)
    x = fe.canon(x)
    x_zero = fe.is_zero(x)
    # x = 0 with sign bit set is a rejection (no valid negative zero).
    sign_reject = jnp.logical_and(x_zero, sign == 1)
    flip = (fe.parity(x) != sign)[..., None]
    x = jnp.where(flip, fe.sub(fe.const(fe.ZERO, batch), x), x)
    ok = jnp.logical_and(
        canonical, jnp.logical_and(has_root, jnp.logical_not(sign_reject))
    )
    point = jnp.stack([x, y, one, fe.mul(x, y)], axis=-2)
    return jnp.where(ok[..., None, None], point, identity(batch)), ok
