"""Vectorized SHA-512 over ragged byte rows, in uint32 pairs.

The per-signature Ed25519 challenge hash k = SHA-512(R || A || M) is the
second-largest cost of batch verification after the MSM; this runs every
lane's compression in lockstep on device. With ``jax_enable_x64`` off
there is no 64-bit lane, so every 64-bit word is an (hi, lo) uint32 pair
and the adders carry explicitly (carry = lo_sum < lo_a, exact for
wrapping uint32) — the same decomposition GPU SHA implementations use on
32-bit ALUs.

Ragged batches pad to a shared block count (bucketed by the caller to
bound compiled shapes); a lane whose message ends early freezes its
state via a per-block mask, so one ``lax.fori_loop`` serves every length
in the batch. Block packing happens host-side in numpy — it is O(bytes)
data movement, not crypto.

Constants are derived, not transcribed: K[t] / H0 are the fractional
parts of cube/square roots of the first primes (FIPS 180-4), computed
with integer Newton roots at import and pinned against hashlib by the
test battery.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

_U32 = jnp.uint32

BLOCK = 128  # bytes per SHA-512 block


def _primes(n: int) -> "list[int]":
    out, c = [], 2
    while len(out) < n:
        if all(c % p for p in out):
            out.append(c)
        c += 1
    return out


def _iroot(x: int, k: int) -> int:
    """Integer floor k-th root (Newton on Python ints)."""
    if x == 0:
        return 0
    r = 1 << ((x.bit_length() + k - 1) // k)
    while True:
        nr = ((k - 1) * r + x // r ** (k - 1)) // k
        if nr >= r:
            return r
        r = nr


def _frac_root_bits(p: int, k: int) -> int:
    """First 64 fractional bits of p^(1/k)."""
    return _iroot(p << (64 * k), k) & ((1 << 64) - 1)


_K64 = [_frac_root_bits(p, 3) for p in _primes(80)]
_H64 = [_frac_root_bits(p, 2) for p in _primes(8)]

K_HI = np.array([k >> 32 for k in _K64], np.uint32)
K_LO = np.array([k & 0xFFFFFFFF for k in _K64], np.uint32)
H0_HI = np.array([h >> 32 for h in _H64], np.uint32)
H0_LO = np.array([h & 0xFFFFFFFF for h in _H64], np.uint32)


def _add64(ah, al, bh, bl):
    lo = al + bl
    hi = ah + bh + (lo < al).astype(_U32)
    return hi, lo


def _ror64(h, lo, r: int):
    if r == 0:
        return h, lo
    if r < 32:
        return (
            (h >> r) | (lo << (32 - r)),
            (lo >> r) | (h << (32 - r)),
        )
    if r == 32:
        return lo, h
    r -= 32
    return (
        (lo >> r) | (h << (32 - r)),
        (h >> r) | (lo << (32 - r)),
    )


def _shr64(h, lo, r: int):
    if r < 32:
        return h >> r, (lo >> r) | (h << (32 - r))
    return jnp.zeros_like(h), h >> (r - 32)


def _sigma(h, lo, r1, r2, r3, shift: bool):
    ah, al = _ror64(h, lo, r1)
    bh, bl = _ror64(h, lo, r2)
    ch, cl = _shr64(h, lo, r3) if shift else _ror64(h, lo, r3)
    return ah ^ bh ^ ch, al ^ bl ^ cl


@jax.jit
def _sha512_blocks(words, nblocks):
    """words: uint32[L, B, 32] (big-endian 64-bit message words as
    (hi, lo) uint32 pairs), nblocks: int32[L] true block counts.
    Returns uint32[L, 16] digest words (hi, lo interleaved)."""
    lanes, max_blocks, _ = words.shape
    k_hi, k_lo = jnp.asarray(K_HI), jnp.asarray(K_LO)
    state_hi = jnp.broadcast_to(jnp.asarray(H0_HI), (lanes, 8)).astype(_U32)
    state_lo = jnp.broadcast_to(jnp.asarray(H0_LO), (lanes, 8)).astype(_U32)

    def block_step(b, state):
        s_hi, s_lo = state
        # Rolling 16-word schedule window, stacked [16, L]; extension
        # for round t+16 is computed every round (discarded past 64) so
        # the 80 rounds stay ONE rolled fori_loop.
        win_hi = jnp.stack([words[:, b, 2 * t] for t in range(16)])
        win_lo = jnp.stack([words[:, b, 2 * t + 1] for t in range(16)])

        def rnd(t, carry):
            (win_hi, win_lo, ah, al, bh, bl, ch, cl, dh, dl,
             eh, el, fh, fl, gh, gl, hh, hl) = carry
            wh, wl = win_hi[0], win_lo[0]
            s1h, s1l = _sigma(eh, el, 14, 18, 41, False)
            chh = (eh & fh) ^ (~eh & gh)
            chl = (el & fl) ^ (~el & gl)
            t1h, t1l = _add64(hh, hl, s1h, s1l)
            t1h, t1l = _add64(t1h, t1l, chh, chl)
            t1h, t1l = _add64(t1h, t1l, k_hi[t], k_lo[t])
            t1h, t1l = _add64(t1h, t1l, wh, wl)
            s0h, s0l = _sigma(ah, al, 28, 34, 39, False)
            majh = (ah & bh) ^ (ah & ch) ^ (bh & ch)
            majl = (al & bl) ^ (al & cl) ^ (bl & cl)
            t2h, t2l = _add64(s0h, s0l, majh, majl)
            ne_h, ne_l = _add64(dh, dl, t1h, t1l)
            na_h, na_l = _add64(t1h, t1l, t2h, t2l)
            sg0h, sg0l = _sigma(win_hi[1], win_lo[1], 1, 8, 7, True)
            sg1h, sg1l = _sigma(win_hi[14], win_lo[14], 19, 61, 6, True)
            nh, nl = _add64(win_hi[0], win_lo[0], sg0h, sg0l)
            nh, nl = _add64(nh, nl, win_hi[9], win_lo[9])
            nh, nl = _add64(nh, nl, sg1h, sg1l)
            win_hi = jnp.concatenate([win_hi[1:], nh[None]])
            win_lo = jnp.concatenate([win_lo[1:], nl[None]])
            return (win_hi, win_lo, na_h, na_l, ah, al, bh, bl, ch, cl,
                    ne_h, ne_l, eh, el, fh, fl, gh, gl)

        init = (win_hi, win_lo,
                s_hi[:, 0], s_lo[:, 0], s_hi[:, 1], s_lo[:, 1],
                s_hi[:, 2], s_lo[:, 2], s_hi[:, 3], s_lo[:, 3],
                s_hi[:, 4], s_lo[:, 4], s_hi[:, 5], s_lo[:, 5],
                s_hi[:, 6], s_lo[:, 6], s_hi[:, 7], s_lo[:, 7])
        regs = lax.fori_loop(0, 80, rnd, init)[2:]
        new_hi, new_lo = [], []
        for i in range(8):
            nh, nl = _add64(s_hi[:, i], s_lo[:, i],
                            regs[2 * i], regs[2 * i + 1])
            new_hi.append(nh)
            new_lo.append(nl)
        new_hi = jnp.stack(new_hi, axis=1)
        new_lo = jnp.stack(new_lo, axis=1)
        # Lanes whose message ended before block b keep their state.
        live = (b < nblocks)[:, None]
        return (jnp.where(live, new_hi, s_hi),
                jnp.where(live, new_lo, s_lo))

    state_hi, state_lo = lax.fori_loop(
        0, max_blocks, block_step, (state_hi, state_lo)
    )
    return jnp.stack([state_hi, state_lo], axis=-1).reshape(lanes, 16)


def blocks_needed(length: int) -> int:
    """SHA-512 block count for a message of ``length`` bytes (payload +
    0x80 + 128-bit length field)."""
    return (length + 17 + BLOCK - 1) // BLOCK


def sha512_batch_dispatch(messages: "list[bytes]", max_blocks: int):
    """Pack + dispatch the batch; returns the un-materialized device
    array of digest words (callers overlap other work, then hand it to
    :func:`digest_bytes`). ``max_blocks`` is the caller's bucket (>=
    every message's block count; bucketing bounds compiled shapes)."""
    lanes = len(messages)
    nblocks = np.array([blocks_needed(len(m)) for m in messages], np.int32)
    if int(nblocks.max()) > max_blocks:
        raise ValueError("max_blocks bucket too small for batch")
    buf = np.zeros((lanes, max_blocks * BLOCK), np.uint8)
    for i, msg in enumerate(messages):
        n = len(msg)
        end = int(nblocks[i]) * BLOCK  # pad at the lane's OWN final block
        buf[i, :n] = np.frombuffer(msg, np.uint8)
        buf[i, n] = 0x80
        buf[i, end - 16:end] = np.frombuffer(
            (n * 8).to_bytes(16, "big"), np.uint8
        )
    words = buf.reshape(lanes, max_blocks, BLOCK // 4, 4)
    w32 = (
        (words[..., 0].astype(np.uint32) << 24)
        | (words[..., 1].astype(np.uint32) << 16)
        | (words[..., 2].astype(np.uint32) << 8)
        | words[..., 3].astype(np.uint32)
    )
    return _sha512_blocks(jnp.asarray(w32), jnp.asarray(nblocks))


def digest_bytes(digest_words) -> np.ndarray:
    """Materialize dispatched digest words into uint8[L, 64] digests."""
    digest_words = np.asarray(digest_words)
    lanes = digest_words.shape[0]
    out = np.zeros((lanes, 64), np.uint8)
    for w in range(16):  # big-endian bytes of each 32-bit half-word
        word = digest_words[:, w]
        for byte in range(4):
            out[:, 4 * w + 3 - byte] = (word >> (8 * byte)) & 0xFF
    return out


def sha512_batch(messages: "list[bytes]", max_blocks: int) -> np.ndarray:
    """SHA-512 digests (uint8[L, 64]) for every message in one device
    dispatch: dispatch + materialize."""
    if not messages:
        return np.zeros((0, 64), np.uint8)
    return digest_bytes(sha512_batch_dispatch(messages, max_blocks))
