"""Vectorized GF(2^255-19) arithmetic in radix-2^16 uint32 limbs.

The device twin of the native runtime's radix-2^51 field core
(``native/consensus_native.cpp``), re-limbed for XLA's integer dtypes:
the repo runs with ``jax_enable_x64`` off, so the widest integer lane is
uint32 and the radix must let a full schoolbook product column
accumulate without overflow. Radix 2^16 does: a 16x16 limb product is an
*exact* uint32 (operands < 2^16), its 16-bit halves land in separate
columns, and a column sums at most 32 half-products (< 2^21) before the
2^256 === 38 (mod p) fold lifts it to < 39*2^21 < 2^27 — comfortably
inside uint32. Carries are lazy in the native sense: additions stack
un-carried and a shared three-pass carry chain restores the invariant.

Representation: a field element is a ``uint32[..., 16]`` array, little-
endian limbs, value = sum(limb[i] * 2^(16 i)). The *carried* form
(every public op's output) has all limbs < 2^16; the value may still be
anywhere in [0, 2^256) — only :func:`canon` reduces below p, and only
the comparison/export paths need it.

Everything here is shape-polymorphic over leading batch axes: one call
squares/multiplies/inverts every signature lane in the batch at once,
which is where the device throughput comes from (and why the inverse-
sqrt exponentiation in :mod:`.curve` runs as ONE 254-squaring chain
across all lanes rather than per-point ladders).
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np
from jax import lax

LIMBS = 16
RADIX = 16
MASK = (1 << RADIX) - 1

P = 2**255 - 19
# 2^256 mod p: the fold factor for product columns >= 16 and for the
# carry out of limb 15.
FOLD = 38

_U32 = jnp.uint32


def _int_to_limbs(value: int) -> np.ndarray:
    return np.array(
        [(value >> (RADIX * i)) & MASK for i in range(LIMBS)], np.uint32
    )


def limbs_to_int(limbs) -> int:
    """Host-side decode (tests / debugging only)."""
    arr = np.asarray(limbs, np.uint64)
    return sum(int(arr[..., i]) << (RADIX * i) for i in range(LIMBS))


P_LIMBS = _int_to_limbs(P)

# Subtraction pad: 4p spread so every limb is >= 2^16 (>= any carried
# limb of the subtrahend), keeping a - b + PAD4P non-negative per limb.
# Derivation: 4p = 2^257 - 76 = (2^18-76) + sum_{i=1..14} (2^18-4) 2^16i
# + (2^17-4) 2^240 — asserted below rather than trusted.
PAD4P = np.array(
    [2**18 - 76] + [2**18 - 4] * 14 + [2**17 - 4], np.uint32
)
assert sum(int(c) << (RADIX * i) for i, c in enumerate(PAD4P)) == 4 * P
assert all(int(c) >= 1 << RADIX for c in PAD4P)

ZERO = _int_to_limbs(0)
ONE = _int_to_limbs(1)
# Curve constants in device limbs (values from the pure-Python twin's
# derivation; signing/_ed25519.py is the host reference).
D = _int_to_limbs((-121665 * pow(121666, P - 2, P)) % P)
D2 = _int_to_limbs((2 * ((-121665 * pow(121666, P - 2, P)) % P)) % P)
SQRT_M1 = _int_to_limbs(pow(2, (P - 1) // 4, P))


def _carry_vec(t):
    """Carry-save pass: every limb sheds its high bits to its neighbor
    simultaneously (the 2^256-weight carry folds to limb 0 as +38c).
    O(1) depth — the cheap way down from 2^27-bound columns to
    almost-carried limbs; cannot resolve a 0xFFFF ripple chain alone."""
    c = t >> RADIX
    t = (t & MASK).at[..., 1:].add(c[..., :-1])
    return t.at[..., 0].add(c[..., -1] * FOLD)


def _carry_seq(t):
    """Exact sequential pass, rolled as lax.scan over the limb axis so
    the compiled graph stays one small loop body. Output limbs < 2^16
    except limb 0, which absorbs 38*carry_out un-masked."""
    xs = jnp.moveaxis(t, -1, 0)
    zero = jnp.zeros(t.shape[:-1], _U32)

    def step(c, x):
        cur = x + c
        return cur >> RADIX, cur & MASK
    carry_out, ys = lax.scan(step, zero, xs)
    out = jnp.moveaxis(ys, 0, -1)
    return out.at[..., 0].add(carry_out * FOLD)


def carry(t):
    """Restore the carried invariant (all limbs strictly < 2^16) from
    column sums < 2^27. Two carry-save passes bound every limb by
    2^16+38; the first sequential pass then rippless exactly, and its
    end fold (+38*c, c <= 1) can only fire after limb 15 wrapped to 0 —
    so the second sequential pass provably carries nothing out of limb
    15 and its own fold is +0. The bound chain is adversarial-input
    rigorous (a 3-pass variant is not: a crafted 0xFFFF ripple survives
    it) and is property-tested against Python ints in
    tests/test_device_crypto.py."""
    return _carry_seq(_carry_seq(_carry_vec(_carry_vec(t))))


def add(a, b):
    """a + b (carried inputs -> carried output)."""
    return carry(a + b)


def sub(a, b):
    """a - b mod p via the 4p pad (no negative intermediates: every pad
    limb exceeds any carried limb of b)."""
    pad = jnp.asarray(PAD4P)
    return carry(a + (pad - b))


def mul(a, b):
    """Schoolbook 16x16 product with hi/lo column split and 2^256===38
    fold. Carried inputs required (products must be exact in uint32)."""
    from . import pallas_msm

    if pallas_msm.enabled():
        return pallas_msm.fe_mul(a, b)
    return _mul_jnp(a, b)


# Column-assignment matrix for the schoolbook product: half-product
# (i, j)'s lo lands in column i+j, its hi in column i+j+1. Encoding the
# anti-diagonal scatter as ONE 0/1 integer matmul compiles and runs far
# better than a 256-way scatter-add (uint32 matmul wraps mod 2^32,
# which is exact here — columns stay < 2^27).
_COL_MATRIX = np.zeros((2 * LIMBS * LIMBS, 2 * LIMBS), np.uint32)
for _i in range(LIMBS):
    for _j in range(LIMBS):
        _COL_MATRIX[_i * LIMBS + _j, _i + _j] = 1              # lo
        _COL_MATRIX[LIMBS * LIMBS + _i * LIMBS + _j, _i + _j + 1] = 1  # hi
del _i, _j


def _mul_jnp(a, b):
    # (..., 16, 16) exact products; hi/lo split, then the column matmul
    # and the 2^256 === 38 fold.
    prod = a[..., :, None] * b[..., None, :]
    halves = jnp.concatenate(
        [
            (prod & MASK).reshape(*prod.shape[:-2], LIMBS * LIMBS),
            (prod >> RADIX).reshape(*prod.shape[:-2], LIMBS * LIMBS),
        ],
        axis=-1,
    )
    cols = halves @ jnp.asarray(_COL_MATRIX)
    t = cols[..., :LIMBS] + cols[..., LIMBS:] * FOLD
    return carry(t)


def sqr(a):
    return mul(a, a)


def pow2k(a, k: int):
    """a^(2^k): k fused squarings as ONE rolled loop (keeps the XLA
    graph small — the inverse-sqrt chain squares 252 times)."""
    return lax.fori_loop(0, k, lambda _, x: sqr(x), a)


def pow22523(z):
    """z^((p-5)/8) = z^(2^252 - 3): the shared exponent of inverse-sqrt
    decompression (RFC 8032 5.1.3), one chain across every lane. Same
    addition chain as the native fe_pow22523."""
    z2 = sqr(z)
    z9 = mul(pow2k(z2, 2), z)            # z^9
    z11 = mul(z9, z2)                    # z^11
    z2_5_0 = mul(sqr(z11), z9)           # z^(2^5 - 1)
    z2_10_0 = mul(pow2k(z2_5_0, 5), z2_5_0)
    z2_20_0 = mul(pow2k(z2_10_0, 10), z2_10_0)
    z2_40_0 = mul(pow2k(z2_20_0, 20), z2_20_0)
    z2_50_0 = mul(pow2k(z2_40_0, 10), z2_10_0)
    z2_100_0 = mul(pow2k(z2_50_0, 50), z2_50_0)
    z2_200_0 = mul(pow2k(z2_100_0, 100), z2_100_0)
    z2_250_0 = mul(pow2k(z2_200_0, 50), z2_50_0)
    return mul(pow2k(z2_250_0, 2), z)    # z^(2^252 - 3)


def invert(z):
    """z^(p-2) = z^(2^255 - 21) (Fermat). Zero maps to zero."""
    z2 = sqr(z)
    z9 = mul(pow2k(z2, 2), z)
    z11 = mul(z9, z2)
    z2_5_0 = mul(sqr(z11), z9)
    z2_10_0 = mul(pow2k(z2_5_0, 5), z2_5_0)
    z2_20_0 = mul(pow2k(z2_10_0, 10), z2_10_0)
    z2_40_0 = mul(pow2k(z2_20_0, 20), z2_20_0)
    z2_50_0 = mul(pow2k(z2_40_0, 10), z2_10_0)
    z2_100_0 = mul(pow2k(z2_50_0, 50), z2_50_0)
    z2_200_0 = mul(pow2k(z2_100_0, 100), z2_100_0)
    z2_250_0 = mul(pow2k(z2_200_0, 50), z2_50_0)
    return mul(pow2k(z2_250_0, 5), z11)  # z^(2^255 - 21)


def _cond_sub_p(x):
    """One conditional subtract of p (borrow chain; carried input)."""
    p_l = jnp.asarray(P_LIMBS)
    out = []
    borrow = jnp.zeros(x.shape[:-1], _U32)
    for i in range(LIMBS):
        d = x[..., i] + (1 << RADIX) - p_l[i] - borrow
        out.append(d & MASK)
        borrow = 1 - (d >> RADIX)
    diff = jnp.stack(out, axis=-1)
    keep = (borrow == 1)[..., None]  # x < p: keep x
    return jnp.where(keep, x, diff)


def canon(x):
    """Canonical representative in [0, p). A carried value is < 2^256 =
    2p + 38, so two conditional subtractions always suffice."""
    return _cond_sub_p(_cond_sub_p(x))


def is_zero(x):
    """Carried input -> bool array over batch axes (exact mod-p test)."""
    return jnp.all(canon(x) == 0, axis=-1)


def eq(a, b):
    return is_zero(sub(a, b))


def parity(x):
    """Bit 0 of the canonical representative (the RFC 8032 sign bit)."""
    return canon(x)[..., 0] & 1


def from_bytes(b):
    """uint8[..., 32] little-endian -> carried limbs (top bit included;
    callers mask the sign bit themselves where the encoding demands)."""
    b32 = b.astype(_U32)
    return b32[..., 0::2] | (b32[..., 1::2] << 8)


def to_bytes(x):
    """Canonical little-endian uint8[..., 32] encoding."""
    c = canon(x)
    lo = (c & 0xFF).astype(jnp.uint8)
    hi = ((c >> 8) & 0xFF).astype(jnp.uint8)
    return jnp.stack([lo, hi], axis=-1).reshape(*c.shape[:-1], 32)


def is_canonical_fe(b):
    """RFC 8032 5.1.3 field-encoding check: the 255-bit y (sign bit
    already masked) must be < p."""
    y = from_bytes(b)
    p_l = jnp.asarray(P_LIMBS)
    borrow = jnp.zeros(y.shape[:-1], _U32)
    for i in range(LIMBS):
        d = y[..., i] + (1 << RADIX) - p_l[i] - borrow
        borrow = 1 - (d >> RADIX)
    return borrow == 1  # y < p


def const(limbs: np.ndarray, batch_shape=()):
    """Broadcast a host constant to a batch of lanes."""
    return jnp.broadcast_to(jnp.asarray(limbs), (*batch_shape, LIMBS))
