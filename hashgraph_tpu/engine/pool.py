"""Device-resident proposal pool: dense slot-indexed consensus state.

The pool is the TPU analogue of the reference's per-scope session maps
(reference: src/storage.rs:188-194): a fixed-capacity, structure-of-arrays
store of ``P`` proposal slots × ``V`` voter lanes living in device HBM.
Scalar per-session control flow becomes batched scatter/scan/gather kernels
(:mod:`hashgraph_tpu.ops`); the host keeps only the irregular bookkeeping XLA
cannot express with static shapes — the free list, slot↔proposal mapping,
owner-bytes→voter-lane dictionaries, and expiry timestamps.

Design notes (TPU):
- fixed capacity: slot allocation/eviction churn never changes array shapes,
  so every kernel compiles once per pool geometry;
- buffer donation on every mutation: the pool state is updated in place in
  HBM, no copy-on-write traffic;
- readbacks are narrow: ingest returns per-vote statuses and touched-slot
  states only; full-row gathers (:meth:`ProposalPool.read_slot`) are a cold
  query path;
- the host mirrors the ``state`` vector (updated from kernel readbacks, never
  re-fetched) so stats and transition detection cost no device traffic.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from functools import partial
from typing import Hashable

import numpy as np

import jax
import jax.numpy as jnp

from ..ops.decide import (
    STATE_ACTIVE,
    STATE_FAILED,
    STATE_FREE,
    STATE_REACHED_NO,
    STATE_REACHED_YES,
    timeout_kernel,
)
from ..ops.ingest import group_batch, ingest_kernel

__all__ = ["ProposalPool", "SlotMeta", "PoolFullError"]


class PoolFullError(RuntimeError):
    """The pool has no free slots (capacity P exhausted)."""


def _bucket(size: int, floor: int = 8) -> int:
    """Round a batch dimension up to a power-of-two bucket so XLA compiles
    one program per bucket, not one per batch shape. Pad entries use the
    out-of-range slot sentinel (scatters drop, gathers clip) or
    ``valid=False`` cells, so padding is semantically inert."""
    return max(floor, 1 << max(size - 1, 0).bit_length())


def _pad_slot_ids(slots: np.ndarray, bucket: int, sentinel: int) -> np.ndarray:
    out = np.full(bucket, sentinel, np.int32)
    out[: len(slots)] = slots
    return out


@dataclass
class SlotMeta:
    """Host-side bookkeeping for one allocated slot."""

    key: Hashable  # engine-level key, e.g. (scope, proposal_id)
    expiry: int  # absolute expiration timestamp (seconds)
    created_at: int
    voter_lanes: dict[bytes, int] = field(default_factory=dict)  # owner -> lane

    def lane_for(self, owner: bytes, capacity: int) -> int | None:
        """Owner-bytes → voter-lane dictionary (SURVEY §7: duplicate-owner
        detection needs exact bytes, not a hash that could collide). Returns
        None when all V lanes are taken by *other* owners — the protocol
        bounds distinct voters by expected_voters_count ≤ V, so this only
        happens for votes that would be rejected anyway."""
        lane = self.voter_lanes.get(owner)
        if lane is None:
            if len(self.voter_lanes) >= capacity:
                return None
            lane = len(self.voter_lanes)
            self.voter_lanes[owner] = lane
        return lane


@partial(jax.jit, donate_argnums=tuple(range(10)))
def _activate_kernel(
    state,
    yes,
    tot,
    vote_mask,
    vote_val,
    n,
    req,
    cap,
    gossip,
    liveness,
    slot_ids,
    n_new,
    req_new,
    cap_new,
    gossip_new,
    live_new,
):
    """Claim slots for new proposals: reset tallies, write per-slot config."""
    put = lambda arr, val: arr.at[slot_ids].set(val, mode="drop")
    state = put(state, STATE_ACTIVE)
    yes = put(yes, 0)
    tot = put(tot, 0)
    vote_mask = put(vote_mask, False)
    vote_val = put(vote_val, False)
    n = put(n, n_new)
    req = put(req, req_new)
    cap = put(cap, cap_new)
    gossip = put(gossip, gossip_new)
    liveness = put(liveness, live_new)
    return state, yes, tot, vote_mask, vote_val, n, req, cap, gossip, liveness


@partial(jax.jit, donate_argnums=(0, 1, 2, 3, 4))
def _load_kernel(
    state,
    yes,
    tot,
    vote_mask,
    vote_val,
    slot_ids,
    state_rows,
    yes_rows,
    tot_rows,
    mask_rows,
    val_rows,
):
    """Snapshot-restore tallies into already-activated slots (resume path:
    a network proposal arrives carrying validated votes, reference:
    src/session.rs:198-221 replays them — here the host replays through the
    scalar oracle and loads the resulting dense rows)."""
    put = lambda arr, rows: arr.at[slot_ids].set(rows, mode="drop")
    return (
        put(state, state_rows),
        put(yes, yes_rows),
        put(tot, tot_rows),
        put(vote_mask, mask_rows),
        put(vote_val, val_rows),
    )


@partial(jax.jit, donate_argnums=(0,))
def _release_kernel(state, slot_ids):
    return state.at[slot_ids].set(STATE_FREE, mode="drop")


@jax.jit
def _read_kernel(state, yes, tot, vote_mask, vote_val, slot_id):
    take = lambda arr: jnp.take(arr, slot_id, axis=0, mode="clip")
    return take(state), take(yes), take(tot), take(vote_mask), take(vote_val)


class ProposalPool:
    """Fixed-capacity device pool of consensus proposal slots.

    ``capacity`` (P) bounds concurrent proposals; ``voter_capacity`` (V)
    bounds ``expected_voters_count`` per proposal. All mutating methods are
    batched; statuses and transitions are returned per call with no global
    readbacks.
    """

    def __init__(self, capacity: int, voter_capacity: int):
        if capacity < 1 or voter_capacity < 1:
            raise ValueError("capacity and voter_capacity must be >= 1")
        self.capacity = capacity
        self.voter_capacity = voter_capacity

        self._state = jnp.full(capacity, STATE_FREE, jnp.int32)
        self._yes = jnp.zeros(capacity, jnp.int32)
        self._tot = jnp.zeros(capacity, jnp.int32)
        self._vote_mask = jnp.zeros((capacity, voter_capacity), bool)
        self._vote_val = jnp.zeros((capacity, voter_capacity), bool)
        self._n = jnp.zeros(capacity, jnp.int32)
        self._req = jnp.zeros(capacity, jnp.int32)
        self._cap = jnp.zeros(capacity, jnp.int32)
        self._gossip = jnp.zeros(capacity, bool)
        self._liveness = jnp.zeros(capacity, bool)

        # Host mirrors / bookkeeping.
        self._state_host = np.full(capacity, STATE_FREE, np.int32)
        self._free: list[int] = list(range(capacity - 1, -1, -1))
        self._meta: dict[int, SlotMeta] = {}

    # ── Introspection ──────────────────────────────────────────────────

    @property
    def free_slots(self) -> int:
        return len(self._free)

    @property
    def allocated_slots(self) -> int:
        return self.capacity - len(self._free)

    def meta(self, slot: int) -> SlotMeta:
        return self._meta[slot]

    def state_of(self, slot: int) -> int:
        """Host-mirrored lifecycle state (no device traffic)."""
        return int(self._state_host[slot])

    def state_counts(self) -> dict[int, int]:
        """Histogram of slot states from the host mirror (stats path,
        reference: src/service_stats.rs:32-59)."""
        values, counts = np.unique(self._state_host, return_counts=True)
        return {int(v): int(c) for v, c in zip(values, counts)}

    # ── Allocation ─────────────────────────────────────────────────────

    def allocate_batch(
        self,
        keys: list[Hashable],
        n: np.ndarray,
        req: np.ndarray,
        cap: np.ndarray,
        gossip: np.ndarray,
        liveness: np.ndarray,
        expiry: np.ndarray,
        created_at: np.ndarray,
    ) -> list[int]:
        """Claim one slot per key and initialise its on-device config.

        ``req``/``cap`` are host-precomputed (exact integer threshold math,
        reference: src/utils.rs:307-313 — see ops.decide.required_votes_np).
        Raises PoolFullError (allocating nothing) if fewer than len(keys)
        slots are free.
        """
        count = len(keys)
        if count == 0:
            return []
        n = np.asarray(n, np.int32)
        if int(n.max()) > self.voter_capacity:
            raise ValueError(
                f"expected_voters_count {int(n.max())} exceeds pool "
                f"voter_capacity {self.voter_capacity}"
            )
        if count > len(self._free):
            raise PoolFullError(
                f"need {count} slots, {len(self._free)} free of {self.capacity}"
            )
        slots = [self._free.pop() for _ in range(count)]
        bucket = _bucket(count)
        slot_ids = jnp.asarray(
            _pad_slot_ids(np.asarray(slots, np.int32), bucket, self.capacity)
        )
        pad1 = lambda arr, dtype: jnp.asarray(
            np.concatenate(
                [np.asarray(arr, dtype), np.zeros(bucket - count, dtype)]
            )
        )

        (
            self._state,
            self._yes,
            self._tot,
            self._vote_mask,
            self._vote_val,
            self._n,
            self._req,
            self._cap,
            self._gossip,
            self._liveness,
        ) = _activate_kernel(
            self._state,
            self._yes,
            self._tot,
            self._vote_mask,
            self._vote_val,
            self._n,
            self._req,
            self._cap,
            self._gossip,
            self._liveness,
            slot_ids,
            pad1(n, np.int32),
            pad1(req, np.int32),
            pad1(cap, np.int32),
            pad1(gossip, bool),
            pad1(liveness, bool),
        )

        expiry = np.asarray(expiry, np.int64)
        created_at = np.asarray(created_at, np.int64)
        for i, slot in enumerate(slots):
            self._state_host[slot] = STATE_ACTIVE
            self._meta[slot] = SlotMeta(
                key=keys[i], expiry=int(expiry[i]), created_at=int(created_at[i])
            )
        return slots

    def load_rows(
        self,
        slots: list[int],
        state: np.ndarray,
        yes: np.ndarray,
        tot: np.ndarray,
        mask_rows: np.ndarray,
        val_rows: np.ndarray,
    ) -> None:
        """Overwrite tallies of already-allocated slots (snapshot restore)."""
        if not slots:
            return
        count = len(slots)
        bucket = _bucket(count)
        slot_ids = jnp.asarray(
            _pad_slot_ids(np.asarray(slots, np.int32), bucket, self.capacity)
        )
        pad1 = lambda arr, dtype: jnp.asarray(
            np.concatenate(
                [np.asarray(arr, dtype), np.zeros(bucket - count, dtype)]
            )
        )
        pad2 = lambda arr: jnp.asarray(
            np.concatenate(
                [
                    np.asarray(arr, bool),
                    np.zeros((bucket - count, self.voter_capacity), bool),
                ]
            )
        )
        (
            self._state,
            self._yes,
            self._tot,
            self._vote_mask,
            self._vote_val,
        ) = _load_kernel(
            self._state,
            self._yes,
            self._tot,
            self._vote_mask,
            self._vote_val,
            slot_ids,
            pad1(state, np.int32),
            pad1(yes, np.int32),
            pad1(tot, np.int32),
            pad2(mask_rows),
            pad2(val_rows),
        )
        self._state_host[np.asarray(slots)] = np.asarray(state, np.int32)

    def release(self, slots: list[int]) -> None:
        """Return slots to the free list (eviction / delete_scope). Tallies
        are lazily cleared on the next allocation of the slot."""
        if not slots:
            return
        self._state = _release_kernel(
            self._state,
            jnp.asarray(
                _pad_slot_ids(
                    np.asarray(slots, np.int32),
                    _bucket(len(slots)),
                    self.capacity,
                )
            ),
        )
        for slot in slots:
            self._state_host[slot] = STATE_FREE
            del self._meta[slot]
            self._free.append(slot)

    # ── Hot paths ──────────────────────────────────────────────────────

    def ingest(
        self,
        slots: np.ndarray,
        lanes: np.ndarray,
        values: np.ndarray,
        now: int,
    ) -> tuple[np.ndarray, list[tuple[int, int]]]:
        """Apply a flat, arrival-ordered vote batch.

        Args:
          slots: int64[B] target slot per vote.
          lanes: int32[B] voter lane per vote (from SlotMeta.lane_for).
          values: bool[B] the yes/no choices.
          now: caller clock, for the per-slot expiry check
            (reference: src/session.rs:226).

        Returns:
          (statuses int32[B] in batch order, transitions) where transitions
          lists (slot, new_state) for every slot whose lifecycle state
          changed — the engine turns these into ConsensusReached events.
        """
        slots = np.asarray(slots, np.int64)
        if slots.size == 0:
            return np.empty(0, np.int32), []
        uniq, row, col, depth = group_batch(slots)
        s_count = len(uniq)
        bucket_s = _bucket(s_count)
        bucket_l = _bucket(depth, floor=1)
        voter_grid = np.zeros((bucket_s, bucket_l), np.int32)
        val_grid = np.zeros((bucket_s, bucket_l), bool)
        valid_grid = np.zeros((bucket_s, bucket_l), bool)
        voter_grid[row, col] = np.asarray(lanes, np.int32)
        val_grid[row, col] = np.asarray(values, bool)
        valid_grid[row, col] = True
        slot_ids = _pad_slot_ids(uniq.astype(np.int32), bucket_s, self.capacity)

        expiry = np.array(
            [self._meta[s].expiry if s in self._meta else 0 for s in uniq],
            np.int64,
        )
        expired = np.zeros(bucket_s, bool)
        expired[:s_count] = expiry <= now

        (
            self._state,
            self._yes,
            self._tot,
            self._vote_mask,
            self._vote_val,
            statuses,
            row_state,
        ) = ingest_kernel(
            self._state,
            self._yes,
            self._tot,
            self._vote_mask,
            self._vote_val,
            self._n,
            self._req,
            self._cap,
            self._gossip,
            self._liveness,
            jnp.asarray(slot_ids),
            jnp.asarray(expired),
            jnp.asarray(voter_grid),
            jnp.asarray(val_grid),
            jnp.asarray(valid_grid),
        )
        statuses = np.asarray(statuses)
        row_state = np.asarray(row_state)[:s_count]

        transitions: list[tuple[int, int]] = []
        for i, slot in enumerate(uniq):
            new_state = int(row_state[i])
            if self._state_host[slot] != new_state:
                self._state_host[slot] = new_state
                transitions.append((int(slot), new_state))
        return statuses[row, col], transitions

    def timeout(self, slots: list[int]) -> list[tuple[int, int]]:
        """Fire the timeout decision for the given slots.

        Returns (slot, new_state) for each *requested* slot after the sweep
        (including unchanged already-decided ones, so the caller can
        implement the reference's idempotent timeout return,
        src/service.rs:331-334).
        """
        if not slots:
            return []
        bucket = _bucket(len(slots))
        slot_ids = jnp.asarray(
            _pad_slot_ids(np.asarray(slots, np.int32), bucket, self.capacity)
        )
        self._state, row_state = timeout_kernel(
            self._state,
            self._yes,
            self._tot,
            self._n,
            self._req,
            self._liveness,
            slot_ids,
        )
        row_state = np.asarray(row_state)[: len(slots)]
        out: list[tuple[int, int]] = []
        for i, slot in enumerate(slots):
            new_state = int(row_state[i])
            self._state_host[slot] = new_state
            out.append((int(slot), new_state))
        return out

    # ── Cold query path ────────────────────────────────────────────────

    def read_slot(self, slot: int) -> dict[str, np.ndarray]:
        """Gather one slot's full row back to host (debug / session export)."""
        state, yes, tot, mask, vals = _read_kernel(
            self._state, self._yes, self._tot, self._vote_mask, self._vote_val,
            jnp.asarray(slot, jnp.int32),
        )
        return dict(
            state=np.asarray(state),
            yes=np.asarray(yes),
            tot=np.asarray(tot),
            vote_mask=np.asarray(mask),
            vote_val=np.asarray(vals),
        )
