"""Device-resident proposal pool: dense slot-indexed consensus state.

The pool is the TPU analogue of the reference's per-scope session maps
(reference: src/storage.rs:188-194): a fixed-capacity, structure-of-arrays
store of ``P`` proposal slots × ``V`` voter lanes living in device HBM.
Scalar per-session control flow becomes batched scatter/scan/gather kernels
(:mod:`hashgraph_tpu.ops`); the host keeps only the irregular bookkeeping XLA
cannot express with static shapes — the free list, slot↔proposal mapping,
owner-bytes→voter-lane dictionaries, and expiry timestamps.

Design notes (TPU):
- fixed capacity + power-of-two batch buckets: array shapes never vary with
  load, so each kernel compiles once per (pool geometry, bucket);
- buffer donation on every mutation: the pool state is updated in place in
  HBM, no copy-on-write traffic;
- readbacks are narrow: ingest returns per-vote statuses and touched-slot
  states only; full-row gathers (:meth:`ProposalPool.read_slot`) are a cold
  query path;
- the host mirrors the ``state`` vector (updated from kernel readbacks, never
  re-fetched) so stats and transition detection cost no device traffic;
- device work is isolated behind ``_dispatch_*`` hooks: the multi-device pool
  (:mod:`hashgraph_tpu.parallel`) overrides only those, inheriting all host
  bookkeeping.
"""

from __future__ import annotations

import os
from dataclasses import dataclass
from functools import partial
from typing import Hashable

import numpy as np

import jax
import jax.numpy as jnp

from ..ops.decide import (
    STATE_ACTIVE,
    STATE_FREE,
    timeout_kernel,
)
from ..ops.ingest import (
    fresh_ingest_kernel,
    fresh_ingest_laneless_kernel,
    group_batch,
    ingest_kernel,
    pack_grid,
    pack_slots,
)

__all__ = ["ProposalPool", "SlotMeta", "PoolFullError"]


class PoolFullError(RuntimeError):
    """The pool has no free slots (capacity P exhausted)."""


def _bucket(size: int, floor: int = 8) -> int:
    """Round a batch dimension up to a power-of-two bucket so XLA compiles
    one program per bucket, not one per batch shape. Pad entries use the
    out-of-range slot sentinel (scatters drop, gathers clip) or
    ``valid=False`` cells, so padding is semantically inert."""
    return max(floor, 1 << max(size - 1, 0).bit_length())


def _pad_slot_ids(slots: np.ndarray, bucket: int, sentinel: int) -> np.ndarray:
    out = np.full(bucket, sentinel, np.int32)
    out[: len(slots)] = slots
    return out


def _pad1(arr: np.ndarray, bucket: int, dtype) -> np.ndarray:
    out = np.zeros(bucket, dtype)
    out[: len(arr)] = np.asarray(arr, dtype)
    return out


def _pad2(arr: np.ndarray, rows: int, cols: int, dtype) -> np.ndarray:
    out = np.zeros((rows, cols), dtype)
    out[: arr.shape[0], : arr.shape[1]] = arr
    return out


@dataclass
class PendingIngest:
    """An in-flight ingest dispatch: the device output plus the host-side
    coordinates needed to interpret it. Lets callers pipeline many dispatches
    (device work and transfers overlap) and pay the readback latency once —
    essential on latency-bound links (tunneled TPUs: ~100ms per sync)."""

    out: object  # device int32[rows, L+1]: statuses + final row state
    uniq: np.ndarray  # [S] touched slots
    row: np.ndarray  # [B] batch item -> grid row
    col: np.ndarray  # [B] batch item -> grid col
    row_select: np.ndarray  # routed-row indexer: out[row_select] -> [S, :]


@dataclass(slots=True)
class SlotMeta:
    """Host-side bookkeeping for one allocated slot. Voter-lane assignments
    live in the pool's dense ``_lane_gids``/``_lane_count`` tables (shared by
    the scalar and columnar resolution paths), not here."""

    key: Hashable  # engine-level key, e.g. (scope, proposal_id)
    expiry: int  # absolute expiration timestamp (seconds)
    created_at: int


def activate_body(
    state,
    yes,
    tot,
    vote_mask,
    vote_val,
    n,
    req,
    cap,
    gossip,
    liveness,
    slot_ids,
    n_new,
    req_new,
    cap_new,
    gossip_new,
    live_new,
):
    """Claim slots for new proposals: reset tallies, write per-slot config.
    (Body form reused inside shard_map blocks by the multi-device pool.)"""
    put = lambda arr, val: arr.at[slot_ids].set(val, mode="drop")
    state = put(state, STATE_ACTIVE)
    yes = put(yes, 0)
    tot = put(tot, 0)
    vote_mask = put(vote_mask, False)
    vote_val = put(vote_val, False)
    n = put(n, n_new)
    req = put(req, req_new)
    cap = put(cap, cap_new)
    gossip = put(gossip, gossip_new)
    liveness = put(liveness, live_new)
    return state, yes, tot, vote_mask, vote_val, n, req, cap, gossip, liveness


def load_body(
    state,
    yes,
    tot,
    vote_mask,
    vote_val,
    slot_ids,
    state_rows,
    yes_rows,
    tot_rows,
    mask_rows,
    val_rows,
):
    """Snapshot-restore tallies into already-activated slots (resume path:
    a network proposal arrives carrying validated votes, reference:
    src/session.rs:198-221 replays them — here the host replays through the
    scalar oracle and loads the resulting dense rows)."""
    put = lambda arr, rows: arr.at[slot_ids].set(rows, mode="drop")
    return (
        put(state, state_rows),
        put(yes, yes_rows),
        put(tot, tot_rows),
        put(vote_mask, mask_rows),
        put(vote_val, val_rows),
    )


def release_body(state, slot_ids):
    return state.at[slot_ids].set(STATE_FREE, mode="drop")


_activate_kernel = partial(jax.jit, donate_argnums=tuple(range(10)))(activate_body)
_load_kernel = partial(jax.jit, donate_argnums=(0, 1, 2, 3, 4))(load_body)
_release_kernel = partial(jax.jit, donate_argnums=(0,))(release_body)


@jax.jit
def _stack_kernel(*xs):
    """Stack same-shape dispatch outputs into one transferable array.
    Jitted: eager jnp.stack dispatches one broadcast per operand (~10ms of
    dispatch overhead each on a tunneled link); this is a single call."""
    return jnp.stack(xs)


@jax.jit
def _read_kernel(state, yes, tot, vote_mask, vote_val, slot_id):
    take = lambda arr: jnp.take(arr, slot_id, axis=0, mode="clip")
    return take(state), take(yes), take(tot), take(vote_mask), take(vote_val)


class ProposalPool:
    """Fixed-capacity device pool of consensus proposal slots.

    ``capacity`` (P) bounds concurrent proposals; ``voter_capacity`` (V)
    bounds ``expected_voters_count`` per proposal. All mutating methods are
    batched; statuses and transitions are returned per call with no global
    readbacks.
    """

    def __init__(
        self, capacity: int, voter_capacity: int, use_pallas: bool | None = None
    ):
        if capacity < 1 or voter_capacity < 1:
            raise ValueError("capacity and voter_capacity must be >= 1")
        self.capacity = capacity
        self.voter_capacity = voter_capacity
        if use_pallas is None:
            use_pallas = os.environ.get("HASHGRAPH_TPU_PALLAS", "") == "1"
        self._ingest_kernel = ingest_kernel
        self._use_pallas = use_pallas
        if use_pallas:
            from ..ops.pallas_ingest import pallas_ingest_body

            self._ingest_kernel = partial(
                jax.jit, donate_argnums=(0, 1, 2, 3, 4)
            )(
                partial(
                    pallas_ingest_body,
                    interpret=jax.default_backend() != "tpu",
                )
            )
            # Keep the pallas A/B meaningful: with the opt-in kernel active
            # the engine must not silently route its dominant fast path to
            # the XLA closed-form kernel instead.
            self.supports_fresh_ingest = False
        self._init_device_arrays()

        # Host mirrors / bookkeeping.
        self._state_host = np.full(capacity, STATE_FREE, np.int32)
        self._expiry_host = np.zeros(capacity, np.int64)
        self._free: list[int] = list(range(capacity - 1, -1, -1))
        self._meta: dict[int, SlotMeta] = {}
        # Voter identity registry + dense lane tables. Owners are interned
        # once to a global integer id (exact-bytes dictionary — SURVEY §7:
        # duplicate-owner detection must not rely on a collidable hash);
        # per-slot lane assignment is first-come order in ``_lane_gids``
        # rows, resolvable one vote at a time (lane_for) or as a flat
        # vectorized batch (lanes_for_batch, the columnar hot path).
        self._gid_of: dict[bytes, int] = {}
        self._owners: list[bytes] = []
        # Registry bound: per-gid count of live slot-lane references. A gid
        # whose last referencing slot is released drops its owner mapping and
        # the id is recycled, so a long-lived pool churning through rotating
        # voter populations holds only the currently-live identities (plus
        # interned-but-never-voted ids, reclaimable via
        # clear_voter_registry at a quiesce point). numpy arrays (geometric
        # growth) keep the refcount bumps vectorized on the columnar path;
        # _gid_live distinguishes mapped ids from freed ones, and _gid_gen
        # counts how many times each index has been evicted: the public gid
        # is ``generation << 32 | index``, so a stale gid held across a
        # release AND a recycling re-intern never equals the new claimant's
        # gid — stale use is a typed rejection, not silent misattribution.
        self._gid_refs = np.zeros(0, np.int64)
        self._gid_live = np.zeros(0, bool)
        self._gid_gen = np.zeros(0, np.int64)
        # Generations start at this floor; clear_voter_registry raises it
        # past every generation ever minted so pre-clear gids can never
        # equal a post-clear claimant's gid.
        self._gen_floor = 0
        self._free_gids: list[int] = []
        self._lane_gids = np.full((capacity, voter_capacity), -1, np.int32)
        self._lane_count = np.zeros(capacity, np.int32)
        # Pipelining discipline: host mirror updates must apply in dispatch
        # order, and no other mutation may interleave with in-flight ingests
        # (the mirror would desync from the device). Enforced, not documented.
        self._inflight: list[PendingIngest] = []

    def _init_device_arrays(self) -> None:
        p, v = self.capacity, self.voter_capacity
        self._state = jnp.full(p, STATE_FREE, jnp.int32)
        self._yes = jnp.zeros(p, jnp.int32)
        self._tot = jnp.zeros(p, jnp.int32)
        self._vote_mask = jnp.zeros((p, v), bool)
        self._vote_val = jnp.zeros((p, v), bool)
        self._n = jnp.zeros(p, jnp.int32)
        self._req = jnp.zeros(p, jnp.int32)
        self._cap = jnp.zeros(p, jnp.int32)
        self._gossip = jnp.zeros(p, bool)
        self._liveness = jnp.zeros(p, bool)

    # ── Introspection ──────────────────────────────────────────────────

    @property
    def free_slots(self) -> int:
        return len(self._free)

    @property
    def allocated_slots(self) -> int:
        return self.capacity - len(self._free)

    def meta(self, slot: int) -> SlotMeta:
        return self._meta[slot]

    # ── Voter identity / lane resolution ───────────────────────────────

    def voter_gid(self, owner: bytes) -> int:
        """Intern owner bytes to a generation-tagged global voter id
        (``generation << 32 | index``; first use assigns, indices of
        fully-released voters are recycled under a bumped generation).
        Columnar callers ship these ids instead of bytes. A gid freed by a
        release is rejected with a typed status from then on — including
        after its index is recycled to a new owner, whose gid carries a
        different generation. Holding a gid across membership-mutating
        calls is therefore safe-but-wasteful (it may start rejecting);
        re-intern per batch (a dict hit) for gids that track membership."""
        gid = self._gid_of.get(owner)
        if gid is None:
            if self._free_gids:
                gid = self._free_gids.pop()
                self._owners[gid] = owner
                self._gid_refs[gid] = 0
            else:
                gid = len(self._owners)
                self._owners.append(owner)
                if gid >= len(self._gid_refs):
                    grow = max(64, len(self._gid_refs))
                    self._gid_refs = np.concatenate(
                        [self._gid_refs, np.zeros(grow, np.int64)]
                    )
                    self._gid_live = np.concatenate(
                        [self._gid_live, np.zeros(grow, bool)]
                    )
                    self._gid_gen = np.concatenate(
                        [self._gid_gen, np.full(grow, self._gen_floor, np.int64)]
                    )
                self._gid_refs[gid] = 0
            self._gid_live[gid] = True
            self._gid_of[owner] = gid
        return (int(self._gid_gen[gid]) << 32) | gid

    def owner_of_gid(self, gid: int) -> bytes:
        """Owner bytes for a gid the caller has checked via gids_live
        (the generation tag is stripped; liveness is not re-checked)."""
        return self._owners[int(gid) & 0xFFFFFFFF]

    @property
    def voter_gid_count(self) -> int:
        """Size of the gid index-space (low 32 bits of public gids).
        Recycled indices keep this from growing with voter churn."""
        return len(self._owners)

    @property
    def live_voter_count(self) -> int:
        """Number of owner identities currently mapped to a gid."""
        return len(self._gid_of)

    def lane_owners(self, slot: int) -> dict[int, bytes]:
        """lane -> owner bytes for one slot's assigned lanes (export path)."""
        row = self._lane_gids[slot]
        out: dict[int, bytes] = {}
        for lane in range(int(self._lane_count[slot])):
            gid = int(row[lane])
            if 0 <= gid < len(self._owners) and self._gid_live[gid]:
                out[lane] = self._owners[gid]
        return out

    def gids_live(self, gids: np.ndarray) -> np.ndarray:
        """Bool mask: True where the gid currently maps an interned owner
        AND carries that index's current generation. Out-of-range ids,
        freed ids, and stale-generation ids (held across a release, even
        after the index was recycled to a new owner) are all False —
        columnar callers use this to reject stale gids with a typed status
        instead of attributing votes to the recycled index's new claimant."""
        gids = np.asarray(gids, np.int64)
        if len(gids) >= 512:
            # Fused native pass (GIL released); ~6 numpy passes otherwise.
            from .. import native as _native

            res = _native.gids_live(
                gids, self._gid_live[: len(self._owners)],
                self._gid_gen[: len(self._owners)],
            )
            if res is not None:
                return res
        idx = gids & 0xFFFFFFFF
        gen = gids >> 32
        out = np.zeros(len(gids), bool)
        ok = (gids >= 0) & (idx < len(self._owners))
        if ok.any():
            sel = idx[ok]
            out[ok] = self._gid_live[sel] & (self._gid_gen[sel] == gen[ok])
        return out

    def clear_voter_registry(self) -> None:
        """Reset the owner↔gid interning tables.

        The registry is append-only while sessions are live (gids are
        embedded in active slots' lane tables), so it grows with the
        distinct-voter population — bounded for real consensus deployments
        (a known peer set), but a long-lived pool that has churned through
        many transient identities can reclaim the memory at any quiesce
        point where no slots are allocated. Interned gids become invalid;
        columnar callers must re-intern via voter_gid."""
        if self._meta:
            raise RuntimeError(
                f"cannot clear voter registry with {len(self._meta)} slots "
                "allocated (their lane tables reference interned gids)"
            )
        # Raise the generation floor past everything ever minted: a gid
        # held across the clear must keep rejecting (typed), not become
        # bit-identical to the first post-clear claimant's gid.
        if len(self._gid_gen):
            self._gen_floor = int(self._gid_gen.max()) + 1
        self._gid_of.clear()
        self._owners.clear()
        self._gid_refs = np.zeros(0, np.int64)
        self._gid_live = np.zeros(0, bool)
        self._gid_gen = np.zeros(0, np.int64)
        self._free_gids.clear()

    def lane_for(self, slot: int, owner: bytes) -> int | None:
        """Resolve (or first-come assign) one owner's voter lane on a slot.
        Returns None when all V lanes are taken by *other* owners — the
        protocol bounds distinct voters by expected_voters_count ≤ V in P2P
        mode; Gossipsub mode accepts arbitrarily many distinct voters, so
        size ``voter_capacity`` accordingly."""
        idx = self.voter_gid(owner) & 0xFFFFFFFF  # lane tables store indices
        row = self._lane_gids[slot]
        hits = np.nonzero(row == idx)[0]
        if hits.size:
            return int(hits[0])
        count = int(self._lane_count[slot])
        if count >= self.voter_capacity:
            return None
        row[count] = idx
        self._lane_count[slot] = count + 1
        self._gid_refs[idx] += 1
        return count

    def lanes_for_batch(
        self, slots: np.ndarray, gids: np.ndarray, assume_live: bool = False
    ) -> np.ndarray:
        """Vectorized lane_for over a flat arrival-ordered batch.

        Existing assignments resolve by a dense [B, V] match; unseen
        (slot, gid) pairs are assigned fresh lanes in first-occurrence
        order. Returns int32 lanes with -1 marking voter-capacity
        exhaustion. Cost is O(B·V) int32 host work — the per-vote Python
        dictionary hop this replaces is ~50x slower per vote.

        ``assume_live=True`` skips the liveness/generation gate for callers
        that already filtered the batch through :meth:`gids_live` (the
        engine's columnar path — avoids a duplicate O(B) pass).
        """
        slots = np.asarray(slots, np.int64)
        gids_i64 = np.asarray(gids, np.int64)
        idx64 = gids_i64 & 0xFFFFFFFF
        gids32 = idx64.astype(np.int32)
        lanes = np.full(len(slots), -1, np.int32)
        if len(slots) == 0:
            return lanes
        # In-range ids are real registry indices: require live + current
        # generation, else refuse the lane (-1). A freed or stale-generation
        # gid must never claim a lane — it would be stored in _lane_gids and
        # then wrongly decrement the recycled index's refcount on slot
        # release, evicting a live voter. Out-of-range ids are synthetic
        # (direct pool callers) and pass through unrefcounted as before.
        if not assume_live:
            in_range = (gids_i64 >= 0) & (idx64 < len(self._owners))
            if in_range.any():
                ir = np.nonzero(in_range)[0]
                sel = idx64[ir]
                bad = ~(
                    self._gid_live[sel]
                    & (self._gid_gen[sel] == (gids_i64[ir] >> 32))
                )
                if bad.any():
                    keep = np.ones(len(slots), bool)
                    keep[ir[bad]] = False
                    ok_rows = np.nonzero(keep)[0]
                    lanes[ok_rows] = self.lanes_for_batch(
                        slots[ok_rows], gids_i64[ok_rows], assume_live=True
                    )
                    return lanes
        # The dense [B, V] match is only needed for votes whose slot already
        # has assignments — on fresh slots (the common streaming case) the
        # whole batch short-circuits to first-occurrence assignment.
        may_exist = self._lane_count[slots] > 0
        if may_exist.any():
            cand = np.nonzero(may_exist)[0]
            match = self._lane_gids[slots[cand]] == gids32[cand, None]
            has_c = match.any(axis=1)
            lanes[cand[has_c]] = np.argmax(match[has_c], axis=1)
        has = lanes >= 0

        rem = np.nonzero(~has)[0]
        if rem.size == 0:
            return lanes
        # One key per unseen (slot, gid); np.unique gives the first flat
        # occurrence of each, and within-slot arrival rank = lane offset.
        # Mask the gid to its unsigned 32-bit pattern: without it a gid
        # >= 2^31 sign-extends and corrupts the slot bits of the key.
        keys = (slots[rem] << 32) | (gids32[rem].astype(np.int64) & 0xFFFFFFFF)
        uniq_keys, first_pos, inverse = np.unique(
            keys, return_index=True, return_inverse=True
        )
        uslot = (uniq_keys >> 32).astype(np.int64)
        ugid = (uniq_keys & 0xFFFFFFFF).astype(np.int32)
        order = np.lexsort((first_pos, uslot))  # by slot, then arrival
        s_sorted = uslot[order]
        is_start = np.empty(len(s_sorted), bool)
        is_start[0] = True
        np.not_equal(s_sorted[1:], s_sorted[:-1], out=is_start[1:])
        grp_starts = np.nonzero(is_start)[0]
        within = np.arange(len(s_sorted)) - grp_starts[np.cumsum(is_start) - 1]
        lane_uniq = np.empty(len(uniq_keys), np.int64)
        lane_uniq[order] = self._lane_count[s_sorted] + within
        valid = lane_uniq < self.voter_capacity
        self._lane_gids[uslot[valid], lane_uniq[valid]] = ugid[valid]
        self._lane_count += np.bincount(
            uslot[valid], minlength=self.capacity
        ).astype(np.int32)
        assigned = ugid[valid].astype(np.int64)
        if assigned.size:
            # In-range ids reaching here are live current-generation indices
            # (stale and freed ids were refused above), so every stored
            # in-range reference is counted and _retire_lanes' decrement is
            # exact; synthetic out-of-range ids pass through unrefcounted
            # (and are never evicted).
            sel = assigned[(assigned >= 0) & (assigned < len(self._owners))]
            np.add.at(self._gid_refs, sel, 1)
        lanes[rem] = np.where(valid, lane_uniq, -1)[inverse].astype(np.int32)
        return lanes

    def fresh_lanes_grouped(
        self,
        s_sorted: np.ndarray,
        gid_idx_sorted: np.ndarray,
        col_sorted: np.ndarray,
        uniq: np.ndarray,
        counts: np.ndarray,
    ) -> np.ndarray | None:
        """Fast-path lane assignment for a slot-grouped batch (sorted by
        slot, arrival order within slot) targeting ALL-FRESH slots with no
        repeated (slot, voter) pair in the batch: each item's lane is then
        simply its within-slot arrival index (``col_sorted``). Returns
        int32 lanes in sorted-domain order (-1 = capacity exhausted), or
        None when the preconditions don't hold and the caller must fall
        back to :meth:`lanes_for_batch`. One nearly-sorted dup-check sort
        replaces lanes_for_batch's unique+lexsort passes — the difference
        is ~4x host time on the multi-million-row columnar batches.

        ``gid_idx_sorted`` are registry *indices* (generation tag already
        stripped) the caller has validated live via :meth:`gids_live`.
        """
        if len(s_sorted) == 0:
            return np.empty(0, np.int32)
        if self._lane_count[uniq].any():
            return None
        keys = (s_sorted << 32) | gid_idx_sorted
        # Plain introsort: numpy's "stable" on int64 is radix sort, which
        # measures ~4x SLOWER here and cannot exploit the slot-major runs.
        ks = np.sort(keys)
        if (ks[1:] == ks[:-1]).any():
            return None  # same voter twice on one slot: general path resolves
        ok = col_sorted < self.voter_capacity
        lanes = np.where(ok, col_sorted, -1).astype(np.int32)
        sl = s_sorted[ok] if not ok.all() else s_sorted
        gi = gid_idx_sorted[ok] if not ok.all() else gid_idx_sorted
        co = col_sorted[ok] if not ok.all() else col_sorted
        self._lane_gids[sl, co] = gi.astype(np.int32)
        self._lane_count[uniq] = np.minimum(
            counts, self.voter_capacity
        ).astype(np.int32)
        # bincount + add is one O(B) pass; np.add.at's unbuffered scatter
        # is ~10x slower per element on multi-million-row batches. (An
        # out-of-range index still fails loudly: the longer bincount
        # result refuses to broadcast.)
        self._gid_refs += np.bincount(gi, minlength=len(self._gid_refs))
        return lanes

    def state_of(self, slot: int) -> int:
        """Host-mirrored lifecycle state (no device traffic)."""
        return int(self._state_host[slot])

    def states_of(self, slots) -> np.ndarray:
        """Vectorized :meth:`state_of` (host mirror gather, no device
        traffic) — the bulk demotion/sweep paths read one array instead
        of N accessor calls."""
        return self._state_host[np.asarray(slots, np.int64)]

    def state_counts(self) -> dict[int, int]:
        """Histogram of slot states from the host mirror (stats path,
        reference: src/service_stats.rs:32-59)."""
        values, counts = np.unique(self._state_host, return_counts=True)
        return {int(v): int(c) for v, c in zip(values, counts)}

    # ── Allocation ─────────────────────────────────────────────────────

    def allocate_batch(
        self,
        keys: list[Hashable],
        n: np.ndarray,
        req: np.ndarray,
        cap: np.ndarray,
        gossip: np.ndarray,
        liveness: np.ndarray,
        expiry: np.ndarray,
        created_at: np.ndarray,
    ) -> list[int]:
        """Claim one slot per key and initialise its on-device config.

        ``req``/``cap`` are host-precomputed (exact integer threshold math,
        reference: src/utils.rs:307-313 — see ops.decide.required_votes_np).
        Raises PoolFullError (allocating nothing) if fewer than len(keys)
        slots are free.
        """
        count = len(keys)
        if count == 0:
            return []
        self._check_no_inflight("allocate_batch")
        n = np.asarray(n, np.int32)
        if int(n.max()) > self.voter_capacity:
            raise ValueError(
                f"expected_voters_count {int(n.max())} exceeds pool "
                f"voter_capacity {self.voter_capacity}"
            )
        if count > len(self._free):
            raise PoolFullError(
                f"need {count} slots, {len(self._free)} free of {self.capacity}"
            )
        # Claim the tail of the free list in one slice (same slots, same
        # order as count pop() calls would yield).
        slots = self._free[-count:][::-1]
        del self._free[-count:]
        slots_arr = np.asarray(slots, np.int32)
        self._dispatch_activate(
            slots_arr,
            n,
            np.asarray(req, np.int32),
            np.asarray(cap, np.int32),
            np.asarray(gossip, bool),
            np.asarray(liveness, bool),
        )

        expiry = np.asarray(expiry, np.int64)
        created_at = np.asarray(created_at, np.int64)
        # Lane rows need no clearing here: free slots always have cleared
        # rows (initialised at construction, retired on release).
        self._state_host[slots_arr] = STATE_ACTIVE
        self._expiry_host[slots_arr] = expiry
        meta = self._meta
        for slot, key, exp, cre in zip(
            slots, keys, expiry.tolist(), created_at.tolist()
        ):
            meta[slot] = SlotMeta(key=key, expiry=exp, created_at=cre)
        return slots

    def load_rows(
        self,
        slots: list[int],
        state: np.ndarray,
        yes: np.ndarray,
        tot: np.ndarray,
        mask_rows: np.ndarray,
        val_rows: np.ndarray,
    ) -> None:
        """Overwrite tallies of already-allocated slots (snapshot restore)."""
        if not slots:
            return
        self._check_no_inflight("load_rows")
        self._dispatch_load(
            np.asarray(slots, np.int32),
            np.asarray(state, np.int32),
            np.asarray(yes, np.int32),
            np.asarray(tot, np.int32),
            np.asarray(mask_rows, bool),
            np.asarray(val_rows, bool),
        )
        self._state_host[np.asarray(slots)] = np.asarray(state, np.int32)

    def release(self, slots: list[int]) -> None:
        """Return slots to the free list (eviction / delete_scope). Tallies
        are lazily cleared on the next allocation of the slot; lane tables
        are retired now so fully-released voter identities leave the
        registry (the id is recycled by a later intern)."""
        if not slots:
            return
        self._check_no_inflight("release")
        self._dispatch_release(np.asarray(slots, np.int32))
        self._retire_lanes(np.asarray(slots, np.int64))
        for slot in slots:
            self._state_host[slot] = STATE_FREE
            self._expiry_host[slot] = 0
            del self._meta[slot]
            self._free.append(slot)

    def _retire_lanes(self, slot_arr: np.ndarray) -> None:
        """Drop the released slots' lane references; evict gids that no live
        slot references anymore."""
        slot_arr = np.unique(slot_arr)  # a duplicated slot must not double-deref
        rows = self._lane_gids[slot_arr]
        referenced = rows[rows >= 0].astype(np.int64)
        self._lane_gids[slot_arr] = -1
        self._lane_count[slot_arr] = 0
        if referenced.size == 0:
            return
        referenced = referenced[referenced < len(self._owners)]
        if referenced.size == 0:
            return
        gids, counts = np.unique(referenced, return_counts=True)
        self._gid_refs[gids] -= counts
        # _gid_live gates eviction so synthetic (never-interned) ids and
        # already-freed ids are skipped.
        for gid in gids[(self._gid_refs[gids] <= 0) & self._gid_live[gids]].tolist():
            del self._gid_of[self._owners[gid]]
            self._owners[gid] = b""
            self._gid_live[gid] = False
            # Bump the generation so every gid minted for this index before
            # the eviction is permanently distinguishable from the next
            # claimant's gid (stale use → typed rejection, never
            # misattribution).
            self._gid_gen[gid] += 1
            self._free_gids.append(gid)

    # ── Hot paths ──────────────────────────────────────────────────────

    def ingest(
        self,
        slots: np.ndarray,
        lanes: np.ndarray,
        values: np.ndarray,
        now: int,
    ) -> tuple[np.ndarray, list[tuple[int, int]]]:
        """Apply a flat, arrival-ordered vote batch (synchronous).

        Args:
          slots: int64[B] target slot per vote.
          lanes: int32[B] voter lane per vote (from SlotMeta.lane_for).
          values: bool[B] the yes/no choices.
          now: caller clock, for the per-slot expiry check
            (reference: src/session.rs:226).

        Returns:
          (statuses int32[B] in batch order, transitions) where transitions
          lists (slot, new_state) for every slot whose lifecycle state
          changed — the engine turns these into ConsensusReached events.
        """
        pending = self.ingest_async(slots, lanes, values, now)
        if pending is None:
            return np.empty(0, np.int32), []
        return self.complete(pending)

    def ingest_async(
        self,
        slots: np.ndarray,
        lanes: np.ndarray,
        values: np.ndarray,
        now: int,
    ) -> PendingIngest | None:
        """Dispatch a vote batch without waiting for results.

        The pool arrays advance immediately (donated in-place on device), so
        subsequent dispatches chain correctly; statuses/transitions become
        visible when :meth:`complete` is called. Streaming callers keep
        several batches in flight to hide host↔device latency (the pipeline
        axis from SURVEY §2.3).
        """
        slots = np.asarray(slots, np.int64)
        if slots.size == 0:
            return None
        uniq, row, col, depth = group_batch(slots)
        return self.ingest_async_grouped(
            uniq, row, col, depth, lanes, values, now
        )

    # True where ingest_async_grouped(fresh=True) routes to the closed-form
    # kernel (single-device, sharded, and multi-host pools — the engine
    # additionally agrees the plan fleet-wide in multi-host mode). The
    # opt-in pallas configuration advertises False to keep its A/B
    # meaningful.
    supports_fresh_ingest = True

    def fresh_grid_within_budget(self, s_count: int, depth: int) -> bool:
        """Absolute cell budget for the [S, depth]-padded fresh grid —
        padding blows up when one huge chain sits amid many shallow slots,
        at which point the segmented scan wins. Multi-host callers check
        this against the FLEET-agreed max shapes (the dispatch pads every
        process to those)."""
        return _bucket(s_count) * _bucket(depth, floor=1) <= 33_554_432

    def fresh_ingest_viable(
        self, uniq: np.ndarray, depth: int, n_items: int
    ) -> bool:
        """Whether a slot-grouped batch may take the closed-form (scan-free)
        ingest dispatch. Owns the invariants next to the kernel they guard:
        the pool supports it, every touched slot is still ACTIVE on the
        host state mirror (rare non-ACTIVE fresh slots: empty sessions
        decided by timeout), and the padded grid stays within the cell
        budget (with a relative padding-factor guard on top). The caller
        must separately establish freshness + no duplicate voters
        (fresh_lanes_grouped does both)."""
        if not self.supports_fresh_ingest:
            return False
        cells = _bucket(len(uniq)) * _bucket(depth, floor=1)
        return (
            cells <= max(8 * n_items, 65_536)
            and self.fresh_grid_within_budget(len(uniq), depth)
            and bool((self._state_host[uniq] == STATE_ACTIVE).all())
        )

    def ingest_async_grouped(
        self,
        uniq: np.ndarray,
        row: np.ndarray,
        col: np.ndarray,
        depth: int,
        lanes: np.ndarray,
        values: np.ndarray,
        now: int,
        fresh: bool = False,
    ) -> PendingIngest:
        """Pre-grouped :meth:`ingest_async`: the caller already grouped the
        batch by slot (``uniq[S]`` touched slots, per-item grid coordinates
        ``row``/``col``, ``depth`` = max votes per slot). The engine's
        columnar path computes the grouping once for a whole multi-dispatch
        batch and slices it per segment — skipping one O(B log B) sort per
        dispatch that :func:`group_batch` would redo.

        ``fresh=True`` dispatches the closed-form kernel (no sequential
        scan) — ONLY valid when every touched slot is freshly ACTIVE with
        zero tallies and the batch has no repeated (slot, voter) pair; the
        engine's fast path establishes exactly that. On >64-lane pools the
        fresh grid additionally requires (and checks) that every lane is
        the within-slot arrival index — the fresh assignment rule — so the
        lane plane need not cross the link at all (laneless uint8 cells,
        half the uint16 upload)."""
        s_count = len(uniq)
        depth = max(int(depth), 1)
        laneless = fresh and self.voter_capacity > 64 and not self._use_pallas
        if laneless and len(row):
            if not np.array_equal(lanes, col):
                raise ValueError(
                    "fresh ingest on a >64-lane pool requires lanes == "
                    "within-slot arrival index (the fresh assignment rule)"
                )
            grid = np.zeros((s_count, depth), np.uint8)
            grid[row, col] = np.asarray(values, np.uint8) | 2  # value|valid
        elif laneless:
            grid = np.zeros((s_count, depth), np.uint8)
        else:
            voter_grid = np.zeros((s_count, depth), np.int32)
            valbit = np.zeros((s_count, depth), np.int32)
            if len(row):
                voter_grid[row, col] = np.asarray(lanes, np.int32)
                valbit[row, col] = np.asarray(values, np.int32) | 2
            # Narrow grid cells to the pool's lane range (uint8/uint16) —
            # the grid is the dominant upload of every dispatch. The Pallas
            # kernel keeps the fixed int32 layout it was written against.
            grid = pack_grid(
                voter_grid,
                valbit & 1,
                valbit >> 1,
                voter_capacity=None if self._use_pallas else self.voter_capacity,
            )

        expired = self._expiry_host[uniq] <= now
        slot_pack2 = pack_slots(uniq.astype(np.int32), expired)
        if fresh:
            out, row_select = self._dispatch_ingest_fresh(
                slot_pack2, grid, laneless=laneless
            )
        else:
            out, row_select = self._dispatch_ingest(slot_pack2, grid)
        pending = PendingIngest(
            out=out, uniq=uniq, row=row, col=col, row_select=row_select
        )
        self._inflight.append(pending)
        return pending

    def complete_all(
        self, pendings: list[PendingIngest]
    ) -> list[tuple[np.ndarray, list[tuple[int, int]]]]:
        """Block on many in-flight ingests with ONE host↔device round-trip.

        jax.device_get transfers each leaf array separately, so fetching N
        dispatch outputs pays N link round-trips — on a tunneled TPU
        (~100ms RTT) that dominates the whole ingest path. Same-shape
        outputs are therefore stacked ON DEVICE (one cheap concat) and
        fetched as a single array. Must be called in dispatch order
        (enforced)."""
        outs = [p.out for p in pendings]
        if len(outs) > 1:
            groups: dict[tuple, list[int]] = {}
            for i, o in enumerate(outs):
                groups.setdefault(tuple(o.shape), []).append(i)
            # Each same-shape group is stacked in power-of-two chunks:
            # _stack_kernel is jitted per (arity, shape), so pow2 chunking
            # bounds the compile set at log2(max group) programs ever, with
            # no padding waste — a varying-depth stream would otherwise
            # trace+compile a fresh program for every distinct segment
            # count it produces.
            chunks: list[list[int]] = []
            for idxs in groups.values():
                pos, n = 0, len(idxs)
                while n:
                    c = 1 << (n.bit_length() - 1)
                    chunks.append(idxs[pos : pos + c])
                    pos += c
                    n -= c
            fetched = jax.device_get(
                [
                    _stack_kernel(*(outs[i] for i in chunk))
                    if len(chunk) > 1
                    else outs[chunk[0]]
                    for chunk in chunks
                ]
            )
            host: list = [None] * len(outs)
            for arr, chunk in zip(fetched, chunks):
                if len(chunk) > 1:
                    for k, i in enumerate(chunk):
                        host[i] = arr[k]
                else:
                    host[chunk[0]] = arr
        else:
            host = jax.device_get(outs)
        return [
            self._finish(pending, out) for pending, out in zip(pendings, host)
        ]

    def complete(
        self, pending: PendingIngest
    ) -> tuple[np.ndarray, list[tuple[int, int]]]:
        """Block on an in-flight ingest; return (statuses[B], transitions)."""
        return self._finish(pending, np.asarray(pending.out))

    def _check_no_inflight(self, op: str) -> None:
        if self._inflight:
            raise RuntimeError(
                f"{op} while {len(self._inflight)} ingest dispatch(es) are "
                "in flight: complete() them first (the host state mirror "
                "must apply updates in dispatch order)"
            )

    def _finish(
        self, pending: PendingIngest, host_out: np.ndarray
    ) -> tuple[np.ndarray, list[tuple[int, int]]]:
        if not self._inflight or self._inflight[0] is not pending:
            raise RuntimeError(
                "ingest completions must happen in dispatch order"
            )
        self._inflight.pop(0)
        arr = host_out[pending.row_select]
        statuses = arr[:, :-1]
        row_state = arr[:, -1]
        prev = self._state_host[pending.uniq]
        changed = prev != row_state
        self._state_host[pending.uniq] = row_state
        transitions = list(
            zip(
                pending.uniq[changed].tolist(),
                row_state[changed].tolist(),
            )
        )
        return statuses[pending.row, pending.col], transitions

    def timeout(self, slots: list[int]) -> list[tuple[int, int]]:
        """Fire the timeout decision for the given slots.

        Returns (slot, new_state) for each *requested* slot after the sweep
        (including unchanged already-decided ones, so the caller can
        implement the reference's idempotent timeout return,
        src/service.rs:331-334).
        """
        if not slots:
            return []
        self._check_no_inflight("timeout")
        row_state = self._dispatch_timeout(np.asarray(slots, np.int32))
        out: list[tuple[int, int]] = []
        for i, slot in enumerate(slots):
            new_state = int(row_state[i])
            self._state_host[slot] = new_state
            out.append((int(slot), new_state))
        return out

    # ── Device dispatch (single-device; overridden by the sharded pool) ─

    def _dispatch_activate(self, slots, n, req, cap, gossip, liveness) -> None:
        bucket = _bucket(len(slots))
        slot_ids = jnp.asarray(_pad_slot_ids(slots, bucket, self.capacity))
        (
            self._state,
            self._yes,
            self._tot,
            self._vote_mask,
            self._vote_val,
            self._n,
            self._req,
            self._cap,
            self._gossip,
            self._liveness,
        ) = _activate_kernel(
            self._state,
            self._yes,
            self._tot,
            self._vote_mask,
            self._vote_val,
            self._n,
            self._req,
            self._cap,
            self._gossip,
            self._liveness,
            slot_ids,
            jnp.asarray(_pad1(n, bucket, np.int32)),
            jnp.asarray(_pad1(req, bucket, np.int32)),
            jnp.asarray(_pad1(cap, bucket, np.int32)),
            jnp.asarray(_pad1(gossip, bucket, bool)),
            jnp.asarray(_pad1(liveness, bucket, bool)),
        )

    def _dispatch_load(self, slots, state, yes, tot, mask_rows, val_rows) -> None:
        bucket = _bucket(len(slots))
        slot_ids = jnp.asarray(_pad_slot_ids(slots, bucket, self.capacity))
        (
            self._state,
            self._yes,
            self._tot,
            self._vote_mask,
            self._vote_val,
        ) = _load_kernel(
            self._state,
            self._yes,
            self._tot,
            self._vote_mask,
            self._vote_val,
            slot_ids,
            jnp.asarray(_pad1(state, bucket, np.int32)),
            jnp.asarray(_pad1(yes, bucket, np.int32)),
            jnp.asarray(_pad1(tot, bucket, np.int32)),
            jnp.asarray(_pad2(mask_rows, bucket, self.voter_capacity, bool)),
            jnp.asarray(_pad2(val_rows, bucket, self.voter_capacity, bool)),
        )

    def _dispatch_release(self, slots) -> None:
        self._state = _release_kernel(
            self._state,
            jnp.asarray(_pad_slot_ids(slots, _bucket(len(slots)), self.capacity)),
        )

    def _dispatch_ingest(self, slot_pack, grid_pack):
        """Dispatch the packed batch; returns (device out [B_s, L+1],
        row-select indexer recovering the S real rows). Does NOT block."""
        s_count, depth = grid_pack.shape
        bucket_s = _bucket(s_count)
        bucket_l = _bucket(depth, floor=1)
        (
            self._state,
            self._yes,
            self._tot,
            self._vote_mask,
            self._vote_val,
            out,
        ) = self._ingest_kernel(
            self._state,
            self._yes,
            self._tot,
            self._vote_mask,
            self._vote_val,
            self._n,
            self._req,
            self._cap,
            self._gossip,
            self._liveness,
            jnp.asarray(_pad_slot_ids(slot_pack, bucket_s, self.capacity)),
            jnp.asarray(_pad2(grid_pack, bucket_s, bucket_l, grid_pack.dtype)),
        )
        return out, np.arange(s_count)

    def _dispatch_ingest_fresh(self, slot_pack, grid_pack, laneless=False):
        """Closed-form (scan-free) ingest dispatch for fresh-slot batches —
        same transfer contract as :meth:`_dispatch_ingest`. ``laneless``
        grids carry value/valid only (uint8); the kernel reconstructs
        lanes as the within-slot arrival index."""
        s_count, depth = grid_pack.shape
        bucket_s = _bucket(s_count)
        bucket_l = _bucket(depth, floor=1)
        kernel = fresh_ingest_laneless_kernel if laneless else fresh_ingest_kernel
        (
            self._state,
            self._yes,
            self._tot,
            self._vote_mask,
            self._vote_val,
            out,
        ) = kernel(
            self._state,
            self._yes,
            self._tot,
            self._vote_mask,
            self._vote_val,
            self._n,
            self._req,
            self._cap,
            self._gossip,
            self._liveness,
            jnp.asarray(_pad_slot_ids(slot_pack, bucket_s, self.capacity)),
            jnp.asarray(_pad2(grid_pack, bucket_s, bucket_l, grid_pack.dtype)),
        )
        return out, np.arange(s_count)

    def _dispatch_timeout(self, slots) -> np.ndarray:
        """Returns new row states, one per requested slot."""
        bucket = _bucket(len(slots))
        self._state, row_state = timeout_kernel(
            self._state,
            self._yes,
            self._tot,
            self._n,
            self._req,
            self._liveness,
            jnp.asarray(_pad_slot_ids(slots, bucket, self.capacity)),
        )
        return np.asarray(row_state)[: len(slots)]

    # ── Cold query path ────────────────────────────────────────────────

    def read_slot(self, slot: int) -> dict[str, np.ndarray]:
        """Gather one slot's full row back to host (debug / session export)."""
        state, yes, tot, mask, vals = _read_kernel(
            self._state, self._yes, self._tot, self._vote_mask, self._vote_val,
            jnp.asarray(slot, jnp.int32),
        )
        return dict(
            state=np.asarray(state),
            yes=np.asarray(yes),
            tot=np.asarray(tot),
            vote_mask=np.asarray(mask),
            vote_val=np.asarray(vals),
        )

    def read_slots(self, slots) -> dict[str, np.ndarray]:
        """Batched :meth:`read_slot`: ONE gather dispatch + transfer for
        many slots (arrays indexed [k] in ``slots`` order). The bulk-export
        path session demotion rides on — per-slot dispatches would make
        tier churn O(sessions) device round-trips."""
        state, yes, tot, mask, vals = _read_kernel(
            self._state, self._yes, self._tot, self._vote_mask, self._vote_val,
            jnp.asarray(np.asarray(slots, np.int32)),
        )
        return dict(
            state=np.asarray(state),
            yes=np.asarray(yes),
            tot=np.asarray(tot),
            vote_mask=np.asarray(mask),
            vote_val=np.asarray(vals),
        )
