"""Shared scalar-session ↔ dense-row conversion for pool clients.

Both the batch engine (loading validated network proposals / restored
checkpoints) and the TPU-backed storage (reconciling after scalar mutations)
must project a ConsensusSession onto a pool slot identically — same
threshold math, same round caps, same lane assignment. One implementation,
two callers.
"""

from __future__ import annotations

from typing import Hashable

import numpy as np

from ..ops.decide import (
    STATE_ACTIVE,
    STATE_FAILED,
    STATE_REACHED_NO,
    STATE_REACHED_YES,
    required_votes_np,
)
from ..session import ConsensusConfig, ConsensusSession, ConsensusState
from ..wire import Proposal
from .pool import ProposalPool

__all__ = [
    "allocate_slot",
    "load_session_rows",
    "state_code_of",
]


def state_code_of(state: ConsensusState) -> int:
    if state.is_reached:
        return STATE_REACHED_YES if state.result else STATE_REACHED_NO
    return STATE_FAILED if state.is_failed else STATE_ACTIVE


def allocate_slot(
    pool: ProposalPool,
    key: Hashable,
    proposal: Proposal,
    config: ConsensusConfig,
    created_at: int,
) -> int:
    """Claim and configure one slot for a proposal (exact integer threshold
    math, reference: src/utils.rs:307-313). Raises PoolFullError/ValueError
    like allocate_batch."""
    n = proposal.expected_voters_count
    return pool.allocate_batch(
        keys=[key],
        n=np.array([n]),
        req=required_votes_np(np.array([n]), config.consensus_threshold),
        cap=np.array([config.max_round_limit(n)]),
        gossip=np.array([config.use_gossipsub_rounds]),
        liveness=np.array([proposal.liveness_criteria_yes]),
        expiry=np.array([proposal.expiration_timestamp]),
        created_at=np.array([created_at]),
    )[0]


def load_session_rows(
    pool: ProposalPool, slot: int, session: ConsensusSession
) -> bool:
    """Write a session's tallies/masks/lifecycle into an allocated slot.

    Returns False (without loading) when the session's distinct voters
    exceed the pool's lane capacity — the caller decides whether that is an
    error (engine: reject the proposal) or a degrade-to-host condition
    (storage: release the slot)."""
    vcap = pool.voter_capacity
    total = len(session.votes) + len(session.tallies)
    if total > vcap:
        return False
    mask = np.zeros((1, vcap), bool)
    vals = np.zeros((1, vcap), bool)
    # Votes and columnar tallies (owner -> bool, no Vote object) project
    # onto lanes identically — each owner holds exactly one of the two.
    participants = [(o, v.vote) for o, v in session.votes.items()] + list(
        session.tallies.items()
    )
    for owner, value in participants:
        lane = pool.lane_for(slot, owner)
        if lane is None:
            return False
        mask[0, lane] = True
        vals[0, lane] = value
    yes = sum(1 for _, value in participants if value)
    pool.load_rows(
        [slot],
        state=np.array([state_code_of(session.state)]),
        yes=np.array([yes]),
        tot=np.array([total]),
        mask_rows=mask,
        val_rows=vals,
    )
    return True
