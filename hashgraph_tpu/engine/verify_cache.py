"""Memoized vote-admission verdicts: verify each unique vote ONCE.

The reference protocol gossips *growing vote chains*: a chain of length L
delivered one extension at a time re-presents every earlier vote L times,
and gossip redelivery re-presents whole chains verbatim. Signature
verification is the engine's host-side wall (BENCHMARKS.md: ~92% of the
validated end-to-end path is ECDSA), so re-verifying a vote that was
already admitted — or already rejected — is the single largest avoidable
cost under redelivery: O(L²) signature checks for an incrementally grown
chain. This module memoizes the *signature verdict* per unique
(vote content, signature) pair so that cost collapses to O(L).

What is cached — and why it is safe:

- The key is ``compute_vote_hash(vote) + vote.signature``. The computed
  hash covers every signed field except the signature and the embedded
  ``vote_hash`` field itself; ``validate_vote`` checks
  ``vote.vote_hash == computed`` *before* consulting the signature
  verdict, so at every consultation point the key fully determines the
  signing payload. A forged signature therefore lives under its own key
  and can never poison (or be served) the verdict of the honestly signed
  vote. Callers must only consult/populate the cache for votes whose
  embedded hash matches the recomputed one (the engine's
  ``_cached_verify`` enforces this).
- The value is exactly what ``ConsensusSignatureScheme.verify_batch``
  yields per item: ``True``, ``False``, or the ``ConsensusSchemeError``
  that scalar ``verify`` would have raised. Negative verdicts are cached
  too — a peer replaying a known-bad vote costs a dict probe, not an
  ECDSA recover.
- Context-dependent checks (replay guard, expiry, duplicate detection,
  chain linkage) are NOT cached: they depend on the receiving session and
  on ``now``, and they are cheap. The cache changes where signature
  verification happens, never its verdict — an engine with the cache
  disabled (``verify_cache=None``) produces byte-for-byte identical
  statuses.

The cache is bounded (entry count and approximate byte caps) with LRU
eviction, and thread-safe so one instance can be shared by every peer
engine in a :class:`~hashgraph_tpu.bridge.BridgeServer` process — a vote
gossiped to N co-hosted peers is then verified once, not N times.
Hit/miss/negative-hit/evict counters land on the process-wide metrics
registry (:mod:`hashgraph_tpu.obs`) and appear in ``/metrics``.
"""

from __future__ import annotations

import threading
from collections import OrderedDict

from ..obs import (
    VERIFY_CACHE_EVICTIONS_TOTAL,
    VERIFY_CACHE_HITS_TOTAL,
    VERIFY_CACHE_MISSES_TOTAL,
    VERIFY_CACHE_NEGATIVE_HITS_TOTAL,
)
from ..obs import registry as default_registry

__all__ = ["VerifiedVoteCache", "MISS"]

# Distinct sentinel for "no cached verdict": False and scheme errors are
# real (negative) verdicts, so None/False cannot signal a miss.
MISS = object()

# Flat per-entry overhead charged against max_bytes on top of the key
# length: OrderedDict node + key bytes object headers + value slot. An
# estimate (CPython internals vary by version) — the byte cap is a
# sizing guardrail, not an accounting ledger.
_ENTRY_OVERHEAD = 160


class VerifiedVoteCache:
    """Bounded, thread-safe LRU map: vote admission key -> signature verdict.

    ``max_entries`` bounds the entry count; ``max_bytes`` (optional)
    additionally bounds the approximate resident size (keys + flat
    per-entry overhead). Either cap triggers least-recently-*used*
    eviction — a hit refreshes recency, so hot chain prefixes survive
    churny gossip tails.
    """

    def __init__(
        self, max_entries: int = 1 << 16, max_bytes: int | None = None
    ):
        if max_entries <= 0:
            raise ValueError("max_entries must be positive")
        if max_bytes is not None and max_bytes <= 0:
            raise ValueError("max_bytes must be positive when set")
        self.max_entries = int(max_entries)
        self.max_bytes = max_bytes
        self._entries: OrderedDict[bytes, object] = OrderedDict()
        self._bytes = 0
        self._lock = threading.Lock()
        reg = default_registry
        self._m_hits = reg.counter(VERIFY_CACHE_HITS_TOTAL)
        self._m_misses = reg.counter(VERIFY_CACHE_MISSES_TOTAL)
        self._m_negative_hits = reg.counter(VERIFY_CACHE_NEGATIVE_HITS_TOTAL)
        self._m_evictions = reg.counter(VERIFY_CACHE_EVICTIONS_TOTAL)

    @staticmethod
    def key(
        computed_hash: bytes, signature: bytes, scheme_tag: bytes = b""
    ) -> bytes:
        """Admission key for one vote. ``computed_hash`` MUST be
        ``protocol.compute_vote_hash(vote)`` and the caller must have
        checked ``vote.vote_hash == computed_hash`` (see module
        docstring) — an unchecked embedded hash would let a mismatched
        payload share a key with the canonical one. ``scheme_tag``
        namespaces verdicts by signature-scheme identity (the engine
        derives it from its scheme type): one cache instance shared by
        engines with DIFFERENT schemes must never serve scheme A's
        verdict for scheme B's verification of the same bytes."""
        return scheme_tag + computed_hash + signature

    def get(self, key: bytes):
        """Cached verdict for ``key``, or :data:`MISS`. A hit refreshes
        LRU recency; negative verdicts (False / scheme error) count
        separately so poisoning attempts are visible in metrics."""
        with self._lock:
            verdict = self._entries.get(key, MISS)
            if verdict is MISS:
                self._m_misses.inc()
                return MISS
            self._entries.move_to_end(key)
        self._m_hits.inc()
        if verdict is not True:
            self._m_negative_hits.inc()
        return verdict

    def get_many(self, keys: "list[bytes]") -> list:
        """Batched :meth:`get`: one lock acquisition and one counter
        update for the whole batch — the engine's per-batch prepass calls
        this so a cache consult costs dict probes, not per-vote lock and
        metrics traffic. Returns one verdict-or-:data:`MISS` per key."""
        hits = misses = negatives = 0
        out = []
        entries = self._entries
        with self._lock:
            for key in keys:
                verdict = entries.get(key, MISS)
                if verdict is MISS:
                    misses += 1
                else:
                    entries.move_to_end(key)
                    hits += 1
                    negatives += verdict is not True
                out.append(verdict)
        if hits:
            self._m_hits.inc(hits)
        if misses:
            self._m_misses.inc(misses)
        if negatives:
            self._m_negative_hits.inc(negatives)
        return out

    def put(self, key: bytes, verdict) -> None:
        """Store one verdict, evicting LRU entries past either cap."""
        self.put_many([(key, verdict)])

    def put_many(self, items: "list[tuple[bytes, object]]") -> None:
        """Batched :meth:`put` (one lock acquisition, one eviction sweep)."""
        evicted = 0
        with self._lock:
            for key, verdict in items:
                old = self._entries.pop(key, MISS)
                if old is not MISS:
                    self._bytes -= len(key) + _ENTRY_OVERHEAD
                self._entries[key] = verdict
                self._bytes += len(key) + _ENTRY_OVERHEAD
            while len(self._entries) > self.max_entries or (
                self.max_bytes is not None
                and self._bytes > self.max_bytes
                and len(self._entries) > 1
            ):
                victim, _ = self._entries.popitem(last=False)
                self._bytes -= len(victim) + _ENTRY_OVERHEAD
                evicted += 1
        if evicted:
            self._m_evictions.inc(evicted)

    def clear(self) -> None:
        with self._lock:
            self._entries.clear()
            self._bytes = 0

    def __len__(self) -> int:
        with self._lock:
            return len(self._entries)

    @property
    def bytes_used(self) -> int:
        """Approximate resident bytes (keys + flat per-entry overhead)."""
        with self._lock:
            return self._bytes

    def stats(self) -> dict:
        """Point-in-time sizing readout (the hit/miss/evict *rates* live
        on the process-wide metrics registry, not per instance)."""
        with self._lock:
            return {
                "entries": len(self._entries),
                "bytes_used": self._bytes,
                "max_entries": self.max_entries,
                "max_bytes": self.max_bytes,
            }
