"""Memoized vote-admission verdicts: verify each unique vote ONCE.

The reference protocol gossips *growing vote chains*: a chain of length L
delivered one extension at a time re-presents every earlier vote L times,
and gossip redelivery re-presents whole chains verbatim. Signature
verification is the engine's host-side wall (BENCHMARKS.md: ~92% of the
validated end-to-end path is ECDSA), so re-verifying a vote that was
already admitted — or already rejected — is the single largest avoidable
cost under redelivery: O(L²) signature checks for an incrementally grown
chain. This module memoizes the *signature verdict* per unique
(vote content, signature) pair so that cost collapses to O(L).

What is cached — and why it is safe:

- The key is a SHA-256 over the length-framed triple (scheme tag,
  ``vote.signing_payload()``, signature) — see :meth:`VerifiedVoteCache.key`.
  ``signing_payload()`` is the exact byte string handed to
  ``scheme.verify``, so the key uniquely determines the (signer, message,
  signature) question whose answer it stores; a forged signature lives
  under its own key and can never poison (or be served) the verdict of
  the honestly signed vote. ``compute_vote_hash`` deliberately is NOT
  the key: it concatenates the variable-length
  ``vote_owner``/``parent_hash``/``received_hash`` fields without length
  framing, so two votes with *different* signing payloads (e.g. bytes
  shifted between ``parent_hash`` and ``received_hash``) can share a
  vote hash — keying on it would let a crafted never-signed vote be
  served the honest vote's cached ``True``.
- The value is exactly what ``ConsensusSignatureScheme.verify_batch``
  yields per item: ``True``, ``False``, or the ``ConsensusSchemeError``
  that scalar ``verify`` would have raised. Negative verdicts are cached
  too — a peer replaying a known-bad vote costs a dict probe, not an
  ECDSA recover.
- Context-dependent checks (replay guard, expiry, duplicate detection,
  chain linkage) are NOT cached: they depend on the receiving session and
  on ``now``, and they are cheap. The cache changes where signature
  verification happens, never its verdict — an engine with the cache
  disabled (``verify_cache=None``) produces byte-for-byte identical
  statuses.

The cache is bounded (entry count and approximate byte caps) with LRU
eviction, and thread-safe so one instance can be shared by every peer
engine in a :class:`~hashgraph_tpu.bridge.BridgeServer` process — a vote
gossiped to N co-hosted peers is then verified once, not N times.
Hit/miss/negative-hit/evict counters land on the process-wide metrics
registry (:mod:`hashgraph_tpu.obs`) and appear in ``/metrics``.
"""

from __future__ import annotations

import hashlib
import threading
from collections import OrderedDict

from ..obs import (
    VERIFY_CACHE_EVICTIONS_TOTAL,
    VERIFY_CACHE_HITS_TOTAL,
    VERIFY_CACHE_MISSES_TOTAL,
    VERIFY_CACHE_NEGATIVE_HITS_TOTAL,
)
from ..obs import registry as default_registry

__all__ = ["VerifiedVoteCache", "MISS"]

# Distinct sentinel for "no cached verdict": False and scheme errors are
# real (negative) verdicts, so None/False cannot signal a miss.
MISS = object()

# Flat per-entry overhead charged against max_bytes on top of the key
# length: OrderedDict node + key bytes object headers + value slot. An
# estimate (CPython internals vary by version) — the byte cap is a
# sizing guardrail, not an accounting ledger.
_ENTRY_OVERHEAD = 160


class VerifiedVoteCache:
    """Bounded, thread-safe LRU map: vote admission key -> signature verdict.

    ``max_entries`` bounds the entry count; ``max_bytes`` (optional)
    additionally bounds the approximate resident size (keys + flat
    per-entry overhead). Either cap triggers least-recently-*used*
    eviction — a hit refreshes recency, so hot chain prefixes survive
    churny gossip tails.
    """

    def __init__(
        self, max_entries: int = 1 << 16, max_bytes: int | None = None
    ):
        if max_entries <= 0:
            raise ValueError("max_entries must be positive")
        if max_bytes is not None and max_bytes <= 0:
            raise ValueError("max_bytes must be positive when set")
        self.max_entries = int(max_entries)
        self.max_bytes = max_bytes
        self._entries: OrderedDict[bytes, object] = OrderedDict()
        self._bytes = 0
        self._lock = threading.Lock()
        reg = default_registry
        self._m_hits = reg.counter(VERIFY_CACHE_HITS_TOTAL)
        self._m_misses = reg.counter(VERIFY_CACHE_MISSES_TOTAL)
        self._m_negative_hits = reg.counter(VERIFY_CACHE_NEGATIVE_HITS_TOTAL)
        self._m_evictions = reg.counter(VERIFY_CACHE_EVICTIONS_TOTAL)

    @staticmethod
    def key(
        signing_payload: bytes, signature: bytes, scheme_tag: bytes = b""
    ) -> bytes:
        """Admission key for one vote: SHA-256 over the length-framed
        (scheme_tag, signing_payload) pair plus the signature.
        ``signing_payload`` MUST be ``vote.signing_payload()`` — the
        exact bytes the scheme verifies — so the key unambiguously
        determines the verification question (see module docstring for
        why ``compute_vote_hash`` is NOT a safe substitute). Each
        variable-length component is length-prefixed; the signature is
        terminal so it needs no frame. ``scheme_tag`` namespaces
        verdicts by signature-scheme identity (the engine derives it
        from its scheme type): one cache instance shared by engines with
        DIFFERENT schemes must never serve scheme A's verdict for scheme
        B's verification of the same bytes. The digest form also keeps
        every entry's key at a flat 32 bytes."""
        h = hashlib.sha256()
        h.update(len(scheme_tag).to_bytes(4, "little"))
        h.update(scheme_tag)
        h.update(len(signing_payload).to_bytes(4, "little"))
        h.update(signing_payload)
        h.update(signature)
        return h.digest()

    def get(self, key: bytes):
        """Cached verdict for ``key``, or :data:`MISS`. A hit refreshes
        LRU recency; negative verdicts (False / scheme error) count
        separately so poisoning attempts are visible in metrics."""
        with self._lock:
            verdict = self._entries.get(key, MISS)
            if verdict is MISS:
                self._m_misses.inc()
                return MISS
            self._entries.move_to_end(key)
        self._m_hits.inc()
        if verdict is not True:
            self._m_negative_hits.inc()
        return verdict

    def get_many(self, keys: "list[bytes]") -> list:
        """Batched :meth:`get`: one lock acquisition and one counter
        update for the whole batch — the engine's per-batch prepass calls
        this so a cache consult costs dict probes, not per-vote lock and
        metrics traffic. Returns one verdict-or-:data:`MISS` per key."""
        hits = misses = negatives = 0
        out = []
        entries = self._entries
        with self._lock:
            for key in keys:
                verdict = entries.get(key, MISS)
                if verdict is MISS:
                    misses += 1
                else:
                    entries.move_to_end(key)
                    hits += 1
                    negatives += verdict is not True
                out.append(verdict)
        if hits:
            self._m_hits.inc(hits)
        if misses:
            self._m_misses.inc(misses)
        if negatives:
            self._m_negative_hits.inc(negatives)
        return out

    def put(self, key: bytes, verdict) -> None:
        """Store one verdict, evicting LRU entries past either cap."""
        self.put_many([(key, verdict)])

    def put_many(self, items: "list[tuple[bytes, object]]") -> None:
        """Batched :meth:`put` (one lock acquisition, one eviction sweep)."""
        evicted = 0
        with self._lock:
            for key, verdict in items:
                old = self._entries.pop(key, MISS)
                if old is not MISS:
                    self._bytes -= len(key) + _ENTRY_OVERHEAD
                self._entries[key] = verdict
                self._bytes += len(key) + _ENTRY_OVERHEAD
            while len(self._entries) > self.max_entries or (
                self.max_bytes is not None
                and self._bytes > self.max_bytes
                and len(self._entries) > 1
            ):
                victim, _ = self._entries.popitem(last=False)
                self._bytes -= len(victim) + _ENTRY_OVERHEAD
                evicted += 1
        if evicted:
            self._m_evictions.inc(evicted)

    def clear(self) -> None:
        with self._lock:
            self._entries.clear()
            self._bytes = 0

    def __len__(self) -> int:
        with self._lock:
            return len(self._entries)

    @property
    def bytes_used(self) -> int:
        """Approximate resident bytes (keys + flat per-entry overhead)."""
        with self._lock:
            return self._bytes

    def stats(self) -> dict:
        """Point-in-time sizing readout (the hit/miss/evict *rates* live
        on the process-wide metrics registry, not per instance)."""
        with self._lock:
            return {
                "entries": len(self._entries),
                "bytes_used": self._bytes,
                "max_entries": self.max_entries,
                "max_bytes": self.max_bytes,
            }
