"""Adaptive per-scope consensus timeouts learned from observed latency.

The reference's timer contract is static and embedder-supplied
(reference: src/lib.rs:15-34): the embedder schedules a fixed
``consensus_timeout`` per proposal and calls ``handle_consensus_timeout``
when it fires. A fixed timeout forces one trade for every network
condition — too short and a transiently-slow network mass-fails healthy
sessions; too long and genuinely-dead sessions linger for the full
worst-case bound.

This learner keeps the reference contract intact (timeouts remain
embedder-driven calls; nothing here schedules anything) and makes the
*value* the embedder should schedule adaptive, PBFT-style
(Castro & Liskov 1999, §2.3 view-change timers):

- every time a consensus timeout actually FIRES for a scope, the scope's
  learned timeout multiplies by ``backoff`` — repeated timeouts mean the
  network is slower than we believed, so back off geometrically;
- every vote-driven decision decays the learned timeout toward the SLO
  engine's observed decision-latency p99 for that scope times
  ``headroom`` — successes mean the observed tail is trustworthy, so the
  timeout tracks it from above instead of staying inflated forever;
- the result is always clamped to the scope's declared
  ``[timeout_min, timeout_max]`` (``ScopeConfig`` validates both-set).

The book is advisory, in-memory, and per-process on purpose: it feeds
``Engine.adaptive_timeout(scope)``, which the embedder polls when
scheduling its next timer. It is NOT replicated state — WAL replay
re-fires no timers (the engine's ``_health_live`` gate pauses learning
during replay), so a restarted process simply re-learns from live
traffic starting at the scope's static default. Determinism of the
consensus state machine is untouched: the learned value only changes
WHEN the embedder chooses to time out, never what a timeout does.

Scope entries live in a bounded LRU (churn benches mint millions of
scopes; unbounded per-scope floats would be a leak).
"""

from __future__ import annotations

from collections import OrderedDict

from ..scope_config import ScopeConfig

DEFAULT_BACKOFF = 2.0
DEFAULT_DECAY = 0.2
DEFAULT_HEADROOM = 1.5
DEFAULT_MAX_SCOPES = 256


class AdaptiveTimeoutBook:
    """Per-scope learned consensus-timeout values (seconds).

    All methods take the scope's ``ScopeConfig`` and are no-ops (returning
    the static default) unless the scope opted in via
    ``config.adaptive_timeout_enabled()``. Callers hold the engine lock;
    the book itself is not thread-safe.
    """

    def __init__(
        self,
        *,
        backoff: float = DEFAULT_BACKOFF,
        decay: float = DEFAULT_DECAY,
        headroom: float = DEFAULT_HEADROOM,
        max_scopes: int = DEFAULT_MAX_SCOPES,
    ):
        if backoff <= 1.0:
            raise ValueError("backoff must exceed 1.0")
        if not 0.0 < decay <= 1.0:
            raise ValueError("decay must be in (0, 1]")
        if headroom < 1.0:
            raise ValueError("headroom must be at least 1.0")
        self.backoff = float(backoff)
        self.decay = float(decay)
        self.headroom = float(headroom)
        self.max_scopes = max_scopes
        self._timeouts: "OrderedDict[object, float]" = OrderedDict()
        # Observability counters (per-process, read via snapshot()).
        self.backoffs_total = 0
        self.decays_total = 0

    @staticmethod
    def _clamp(value: float, config: ScopeConfig) -> float:
        return min(config.timeout_max, max(config.timeout_min, value))

    def _seed(self, scope, config: ScopeConfig) -> float:
        current = self._timeouts.get(scope)
        if current is None:
            current = self._clamp(config.default_timeout, config)
            self._timeouts[scope] = current
            while len(self._timeouts) > self.max_scopes:
                self._timeouts.popitem(last=False)
        else:
            self._timeouts.move_to_end(scope)
        return current

    def current(self, scope, config: ScopeConfig | None) -> float | None:
        """The timeout the embedder should schedule next for ``scope``:
        the learned value when the scope opted in, else None (caller
        falls back to the static resolution path)."""
        if config is None or not config.adaptive_timeout_enabled():
            return None
        return self._clamp(self._seed(scope, config), config)

    def on_timeout(self, scope, config: ScopeConfig | None) -> float | None:
        """A consensus timeout actually fired for ``scope``: multiply the
        learned timeout by ``backoff`` (clamped). Returns the new value,
        or None when the scope is not adaptive."""
        if config is None or not config.adaptive_timeout_enabled():
            return None
        nxt = self._clamp(self._seed(scope, config) * self.backoff, config)
        self._timeouts[scope] = nxt
        self.backoffs_total += 1
        return nxt

    def on_decided(
        self, scope, config: ScopeConfig | None, observed_p99_s: float
    ) -> float | None:
        """A vote-driven decision landed for ``scope``: decay the learned
        timeout toward ``observed_p99_s * headroom`` (clamped). A zero
        observation (no recent window data) leaves the value untouched —
        never decay toward a target the SLO engine has not measured."""
        if config is None or not config.adaptive_timeout_enabled():
            return None
        current = self._seed(scope, config)
        if observed_p99_s <= 0.0:
            return current
        target = self._clamp(observed_p99_s * self.headroom, config)
        nxt = self._clamp(current + self.decay * (target - current), config)
        self._timeouts[scope] = nxt
        self.decays_total += 1
        return nxt

    def snapshot(self) -> dict:
        """Debug/introspection readout (keys stringified for JSON)."""
        return {
            "scopes": {str(k): round(v, 6) for k, v in self._timeouts.items()},
            "backoffs_total": self.backoffs_total,
            "decays_total": self.decays_total,
        }

    def reset(self) -> None:
        self._timeouts.clear()
        self.backoffs_total = 0
        self.decays_total = 0
