"""TpuBackedStorage: the ConsensusStorage implementation over the device pool.

This is the BASELINE north-star integration shape: "a JAX/TPU execution
backend, exposed as a new ConsensusStorage implementation so the existing
ConsensusService API is unchanged." Drop it into a plain
:class:`~hashgraph_tpu.service.ConsensusService` and every session's
tally/mask/lifecycle state lives in device HBM; nothing else about the
service changes, and behavior stays bit-identical (the storage contract
suite and a service-on-TPU parity test enforce it).

Division of truth:
- the scalar parts a device can't hold (vote bytes, signatures, proposals,
  configs) stay in host records, exactly like the engine's SessionRecord;
- dense per-session state (tallies, voter masks, lifecycle) lives in pool
  slots and is *reconciled on every write*: `save_session`/`update_session`
  load the session's dense row back into its slot, so the device state is
  always current and batch consumers (TpuConsensusEngine-style kernels,
  timeout sweeps, global psum stats on a ShardedPool) can operate on it
  directly.

This storage is the compatibility path — per-call work is scalar, as the
trait's closure-based `update_session` demands. Throughput workloads use the
batch-first :class:`~hashgraph_tpu.engine.TpuConsensusEngine`, which shares
the same pool machinery.
"""

from __future__ import annotations

import threading
from typing import Callable, Generic, Hashable, Iterator, TypeVar

from ..errors import SessionNotFound
from ..scope_config import ScopeConfig
from ..session import ConsensusSession
from ..storage import ConsensusStorage
from .pool import PoolFullError, ProposalPool
from .session_sync import allocate_slot, load_session_rows

Scope = TypeVar("Scope", bound=Hashable)


class TpuBackedStorage(ConsensusStorage[Scope], Generic[Scope]):
    """Device-pool-backed ConsensusStorage (north-star integration)."""

    def __init__(
        self,
        capacity: int = 4096,
        voter_capacity: int = 64,
        pool: ProposalPool | None = None,
    ):
        self._pool = (
            pool if pool is not None else ProposalPool(capacity, voter_capacity)
        )
        self._lock = threading.RLock()
        self._sessions: dict[Scope, dict[int, ConsensusSession]] = {}
        self._slots: dict[tuple[Scope, int], int] = {}
        self._configs: dict[Scope, ScopeConfig] = {}

    def pool(self) -> ProposalPool:
        return self._pool

    # ── Device reconciliation ──────────────────────────────────────────

    def _sync_slot(self, scope: Scope, session: ConsensusSession) -> None:
        """Reconcile the session's dense row: drop any previous slot and
        load a fresh one. Mutators (and save_session overwrites) can change
        ANYTHING — config, voters, expiry — so slot reuse would leave stale
        device config/lanes; a fresh allocate+load is always correct. A
        session the pool cannot hold (voter lanes exhausted, pool full,
        n > lane capacity) degrades to host-only: the slot is released and
        ``device_state_of`` reports None rather than a stale row."""
        key = (scope, session.proposal.proposal_id)
        self._drop_slot(*key)
        if session.proposal.expected_voters_count > self._pool.voter_capacity:
            return
        try:
            slot = allocate_slot(
                self._pool, key, session.proposal, session.config,
                session.created_at,
            )
        except PoolFullError:
            return
        if not load_session_rows(self._pool, slot, session):
            self._pool.release([slot])
            return
        self._slots[key] = slot

    def _drop_slot(self, scope: Scope, proposal_id: int) -> None:
        slot = self._slots.pop((scope, proposal_id), None)
        if slot is not None:
            self._pool.release([slot])

    # ── Primitives ─────────────────────────────────────────────────────

    def save_session(self, scope: Scope, session: ConsensusSession) -> None:
        with self._lock:
            self._sessions.setdefault(scope, {})[
                session.proposal.proposal_id
            ] = session.clone()
            self._sync_slot(scope, session)

    def get_session(self, scope: Scope, proposal_id: int) -> ConsensusSession | None:
        with self._lock:
            session = self._sessions.get(scope, {}).get(proposal_id)
            return session.clone() if session is not None else None

    def remove_session(self, scope: Scope, proposal_id: int) -> ConsensusSession | None:
        with self._lock:
            scope_map = self._sessions.get(scope)
            if scope_map is None:
                return None
            session = scope_map.pop(proposal_id, None)
            # The emptied scope entry is kept, matching the in-memory
            # backend (list_scope_sessions then returns [], not None).
            if session is not None:
                self._drop_slot(scope, proposal_id)
            return session

    def list_scope_sessions(self, scope: Scope) -> list[ConsensusSession] | None:
        with self._lock:
            scope_map = self._sessions.get(scope)
            if scope_map is None:
                return None
            return [s.clone() for s in scope_map.values()]

    def stream_scope_sessions(self, scope: Scope) -> Iterator[ConsensusSession]:
        sessions = self.list_scope_sessions(scope) or []
        return iter(sessions)

    def replace_scope_sessions(
        self, scope: Scope, sessions: list[ConsensusSession]
    ) -> None:
        with self._lock:
            for pid in list(self._sessions.get(scope, {})):
                self._drop_slot(scope, pid)
            # Empty replacements keep the (empty) scope entry, matching the
            # in-memory backend.
            self._sessions[scope] = {
                s.proposal.proposal_id: s.clone() for s in sessions
            }
            for s in self._sessions[scope].values():
                self._sync_slot(scope, s)

    def list_scopes(self) -> list[Scope] | None:
        with self._lock:
            return list(self._sessions.keys()) or None

    def update_session(
        self,
        scope: Scope,
        proposal_id: int,
        mutator: Callable[[ConsensusSession], object],
    ) -> object:
        with self._lock:
            scope_map = self._sessions.get(scope)
            if not scope_map or proposal_id not in scope_map:
                raise SessionNotFound()
            session = scope_map[proposal_id]
            try:
                # Exceptions propagate; partial mutations stay (reference
                # closure semantics) — so the device row re-syncs either way.
                return mutator(session)
            finally:
                self._sync_slot(scope, session)

    def update_scope_sessions(
        self, scope: Scope, mutator: Callable[[list[ConsensusSession]], None]
    ) -> None:
        """Materialize -> mutate -> write back; a missing scope starts from
        an empty list, and dropping the last session removes the scope entry
        (matching InMemoryConsensusStorage / reference src/storage.rs:320-342)."""
        with self._lock:
            scope_map = self._sessions.setdefault(scope, {})
            sessions = list(scope_map.values())
            mutator(sessions)
            for pid in list(scope_map):
                self._drop_slot(scope, pid)
            if not sessions:
                del self._sessions[scope]
                return
            self._sessions[scope] = {
                s.proposal.proposal_id: s for s in sessions
            }
            for s in sessions:
                self._sync_slot(scope, s)

    def get_scope_config(self, scope: Scope) -> ScopeConfig | None:
        with self._lock:
            config = self._configs.get(scope)
            return config.clone() if config is not None else None

    def set_scope_config(self, scope: Scope, config: ScopeConfig) -> None:
        config.validate()
        with self._lock:
            self._configs[scope] = config.clone()

    def delete_scope(self, scope: Scope) -> None:
        with self._lock:
            for pid in list(self._sessions.get(scope, {})):
                self._drop_slot(scope, pid)
            self._sessions.pop(scope, None)
            self._configs.pop(scope, None)

    def update_scope_config(
        self, scope: Scope, updater: Callable[[ScopeConfig], None]
    ) -> None:
        with self._lock:
            config = self._configs.get(scope)
            if config is None:
                config = ScopeConfig()
            updater(config)
            config.validate()
            self._configs[scope] = config

    # ── Device-side verification helper ────────────────────────────────

    def device_state_of(self, scope: Scope, proposal_id: int) -> int | None:
        """The pool slot's lifecycle code for a session (None if the session
        is host-only). Used by tests to prove the device replica tracks the
        scalar truth."""
        slot = self._slots.get((scope, proposal_id))
        return self._pool.state_of(slot) if slot is not None else None
