"""The TPU execution engine: device-resident pool + batch-first service.

This package is where the framework stops mirroring the reference's shape
and becomes a TPU program: consensus state lives in dense ``[P]``/``[P, V]``
HBM arrays (:mod:`.pool`), mutations are batched kernel dispatches, and the
reference's scalar API is a thin veneer over the batch path (:mod:`.engine`).
"""

from .engine import SessionRecord, TpuConsensusEngine
from .pool import PoolFullError, ProposalPool, SlotMeta
from .storage import TpuBackedStorage
from .verify_cache import VerifiedVoteCache

__all__ = [
    "TpuConsensusEngine",
    "TpuBackedStorage",
    "SessionRecord",
    "ProposalPool",
    "SlotMeta",
    "PoolFullError",
    "VerifiedVoteCache",
]
