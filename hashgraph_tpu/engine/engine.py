"""TpuConsensusEngine: the batch-first consensus service backed by the pool.

This is the framework's flagship execution path (SURVEY §7, BASELINE north
star): the same observable semantics as :class:`~hashgraph_tpu.service.
ConsensusService` — scalar entry points included — but with all tally/round/
decision state dense on device and a native batch API (:meth:`ingest_votes`)
the scalar calls funnel into. Host work per vote is limited to what XLA
cannot do: signature/hash validation (pluggable scheme, CPU), owner→lane
dictionary lookups, and event emission.

Division of labor:
- device (ProposalPool): tallies, vote masks, round-cap projection, the
  decision kernel, timeout sweeps — everything order-sensitive is replayed
  arrival-ordered by the scan inside the ingest kernel;
- host (this class): vote build/validation (reference: src/utils.rs:55-171),
  scope configs and their resolution precedence (src/service.rs:440-484),
  per-scope session registries with LRU eviction (src/service.rs:512-522),
  proposal reconstruction for gossip, and the event bus.
"""

from __future__ import annotations

import functools
import threading
import time
import weakref
from dataclasses import dataclass, field
from typing import Generic, Hashable, TypeVar

import numpy as np

from ..errors import (
    ConsensusError,
    ConsensusFailed,
    InsufficientVotesAtTimeout,
    ProposalAlreadyExist,
    SessionNotFound,
    StatusCode,
    UserAlreadyVoted,
    error_for_code,
)
from ..events import BroadcastEventBus, ConsensusEventBus
from ..obs import (
    CHAIN_KERNEL_SECONDS,
    CHAIN_SUFFIX_LENGTH,
    DECISION_LATENCY,
    DECISIONS_TOTAL,
    DEFAULT_SIZE_BUCKETS,
    DEVICE_INGEST_SECONDS,
    INGEST_BATCH_SIZE,
    LIVE_PROPOSALS,
    PROPOSALS_CREATED_TOTAL,
    TIER_BYTES,
    TIER_DEMOTED_SESSIONS,
    TIER_DEMOTIONS_TOTAL,
    TIER_GC_TOTAL,
    TIER_PROMOTIONS_TOTAL,
    TIMEOUTS_FIRED_TOTAL,
    VERIFIED_SIGNATURES_TOTAL,
    VERIFY_BATCH_SECONDS,
    VOTE_TABLE_OCCUPANCY,
    VOTES_ACCEPTED_TOTAL,
    VOTES_TOTAL,
    WIRE_APPLY_ROWS_TOTAL,
    WIRE_DEVICE_DISPATCHES_TOTAL,
    TimelineStore,
    flight_recorder,
    observed_span,
    slo_engine,
)
from ..obs.prometheus import _escape_label
from ..obs import health_monitor as default_health_monitor
from ..obs import install_jax_telemetry
from ..obs import registry as default_registry
from ..obs.health import HealthMonitor
from ..obs.registry import Counter
from ..obs.timeline import OUTCOME_FAILED, OUTCOME_NO, OUTCOME_YES
from ..obs.trace import TraceContext, current_context, trace_store
from ..ops.decide import (
    STATE_ACTIVE,
    STATE_FAILED,
    STATE_REACHED_NO,
    STATE_REACHED_YES,
)
from ..protocol import (
    _F64_EPSILON,
    _TWO_THIRDS,
    COMPUTE_CHAIN,
    build_vote,
    calculate_required_votes,
    calculate_threshold_based_value,
    compute_vote_hash,
    regenerate_until_unique,
    validate_proposal_timestamp,
    validate_vote,
    validate_vote_chain,
)
from ..scope_config import (
    DEFAULT_TIMEOUT_SECONDS,
    ScopeConfig,
    ScopeConfigBuilder,
)
from .adaptive import AdaptiveTimeoutBook
from ..service import DEFAULT_MAX_SESSIONS_PER_SCOPE, ConsensusStats
from ..session import ConsensusConfig, ConsensusSession, ConsensusState
from ..signing import ConsensusSignatureScheme
from ..tracing import tracer as default_tracer
from ..types import (
    ConsensusEvent,
    ConsensusFailedEvent,
    ConsensusReached,
    CreateProposalRequest,
)
from ..wire import Proposal, Vote, normalize_wire_votes
from .pool import ProposalPool
from .session_sync import allocate_slot, load_session_rows, state_code_of
from .verify_cache import MISS, VerifiedVoteCache

Scope = TypeVar("Scope", bound=Hashable)

_U32_MAX = 0xFFFFFFFF


def hashlib_sha256_8(data: bytes) -> bytes:
    """First 8 bytes of SHA-256 — the admission-cache scheme tag (a
    stable, collision-negligible namespace for a handful of scheme
    types; full digests would fatten every cache key for nothing)."""
    import hashlib

    return hashlib.sha256(data).digest()[:8]


def _canonical_scope_bytes(scope) -> bytes:
    """Process-independent byte encoding of a scope for the multi-host
    deterministic pid derivation. repr() is NOT safe here: the default
    object repr embeds a memory address, which would silently de-sync the
    replicated control plane — the exact failure deterministic pids exist
    to prevent — so non-canonical scope types are a hard error in
    multi-host mode."""
    if isinstance(scope, str):
        return b"s:" + scope.encode()
    if isinstance(scope, (bytes, bytearray)):
        return b"b:" + bytes(scope)
    if isinstance(scope, int):
        # int(scope) so bool encodes identically to the int it equals
        # (True and 1 are the same dict key, so they are the same scope).
        return b"i:" + str(int(scope)).encode()
    raise TypeError(
        f"multi-host mode requires str/bytes/int scopes (canonical "
        f"cross-process encoding); got {type(scope).__name__}"
    )

_STATE_TO_SCALAR = {
    STATE_ACTIVE: ConsensusState.active(),
    STATE_FAILED: ConsensusState.failed(),
    STATE_REACHED_YES: ConsensusState.reached(True),
    STATE_REACHED_NO: ConsensusState.reached(False),
}

# Timeline outcome labels per dense lifecycle state (ACTIVE maps to None:
# a transition list never carries it, but the .get guard is cheap).
_OUTCOME_OF_STATE = {
    STATE_REACHED_YES: OUTCOME_YES,
    STATE_REACHED_NO: OUTCOME_NO,
    STATE_FAILED: OUTCOME_FAILED,
}


class _CreationCols:
    """Per-proposal scalar columns accumulated while batch creation mints
    its Proposal objects. The allocator turns these plain int/float/bool
    lists into device config arrays with np.asarray — several times cheaper
    than re-walking the freshly-built objects with fromiter generators."""

    __slots__ = ("n", "expiry", "liveness", "thr", "gossip", "maxr")

    def __init__(self):
        self.n: list[int] = []
        self.expiry: list[int] = []
        self.liveness: list[bool] = []
        self.thr: list[float] = []
        self.gossip: list[bool] = []
        self.maxr: list[int] = []


@dataclass(slots=True)
class SessionRecord(Generic[Scope]):
    """Host-side view of one session (scalar bookkeeping the device doesn't
    need; vote bytes kept for gossip reconstruction and chain linking,
    reference: src/utils.rs:62-77).

    Two substrates share this record type: pooled sessions (``slot`` >= 0,
    tallies live in device HBM) and host-spilled sessions (``session`` set,
    ``slot`` a negative synthetic id) — the graceful-degrade path for
    proposals the fixed pool geometry cannot hold. The reference service has
    no capacity limits at all (src/service.rs:86-97), so spilling keeps the
    public API's envelope unbounded even though the device pool is not."""

    scope: Scope
    slot: int
    proposal: Proposal  # votes list appended in acceptance order
    config: ConsensusConfig
    created_at: int
    votes: dict[bytes, Vote] = field(default_factory=dict)  # accepted only
    session: ConsensusSession | None = None  # host fallback substrate
    # Opt-in columnar retention: verbatim wire bytes of accepted votes as
    # (arrival seq, packed blob, local offsets) chunks. Decoded lazily on
    # proposal export so a columnar-ingested session can be re-gossiped
    # with a chain-valid vote list; empty unless the caller passed
    # wire_votes to ingest_columnar. ``retained_cache`` memoizes the decode
    # (chunk-count keyed: retained_wire only grows by append).
    retained_wire: list[tuple[int, bytes, np.ndarray]] = field(default_factory=list)
    retained_cache: tuple[int, list[tuple[int, list[Vote]]]] | None = None
    # Per-record arrival clock: scalar accepted votes take one tick each,
    # every retained columnar chunk takes one tick, so exports can merge
    # the two paths back into true (call-granularity) arrival order.
    arrival_seq: int = 0
    scalar_seqs: list[int] = field(default_factory=list)
    # Wire-columnar chain continuity (ingest_wire_columnar): the
    # session's effective tail hash and accepted-owner set as tracked by
    # the validated wire path, plus a (retained chunks, scalar accepts)
    # sync stamp. While the stamp matches the record, the dangling-vote
    # guard keeps enforcing received-hash linkage across wire frames —
    # without this, any frame after the first would be permissive and a
    # dropped/reordered gossip stream could diverge peers. A mismatched
    # stamp (legacy pre-validated columnar ingest, interleaved paths)
    # falls back to the documented permissive behavior.
    wire_tail: bytes | None = None
    wire_seen: "set[bytes] | None" = None
    wire_sync: "tuple[int, int] | None" = None
    # True while EVERY retained chunk on this record came from the
    # validated wire path (ingest_wire_columnar): its accepts are
    # guard-ordered, so the merged retained+scalar chain stays
    # positionally comparable — the anti-entropy watermark and the fork
    # probe keep working on wire-fed sessions. The legacy pre-validated
    # columnar ingest flips it False (arbitrary order; the documented
    # permissive behavior).
    wire_only: bool = True
    # Distributed trace identity bound at create/process time (None when
    # the trace store is disabled or the session arrived via an untraced
    # batch path): every later span/instant for this session joins this
    # trace, and the wire layers serialize it alongside the proposal.
    trace: "TraceContext | None" = None
    # Tiered-lifecycle bookkeeping: logical timestamp of the session's
    # last accepted activity (registration, accepted vote, fired timeout
    # — the idle clock the per-scope ``demote_after`` / GC TTLs measure
    # against), and the per-scope registration sequence number that keeps
    # LRU tie-order identical across demote/promote round-trips.
    last_activity: int = 0
    seq: int = 0

    @classmethod
    def fresh_pooled(
        cls, scope, slot: int, proposal, config, created_at: int
    ) -> "SessionRecord":
        """Fast constructor for a just-allocated pooled session (no spill
        substrate, empty collections). Batch registration creates one record
        per proposal, and the dataclass __init__'s keyword dispatch is ~2x
        the cost of direct slot stores at that volume."""
        rec = cls.__new__(cls)
        rec.scope = scope
        rec.slot = slot
        rec.proposal = proposal
        rec.config = config
        rec.created_at = created_at
        rec.votes = {}
        rec.session = None
        rec.retained_wire = []
        rec.retained_cache = None
        rec.arrival_seq = 0
        rec.scalar_seqs = []
        rec.wire_tail = None
        rec.wire_seen = None
        rec.wire_sync = None
        rec.wire_only = True
        rec.trace = None
        rec.last_activity = created_at
        rec.seq = 0
        return rec

    def next_arrival_seq(self) -> int:
        seq = self.arrival_seq
        self.arrival_seq += 1
        return seq

    def bump_round(self, accepted: int) -> None:
        """Host mirror of the device round update
        (reference: src/session.rs:351-366)."""
        if accepted <= 0:
            return
        if self.config.use_gossipsub_rounds:
            if self.proposal.round == 1:
                self.proposal.round = 2
        else:
            self.proposal.round = min(self.proposal.round + accepted, _U32_MAX)


class PendingVoteVerdicts:
    """Handle for an in-flight admission-verify prepass
    (:meth:`TpuConsensusEngine.verify_votes_async`): ``collect()`` blocks
    until the signature batch resolves and returns ``(verdicts,
    computed_hashes)`` aligned with the submitted votes. Idempotent —
    the first collect does the waiting. While uncollected, the crypto
    runs on the native verify pool with no GIL involvement, so the
    interpreter is free to drive device ingest of an earlier batch."""

    __slots__ = ("_collect_fn", "_result")

    def __init__(self, collect_fn):
        self._collect_fn = collect_fn
        self._result = None

    def collect(self) -> "tuple[list, list[bytes]]":
        if self._collect_fn is not None:
            self._result = self._collect_fn()
            self._collect_fn = None
        return self._result


class WireVotePrepass:
    """Handle for an in-flight wire-columnar validation prepass
    (:meth:`TpuConsensusEngine.wire_verify_begin`): ``pre_status`` holds
    the structural/hash verdicts already decided (0 = still live),
    ``crypto_rows`` the row indices whose signatures were submitted, and
    ``collect()`` blocks for their verdicts (idempotent). While
    uncollected, the crypto runs on the native verify pool with no GIL
    involvement — the bridge's reader thread starts the prepass for
    frame k+1 while frame k's apply runs on the serial lane.

    ``buf`` caches the frame's vote region as ``bytes`` when the prepass
    sliced it for crypto, so the apply stage (and a durable wrapper's
    WAL blob) reuse ONE copy instead of re-running ``tobytes()`` per
    stage — the prepass and apply always see the same ``data`` array."""

    __slots__ = ("pre_status", "crypto_rows", "buf", "_collect_fn", "_result")

    def __init__(self, pre_status, crypto_rows, collect_fn, buf=None):
        self.pre_status = pre_status
        self.crypto_rows = crypto_rows
        self.buf = buf
        self._collect_fn = collect_fn
        self._result = None

    def collect(self) -> list:
        if self._collect_fn is not None:
            self._result = self._collect_fn()
            self._collect_fn = None
        return self._result


class _TierEntry:
    """One demoted session: the exact PR-8 snapshot ITEM_SESSION payload
    bytes (:func:`hashgraph_tpu.sync.snapshot.encode_session_item` — the
    canonical serialized session, signed vote wire included, so promotion
    needs no re-signing and ``state_fingerprint`` hashes the same item
    bytes whether a session is live or demoted) plus the scalar metadata
    reads need WITHOUT decoding: lifecycle state for stats, created_at +
    seq for LRU ranking, expiry for the timeout sweep, last_activity for
    the GC TTL."""

    __slots__ = (
        "item",
        "state",  # snapshot state code: 0 active / 1 reached / 2 failed
        "result",  # meaningful iff state == 1
        "created_at",
        "seq",
        "expiry",
        "last_activity",
    )

    def __init__(self, item, state, result, created_at, seq, expiry, last_activity):
        self.item = item
        self.state = state
        self.result = result
        self.created_at = created_at
        self.seq = seq
        self.expiry = expiry
        self.last_activity = last_activity


# Sentinel: "compute the signature prepass inside ingest_votes" (the
# non-pipelined default) as opposed to an explicit None / prepass handle
# handed in by ingest_votes_pipelined.
_PREPASS_INLINE = object()


class TpuConsensusEngine(Generic[Scope]):
    """Batch consensus engine with the ConsensusService API surface.

    Capacity is fixed at construction (XLA static shapes): ``capacity``
    concurrent sessions across all scopes, ``voter_capacity`` voter lanes per
    proposal. Scalar and batch entry points share one code path: every
    mutation flows through :meth:`ingest_votes`.
    """

    def __init__(
        self,
        signer: ConsensusSignatureScheme,
        event_bus: ConsensusEventBus[Scope] | None = None,
        capacity: int | None = None,
        voter_capacity: int | None = None,
        max_sessions_per_scope: int = DEFAULT_MAX_SESSIONS_PER_SCOPE,
        pool: ProposalPool | None = None,
        verify_cache: "VerifiedVoteCache | None | str" = "default",
        health_monitor: "HealthMonitor | None" = None,
    ):
        self._signer = signer
        # Per-peer health accounting (scorecards, equivocation/fork
        # evidence, liveness watchdog — obs.health). Engines default to
        # the process-wide monitor so co-hosted peers accumulate one
        # fleet view; pass a private HealthMonitor for isolation. Gated
        # off during WAL replay (_health_live): replayed anomalies were
        # recorded before the crash and must not double-count.
        self.health: HealthMonitor = (
            health_monitor if health_monitor is not None else default_health_monitor
        )
        self._health_live = True
        # Memoized vote-admission verdicts (verify each unique vote once —
        # the redelivery/incremental-chain amortization, see verify_cache
        # module docstring). "default" builds a per-engine cache; pass a
        # shared instance to pool verdicts across engines (BridgeServer
        # does, one cache per server process), or None to disable —
        # disabled restores the pre-cache verification flow byte for byte.
        if isinstance(verify_cache, str) and verify_cache != "default":
            # Any other string (e.g. BridgeServer's "shared" sentinel, or
            # a typo) would be stored as the cache object and crash at the
            # first ingest — fail at the call site instead.
            raise ValueError(
                'verify_cache must be "default", a VerifiedVoteCache, or None'
            )
        self._verify_cache: VerifiedVoteCache | None = (
            VerifiedVoteCache() if verify_cache == "default" else verify_cache
        )
        # Scheme-identity namespace for admission keys: a shared cache
        # serving engines with different signature schemes must never
        # cross-serve verdicts (scheme A's True is not scheme B's).
        scheme = type(signer)
        self._verify_scheme_tag = hashlib_sha256_8(
            f"{scheme.__module__}.{scheme.__qualname__}".encode()
        )
        self._event_bus: ConsensusEventBus[Scope] = (
            event_bus if event_bus is not None else BroadcastEventBus()
        )
        # An injected pool (e.g. parallel.ShardedPool over a device mesh)
        # swaps the execution substrate without touching engine semantics.
        if pool is not None:
            if capacity is not None or voter_capacity is not None:
                raise ValueError(
                    "pass capacity/voter_capacity OR an explicit pool, not "
                    "both (the pool's own geometry wins)"
                )
            self._pool = pool
        else:
            self._pool = ProposalPool(
                capacity if capacity is not None else 4096,
                voter_capacity if voter_capacity is not None else 64,
            )
        self._max_sessions_per_scope = max_sessions_per_scope
        # Multi-host awareness: a pool exposing local_slots() shards the
        # slot axis across jax.distributed processes (parallel.MultiHostPool).
        # The engine then runs SPMD: control-plane calls (create/process
        # proposal, delete_scope, timeouts) replicated with IDENTICAL
        # arguments on every process, vote ingest process-local, and every
        # event emitted by exactly one owning process (see _owns_slot).
        self._multihost = hasattr(self._pool, "local_slots")
        if self._multihost:
            import jax

            # process_index is immutable for the process lifetime; cache it
            # off the event-gating paths.
            self._process_zero = jax.process_index() == 0
        else:
            self._process_zero = True
        self.tracer = default_tracer
        # Distributed-trace peer label: spans this engine records are
        # attributed to its signer identity, so one process hosting many
        # bridge peers still yields per-peer stitched timelines.
        self._trace_peer = "peer:" + signer.identity().hex()[:12]
        # Always-on metrics (process-wide registry). Instruments are
        # resolved once here so the per-batch hot paths pay attribute
        # loads, not registry dict probes.
        self.metrics = default_registry
        self._m_votes_total = self.metrics.counter(VOTES_TOTAL)
        self._m_votes_accepted = self.metrics.counter(VOTES_ACCEPTED_TOTAL)
        self._m_decisions = self.metrics.counter(DECISIONS_TOTAL)
        self._m_proposals = self.metrics.counter(PROPOSALS_CREATED_TOTAL)
        self._m_timeouts = self.metrics.counter(TIMEOUTS_FIRED_TOTAL)
        self._m_batch_size = self.metrics.histogram(
            INGEST_BATCH_SIZE, DEFAULT_SIZE_BUCKETS
        )
        self._m_verify = self.metrics.histogram(VERIFY_BATCH_SECONDS)
        # Signatures actually handed to the scheme (cache hits excluded):
        # the base family plus a per-scheme labelled variant, so a mixed
        # fleet's dashboards can split Ed25519 batch traffic from ECDSA.
        self._m_verified_sigs = self.metrics.counter(VERIFIED_SIGNATURES_TOTAL)
        self._m_verified_sigs_scheme = self.metrics.counter(
            f'{VERIFIED_SIGNATURES_TOTAL}{{scheme="{_escape_label(scheme.__name__)}"}}'
        )
        # Dispatch amortization (the apply reactor's measured claim):
        # every ingest_wire_columnar call is one fused device dispatch;
        # rows ride along so votes_per_dispatch = rows / dispatches.
        self._m_wire_dispatches = self.metrics.counter(
            WIRE_DEVICE_DISPATCHES_TOTAL
        )
        self._m_wire_apply_rows = self.metrics.counter(WIRE_APPLY_ROWS_TOTAL)
        self._m_chain = self.metrics.histogram(CHAIN_KERNEL_SECONDS)
        self._m_device = self.metrics.histogram(DEVICE_INGEST_SECONDS)
        self._m_suffix_len = self.metrics.histogram(
            CHAIN_SUFFIX_LENGTH, DEFAULT_SIZE_BUCKETS
        )
        # Per-proposal lifecycle timelines (created → first_vote → decided /
        # timed_out), feeding the decision-latency histogram.
        self._timelines = TimelineStore(
            self.metrics.histogram(DECISION_LATENCY)
        )
        # SLO plane: every observed decision latency also lands in the
        # process SLO engine's sliding windows, carrying the scope's
        # declared objective (ScopeConfig.decide_p99_ms) and the bound
        # trace id so a breach's incident dump can link the causal trace.
        # The shard label is stamped by the fleet router at shard build
        # time; a standalone engine reports unlabelled.
        self._slo_shard: str | None = None
        self._timelines.slo_sink = self._slo_observe
        # Adaptive consensus-timeout learner (engine/adaptive.py):
        # advisory per-scope values the embedder polls via
        # adaptive_timeout(). Learning shares the _health_live gate with
        # the watchdog — WAL replay re-fires nothing and must not teach.
        self._adaptive = AdaptiveTimeoutBook()
        # Engine-state gauges sampled at scrape time, weakly bound: a
        # collected engine's contribution vanishes instead of freezing.
        ref = weakref.ref(self)

        def _live_proposals() -> int:
            engine = ref()
            return len(engine._records) if engine is not None else 0

        def _pool_occupancy() -> int:
            engine = ref()
            if engine is None:
                return 0
            # Claimed device slots (host-spilled sessions use negative
            # synthetic ids and hold no pool row). list() snapshots the
            # keys in one atomic C call — the scrape thread runs without
            # the engine lock, and iterating the live dict would race
            # with a concurrent insert/evict resize.
            return sum(1 for s in list(engine._records) if s >= 0)

        self.metrics.register_gauge(LIVE_PROPOSALS, _live_proposals, owner=self)
        self.metrics.register_gauge(
            VOTE_TABLE_OCCUPANCY, _pool_occupancy, owner=self
        )

        def _tier_sessions() -> int:
            engine = ref()
            return engine._tier_count if engine is not None else 0

        def _tier_bytes() -> int:
            engine = ref()
            return engine._tier_bytes if engine is not None else 0

        self.metrics.register_gauge(
            TIER_DEMOTED_SESSIONS, _tier_sessions, owner=self
        )
        self.metrics.register_gauge(TIER_BYTES, _tier_bytes, owner=self)
        self._m_tier_demotions = self.metrics.counter(TIER_DEMOTIONS_TOTAL)
        self._m_tier_promotions = self.metrics.counter(TIER_PROMOTIONS_TOTAL)
        self._m_tier_gc = self.metrics.counter(TIER_GC_TOTAL)
        # Device/XLA telemetry (live-buffer gauge provider is global;
        # this routes the persistent-compile-cache monitoring events onto
        # the registry). Idempotent, and this module already imports JAX
        # through the pool, so obs itself stays jax-free.
        install_jax_telemetry()
        # One engine-wide reentrant lock: the reference service is fully
        # thread-safe (whole-map RwLocks, src/storage.rs:192-193); the pool's
        # host mirrors and free lists need the same discipline. Coarse
        # locking is correct here — the device does the heavy lifting and
        # host sections are short.
        self._lock = threading.RLock()

        self._records: dict[int, SessionRecord[Scope]] = {}  # slot -> record
        self._index: dict[tuple[Scope, int], int] = {}  # (scope, pid) -> slot
        self._scopes: dict[Scope, list[int]] = {}  # scope -> slots (insertion order)
        self._scope_configs: dict[Scope, ScopeConfig] = {}
        self._next_host_slot = -1  # synthetic ids for host-spilled sessions
        # Columnar-path cache: per-scope sorted (pids, slots) arrays for
        # vectorized proposal-id resolution; dropped on any membership change.
        self._pid_tables: dict[Scope, tuple[np.ndarray, np.ndarray]] = {}
        self._pid_hashes: dict[Scope, _PidLookup] = {}
        # Fused multi-scope resolution cache: one composite-key hash per
        # distinct scope tuple of an ingest_columnar_multi call (small
        # bounded dict, so alternating scope orders don't thrash a single
        # slot). ANY scope's membership change clears the whole cache
        # outright (_drop_pid_cache) — cheaper than tracking which scopes
        # each tuple spans, and rebuilds are one vectorized pass.
        self._fused_pid_cache: dict[tuple, "_PidLookup"] = {}
        # ── Demoted session tier (storage tiering, ROADMAP item 5) ─────
        # scope -> {pid -> _TierEntry}: sessions moved out of their device
        # slot / host record into the compact serialized tier (the PR-8
        # snapshot item format). Insertion order per scope = demotion
        # order. Demoted sessions stay fully addressable — every public
        # read/mutation either pages them back in (_promote_key) or reads
        # through the tier without promoting (stats, enumerations,
        # save_to_storage), so callers observe an untier'd engine.
        self._tier: dict[Scope, dict[int, _TierEntry]] = {}
        self._tier_count = 0
        self._tier_bytes = 0
        # ACTIVE demoted sessions only, (scope, pid) -> expiry: the
        # timeout sweep must page an expired idle session back in to fire
        # its timeout; keeping this tiny side map means the sweep never
        # scans the (potentially huge) decided-session tier.
        self._tier_active: dict[tuple[Scope, int], int] = {}
        # Per-scope demoted-pid arrays for batch id draws (invalidated on
        # tier membership change; rebuilt lazily by _taken_pids).
        self._tier_pid_arrays: dict[Scope, np.ndarray] = {}
        # Scopes excluded from the lifecycle sweep's demote/GC policies
        # (fleet/federation pin a scope while migrating its shard so the
        # routers never page state mid-flip).
        self._pinned_scopes: set[Scope] = set()
        # Per-scope registration sequence (LRU tie order across tiers).
        self._scope_seq: dict[Scope, int] = {}
        # Reentrancy flag: promotion re-registers a session through
        # _register, which must not count it as a fresh proposal.
        self._promoting = False
        # Lifecycle gate (set_replay_mode): False during WAL replay.
        self._lifecycle_live = True
        # Engine-local tier traffic counts (occupancy() is per-engine;
        # the hashgraph_tier_* counters are process-wide).
        self._tier_demotions = 0
        self._tier_promotions = 0
        self._tier_gc = 0

    # ── Accessors ──────────────────────────────────────────────────────

    def signer(self) -> ConsensusSignatureScheme:
        return self._signer

    def set_replay_mode(self, on: bool) -> None:
        """Metrics gate for WAL recovery (DurableEngine.recover): replayed
        traffic drives the live ingest paths, but the decisions it
        re-applies were made before the crash — with replay mode on,
        timelines stamp them ``pre_decided`` (outcome without latency) and
        the decisions/timeouts counters hold still, so a restart doesn't
        collapse the decision-latency quantiles or re-count pre-crash
        decisions. Vote/proposal counters keep counting: they measure work
        this process performed, and replay IS work."""
        self._timelines.replay_mode = on
        # Health accounting pauses with replay for the same reason:
        # replayed equivocations/forks were evidenced before the crash;
        # re-recording them would double-count scorecards (evidence
        # itself dedups, but counters do not).
        self._health_live = not on
        # The tier lifecycle pauses too: TTL decisions depend on idle
        # clocks a snapshot restore does not carry, so replay must not
        # re-derive them — the live run's GC outcome arrives as explicit
        # KIND_GC records (applied via gc_sessions), and demotion is
        # pure cache management recovery legitimately skips.
        self._lifecycle_live = not on
        if on:
            # Throwaway instruments: the ingest paths inc attributes
            # unconditionally, so swapping the targets is cheaper (and
            # less invasive) than flag checks on every site.
            self._m_decisions = Counter("replay.decisions.discard")
            self._m_timeouts = Counter("replay.timeouts.discard")
        else:
            self._m_decisions = self.metrics.counter(DECISIONS_TOTAL)
            self._m_timeouts = self.metrics.counter(TIMEOUTS_FIRED_TOTAL)

    def event_bus(self) -> ConsensusEventBus[Scope]:
        return self._event_bus

    def pool(self) -> ProposalPool:
        return self._pool

    def verify_cache(self) -> VerifiedVoteCache | None:
        """The memoized-admission cache (None when disabled)."""
        return self._verify_cache

    @property
    def _scheme(self) -> type[ConsensusSignatureScheme]:
        return type(self._signer)

    # ── Proposal lifecycle ─────────────────────────────────────────────

    def create_proposal(
        self,
        scope: Scope,
        request: CreateProposalRequest,
        now: int,
        config: ConsensusConfig | None = None,
    ) -> Proposal:
        """Create a local proposal and claim a pool slot
        (reference: src/service.rs:183-209)."""
        wall0 = time.time()
        proposal = request.into_proposal(now)
        self._ensure_unique_pid(scope, proposal)
        # Same gauntlet the scalar service runs via from_proposal ->
        # validate_proposal (trivial for a fresh, vote-free proposal but
        # keeps the error surface identical, reference: src/utils.rs:106-120).
        validate_proposal_timestamp(proposal.expiration_timestamp, now)
        resolved = self._resolve_config(scope, config, proposal)
        record = self._register(scope, proposal, resolved, now)
        if trace_store.enabled:
            self._bind_trace(
                record, "consensus.create_proposal", scope, wall0
            )
        return proposal.clone()

    def _bind_trace(
        self, record: "SessionRecord[Scope]", span_name: str, scope, wall0: float
    ) -> None:
        """Mint (or continue) the distributed trace for a freshly
        registered session: the ambient context — set by the bridge from a
        frame suffix, or by an embedder around a gossip delivery — is the
        causal parent; with none this engine is the trace root. The bound
        context's span is recorded so every peer contributes at least one
        span per proposal to the stitched timeline."""
        parent = current_context()
        ctx = parent.child() if parent is not None else TraceContext.generate()
        record.trace = ctx
        tl = self._timelines.get(record.slot)
        if tl is not None and tl.proposal_id == record.proposal.proposal_id:
            tl.trace_hex = ctx.trace_id.hex()
        trace_store.record(
            span_name,
            ctx,
            wall0,
            time.time() - wall0,
            parent=parent.span_id if parent is not None else None,
            peer=self._trace_peer,
            attrs={
                "scope": str(scope),
                "proposal_id": record.proposal.proposal_id,
            },
        )

    def _slo_observe(self, tl, latency: float) -> None:
        """TimelineStore slo_sink: one call per observed decision (same
        gating as the latency histogram). Resolves the scope's declared
        objective and forwards to the process SLO engine — a single
        short-lock windowed-sketch update, cheap enough to stay always-on
        (the <5% bound is held by bench.py's slo-overhead A/B)."""
        cfg = self._scope_configs.get(tl.scope)
        objective = None
        if cfg is not None and cfg.decide_p99_ms is not None:
            objective = cfg.decide_p99_ms * 1e-3
        slo_engine.observe(
            tl.scope,
            latency,
            shard=self._slo_shard,
            objective_s=objective,
            trace_hex=tl.trace_hex,
        )
        # Vote-driven decisions (never timeout outcomes — those feed the
        # backoff side) decay the scope's learned timeout toward the SLO
        # engine's observed tail.
        if (
            self._health_live
            and not tl.by_timeout
            and cfg is not None
            and cfg.adaptive_timeout_enabled()
        ):
            self._adaptive.on_decided(
                tl.scope, cfg, slo_engine.observed_p99(tl.scope)
            )

    def _ensure_unique_pid(
        self, scope: Scope, proposal: Proposal, taken: set[int] | None = None
    ) -> None:
        """Collision-proof a locally-generated proposal id against live
        sessions in this scope and (for batch creation) earlier proposals in
        the same batch. Policy and rationale: protocol.regenerate_until_unique.

        Multi-host: uuid-random ids would differ per process and silently
        de-sync the replicated control plane, so the id is derived
        deterministically from the proposal's content plus the (replicated)
        per-scope population — identical create_proposal calls then mint the
        identical pid on every process.
        """
        if self._multihost:
            import hashlib

            taken_set = taken or set()
            seq = len(self._scopes.get(scope, []))
            salt = 0
            while True:
                digest = hashlib.sha256(
                    b"|".join(
                        [
                            _canonical_scope_bytes(scope),
                            proposal.name.encode(),
                            proposal.payload,
                            proposal.proposal_owner,
                            str(
                                (
                                    proposal.expected_voters_count,
                                    proposal.timestamp,
                                    seq,
                                    salt,
                                )
                            ).encode(),
                        ]
                    )
                ).digest()
                pid = int.from_bytes(digest[:4], "little") ^ int.from_bytes(
                    digest[4:8], "little"
                )
                if (
                    pid
                    and (scope, pid) not in self._index
                    and not self._tier_has(scope, pid)
                    and pid not in taken_set
                ):
                    proposal.proposal_id = pid
                    return
                salt += 1
                self.tracer.count("engine.pid_collisions")
        collisions = regenerate_until_unique(
            proposal,
            lambda pid: (scope, pid) in self._index
            or self._tier_has(scope, pid)
            or (taken is not None and pid in taken),
        )
        if collisions:
            self.tracer.count("engine.pid_collisions", collisions)

    def _draw_unique_pids(
        self, existing: np.ndarray, count: int
    ) -> np.ndarray:
        """Batch id draw: one urandom read, vectorized collision rejection
        against ``existing`` live pids and within the batch itself. Multi-
        scope creation passes the union of all target scopes' pids and
        slices one draw per scope — global uniqueness is stronger than the
        per-scope requirement and costs one pass instead of one per scope."""
        import os as _os

        ids = np.frombuffer(_os.urandom(4 * count), dtype=np.uint32).astype(
            np.int64
        )
        for _ in range(64):
            # 0 is treated as a collision: the multi-host deterministic path
            # rejects it and proto3 drops zero fields from the wire encoding
            # — both creation paths must mint from the same id space.
            bad = np.isin(ids, existing) | (ids == 0)
            _, first_idx, inverse, counts = np.unique(
                ids, return_index=True, return_inverse=True, return_counts=True
            )
            is_first = np.zeros(count, bool)
            is_first[first_idx] = True
            bad |= (counts[inverse] > 1) & ~is_first
            n_bad = int(bad.sum())
            if n_bad == 0:
                return ids
            self.tracer.count("engine.pid_collisions", n_bad)
            ids[bad] = np.frombuffer(
                _os.urandom(4 * n_bad), dtype=np.uint32
            ).astype(np.int64)
        raise RuntimeError("could not draw unique proposal ids")  # pragma: no cover

    def create_proposals(
        self,
        scope: Scope,
        requests: list[CreateProposalRequest],
        now: int,
        config: ConsensusConfig | None = None,
    ) -> list[Proposal]:
        """Batch counterpart of create_proposal: one device dispatch claims
        and configures every slot (pool.allocate_batch), instead of one
        dispatch per proposal. No reference analogue (its creation path is a
        scalar call, src/service.rs:183-209) — this is the TPU-native bulk
        feed for large concurrent-proposal populations (BASELINE configs
        3-5). Success semantics match calling create_proposal in a loop;
        the error path is batch-atomic (any invalid request raises before
        anything registers, unlike the loop which keeps earlier items).
        """
        return self.create_proposals_multi([(scope, requests)], now, config)[0]

    def create_proposals_multi(
        self,
        items: "list[tuple[Scope, list[CreateProposalRequest]]]",
        now: int,
        config: ConsensusConfig | None = None,
    ) -> "list[list[Proposal]]":
        """Multi-scope batch creation (mirror of :meth:`ingest_columnar_multi`):
        ONE device dispatch claims slots for every scope's proposals instead
        of one dispatch per scope — the registration half of the config-5
        churn shape. Returns one Proposal list per input item, in order.
        Scopes must be distinct within one call (id uniqueness is checked
        against registered sessions, which a same-call sibling batch is
        not yet). A scope near its session cap falls back to the scalar
        path for that scope only (reference eviction semantics interleave
        with insertion there); fallback scopes run AFTER the batched
        allocation, so device-slot priority deterministically favors the
        batched population when the pool is nearly full."""
        seen: set = set()
        for scope, _ in items:
            if scope in seen:
                raise ValueError("create_proposals_multi: duplicate scope")
            seen.add(scope)
        out: list = [None] * len(items)
        entries: list = []
        spans: list = []
        fallbacks: list = []
        cols = _CreationCols()
        batched: list[int] = []
        for idx, (scope, requests) in enumerate(items):
            # Demoted sessions still count against the per-scope cap (the
            # reference trims on TOTAL population; a tier'd engine must
            # evict at the same points an untier'd one would).
            existing = len(self._scopes.get(scope, [])) + len(
                self._tier.get(scope, ())
            )
            if existing + len(requests) > self._max_sessions_per_scope:
                fallbacks.append(idx)
            else:
                batched.append(idx)
        # Single-host: ONE id draw for the whole call, collision-checked
        # against the union of every batched scope's live pids, sliced per
        # scope below (a per-scope draw pays the fixed numpy overhead
        # len(items) times).
        pre_ids: dict[int, np.ndarray] = {}
        if not self._multihost and batched:
            total = sum(len(items[i][1]) for i in batched)
            if total:
                parts = [self._taken_pids(items[i][0]) for i in batched]
                all_ids = self._draw_unique_pids(np.concatenate(parts), total)
                off = 0
                for i in batched:
                    k = len(items[i][1])
                    pre_ids[i] = all_ids[off : off + k]
                    off += k
        done = 0
        for idx, (scope, requests) in enumerate(items):
            if done < len(batched) and batched[done] == idx:
                done += 1
                proposals, configs = self._prepare_creation(
                    scope, requests, now, config, cols, pre_ids.get(idx)
                )
                spans.append((len(entries), len(proposals)))
                entries.extend(
                    (scope, p, c) for p, c in zip(proposals, configs)
                )
            else:
                spans.append(None)
        created = self._allocate_and_register(entries, now, cols)
        for idx, span in enumerate(spans):
            if span is not None:
                start, count = span
                out[idx] = created[start : start + count]
        for idx in fallbacks:
            scope, requests = items[idx]
            out[idx] = [
                self.create_proposal(scope, r, now, config) for r in requests
            ]
        return out

    def _prepare_creation(
        self,
        scope: Scope,
        requests: list[CreateProposalRequest],
        now: int,
        config: ConsensusConfig | None,
        cols: "_CreationCols",
        pre_ids: np.ndarray | None = None,
    ) -> tuple[list[Proposal], list[ConsensusConfig]]:
        """Python-side prep shared by the batch creators: mint proposals
        with batch-drawn ids (single-host) or deterministic ids (multi-host)
        and resolve configs with per-batch memoization. Per-proposal scalars
        the allocator needs (n, expiry, config fields) accumulate into
        ``cols`` during this loop — np.asarray over plain int lists later is
        several times cheaper than re-walking the objects with fromiter."""
        proposals: list[Proposal] = []
        configs: list[ConsensusConfig] = []
        # Single-host fast path: draw the whole batch's proposal ids in one
        # urandom read with vectorized collision checks (same id space and
        # uniqueness policy as generate_id/regenerate_until_unique, minus
        # the per-proposal uuid4 cost). Multi-host keeps the deterministic
        # per-proposal derivation (_ensure_unique_pid).
        if pre_ids is not None:
            batch_ids = pre_ids
        elif self._multihost:
            batch_ids = None
        else:
            batch_ids = self._draw_unique_pids(
                self._taken_pids(scope), len(requests)
            )
        # Config resolution is identical for requests sharing (expiration,
        # liveness) when no per-proposal override exists — memoize per batch.
        cfg_cache: dict = {}
        add_p = proposals.append
        add_c = configs.append
        c_n = cols.n.append
        c_exp = cols.expiry.append
        c_live = cols.liveness.append
        c_thr = cols.thr.append
        c_gos = cols.gossip.append
        c_maxr = cols.maxr.append
        batch_pids: set[int] | None = None if batch_ids is not None else set()
        pid_iter = (
            batch_ids.tolist() if batch_ids is not None else [None] * len(requests)
        )
        for request, pid in zip(requests, pid_iter):
            proposal = request.into_proposal(now, pid=pid)
            if batch_pids is not None:
                self._ensure_unique_pid(scope, proposal, taken=batch_pids)
                batch_pids.add(proposal.proposal_id)
            validate_proposal_timestamp(proposal.expiration_timestamp, now)
            add_p(proposal)
            key = (
                proposal.expiration_timestamp,
                proposal.liveness_criteria_yes,
            )
            entry = cfg_cache.get(key)
            if entry is None:
                resolved = self._resolve_config(scope, config, proposal)
                entry = (
                    resolved,
                    resolved.consensus_threshold,
                    resolved.use_gossipsub_rounds,
                    resolved.max_rounds,
                )
                cfg_cache[key] = entry
            add_c(entry[0])
            c_n(request.expected_voters_count)
            c_exp(proposal.expiration_timestamp)
            c_live(proposal.liveness_criteria_yes)
            c_thr(entry[1])
            c_gos(entry[2])
            c_maxr(entry[3])
        return proposals, configs

    def _allocate_and_register(
        self,
        entries: "list[tuple[Scope, Proposal, ConsensusConfig]]",
        now: int,
        cols: "_CreationCols",
    ) -> list[Proposal]:
        """One pool.allocate_batch for every (scope, proposal, config) entry
        (first-fit against the free budget; the rest host-spill), then host
        registration. Returns clones in entry order. ``cols`` carries the
        per-entry scalars collected during _prepare_creation, so the device
        config arrays build from plain int lists instead of re-walking the
        proposal/config objects."""
        from ..ops.decide import required_votes_np

        free = self._pool.free_slots
        n_all = np.asarray(cols.n, np.int64)
        # First-fit against the free budget, vectorized: rows small enough
        # for the lane grid claim slots in entry order until the budget is
        # spent (identical to the old per-entry scan).
        ok = n_all <= self._pool.voter_capacity
        fit_mask = ok & (np.cumsum(ok) <= free)
        fit_idx = np.nonzero(fit_mask)[0]
        all_fit = len(fit_idx) == len(entries)
        slots_by_item: dict[int, int] = {}
        slots: list[int] = []
        if len(fit_idx):
            count = len(fit_idx)
            if all_fit:
                n_arr = n_all
                thr_arr = np.asarray(cols.thr, np.float64)
                gossip_arr = np.asarray(cols.gossip, bool)
                maxr_arr = np.asarray(cols.maxr, np.int64)
                expiry_arr = np.asarray(cols.expiry, np.int64)
                liveness_arr = np.asarray(cols.liveness, bool)
                keys = [(s, p.proposal_id) for s, p, _ in entries]
            else:
                n_arr = n_all[fit_idx]
                thr_arr = np.asarray(cols.thr, np.float64)[fit_idx]
                gossip_arr = np.asarray(cols.gossip, bool)[fit_idx]
                maxr_arr = np.asarray(cols.maxr, np.int64)[fit_idx]
                expiry_arr = np.asarray(cols.expiry, np.int64)[fit_idx]
                liveness_arr = np.asarray(cols.liveness, bool)[fit_idx]
                keys = [
                    (entries[i][0], entries[i][1].proposal_id)
                    for i in fit_idx.tolist()
                ]
            req_arr = required_votes_np(n_arr, thr_arr)
            # max_round_limit semantics (reference: src/session.rs:120-128):
            # gossipsub -> max_rounds; P2P -> explicit override, else the
            # dynamic ceil(n*t) cap — which shares calculate_threshold_based_
            # value with required votes (src/utils.rs:292-304), so req_arr
            # doubles as the dynamic cap.
            cap_arr = np.where(
                gossip_arr,
                maxr_arr,
                np.where(maxr_arr == 0, req_arr, maxr_arr),
            )
            slots = self._pool.allocate_batch(
                keys=keys,
                n=n_arr,
                req=req_arr,
                cap=cap_arr,
                gossip=gossip_arr,
                liveness=liveness_arr,
                expiry=expiry_arr,
                created_at=np.full(count, now, np.int64),
            )
            if not all_fit:
                slots_by_item = dict(zip(fit_idx.tolist(), slots))

        # Entries arrive grouped by scope (one span per input item), so the
        # scope-keyed bookkeeping caches the current scope's slot list
        # instead of paying a setdefault + membership per proposal. The
        # all-fit case (the churn steady state) also skips the per-item
        # dict probe: fit_idx is then simply 0..len(entries).
        records = self._records
        index = self._index
        timelines = self._timelines
        wall = time.monotonic()
        touched: set = set()
        cur_scope: object = object()  # sentinel unequal to any real scope
        cur_list: list = []
        fresh = SessionRecord.fresh_pooled
        for i, (scope, proposal, cfg) in enumerate(entries):
            slot = slots[i] if all_fit else slots_by_item.get(i)
            if slot is None:  # host spill (oversized n or pool exhausted)
                host_session = ConsensusSession._new(proposal, cfg, now)
                slot = self._next_host_slot
                self._next_host_slot -= 1
                record = fresh(scope, slot, proposal, cfg, now)
                record.session = host_session
                record.votes = host_session.votes
                self.tracer.count("engine.host_spills")
            else:
                record = fresh(scope, slot, proposal, cfg, now)
            records[slot] = record
            index[(scope, proposal.proposal_id)] = slot
            timelines.created(slot, scope, proposal.proposal_id, now, wall)
            if scope is not cur_scope:
                cur_scope = scope
                cur_list = self._scopes.setdefault(scope, [])
                touched.add(scope)
            cur_list.append(slot)
        for scope in touched:
            self._drop_pid_cache(scope)
        if entries:
            self._m_proposals.inc(len(entries))
            flight_recorder.record("engine.create", proposals=len(entries))
        return [p.clone() for _, p, _ in entries]

    def process_incoming_proposal(
        self,
        scope: Scope,
        proposal: Proposal,
        now: int,
        config: ConsensusConfig | None = None,
    ) -> None:
        """Validate a network proposal (signatures, chain, expiry — the full
        scalar gauntlet, reference: src/session.rs:198-221) and load the
        replayed session into the pool as a dense row (resume-from-snapshot).
        ``config`` optionally overrides the scope-config resolution with the
        same precedence create_proposal gives its explicit override — WAL
        replay uses this to preserve a logged override across recovery.
        """
        if (scope, proposal.proposal_id) in self._index or self._tier_has(
            scope, proposal.proposal_id
        ):
            # Demoted sessions exist; the no-redelivery contract rejects
            # without paging them in.
            raise ProposalAlreadyExist()
        wall0 = time.time()
        config = self._resolve_config(scope, config, proposal)
        # Fail-fast BEFORE the signature prepass, preserving the scalar
        # path's zero-crypto rejection of expired gossip (validate_proposal
        # re-runs the same check first, so error precedence is unchanged —
        # an attacker redelivering expired chains must not be able to buy
        # ECDSA work or churn the shared cache's LRU).
        try:
            validate_proposal_timestamp(proposal.expiration_timestamp, now)
        except ConsensusError:
            self._note_expired_proposal(proposal, now)
            raise
        # Admission cache for the embedded chain: verdicts for known votes
        # come from the cache, the rest from one batched verify (None
        # disables the prepass entirely — from_proposal then verifies each
        # vote inline, the original scalar flow).
        sv = ch = None
        if proposal.votes and self._verify_cache is not None:
            sv, ch = self._cached_verify(proposal.votes)
        # The scalar oracle replays embedded votes with exact reference
        # semantics (chain validation, per-vote ECDSA, round caps); the dense
        # row is loaded from its final state.
        session, transition = ConsensusSession.from_proposal(
            proposal.clone(),
            self._scheme,
            config,
            now,
            sig_verdicts=sv,
            computed_hashes=ch,
        )
        # Event before save, as in the reference (src/service.rs:275-277).
        if transition.is_reached and self._owns_replicated_event():
            self._emit(
                scope,
                ConsensusReached(
                    proposal_id=proposal.proposal_id,
                    result=transition.reached,
                    timestamp=now,
                ),
            )
        self._register_session(scope, session, now)
        self._note_chain_admitted(proposal.votes, config, now)
        if trace_store.enabled:
            slot = self._index.get((scope, proposal.proposal_id))
            if slot is not None:
                # Continues the trace the proposal travelled with (ambient
                # context from the bridge frame / gossip envelope); roots a
                # fresh one for untraced senders.
                self._bind_trace(
                    self._records[slot],
                    "consensus.process_proposal",
                    scope,
                    wall0,
                )

    def _note_chain_admitted(
        self, votes: "list[Vote]", config: ConsensusConfig, now: int
    ) -> None:
        """Scorecard admissions for an embedded chain accepted whole
        (process_incoming_proposal / ingest_proposals): one dict pass per
        chain, one monitor call — O(L) dict stores against the O(L)
        SHA/ECDSA the chain already cost."""
        if not self._health_live or not votes:
            return
        counts: dict[bytes, int] = {}
        for vote in votes:
            counts[vote.vote_owner] = counts.get(vote.vote_owner, 0) + 1
        self.health.note_admitted(
            counts, now, timeout_hint=config.consensus_timeout
        )

    def _note_expired_proposal(self, proposal: Proposal, now: int) -> None:
        """Expired-gossip scorecard hit for a whole stale proposal,
        attributed to the chain's most recent signer (falling back to the
        proposal owner for vote-free proposals)."""
        if not self._health_live:
            return
        source = (
            proposal.votes[-1].vote_owner
            if proposal.votes
            else proposal.proposal_owner
        )
        if source:
            self.health.note_expired(source, now)

    def ingest_proposals(
        self,
        items: list[tuple[Scope, Proposal]],
        now: int,
        configs: "list[ConsensusConfig | None] | None" = None,
    ) -> list[int]:
        """Batch counterpart of process_incoming_proposal: validate and load
        many (possibly vote-carrying) proposals in bulk.

        The expensive per-vote work is batched — ALL embedded signatures go
        through one scheme.verify_batch call (native threaded path) and ALL
        chains with >1 votes through one vmapped device chain kernel — then
        each proposal replays the exact scalar check sequence with the
        precomputed verdicts injected, so error precedence is identical to
        the scalar path. Returns one StatusCode per item (OK = registered;
        events emitted exactly as the scalar path would). ``configs``
        optionally supplies a per-item explicit config override (same
        precedence as create_proposal's; None entries resolve from the
        scope config) — WAL replay uses it to preserve logged overrides.
        """
        from ..ops.chain import chain_kernel_batch, first_chain_error, pack_chain

        if configs is not None and len(configs) != len(items):
            raise ValueError("configs must supply one entry per item")
        statuses = [int(StatusCode.OK)] * len(items)

        # Items that cannot pass — already registered at entry, or already
        # expired — are excluded from the verification prepass and the
        # chain kernel: under gossip redelivery the same vote-carrying
        # proposal arrives over and over, and re-verifying a chain that is
        # about to be dropped anyway was the per-delivery O(chain)
        # redelivery tax (expired chains are the same attack surface —
        # buying ECDSA work and churning the shared cache's LRU with a
        # stale proposal must not be possible on ANY entry point). Their
        # statuses come from the final loop's inline gauntlet, which
        # raises ProposalExpired / reports PROPOSAL_ALREADY_EXIST before
        # any signature work — exact scalar error precedence preserved.
        skip = [
            (scope, proposal.proposal_id) in self._index
            or self._tier_has(scope, proposal.proposal_id)
            or now >= proposal.expiration_timestamp
            for scope, proposal in items
        ]

        # Bulk signature verification across every embedded vote of the
        # surviving items, through the admission cache: identical votes
        # appearing across many chains collapse to one verify item, known
        # votes to none (plain one-shot verify_batch when the cache is
        # disabled — see _cached_verify).
        flat_votes: list[Vote] = []
        spans: list[tuple[int, int] | None] = []  # (start, count) per item
        for i, (scope, proposal) in enumerate(items):
            if skip[i]:
                spans.append(None)
                continue
            start = len(flat_votes)
            flat_votes.extend(proposal.votes)
            spans.append((start, len(proposal.votes)))
        # Crypto/device pipelining: the signature batch is SUBMITTED to
        # the verify pool here, the chain kernel below dispatches to the
        # device while the pool verifies, and the verdicts are collected
        # only when both are needed — host ECDSA/Ed25519 and device chain
        # validation for the same call overlap instead of serializing.
        pending_verify = (
            self._cached_verify_begin(flat_votes) if flat_votes else None
        )

        # Bulk chain validation on device (only chains that need it).
        chain_errors: dict[int, ConsensusError | None] = {}
        chain_idx = [
            i
            for i, (_, p) in enumerate(items)
            if not skip[i] and len(p.votes) > 1
        ]
        if chain_idx:
            pad = max(len(items[i][1].votes) for i in chain_idx)
            packs = [pack_chain(items[i][1].votes, pad_to=pad) for i in chain_idx]
            batchpack = {
                key: np.stack([p[key] for p in packs]) for key in packs[0]
            }
            with observed_span(
                self.tracer,
                "engine.chain_kernel",
                self._m_chain,
                chains=len(chain_idx),
            ):
                chain_statuses = np.asarray(
                    chain_kernel_batch(
                        batchpack["vote_hash"],
                        batchpack["received_hash"],
                        batchpack["parent_hash"],
                        batchpack["owner"],
                        batchpack["ts"],
                        batchpack["valid"],
                    )
                )
            for j, i in enumerate(chain_idx):
                code = first_chain_error(chain_statuses[j])
                exc_cls = error_for_code(code) if code else None
                chain_errors[i] = exc_cls() if exc_cls is not None else None

        verdicts: list = []
        vote_hashes: list = []
        if pending_verify is not None:
            verdicts, vote_hashes = pending_verify.collect()

        for i, (scope, proposal) in enumerate(items):
            if (scope, proposal.proposal_id) in self._index or self._tier_has(
                scope, proposal.proposal_id
            ):
                # Demoted sessions exist: this path's strict
                # no-redelivery contract rejects without paging them in.
                statuses[i] = int(StatusCode.PROPOSAL_ALREADY_EXIST)
                continue
            if spans[i] is None:
                # Nothing precomputed for this item: expired at entry
                # (the inline gauntlet below raises ProposalExpired
                # before any signature work), or registered at entry but
                # freed mid-batch by an earlier item's per-scope-cap
                # eviction — either way, run the full scalar gauntlet, as
                # a sequential process_incoming_proposal would.
                sv = ch = None
                chain_error = COMPUTE_CHAIN
            else:
                start, count = spans[i]
                sv = verdicts[start : start + count] if count else None
                ch = vote_hashes[start : start + count] if count else None
                chain_error = chain_errors.get(i)
            try:
                config = self._resolve_config(
                    scope, configs[i] if configs is not None else None, proposal
                )
                session, transition = ConsensusSession.from_proposal(
                    proposal.clone(),
                    self._scheme,
                    config,
                    now,
                    sig_verdicts=sv,
                    chain_error=chain_error,
                    computed_hashes=ch,
                )
                if transition.is_reached and self._owns_replicated_event():
                    self._emit(
                        scope,
                        ConsensusReached(
                            proposal_id=proposal.proposal_id,
                            result=transition.reached,
                            timestamp=now,
                        ),
                    )
                self._register_session(scope, session, now)
                self._note_chain_admitted(proposal.votes, config, now)
            except ConsensusError as exc:
                statuses[i] = int(exc.code)
                if exc.code == StatusCode.PROPOSAL_EXPIRED:
                    self._note_expired_proposal(proposal, now)
        return statuses

    # ── Gossip delivery: create-or-extend (chain-prefix watermark) ─────

    def deliver_proposal(
        self,
        scope: Scope,
        proposal: Proposal,
        now: int,
        config: ConsensusConfig | None = None,
    ) -> int:
        """Scalar :meth:`deliver_proposals` (one StatusCode int)."""
        return self.deliver_proposals(
            [(scope, proposal)], now,
            configs=[config] if config is not None else None,
        )[0]

    def deliver_proposals(
        self,
        items: "list[tuple[Scope, Proposal]]",
        now: int,
        configs: "list[ConsensusConfig | None] | None" = None,
    ) -> "list[int]":
        """Gossip-facing delivery of (possibly vote-carrying) proposals:
        create unknown sessions, EXTEND known ones along the validated-chain
        watermark, and absorb pure redeliveries for free.

        The reference protocol gossips growing vote chains; its
        ``process_incoming_proposal`` rejects any redelivery outright
        (ProposalAlreadyExist), forcing embedders to re-feed every embedded
        vote through the vote path — O(chain) signature checks per
        delivery, O(L²) for an incrementally grown chain. This entry point
        is the amortized alternative. Per item:

        - unknown ``(scope, proposal_id)``: the full
          :meth:`ingest_proposals` gauntlet (batched, cache-aware);
          status as that path reports it;
        - known, and the incoming chain strictly extends the accepted one
          (every accepted vote's hash matches positionally — the
          watermark): ONLY the suffix is hash/signature/chain-checked
          (cache-aware) and applied through the batch vote path. Status
          OK when every suffix vote landed (duplicates from concurrent
          vote gossip and post-decision extras are absorbed), else the
          first hard per-vote error. Admission failures apply nothing
          (checked up front); apply-stage rejections — capacity, round
          caps — leave earlier suffix votes applied, exactly as feeding
          the suffix through the per-vote gossip path would;
        - known otherwise — identical chain, shorter chain, fork before
          the watermark, or a session whose chain was retained through
          the columnar path (merged order not positionally comparable):
          PROPOSAL_ALREADY_EXIST with zero crypto, exactly what
          process_incoming_proposal reports for a redelivery.

        Items are processed STRICTLY in order, each against the state the
        previous items left: a batch call is definitionally equivalent to
        the same deliveries made one by one (so ``[create X, extend X]``
        extends, and a same-batch duplicate settles as a redelivery).
        That equivalence is load-bearing for durability — the WAL chunks
        oversized KIND_DELIVER records into consecutive smaller batches
        and replays them as separate calls. Consecutive UNKNOWN items
        with distinct pids are still dispatched as one
        :meth:`ingest_proposals` call (one verify batch, one chain-kernel
        dispatch) — safe because that path also processes in order — and
        repeated signatures across items cost one verify via the
        admission cache, so ordering does not forfeit the batch's
        amortization.

        Multi-host: a device-pooled session owned by another process
        reports SESSION_NOT_FOUND *before* any suffix validation — the
        relay routes on that status, and a misrouted-but-invalid delivery
        must look the same as a misrouted-valid one (the ingest_votes
        convention).

        Semantics with the verify cache disabled are identical (the
        watermark is structural, not cached); only the signature work
        changes. Events/decisions fire exactly as the underlying
        create/vote paths emit them.
        """
        if configs is not None and len(configs) != len(items):
            raise ValueError("configs must supply one entry per item")
        statuses: list[int] = [0] * len(items)
        run: list[int] = []  # consecutive unknown items, distinct pids
        run_keys: set = set()

        def flush_run() -> None:
            if not run:
                return
            sub = self.ingest_proposals(
                [items[j] for j in run],
                now,
                configs=(
                    [configs[j] for j in run] if configs is not None else None
                ),
            )
            for j, code in zip(run, sub):
                statuses[j] = int(code)
            run.clear()
            run_keys.clear()

        for k, (scope, proposal) in enumerate(items):
            key = (scope, proposal.proposal_id)
            # A known pid — or a pid this run is about to register — must
            # see the state all earlier items produced: flush first.
            # Demoted sessions are known: a redelivery that strictly
            # extends one pages it back in and applies the suffix.
            if key in self._index or key in run_keys or self._tier_has(*key):
                flush_run()
            slot = self._index.get(key)
            if slot is None:
                slot = self._tier_lookup_promote(*key)
            if slot is None:
                run.append(k)
                run_keys.add(key)
                continue
            record = self._records[slot]
            if (
                self._multihost
                and record.session is None
                and not self._owns_slot(slot)
            ):
                # Misrouted, rejected BEFORE validation (see docstring).
                statuses[k] = int(StatusCode.SESSION_NOT_FOUND)
                continue
            suffix = self._extension_suffix(record, proposal)
            if suffix:
                statuses[k] = self._apply_chain_suffix(record, suffix, now)
            else:
                statuses[k] = int(StatusCode.PROPOSAL_ALREADY_EXIST)
                # The settle is still crypto-free; the health probe only
                # re-walks the already-compared prefix to classify WHY the
                # redelivery failed to extend (fork evidence / truncation
                # lag) instead of discarding the signal.
                self._note_redelivery_health(record, proposal, now)
        flush_run()
        return statuses

    def _note_redelivery_health(
        self, record: SessionRecord[Scope], proposal: Proposal, now: int
    ) -> None:
        """Classify a non-extending redelivery for the health layer. A
        prefix mismatch before the validated watermark is a FORK: the
        accepted vote and the divergent incoming vote at that position are
        retained as a self-authenticating evidence pair, attributed to the
        divergent vote's signer (its signature is NOT verified here — the
        watermark path settles forks crypto-free; the bytes authenticate
        themselves offline). A matching-but-shorter chain is a TRUNCATION:
        the chain's most recent signer — the closest accountable identity
        to the gossip source — is scored with the lag. Identical
        redeliveries are benign and score nothing. Only LEGACY
        (pre-validated) columnar retention is skipped — merged order not
        positionally comparable, same reason _extension_suffix bails;
        wire-validated retention probes against the merged chain, so a
        forker cannot hide behind a victim's columnar ingest path."""
        if not self._health_live or (
            record.retained_wire and not record.wire_only
        ):
            return
        accepted = (
            self._accepted_vote_chain(record)
            if record.retained_wire
            else record.proposal.votes
        )
        incoming = proposal.votes
        n = len(incoming)
        if n and n <= len(accepted):
            # Benign fast path — identical redelivery (equal length) or a
            # lagging peer (shorter): ONE tail-hash compare, no prefix
            # walk, so the steady-state gossip settle stays O(1). The
            # accepted chain's received_hash links commit each vote to
            # its predecessor, so a matching tail at the same position
            # means a matching prefix for fully-linked chains; chains
            # with empty links could in principle share the tail while
            # diverging earlier — evidence capture is best-effort there
            # (the API status is PROPOSAL_ALREADY_EXIST either way).
            if incoming[-1].vote_hash == accepted[n - 1].vote_hash:
                if n < len(accepted):
                    self.health.note_truncation(
                        incoming[-1].vote_owner, len(accepted) - n, now
                    )
                return
        elif not n:
            if accepted and proposal.proposal_owner:
                self.health.note_truncation(
                    proposal.proposal_owner, len(accepted), now
                )
            return
        # Mismatch guaranteed somewhere in the shared prefix (a strict
        # extension would have taken the watermark path; a shorter/equal
        # chain with an agreeing prefix matched its tail above — its
        # differing vote at any position, tail included, IS a divergent
        # history). Conviction bar (chaos-harness refinement, PARITY.md):
        # a positional divergence alone is NOT evidence against the
        # divergent vote's signer — an honest vote can land at a
        # different position under loss/reorder (or a racing embedder),
        # and grading its signer suspect would defame an honest peer.
        # Fork evidence is retained only when the divergent vote's owner
        # ALSO has a different accepted vote in this session — two
        # validly-shaped distinct votes by one signer, the same
        # self-authenticating double-sign bar the equivocation probe
        # applies. Anything weaker is counted, not convicted.
        for ours, theirs in zip(accepted, incoming):
            if ours.vote_hash != theirs.vote_hash:
                prior = record.votes.get(theirs.vote_owner)
                if prior is None and record.session is not None:
                    prior = record.session.votes.get(theirs.vote_owner)
                if prior is None and record.retained_wire:
                    # Wire-retained accepts live in the merged chain, not
                    # the scalar vote map.
                    for vote in accepted:
                        if vote.vote_owner == theirs.vote_owner:
                            prior = vote
                            break
                if prior is not None and prior.vote_hash != theirs.vote_hash:
                    # The retained pair is (offender's accepted vote,
                    # offender's divergent vote) — both carry the
                    # offender's signature, verifiable offline.
                    self.health.note_fork(
                        record.scope,
                        proposal.proposal_id,
                        prior.encode(),
                        theirs.encode(),
                        theirs.vote_owner,
                        now,
                    )
                else:
                    self.tracer.count("engine.divergent_redeliveries")
                return

    def _extension_suffix(
        self, record: SessionRecord[Scope], proposal: Proposal
    ) -> "list[Vote] | None":
        """Suffix of ``proposal.votes`` beyond the session's accepted chain,
        or None when the incoming chain is not a strict extension of it
        (shorter, equal-length, forked before the watermark, or the
        accepted chain is partly columnar-retained wire whose merged order
        is not positionally comparable). Wire-validated retention
        (``record.wire_only`` — the bridge's zero-copy OP_VOTE_BATCH
        path) stays comparable: its accepts are guard-ordered, so the
        merged chain is positional and anti-entropy can extend a
        wire-fed session exactly as a scalar-fed one. The prefix compare
        is bytes equality over already-validated hashes — no crypto."""
        if record.retained_wire:
            if not record.wire_only:
                return None
            accepted = self._accepted_vote_chain(record)
        else:
            accepted = record.proposal.votes
        incoming = proposal.votes
        if len(incoming) <= len(accepted):
            return None
        for ours, theirs in zip(accepted, incoming):
            if ours.vote_hash != theirs.vote_hash:
                return None
        return [v.clone() for v in incoming[len(accepted) :]]

    def _apply_chain_suffix(
        self, record: SessionRecord[Scope], suffix: "list[Vote]", now: int
    ) -> int:
        """Validate and apply a watermark extension: hash/signature checks
        (admission cache) and chain-link checks cover ONLY the suffix — the
        accepted prefix was validated when it was accepted. Admission is
        all-or-nothing (the first bad suffix vote rejects the delivery
        before anything mutates); APPLY-stage rejections — capacity,
        round caps — mirror the per-vote gossip path this call amortizes:
        earlier suffix votes stay applied and the first hard code is
        returned, exactly the state feeding the suffix through
        process_incoming_vote one by one would leave. One documented
        boundary divergence (PARITY.md): the expiry fail-fast below uses
        the proposal-level ``now >= expiration`` check shared by every
        proposal entry point, while the per-vote path expires strictly
        after (``now > expiration``) — a delivery at exactly
        ``now == expiration_timestamp`` is rejected here but would apply
        through the per-vote fallback."""
        proposal = record.proposal
        # Fail-fast BEFORE the signature prepass, matching the expiry
        # guards in process_incoming_proposal / ingest_proposals: an
        # attacker redelivering extensions of an expired session must not
        # be able to buy ECDSA work or churn the shared cache's LRU.
        try:
            validate_proposal_timestamp(proposal.expiration_timestamp, now)
        except ConsensusError as exc:
            if self._health_live and suffix[-1].vote_owner:
                # Expired-gossip scorecard hit on the chain's most recent
                # signer (the closest accountable identity to the
                # redelivery source) — still zero crypto.
                self.health.note_expired(suffix[-1].vote_owner, now)
            return int(exc.code)
        verdicts, hashes = self._cached_verify(suffix)
        for i, vote in enumerate(suffix):
            if vote.proposal_id != proposal.proposal_id:
                return int(StatusCode.VOTE_PROPOSAL_ID_MISMATCH)
            try:
                validate_vote(
                    vote,
                    self._scheme,
                    proposal.expiration_timestamp,
                    proposal.timestamp,
                    now,
                    sig_verdict=verdicts[i],
                    computed_hash=hashes[i],
                )
            except ConsensusError as exc:
                self._note_reject_health(vote, int(exc.code), now)
                return int(exc.code)
        code = self._validate_suffix_chain(record, suffix)
        if code:
            return code
        sub = self.ingest_votes(
            [(record.scope, vote) for vote in suffix], now, pre_validated=True
        )
        # The histogram is documented as "votes applied per watermark
        # extension": observe what actually LANDED (apply-stage rejections
        # and already-voted absorptions excluded), so rejected deliveries
        # and partial applies never read as healthy extension traffic.
        applied = int(np.sum(np.asarray(sub) == int(StatusCode.OK)))
        if applied:
            self._m_suffix_len.observe(applied)
            self.tracer.count("engine.chain_extensions")
        # Soft codes a live session legitimately produces for chain votes
        # that raced concurrent gossip: the owner already voted via the
        # vote path, or the session decided mid-suffix. Anything else is a
        # hard error the caller must see.
        soft = (
            int(StatusCode.OK),
            int(StatusCode.ALREADY_REACHED),
            int(StatusCode.DUPLICATE_VOTE),
            int(StatusCode.USER_ALREADY_VOTED),
        )
        for code in sub:
            if int(code) not in soft:
                return int(code)
        return int(StatusCode.OK)

    def _validate_suffix_chain(
        self, record: SessionRecord[Scope], suffix: "list[Vote]"
    ) -> int:
        """protocol.validate_vote_chain over accepted + suffix, checked
        from the watermark onward (``start``): the accepted prefix's links
        were validated at acceptance, and the chain rules live in exactly
        one place. Wire-retained records supply the MERGED accepted chain
        (scalar votes alone would make a correctly-linked suffix look
        dangling — its received_hash names the retained tail). Returns a
        StatusCode int, 0 when valid."""
        accepted = (
            self._accepted_vote_chain(record)
            if record.retained_wire
            else record.proposal.votes
        )
        try:
            validate_vote_chain(accepted + suffix, start=len(accepted))
        except ConsensusError as exc:
            return int(exc.code)
        return 0

    def _register(
        self,
        scope: Scope,
        proposal: Proposal,
        config: ConsensusConfig,
        now: int,
        session: ConsensusSession | None = None,
    ) -> SessionRecord[Scope]:
        """Claim a pool slot for the proposal — or, when the pool geometry
        cannot hold it (expected_voters_count or embedded voters beyond the
        lane capacity, or no free slots), degrade to a host-backed scalar
        session. Registration therefore never fails on capacity, matching the
        reference service's unbounded envelope (src/service.rs:86-97) and its
        invariant that session save cannot fail (events may be emitted before
        registration, src/service.rs:275-277)."""
        # Per-scope LRU eviction runs BEFORE slot allocation so overflow
        # eviction can free a device slot for the incoming session (the
        # reference trims after save, src/service.rs:512-522 — the surviving
        # set is identical either way, but trimming first avoids stranding
        # the newcomer on the host path while a freed slot sits idle).
        if self._evict_for(scope, now):
            # The incoming session itself loses the LRU ranking (created_at
            # tie): never tracked, nothing allocated — same observable result
            # as insert-then-trim.
            host_session = (
                session
                if session is not None
                else ConsensusSession._new(proposal, config, now)
            )
            slot = self._next_host_slot
            self._next_host_slot -= 1
            record = SessionRecord(
                scope=scope,
                slot=slot,
                proposal=host_session.proposal,
                config=config,
                created_at=now,
                session=host_session,
            )
            record.votes = host_session.votes
            return record
        fits = (
            proposal.expected_voters_count <= self._pool.voter_capacity
            and (
                session is None
                or (
                    len(session.votes) <= self._pool.voter_capacity
                    # Tally-carrying sessions (columnar spill survivors) stay
                    # host-backed: pooling would bake tallies into the dense
                    # row but drop them from the exportable session, so a
                    # save->load->save round-trip would lose them.
                    and not session.tallies
                )
            )
            and self._pool.free_slots > 0
        )
        if fits:
            slot = allocate_slot(
                self._pool, (scope, proposal.proposal_id), proposal, config, now
            )
            host_session = None
        else:
            slot = self._next_host_slot
            self._next_host_slot -= 1
            host_session = (
                session
                if session is not None
                else ConsensusSession._new(proposal, config, now)
            )
            self.tracer.count("engine.host_spills")
        record = SessionRecord(
            scope=scope,
            slot=slot,
            proposal=proposal if host_session is None else host_session.proposal,
            config=config,
            created_at=now,
            session=host_session,
        )
        if host_session is not None:
            record.votes = host_session.votes  # shared dict: one source of truth
        record.last_activity = now
        seq = self._scope_seq.get(scope, 0)
        self._scope_seq[scope] = seq + 1
        record.seq = seq
        self._records[slot] = record
        self._index[(scope, record.proposal.proposal_id)] = slot
        self._scopes.setdefault(scope, []).append(slot)
        self._drop_pid_cache(scope)
        self._timelines.created(
            slot, scope, record.proposal.proposal_id, now, time.monotonic()
        )
        if not self._promoting:
            # Paging a demoted session back in is not a fresh proposal.
            self._m_proposals.inc()
        return record

    def _register_session(
        self, scope: Scope, session: ConsensusSession, created_at: int
    ) -> None:
        """Load a scalar session (possibly already decided) into a fresh
        slot — the shared path for validated network proposals and
        storage-backed restore (device tensors are a cache; the session is
        the source of truth, SURVEY §5 checkpoint row). Sessions the pool
        cannot hold stay host-backed (see _register)."""
        record = self._register(
            scope, session.proposal, session.config, created_at, session=session
        )
        if record.slot not in self._records:
            return  # evicted immediately by the per-scope cap (created_at tie)
        state = state_code_of(session.state)
        if state != STATE_ACTIVE:
            # Loaded already-decided (snapshot restore / vote-carrying
            # gossip): stamp the timeline's outcome but do NOT observe
            # decision latency — the decision wasn't made by this engine.
            self._timelines.decided(
                record.slot,
                _OUTCOME_OF_STATE[state],
                created_at,
                time.monotonic(),
                pre_decided=True,
            )
        if record.session is not None:
            return  # host-backed: the scalar session IS the state
        record.votes = {k: v.clone() for k, v in session.votes.items()}
        if session.votes or not session.state.is_active:
            loaded = load_session_rows(self._pool, record.slot, session)
            assert loaded  # capacity pre-checked in _register

    # ── Voting ─────────────────────────────────────────────────────────

    def _cached_verify(
        self, votes: "list[Vote]"
    ) -> "tuple[list, list[bytes]]":
        """Synchronous admission-verify prepass: exactly
        ``_cached_verify_begin(votes).collect()`` (see there)."""
        return self._cached_verify_begin(votes).collect()

    def verify_votes_async(self, votes: "list[Vote]") -> "PendingVoteVerdicts":
        """Public admission-verify prepass for pipelining embedders.

        Starts the full host validation front half NOW — vote-hash
        recompute, structural prechecks, verify-cache consult, and the
        signature batch submitted to the scheme (on the native worker
        pool, the crypto runs GIL-free in the background) — and returns a
        handle whose ``collect()`` yields ``(verdicts, computed_hashes)``
        aligned with ``votes``, exactly what the engine's own entry
        points consume. Embedders that drive :meth:`ingest_columnar`
        with pre-validated traffic use this to overlap batch k+1's
        crypto with batch k's device ingest (the `bench.py
        validated-sweep` cold path); verdicts must all be True and each
        ``computed_hash`` must equal the vote's ``vote_hash`` before the
        rows may be ingested as validated."""
        return self._cached_verify_begin(votes)

    def _cached_verify_begin(self, votes: "list[Vote]") -> "PendingVoteVerdicts":
        """Signature verdicts for ``votes`` through the admission cache,
        in two halves. This half: in-batch dedup (identical votes across
        many chains collapse to one verify item), cache consultation, and
        ONE scheme.verify_batch_submit over the surviving misses — the
        crypto is in flight on the verify pool when this returns. The
        ``collect()`` half: await verdicts, fan out, populate the cache,
        and return (verdicts, computed_hashes) aligned with ``votes`` —
        callers feed both into validate_vote so the SHA pass here is the
        only one. The verify-batch histogram observes the *collect* wait,
        so a well-overlapped pipeline shows near-zero residence while an
        unpipelined caller still sees the full verify cost (begin is
        immediately followed by collect).

        With the cache disabled this is a plain batched verify (identical
        to the pre-cache flow). Admission keys are derived from each
        vote's ``signing_payload()`` — the exact bytes the scheme
        verifies — so a key can never be shared by two different
        verification questions (see the verify_cache module docstring).
        Rows whose embedded ``vote_hash`` field does not match the
        recomputed digest — or with structurally empty
        owner/hash/signature — are neither verified nor cached:
        validate_vote rejects them before ever consulting the signature
        verdict, so caching them would only churn the LRU."""
        hashes = [compute_vote_hash(v) for v in votes]
        if self._verify_cache is None:
            if not votes:
                return PendingVoteVerdicts(lambda: ([], hashes))
            pending = self._scheme.verify_batch_submit(
                [v.vote_owner for v in votes],
                [v.signing_payload() for v in votes],
                [v.signature for v in votes],
            )

            def _finish_uncached():
                with observed_span(
                    self.tracer,
                    "engine.verify_batch",
                    self._m_verify,
                    votes=len(votes),
                ):
                    verdicts = pending.collect()
                self._note_verified(len(votes))
                return list(verdicts), hashes

            return PendingVoteVerdicts(_finish_uncached)
        cache = self._verify_cache
        verdicts: list = [False] * len(votes)
        rows: list[int] = []
        keys: list[bytes] = []
        payloads: list[bytes] = []
        for i, (vote, digest) in enumerate(zip(votes, hashes)):
            if (
                not vote.vote_owner
                or not vote.signature
                or vote.vote_hash != digest
            ):
                continue  # verdict unreachable in validate_vote's ordering
            payload = vote.signing_payload()
            rows.append(i)
            payloads.append(payload)
            keys.append(
                VerifiedVoteCache.key(
                    payload, vote.signature, self._verify_scheme_tag
                )
            )
        miss_rows: dict[bytes, list[int]] = {}
        miss_payloads: dict[bytes, bytes] = {}
        for i, key, payload, hit in zip(
            rows, keys, payloads, cache.get_many(keys)
        ):
            if hit is not MISS:
                verdicts[i] = hit
            else:
                miss_rows.setdefault(key, []).append(i)
                miss_payloads.setdefault(key, payload)
        if not miss_rows:
            return PendingVoteVerdicts(lambda: (verdicts, hashes))
        rep = [r[0] for r in miss_rows.values()]
        pending = self._scheme.verify_batch_submit(
            [votes[i].vote_owner for i in rep],
            list(miss_payloads.values()),
            [votes[i].signature for i in rep],
        )

        def _finish():
            with observed_span(
                self.tracer,
                "engine.verify_batch",
                self._m_verify,
                votes=len(rep),
            ):
                fresh = pending.collect()
            self._note_verified(len(rep))
            for (_, miss), verdict in zip(miss_rows.items(), fresh):
                for i in miss:
                    verdicts[i] = verdict
            cache.put_many(list(zip(miss_rows, fresh)))
            return verdicts, hashes

        return PendingVoteVerdicts(_finish)

    def _note_verified(self, count: int) -> None:
        self._m_verified_sigs.inc(count)
        self._m_verified_sigs_scheme.inc(count)

    def cast_vote(self, scope: Scope, proposal_id: int, choice: bool, now: int) -> Vote:
        """Sign, chain, and apply this peer's vote
        (reference: src/service.rs:216-237)."""
        record = self._get_record(scope, proposal_id)
        validate_proposal_timestamp(record.proposal.expiration_timestamp, now)
        identity = self._signer.identity()
        if identity in record.votes or (
            record.session is not None and identity in record.session.tallies
        ) or (
            record.retained_wire
            and any(
                identity == vote.vote_owner
                for vote in self._accepted_vote_chain(record)
            )
        ):
            raise UserAlreadyVoted()
        # Chain against the MERGED accepted chain: votes accepted through
        # the zero-copy wire path live in retained wire chunks, not
        # record.proposal.votes — linking against the scalar list alone
        # would mint a vote whose received_hash ignores the real tail,
        # and every other peer's dangling guard would (rightly) reject
        # it. The materialized view is decode-cached per growth.
        link_source = (
            self._materialized_proposal(record)
            if record.retained_wire
            else record.proposal
        )
        vote = build_vote(link_source, choice, self._signer, now)
        statuses = self.ingest_votes(
            [(scope, vote)], now, pre_validated=True
        )
        exc = error_for_code(int(statuses[0]))
        if exc is not None:
            raise exc()
        return vote

    def cast_vote_and_get_proposal(
        self, scope: Scope, proposal_id: int, choice: bool, now: int
    ) -> Proposal:
        """reference: src/service.rs:243-253"""
        self.cast_vote(scope, proposal_id, choice, now)
        return self._materialized_proposal(self._get_record(scope, proposal_id))

    def process_incoming_vote(self, scope: Scope, vote: Vote, now: int) -> None:
        """Scalar network-vote entry point (reference: src/service.rs:286-305):
        full host validation, then the batched device path."""
        statuses = self.ingest_votes([(scope, vote)], now)
        exc = error_for_code(int(statuses[0]))
        if exc is not None:
            raise exc()

    def _vote_prepass_begin(
        self, items: "list[tuple[Scope, Vote]]", pre_validated: bool
    ) -> "tuple[list[int], PendingVoteVerdicts] | None":
        """Start the batched signature prepass for an ingest_votes batch:
        resolve which rows have a locally-owned session (the same filter
        the apply loop uses), and submit their signatures through the
        admission cache to the verify pool. Returns (row indices, pending
        handle), or None when the batch takes no prepass (pre-validated,
        or a cacheless scalar call).

        Safe to call for batch k+1 BEFORE batch k applies — that is the
        double-buffered pipeline — because ingest_votes never evicts or
        unregisters sessions: everything the prepass RESOLVED stays
        resolved across vote applies. A batch may page a demoted session
        back in (tier promotion), but that only ADDS index entries — a
        row the prepass saw as session-less simply verifies inline at
        apply time, exactly like the cacheless path. (Interleaving
        proposal registration/eviction between begin and apply is NOT
        supported; ingest_votes_pipelined only chains vote batches, so
        the invariant holds by construction.)"""
        batch = len(items)
        if pre_validated or not (
            batch > 1 or (batch == 1 and self._verify_cache is not None)
        ):
            return None
        idxs = [
            i
            for i, (scope, vote) in enumerate(items)
            if (slot := self._index.get((scope, vote.proposal_id))) is not None
            and (slot < 0 or self._owns_slot(slot))  # skip misrouted rows
        ]
        if not idxs:
            return None
        return idxs, self._cached_verify_begin([items[i][1] for i in idxs])

    def ingest_votes_pipelined(
        self,
        batches: "list[list[tuple[Scope, Vote]]]",
        now: int,
        pre_validated: bool = False,
    ) -> "list[np.ndarray]":
        """Double-buffered :meth:`ingest_votes` over consecutive batches:
        batch k+1's signature prepass is submitted to the verify pool
        BEFORE batch k applies, so host crypto overlaps the previous
        batch's device dispatch and host bookkeeping. Result-identical to
        ``[ingest_votes(b, now, pre_validated) for b in batches]`` — the
        prepass is order-invariant across vote applies (see
        :meth:`_vote_prepass_begin`), statuses and events fire in the
        same per-batch order, and with the native pool absent the
        deferred-sync fallback restores today's sequential behavior byte
        for byte."""
        results: "list[np.ndarray]" = []
        prev: "tuple[list[tuple[Scope, Vote]], object] | None" = None
        for items in batches:
            items = list(items)
            prepass = self._vote_prepass_begin(items, pre_validated)
            if prev is not None:
                results.append(
                    self.ingest_votes(
                        prev[0], now, pre_validated, _prepass=prev[1]
                    )
                )
            prev = (items, prepass)
        if prev is not None:
            results.append(
                self.ingest_votes(prev[0], now, pre_validated, _prepass=prev[1])
            )
        return results

    def ingest_votes(
        self,
        items: list[tuple[Scope, Vote]],
        now: int,
        pre_validated: bool = False,
        _prepass=_PREPASS_INLINE,
    ) -> np.ndarray:
        """THE batch hot path: apply many votes across many sessions/scopes
        in one device dispatch.

        Per vote: resolve the session, host-validate (hash, signature,
        replay/expiry — skipped when ``pre_validated``, for locally built or
        already-verified replay traces), map owner→lane, then run the
        arrival-ordered ingest kernel. Emits ConsensusReached events for every
        session the batch decides. Returns int32 status codes in batch order
        (StatusCode.OK / ALREADY_REACHED are successes).

        ``_prepass`` (private) lets :meth:`ingest_votes_pipelined` hand in
        a signature prepass it already started for this batch; the
        default recomputes it inline, which is the same thing minus the
        overlap.
        """
        batch = len(items)
        self.tracer.count("engine.votes_in", batch)
        wall = time.monotonic()
        if batch:
            self._m_votes_total.inc(batch)
            self._m_batch_size.observe(batch)
            flight_recorder.record("engine.ingest_votes", votes=batch)
        statuses = np.zeros(batch, np.int32)
        dev_rows: list[int] = []  # indices into items that reach the device
        slots = np.empty(batch, np.int64)
        lanes = np.empty(batch, np.int32)
        values = np.empty(batch, bool)
        # Host-spilled sessions apply immediately but their events are queued
        # as (batch index, scope, event) and emitted interleaved with the
        # device path's, preserving per-vote arrival order across substrates.
        host_events: list[tuple[int, Scope, ConsensusEvent]] = []
        host_accepted = 0
        host_transitions = 0
        host_owned_transitions = 0
        # Per-signer health accounting, batched: admissions accumulate
        # into one dict flushed in a single monitor call (_flush_vote_
        # health), so the hot path pays dict stores, not per-vote locks.
        admit_counts: dict[bytes, int] = {}
        admit_timeout = 0.0
        # Chain-linkage tails per record for THIS batch: a same-batch
        # chained run (v2 extends tail, v3 extends v2) must see v2 as the
        # effective tail even on the device substrate, whose host-side
        # append happens after the dispatch. Optimistic — a mid-batch
        # apply-stage rejection (round cap) can let one dangling
        # follower through, matching the pre-guard behavior there.
        pending_tail: dict[int, bytes] = {}

        # Batched signature verification: one scheme call for the whole batch
        # (native runtime: one pool-fanned C batch, GIL-free). Verdicts are
        # injected into the per-vote check sequence, preserving exact scalar
        # error precedence. With the admission cache enabled the prepass
        # also covers batch == 1 (the process_incoming_vote / bridge scalar
        # path hits the cache too), dedups identical votes within the
        # batch, and only the cache misses reach the scheme. A pipelined
        # caller hands in the prepass it began before the PREVIOUS batch
        # applied; the crypto has been running in the background since.
        sig_verdicts: dict[int, object] = {}
        vote_hashes: dict[int, bytes] = {}
        if _prepass is _PREPASS_INLINE:
            _prepass = self._vote_prepass_begin(items, pre_validated)
        if _prepass is not None:
            idxs, pending = _prepass
            verdicts, hashes = pending.collect()
            sig_verdicts = dict(zip(idxs, verdicts))
            vote_hashes = dict(zip(idxs, hashes))

        for i, (scope, vote) in enumerate(items):
            slot = self._index.get((scope, vote.proposal_id))
            if slot is None:
                # Late vote on a demoted session: demand-page it back in
                # and apply exactly as if it had never left.
                slot = self._tier_lookup_promote(scope, vote.proposal_id)
                if slot is None:
                    statuses[i] = int(StatusCode.SESSION_NOT_FOUND)
                    continue
            record = self._records[slot]
            if (
                self._multihost
                and record.session is None
                and not self._owns_slot(slot)
            ):
                # Misrouted vote, rejected BEFORE validation: the relay
                # routes on this status, and a misrouted-but-invalid vote
                # must look the same as a misrouted-valid one.
                statuses[i] = int(StatusCode.SESSION_NOT_FOUND)
                continue
            if not pre_validated:
                try:
                    validate_vote(
                        vote,
                        self._scheme,
                        record.proposal.expiration_timestamp,
                        record.proposal.timestamp,
                        now,
                        sig_verdict=sig_verdicts.get(i),
                        computed_hash=vote_hashes.get(i),
                    )
                except ConsensusError as exc:
                    statuses[i] = int(exc.code)
                    self._note_reject_health(vote, int(exc.code), now)
                    continue
            # Dangling-vote guard (chaos-harness hardening, PARITY.md): a
            # FIRST-TIME voter whose received_hash names a vote this
            # session never accepted is rejected instead of appended. An
            # appended dangling vote makes the local chain positionally
            # incomparable to the sender's — the watermark can then never
            # extend it and anti-entropy can never repair the peer to
            # byte-identical state (and the divergence used to read as
            # fork "evidence" against an honest signer). Redeliveries and
            # equivocations (known owners) keep their duplicate-shaped
            # statuses; empty links and columnar-retained sessions keep
            # the reference's permissive behavior.
            # Wire-validated retention keeps the guard armed here too
            # (continuity state maintained by ingest_wire_columnar): a
            # session fed through the zero-copy bridge path and then hit
            # by a scalar/object-path vote must guard exactly as if every
            # vote had taken one path — otherwise the two paths' statuses
            # could diverge on the same byte stream. Only LEGACY
            # pre-validated columnar retention stays permissive.
            wire_guarded = record.retained_wire and record.wire_only
            if wire_guarded and (
                record.wire_seen is None
                or record.wire_sync
                != (len(record.retained_wire), len(record.scalar_seqs))
            ):
                self._resync_wire_chain(record)
            first_time_voter = not (
                record.retained_wire and not record.wire_only
            ) and (
                vote.vote_owner not in record.votes
                and not (wire_guarded and vote.vote_owner in record.wire_seen)
                and (
                    record.session is None
                    or (
                        vote.vote_owner not in record.session.tallies
                        and vote.vote_owner not in record.session.votes
                    )
                )
            )
            if first_time_voter:
                if vote.received_hash:
                    # An empty chain has no tail: a first vote claiming a
                    # received link is dangling by definition (the chain
                    # head always carries an empty link).
                    tail = pending_tail.get(
                        slot,
                        (record.wire_tail or b"")
                        if wire_guarded
                        else record.proposal.votes[-1].vote_hash
                        if record.proposal.votes
                        else b"",
                    )
                    if vote.received_hash != tail:
                        statuses[i] = int(StatusCode.RECEIVED_HASH_MISMATCH)
                        self.tracer.count("engine.dangling_votes_rejected")
                        continue
                # This vote is now the batch-effective tail for the
                # record (known-owner duplicates never move the tail).
                pending_tail[slot] = vote.vote_hash
            if record.session is not None:
                was_active = record.session.state.is_active
                code, event = self._host_add_vote(record, vote, now)
                statuses[i] = code
                if code == int(StatusCode.OK):
                    host_accepted += 1
                    record.last_activity = now
                    owner = vote.vote_owner
                    admit_counts[owner] = admit_counts.get(owner, 0) + 1
                    if record.config.consensus_timeout > admit_timeout:
                        admit_timeout = record.config.consensus_timeout
                    self._timelines.voted(slot, now, wall)
                    if trace_store.enabled and record.trace is not None:
                        trace_store.instant(
                            "consensus.vote_applied",
                            record.trace,
                            peer=self._trace_peer,
                            attrs={"owner": vote.vote_owner.hex()[:12]},
                        )
                if was_active and not record.session.state.is_active:
                    host_transitions += 1
                    # Host-spilled sessions are replicated on every
                    # process: decision metrics are ownership-gated like
                    # events so a fleet-wide sum counts each decision once.
                    owned = self._owns_slot(slot)
                    host_owned_transitions += owned
                    outcome = _OUTCOME_OF_STATE[state_code_of(record.session.state)]
                    self._timelines.decided(
                        slot, outcome, now, wall, observe=owned,
                    )
                    if trace_store.enabled and record.trace is not None:
                        trace_store.instant(
                            "consensus.decided",
                            record.trace,
                            peer=self._trace_peer,
                            attrs={"outcome": outcome},
                        )
                if event is not None and self._owns_slot(slot):
                    host_events.append((i, scope, event))
                continue
            lane = self._pool.lane_for(slot, vote.vote_owner)
            if lane is None:
                statuses[i] = int(StatusCode.VOTER_CAPACITY_EXCEEDED)
                continue
            slots[len(dev_rows)] = slot
            lanes[len(dev_rows)] = lane
            values[len(dev_rows)] = vote.vote
            dev_rows.append(i)

        if not dev_rows:
            if self._multihost:
                # Collective cadence: the other processes' batches are part
                # of the same global dispatch, so an empty one still joins.
                self._pool.ingest(
                    np.empty(0, np.int64), np.empty(0, np.int32),
                    np.empty(0, bool), now,
                )
            self.tracer.count("engine.votes_accepted", host_accepted)
            self.tracer.count("engine.transitions", host_transitions)
            self._m_votes_accepted.inc(host_accepted)
            self._m_decisions.inc(host_owned_transitions)
            for _, ev_scope, event in host_events:
                self._emit(ev_scope, event)
            self._flush_vote_health(
                items, statuses, admit_counts, admit_timeout, now,
                pre_validated,
            )
            return statuses

        k = len(dev_rows)
        with observed_span(
            self.tracer, "engine.device_ingest", self._m_device, votes=k
        ):
            dev_statuses, transitions = self._pool.ingest(
                slots[:k], lanes[:k], values[:k], now
            )
        statuses[np.asarray(dev_rows)] = dev_statuses
        # Re-stamp the wall clock AFTER the device dispatch completed: a
        # decision's latency must include the ingest that produced it (the
        # columnar path stamps at the same point), not the batch-entry time.
        wall = time.monotonic()
        accepted = int(np.sum(dev_statuses == int(StatusCode.OK))) + host_accepted
        self.tracer.count("engine.votes_accepted", accepted)
        self.tracer.count("engine.transitions", len(transitions) + host_transitions)
        self._m_votes_accepted.inc(accepted)
        # Device transitions are local by construction (misrouted votes
        # were rejected before the dispatch); host-spilled ones were
        # ownership-filtered above.
        self._m_decisions.inc(len(transitions) + host_owned_transitions)
        for slot, new_state in transitions:
            outcome = _OUTCOME_OF_STATE.get(new_state)
            if outcome is not None:
                self._timelines.decided(slot, outcome, now, wall)
                if trace_store.enabled:
                    tctx = self._records[slot].trace
                    if tctx is not None:
                        trace_store.instant(
                            "consensus.decided",
                            tctx,
                            peer=self._trace_peer,
                            attrs={"outcome": outcome},
                        )

        # Host bookkeeping for accepted votes, in arrival order; remember the
        # last accepted vote per slot — that is the vote that flipped a slot
        # that ended the batch decided (OK can never follow REACHED).
        last_ok: dict[int, int] = {}
        for j, i in enumerate(dev_rows):
            if dev_statuses[j] == int(StatusCode.OK):
                scope, vote = items[i]
                record = self._records[int(slots[j])]
                stored = vote.clone()  # as the scalar add_vote does
                record.votes[stored.vote_owner] = stored
                record.proposal.votes.append(stored)
                record.scalar_seqs.append(record.next_arrival_seq())
                record.bump_round(1)
                admit_counts[stored.vote_owner] = (
                    admit_counts.get(stored.vote_owner, 0) + 1
                )
                last_ok[int(slots[j])] = j
        for slot in last_ok:
            record = self._records[slot]
            record.last_activity = now
            cfg_timeout = record.config.consensus_timeout
            if cfg_timeout > admit_timeout:
                admit_timeout = cfg_timeout
            self._timelines.voted(slot, now, wall)
            if trace_store.enabled:
                tctx = self._records[slot].trace
                if tctx is not None:
                    trace_store.instant(
                        "consensus.vote_applied",
                        tctx,
                        peer=self._trace_peer,
                        attrs={"batch": int(batch)},
                    )

        # Event emission in per-vote arrival order, mirroring the scalar
        # path exactly: the deciding vote emits ConsensusReached, and every
        # later vote to the decided session re-emits it (the reference's
        # add_vote returns the existing result, which process_incoming_vote
        # turns into another event — src/session.rs:246, src/service.rs:303).
        # A STATE_FAILED transition (round-cap overrun) emits nothing,
        # matching the MaxRoundsExceeded error path (src/session.rs:334-343).
        newly_reached = {
            slot: new_state
            for slot, new_state in transitions
            if new_state in (STATE_REACHED_YES, STATE_REACHED_NO)
        }
        pending_events = host_events
        for j, i in enumerate(dev_rows):
            slot = int(slots[j])
            code = int(dev_statuses[j])
            emit_reached = (
                code == int(StatusCode.OK)
                and slot in newly_reached
                and last_ok.get(slot) == j
            ) or code == int(StatusCode.ALREADY_REACHED)
            if emit_reached:
                record = self._records[slot]
                state = self._pool.state_of(slot)
                pending_events.append(
                    (
                        i,
                        record.scope,
                        ConsensusReached(
                            proposal_id=record.proposal.proposal_id,
                            result=state == STATE_REACHED_YES,
                            timestamp=now,
                        ),
                    )
                )
        pending_events.sort(key=lambda t: t[0])
        for _, ev_scope, event in pending_events:
            self._emit(ev_scope, event)
        self._flush_vote_health(
            items, statuses, admit_counts, admit_timeout, now, pre_validated
        )
        return statuses

    # Duplicate-shaped statuses worth an equivocation probe: the session
    # already holds a vote by this owner (device DUPLICATE_VOTE, scalar
    # USER_ALREADY_VOTED) or absorbed a late vote post-decision
    # (ALREADY_REACHED) — all three reached the engine AFTER signature
    # admission, so a differing vote_hash means the owner validly signed
    # two distinct votes for one proposal.
    _EQUIVOCATION_PROBE_CODES = (
        int(StatusCode.DUPLICATE_VOTE),
        int(StatusCode.USER_ALREADY_VOTED),
        int(StatusCode.ALREADY_REACHED),
    )

    def _flush_vote_health(
        self,
        items: "list[tuple[Scope, Vote]]",
        statuses: np.ndarray,
        admit_counts: "dict[bytes, int]",
        admit_timeout: float,
        now: int,
        pre_validated: bool,
    ) -> None:
        """Per-batch health flush for ingest_votes: one batched admission
        update, then an equivocation probe over the (rare) duplicate-shaped
        rejections — two validly-signed votes with different hashes from
        one owner on one proposal become a retained evidence pair
        (obs.health module docstring)."""
        if not self._health_live or not len(items):
            return
        if admit_counts:
            self.health.note_admitted(
                admit_counts, now, timeout_hint=admit_timeout
            )
        if pre_validated:
            # No signature admission ran in THIS call (locally-built
            # votes, WAL replay, already-validated suffixes): a
            # duplicate-shaped rejection here must not mint a
            # verified-evidence record — an embedder bug or forged
            # replay row could otherwise fabricate "self-authenticating"
            # proof and 503 the node. The network-facing paths (the
            # only ones an attacker reaches) all validate, so coverage
            # is unchanged where it matters.
            return
        # Candidate selection must stay cheap on the clean path. Scalar
        # batches (the watermark/bridge shape) read one int; larger ones
        # take ONE vectorized any() pass (OK == 0, so any nonzero means
        # some rejection) before the per-code compares. np.isin is NOT
        # used — it costs ~250us per call at small batch sizes, which
        # alone would blow the redelivery budget.
        if len(items) == 1:
            if int(statuses[0]) not in self._EQUIVOCATION_PROBE_CODES:
                return
            rows = [0]
        else:
            if not statuses.any():
                return
            candidates = statuses == self._EQUIVOCATION_PROBE_CODES[0]
            for code in self._EQUIVOCATION_PROBE_CODES[1:]:
                candidates |= statuses == code
            if not candidates.any():
                return
            rows = np.nonzero(candidates)[0].tolist()
        last_key: "tuple | None" = None  # duplicates cluster per proposal
        record: "SessionRecord[Scope] | None" = None
        for i in rows:
            scope, vote = items[i]
            key = (scope, vote.proposal_id)
            if key != last_key:
                last_key = key
                slot = self._index.get(key)
                record = self._records[slot] if slot is not None else None
            if record is None:
                continue
            prior = record.votes.get(vote.vote_owner)
            if prior is not None and prior.vote_hash != vote.vote_hash:
                self.health.note_equivocation(
                    scope,
                    vote.proposal_id,
                    prior.encode(),
                    vote.encode(),
                    vote.vote_owner,
                    now,
                )

    def _note_reject_health(self, vote: Vote, code: int, now: int) -> None:
        """Scorecard attribution for per-vote admission rejections (the
        identity is the vote's *claimed* signer — see
        HealthMonitor.note_invalid_signature)."""
        if not self._health_live:
            return
        if code in (
            int(StatusCode.INVALID_VOTE_SIGNATURE),
            int(StatusCode.INVALID_VOTE_HASH),
            int(StatusCode.SIGNATURE_SCHEME),
        ):
            if vote.vote_owner:
                self.health.note_invalid_signature(vote.vote_owner, now)
        elif code == int(StatusCode.VOTE_EXPIRED):
            if vote.vote_owner:
                self.health.note_expired(vote.vote_owner, now)

    def voter_gid(self, owner: bytes) -> int:
        """Intern an owner identity for the columnar ingest path.

        Gids are generation-tagged (``generation << 32 | index``): a gid
        freed by any session-releasing call (delete_scope, per-scope-cap
        eviction inside create_proposal, spill) is rejected with
        EMPTY_VOTE_OWNER from then on — including after its index is
        recycled to a new owner, whose gid carries a newer generation.
        Stale use is therefore always a typed error, never silent
        misattribution; re-interning per batch (a dict hit) merely avoids
        the rejections for voters whose membership churns."""
        return self._pool.voter_gid(owner)

    def ingest_columnar(
        self,
        scope: Scope,
        proposal_ids: np.ndarray,
        voter_gids: np.ndarray,
        values: np.ndarray,
        now: int,
        max_depth: int = 8,
        wire_votes: "list[bytes] | tuple[bytes, np.ndarray] | None" = None,
    ) -> np.ndarray:
        """THE throughput path: apply an arrival-ordered vote batch given as
        dense columns (structure-of-arrays) — proposal ids, interned voter
        ids (:meth:`voter_gid`), yes/no values — with zero per-vote Python.

        Same observable semantics as :meth:`ingest_votes` with
        ``pre_validated=True`` (validation, when needed, happens upstream:
        wire decode + signature verification are batch host stages), with
        two deliberate trade-offs, both documented in PARITY.md:
        - by default no per-vote ``Vote`` objects are accumulated host-side,
          so gossip reconstruction/export sees tallies but not vote chains;
          pass ``wire_votes`` (the encoded Vote bytes per row, either a list
          or a ``(packed, offsets)`` pair) to retain accepted rows' verbatim
          bytes off the timing path — proposal exports then re-embed them,
          merged with any scalar-ingested votes in true (call-granularity)
          arrival order, so the proposal re-gossips with a chain-valid vote
          list even for sessions fed through both paths (reference:
          src/utils.rs:175-215);
        - event ordering is guaranteed per-session, not across sessions.

        Resolution is fully vectorized (open-addressing _PidLookup hash for
        proposal→slot, dense lane tables for voter→lane), and the device
        work is split into bounded-depth dispatches pipelined through
        ``ingest_async`` so scan depth never exceeds ``max_depth`` and
        transfers overlap device compute. Returns int32 statuses in batch
        order (reference semantics per code, as ingest_votes).
        """
        proposal_ids = np.asarray(proposal_ids, np.int64)
        voter_gids = np.asarray(voter_gids, np.int64)
        values = np.asarray(values, bool)
        wire_norm, statuses, done = self._columnar_preamble(
            len(proposal_ids), wire_votes
        )
        if done:
            return statuses
        found, slots = self._pid_lookup(scope).lookup(proposal_ids)
        if self._promote_columnar_misses([scope], None, proposal_ids, found):
            found, slots = self._pid_lookup(scope).lookup(proposal_ids)
        return self._columnar_finish(
            slots, found, voter_gids, values, now, max_depth, statuses,
            wire_norm,
        )

    def _columnar_preamble(
        self, batch: int, wire_votes
    ) -> "tuple[tuple[np.ndarray, np.ndarray] | None, np.ndarray, bool]":
        """Shared entry sequence of the columnar paths: normalize wire
        bytes BEFORE any state mutates, count the batch, init statuses.
        The returned ``done`` flag short-circuits empty single-host
        batches; multi-host must NOT shortcut — an empty local batch still
        joins the fleet's agreed dispatch cadence (allgather + padding in
        _columnar_apply)."""
        wire_norm = (
            self._normalize_wire(wire_votes, batch)
            if wire_votes is not None
            else None
        )
        self.tracer.count("engine.votes_in", batch)
        if batch:
            self._m_votes_total.inc(batch)
            self._m_batch_size.observe(batch)
            flight_recorder.record("engine.ingest_columnar", votes=batch)
        statuses = np.full(batch, int(StatusCode.SESSION_NOT_FOUND), np.int32)
        return wire_norm, statuses, batch == 0 and not self._multihost

    def _columnar_finish(
        self,
        slots: np.ndarray,
        found: np.ndarray,
        voter_gids: np.ndarray,
        values: np.ndarray,
        now: int,
        max_depth: int,
        statuses: np.ndarray,
        wire_norm: "tuple[np.ndarray, np.ndarray] | None",
        wire_validated: bool = False,
    ) -> np.ndarray:
        """Shared tail of the columnar paths: apply, then retain accepted
        rows' wire bytes keyed by the resolved slots. ``wire_validated``
        marks retention coming from the guard-ordered wire path — the
        only kind that keeps a record's chain positionally comparable
        (SessionRecord.wire_only)."""
        statuses = self._columnar_apply(
            slots, found, voter_gids, values, now, max_depth, statuses
        )
        if wire_norm is not None:
            self._retain_wire_slots(statuses, slots, wire_norm, wire_validated)
        return statuses

    @staticmethod
    def _normalize_wire(wire_votes, batch: int) -> tuple[np.ndarray, np.ndarray]:
        """Validate and normalize wire_votes to (u8 data, i64 offsets)
        BEFORE any state mutates — a malformed argument must fail the call,
        not strand already-applied votes without their retained bytes."""
        blob, offsets = normalize_wire_votes(wire_votes, batch)
        return np.frombuffer(blob, np.uint8), offsets

    def _retain_wire_slots(
        self,
        statuses: np.ndarray,
        slots: np.ndarray,
        wire_norm: tuple[np.ndarray, np.ndarray],
        wire_validated: bool = False,
    ) -> None:
        """Attach accepted rows' verbatim vote bytes to their session
        records, keyed by the already-resolved slots (vectorized gather;
        one Python iteration per touched session, not per vote). Shared by
        the single- and multi-scope columnar entry points — slots identify
        records directly, so retention is scope-agnostic."""
        ok_rows = np.nonzero(statuses == int(StatusCode.OK))[0]
        if ok_rows.size == 0:
            return
        data_arr, offsets = wire_norm
        ok_slots = slots[ok_rows]
        order = np.argsort(ok_slots, kind="stable")  # arrival order per slot
        rows = ok_rows[order]
        s_sorted = ok_slots[order]
        starts = offsets[rows]
        lens = offsets[rows + 1] - starts
        ends = starts + lens
        uniq, seg_start = np.unique(s_sorted, return_index=True)
        seg_bounds = np.append(seg_start, len(rows))

        # Fast path: every slot's accepted rows occupy one contiguous span
        # of the packed data (the common streaming layout — batch packed in
        # arrival order, slot-major). Each slot's blob is then ONE slice;
        # the general path below materializes a per-byte gather index,
        # which is ~len(data) int64 entries of host work.
        contig = np.ones(len(rows), bool)
        if len(rows) > 1:
            contig[1:] = starts[1:] == ends[:-1]
            contig[seg_start] = True  # span breaks at slot boundaries are fine
        if contig.all():
            # All per-slot offset arrays are built in ONE pass (each slot's
            # cells plus a trailing end cell), so the per-slot loop is just
            # two small slices — per-slot np.append/tobytes overhead was
            # ~15us x touched-slots, the retained-churn bench's biggest
            # line item.
            s_count = len(uniq)
            counts = np.diff(seg_bounds)
            base = starts[seg_start]  # [S] span base per slot
            k_of_row = np.repeat(np.arange(s_count), counts)
            all_off = np.empty(len(rows) + s_count, np.int64)
            all_off[np.arange(len(rows)) + k_of_row] = starts - base[k_of_row]
            end_pos = seg_bounds[1:] + np.arange(s_count)
            seg_ends = ends[seg_bounds[1:] - 1]
            all_off[end_pos] = seg_ends - base
            data_bytes = data_arr.tobytes()
            records = self._records
            base_l = base.tolist()
            ends_l = seg_ends.tolist()
            lo_l = (seg_bounds[:-1] + np.arange(s_count)).tolist()
            hi_l = end_pos.tolist()
            for k, slot in enumerate(uniq.tolist()):
                record = records[slot]
                record.wire_only = record.wire_only and wire_validated
                seq = record.arrival_seq
                record.arrival_seq = seq + 1
                record.retained_wire.append(
                    (
                        seq,
                        data_bytes[base_l[k] : ends_l[k]],
                        all_off[lo_l[k] : hi_l[k] + 1].copy(),
                    )
                )
            return

        out_off = np.zeros(len(rows) + 1, np.int64)
        np.cumsum(lens, out=out_off[1:])
        gather = (
            np.arange(int(out_off[-1]), dtype=np.int64)
            - np.repeat(out_off[:-1], lens)
            + np.repeat(starts, lens)
        )
        blob = data_arr[gather]
        for k, slot in enumerate(uniq.tolist()):
            lo, hi = int(seg_bounds[k]), int(seg_bounds[k + 1])
            seg_off = (out_off[lo : hi + 1] - out_off[lo]).copy()
            seg_blob = blob[int(out_off[lo]) : int(out_off[hi])].tobytes()
            record = self._records[int(slot)]
            record.wire_only = record.wire_only and wire_validated
            record.retained_wire.append(
                (record.next_arrival_seq(), seg_blob, seg_off)
            )

    def ingest_columnar_multi(
        self,
        scopes: list,
        scope_idx: np.ndarray,
        proposal_ids: np.ndarray,
        voter_gids: np.ndarray,
        values: np.ndarray,
        now: int,
        max_depth: int = 8,
        wire_votes: "list[bytes] | tuple[bytes, np.ndarray] | None" = None,
    ) -> np.ndarray:
        """Mixed-scope columnar ingest: one fused device pipeline across
        many scopes (BASELINE config-5 churn shape). ``scopes`` lists the
        distinct scopes; ``scope_idx`` (int32, per row) indexes into it.
        Per-scope work is only the proposal-id resolution — one _PidLookup
        hash probe pass per scope — so a 256-scope stream costs 256 cheap
        vectorized lookups, not 256 device dispatches; lanes, dispatch
        segmentation, statuses, events, and opt-in ``wire_votes`` retention
        (accepted rows' verbatim bytes, re-embedded chain-valid on export —
        reference: src/utils.rs:175-215) are shared with
        :meth:`ingest_columnar`."""
        proposal_ids = np.asarray(proposal_ids, np.int64)
        scope_idx = np.asarray(scope_idx, np.int64)
        voter_gids = np.asarray(voter_gids, np.int64)
        values = np.asarray(values, bool)
        batch = len(proposal_ids)
        wire_norm, statuses, done = self._columnar_preamble(batch, wire_votes)
        if done:
            return statuses
        found, slots = self._resolve_slots_multi(scopes, scope_idx, proposal_ids)
        return self._columnar_finish(
            slots, found, voter_gids, values, now, max_depth, statuses,
            wire_norm,
        )

    def _resolve_slots_multi(
        self, scopes: list, scope_idx: np.ndarray, proposal_ids: np.ndarray
    ) -> "tuple[np.ndarray, np.ndarray]":
        """Mixed-scope proposal-id resolution shared by the columnar entry
        points: (found bool[B], slots int64[B]). Rows that miss the live
        index but hit the demoted tier page their sessions back in and
        re-resolve — columnar late votes see an untier'd engine."""
        found, slots = self._resolve_slots_multi_once(
            scopes, scope_idx, proposal_ids
        )
        if self._promote_columnar_misses(scopes, scope_idx, proposal_ids, found):
            found, slots = self._resolve_slots_multi_once(
                scopes, scope_idx, proposal_ids
            )
        return found, slots

    def _resolve_slots_multi_once(
        self, scopes: list, scope_idx: np.ndarray, proposal_ids: np.ndarray
    ) -> "tuple[np.ndarray, np.ndarray]":
        batch = len(proposal_ids)
        found = np.zeros(batch, bool)
        slots = np.zeros(batch, np.int64)
        fused = self._fused_pid_lookup(scopes)
        if fused is not None:
            # Composite (scope_ordinal << 32 | pid) probe: the whole
            # mixed-scope batch resolves in one vectorized pass. Rows whose
            # pid falls outside u32 can never match a registered id.
            rows = np.nonzero(
                (proposal_ids >= 0) & (proposal_ids >> np.int64(32) == 0)
            )[0]
            if rows.size:
                comp = (scope_idx[rows] << np.int64(32)) | proposal_ids[rows]
                hit, hit_slots = fused.lookup(comp)
                found[rows] = hit
                slots[rows] = hit_slots
        else:
            # Fallback: one stable sort groups the rows of every scope
            # (O(batch log batch) total, not one full scan per scope).
            order = np.argsort(scope_idx, kind="stable")
            bounds = np.searchsorted(
                scope_idx[order], np.arange(len(scopes) + 1)
            )
            for k, scope in enumerate(scopes):
                rows = order[bounds[k] : bounds[k + 1]]
                if rows.size == 0:
                    continue
                hit, hit_slots = self._pid_lookup(scope).lookup(
                    proposal_ids[rows]
                )
                found[rows] = hit
                slots[rows] = hit_slots
        return found, slots

    # ── Zero-copy wire ingest (OP_VOTE_BATCH columnar fast path) ───────

    def wire_verify_begin(
        self,
        data: np.ndarray,
        cols: np.ndarray,
        offsets: np.ndarray,
        buf: "bytes | None" = None,
    ) -> "WireVotePrepass":
        """Session-independent half of the wire-columnar validation:
        structural emptiness checks, the batched vote-hash pass, and ONE
        cache-aware signature batch submit over the survivors — all from
        parsed columns (:mod:`hashgraph_tpu.bridge.columnar`), no Vote
        objects anywhere. The crypto is in flight on the verify pool when
        this returns, so a pipelined bridge connection submits frame
        k+1's prepass while frame k applies (the 3-stage wire pipeline).
        Safe to run before earlier queued frames apply because nothing
        here reads session state — the same order-invariance contract as
        :meth:`_vote_prepass_begin`, extended across session
        registration (slot resolution happens at apply time, in receive
        order, inside :meth:`ingest_wire_columnar`).

        Check precedence mirrors ``validate_vote`` exactly: empty owner,
        empty hash, empty signature, hash mismatch, then signature.
        Replay/expiry need the session record and stay in
        :meth:`ingest_wire_columnar`. ``offsets`` are the per-row spans
        into ``data`` — the signing payload of a canonical row is the
        PREFIX ``data[offsets[i] : offsets[i] + sign_len]``, so no
        re-encode ever happens."""
        from ..bridge import columnar as C

        k = len(cols)
        pre = np.zeros(k, np.int32)
        owner_len = cols[:, C.COL_OWNER_LEN]
        hash_len = cols[:, C.COL_HASH_LEN]
        sig_len = cols[:, C.COL_SIG_LEN]
        pre[owner_len == 0] = int(StatusCode.EMPTY_VOTE_OWNER)
        pre[(pre == 0) & (hash_len == 0)] = int(StatusCode.EMPTY_VOTE_HASH)
        pre[(pre == 0) & (sig_len == 0)] = int(StatusCode.EMPTY_SIGNATURE)
        live = pre == 0
        if live.any():
            digests = C.vote_hash_columns(data, cols)
            rows32 = np.nonzero(live & (hash_len == 32))[0]
            if rows32.size:
                gather = (
                    cols[rows32, C.COL_HASH_OFF, None]
                    + np.arange(32, dtype=np.int64)
                )
                mismatch = (data[gather] != digests[rows32]).any(axis=1)
                pre[rows32[mismatch]] = int(StatusCode.INVALID_VOTE_HASH)
            pre[live & (hash_len != 32)] = int(StatusCode.INVALID_VOTE_HASH)
        crypto_rows = np.nonzero(pre == 0)[0]
        if crypto_rows.size == 0:
            return WireVotePrepass(pre, crypto_rows, lambda: [], buf=buf)
        # Byte slices only for rows that reach crypto: one slice each for
        # owner / payload / signature — no decode, no re-encode (the
        # signing payload is a prefix of the canonical wire bytes).
        if buf is None:
            buf = data.tobytes()
        base = np.asarray(offsets, np.int64)[crypto_rows].tolist()
        row_l = cols[crypto_rows].tolist()
        owners: list[bytes] = []
        payloads: list[bytes] = []
        sigs: list[bytes] = []
        for start, c in zip(base, row_l):
            owners.append(
                buf[c[C.COL_OWNER_OFF]:c[C.COL_OWNER_OFF] + c[C.COL_OWNER_LEN]]
            )
            payloads.append(buf[start:start + c[C.COL_SIGN_LEN]])
            sigs.append(
                buf[c[C.COL_SIG_OFF]:c[C.COL_SIG_OFF] + c[C.COL_SIG_LEN]]
            )
        return WireVotePrepass(
            pre,
            crypto_rows,
            self._wire_crypto_begin(owners, payloads, sigs),
            buf=buf,
        )

    def _wire_crypto_begin(self, owners, payloads, sigs):
        """Cache-aware batched signature verify over byte triples (the
        object path's :meth:`_cached_verify_begin` minus Vote objects):
        dedups identical (payload, signature) items, consults the
        admission cache, submits ONE scheme batch over the misses, and
        returns a zero-arg collect -> verdicts aligned with the input."""
        k = len(owners)
        if self._verify_cache is None:
            pending = self._scheme.verify_batch_submit(owners, payloads, sigs)

            def _finish_uncached():
                with observed_span(
                    self.tracer, "engine.verify_batch", self._m_verify, votes=k
                ):
                    verdicts = pending.collect()
                self._note_verified(k)
                return list(verdicts)

            return _finish_uncached
        cache = self._verify_cache
        verdicts: list = [False] * k
        keys = [
            VerifiedVoteCache.key(payload, sig, self._verify_scheme_tag)
            for payload, sig in zip(payloads, sigs)
        ]
        miss_rows: dict[bytes, list[int]] = {}
        for i, (key, hit) in enumerate(zip(keys, cache.get_many(keys))):
            if hit is not MISS:
                verdicts[i] = hit
            else:
                miss_rows.setdefault(key, []).append(i)
        if not miss_rows:
            return lambda: verdicts
        rep = [rows[0] for rows in miss_rows.values()]
        pending = self._scheme.verify_batch_submit(
            [owners[i] for i in rep],
            [payloads[i] for i in rep],
            [sigs[i] for i in rep],
        )

        def _finish():
            with observed_span(
                self.tracer, "engine.verify_batch", self._m_verify,
                votes=len(rep),
            ):
                fresh = pending.collect()
            self._note_verified(len(rep))
            for (_, rows), verdict in zip(miss_rows.items(), fresh):
                for i in rows:
                    verdicts[i] = verdict
            cache.put_many(list(zip(miss_rows, fresh)))
            return verdicts

        return _finish

    def ingest_wire_columnar(
        self,
        scopes: list,
        scope_idx: np.ndarray,
        cols: np.ndarray,
        data: np.ndarray,
        offsets: np.ndarray,
        now: int,
        max_depth: int = 8,
        stage_seconds: "dict | None" = None,
        _prepass: "WireVotePrepass | None" = None,
        _buf: "bytes | None" = None,
    ) -> np.ndarray:
        """THE wire throughput path: fully *validated* mixed-scope ingest
        straight from parsed ``OP_VOTE_BATCH`` columns — hash, signature
        (batched, admission-cached), replay/expiry, and the dangling-vote
        guard all run without constructing a single ``Vote`` object, then
        the surviving rows land on the shared columnar apply pipeline
        (:meth:`_columnar_finish`) with wire retention on.

        Status-identical to ``ingest_votes`` (``pre_validated=False``)
        over the same decoded rows — the bridge's object path remains the
        parity oracle, property-tested in tests/test_wire_columnar.py and
        fuzz-tested in tests/test_wire_fuzz.py; divergences that remain
        (health admission granularity) are documented in PARITY.md.

        ``cols``/``data``/``offsets`` come from
        :func:`hashgraph_tpu.bridge.columnar.parse_vote_columns` over
        canonical rows ONLY (callers fall back to the object path for
        anything else). ``stage_seconds`` (optional dict) accumulates
        ``"crypto"`` and ``"apply"`` wall seconds for the bench's stage
        attribution. ``_prepass`` accepts a
        :meth:`wire_verify_begin` started earlier (the pipelined bridge
        starts it on the reader thread); default recomputes it inline.
        ``_buf`` accepts the vote region already materialized as bytes
        (a durable wrapper shares its WAL blob; the prepass's copy is
        reused the same way) — one ``tobytes()`` per frame, not three."""
        from ..bridge import columnar as C

        scope_idx = np.asarray(scope_idx, np.int64)
        offsets = np.asarray(offsets, np.int64)
        batch = len(cols)
        self.tracer.count("engine.votes_in", batch)
        if batch:
            self._m_votes_total.inc(batch)
            self._m_batch_size.observe(batch)
            self._m_wire_dispatches.inc()
            self._m_wire_apply_rows.inc(batch)
            flight_recorder.record("engine.ingest_wire_columnar", votes=batch)
        statuses = np.full(batch, int(StatusCode.SESSION_NOT_FOUND), np.int32)
        if batch == 0 and not self._multihost:
            return statuses
        pids = np.ascontiguousarray(cols[:, C.COL_PID])
        found, slots = self._resolve_slots_multi(scopes, scope_idx, pids)
        if self._multihost:
            # Misrouted rows reject BEFORE validation (SESSION_NOT_FOUND),
            # mirroring ingest_votes' precedence: the relay routes on this
            # status and a misrouted-but-invalid vote must look the same
            # as a misrouted-valid one.
            lo, hi = self._pool.local_slots()
            non_local = found & (slots >= 0) & ((slots < lo) | (slots >= hi))
            found &= ~non_local
        t0 = time.monotonic()
        prepass = (
            _prepass
            if _prepass is not None
            else self.wire_verify_begin(data, cols, offsets, buf=_buf)
        )
        buf = _buf if _buf is not None else prepass.buf
        if buf is None:
            buf = data.tobytes()
        prepass.buf = buf
        verdicts = prepass.collect()
        pre = prepass.pre_status
        valid = found.copy()
        fail = found & (pre != 0)
        statuses[fail] = pre[fail]
        valid &= pre == 0
        # Signature verdicts (validate_vote's injection semantics: an
        # exception verdict carries its own status code).
        sig_reject: list[tuple[int, int]] = []
        for row, verdict in zip(prepass.crypto_rows.tolist(), verdicts):
            if verdict is True:
                continue
            if isinstance(verdict, Exception):
                code = int(getattr(verdict, "code", StatusCode.SIGNATURE_SCHEME))
            else:
                code = int(StatusCode.INVALID_VOTE_SIGNATURE)
            sig_reject.append((row, code))
        for row, code in sig_reject:
            if valid[row]:
                statuses[row] = code
                valid[row] = False
        if stage_seconds is not None:
            stage_seconds["crypto"] = (
                stage_seconds.get("crypto", 0.0) + time.monotonic() - t0
            )
        t1 = time.monotonic()
        # Replay/expiry checks need the session record: per-UNIQUE-slot
        # timestamp lookup, then one vectorized compare per rule.
        ts_u64 = np.ascontiguousarray(cols[:, C.COL_TS]).view(np.uint64)
        rows_v = np.nonzero(valid)[0]
        admit_timeout = 0.0
        if rows_v.size:
            uniq = np.unique(slots[rows_v])
            creation = np.empty(len(uniq), np.uint64)
            expiry = np.empty(len(uniq), np.uint64)
            for j, slot in enumerate(uniq.tolist()):
                record = self._records[slot]
                creation[j] = record.proposal.timestamp
                expiry[j] = record.proposal.expiration_timestamp
                if record.config.consensus_timeout > admit_timeout:
                    admit_timeout = record.config.consensus_timeout
            pos = np.searchsorted(uniq, slots[rows_v])
            ts_rows = ts_u64[rows_v]
            old = ts_rows < creation[pos]
            expired = ~old & (
                (ts_rows > expiry[pos]) | (np.uint64(now) > expiry[pos])
            )
            statuses[rows_v[old]] = int(
                StatusCode.TIMESTAMP_OLDER_THAN_CREATION_TIME
            )
            statuses[rows_v[expired]] = int(StatusCode.VOTE_EXPIRED)
            valid[rows_v[old | expired]] = False
        self._wire_reject_health(buf, cols, found, statuses, now)
        self._wire_dangling_guard(buf, cols, slots, valid, statuses)
        # Voter interning: one gid per UNIQUE owner (vectorized when the
        # scheme's identities are fixed-width — the common case), then the
        # shared columnar apply with wire retention on.
        gids = self._wire_intern_gids(buf, cols, valid)
        values = cols[:, C.COL_VALUE] != 0
        statuses = self._columnar_finish(
            slots, valid, gids, values, now, max_depth, statuses,
            (data, offsets), wire_validated=True,
        )
        self._wire_track_chain(buf, cols, slots, offsets, statuses)
        self._wire_admit_health(
            buf, cols, scopes, scope_idx, slots, offsets, statuses,
            admit_timeout, now,
        )
        if stage_seconds is not None:
            stage_seconds["apply"] = (
                stage_seconds.get("apply", 0.0) + time.monotonic() - t1
            )
        return statuses

    def _wire_reject_health(self, buf, cols, found, statuses, now) -> None:
        """Scorecard attribution for wire-columnar validation rejects —
        the vectorized twin of the object path's per-vote
        ``_note_reject_health`` (same code set, same claimed-signer
        attribution), sliced from the frame only on the failure path."""
        if not self._health_live:
            return
        from ..bridge import columnar as C

        sig_codes = (
            int(StatusCode.INVALID_VOTE_SIGNATURE),
            int(StatusCode.INVALID_VOTE_HASH),
            int(StatusCode.SIGNATURE_SCHEME),
        )
        mask = found & (
            (statuses == sig_codes[0])
            | (statuses == sig_codes[1])
            | (statuses == sig_codes[2])
            | (statuses == int(StatusCode.VOTE_EXPIRED))
        )
        for row in np.nonzero(mask)[0].tolist():
            c = cols[row]
            owner = buf[c[C.COL_OWNER_OFF]:c[C.COL_OWNER_OFF] + c[C.COL_OWNER_LEN]]
            if not owner:
                continue
            if int(statuses[row]) == int(StatusCode.VOTE_EXPIRED):
                self.health.note_expired(owner, now)
            else:
                self.health.note_invalid_signature(owner, now)

    def _wire_dangling_guard(self, buf, cols, slots, valid, statuses) -> None:
        """The ingest_votes dangling-vote guard over columns: a
        first-time voter whose received_hash does not name the session's
        effective tail is rejected instead of appended — identical
        semantics (including the optimistic in-batch tail walk) to the
        object path on a fresh session. Unlike the legacy pre-validated
        columnar path, the guard STAYS armed across wire frames: the
        record's ``wire_tail``/``wire_seen`` continuity state (updated
        from ground-truth accepted rows in :meth:`_wire_track_chain`)
        carries the tail forward, so a dropped or reordered gossip frame
        rejects its dangling followers on every peer the same way —
        without this a storm could diverge peers into states anti-entropy
        cannot reconcile. Sessions whose retained wire came from the
        legacy permissive path (stale sync stamp) stay permissive, as
        documented in PARITY.md."""
        from ..bridge import columnar as C

        rows = np.nonzero(valid)[0]
        if rows.size == 0:
            return
        order = np.argsort(slots[rows], kind="stable")
        prev_slot = -1
        guard = False
        tail = b""
        seen: set = set()
        for i in rows[order].tolist():
            slot = int(slots[i])
            if slot != prev_slot:
                prev_slot = slot
                record = self._records[slot]
                if not record.retained_wire:
                    guard = True
                    tail = (
                        record.proposal.votes[-1].vote_hash
                        if record.proposal.votes
                        else b""
                    )
                    seen = set(record.votes)
                    if record.session is not None:
                        seen.update(record.session.tallies)
                        seen.update(record.session.votes)
                elif record.wire_only:
                    if record.wire_seen is None or record.wire_sync != (
                        len(record.retained_wire), len(record.scalar_seqs)
                    ):
                        # Scalar accepts or a watermark extension landed
                        # since the last wire frame: rebuild the
                        # continuity state from the merged accepted
                        # chain (decode is cached per growth).
                        self._resync_wire_chain(record)
                    guard = True
                    tail = record.wire_tail or b""
                    seen = set(record.wire_seen)
                    if record.session is not None:
                        seen.update(record.session.tallies)
                        seen.update(record.session.votes)
                else:
                    guard = False
            if not guard:
                continue
            c = cols[i]
            owner = buf[c[C.COL_OWNER_OFF]:c[C.COL_OWNER_OFF] + c[C.COL_OWNER_LEN]]
            if owner in seen:
                continue
            received = buf[c[C.COL_RECV_OFF]:c[C.COL_RECV_OFF] + c[C.COL_RECV_LEN]]
            if received and received != tail:
                statuses[i] = int(StatusCode.RECEIVED_HASH_MISMATCH)
                valid[i] = False
                self.tracer.count("engine.dangling_votes_rejected")
                continue
            tail = buf[c[C.COL_HASH_OFF]:c[C.COL_HASH_OFF] + c[C.COL_HASH_LEN]]
            seen.add(owner)

    def _accepted_vote_chain(self, record: "SessionRecord[Scope]") -> list:
        """The session's accepted votes in true arrival order, retained
        wire chunks and scalar accepts merged (no clones — callers read,
        never mutate). For wire_only records this IS the positional
        chain the watermark compares against."""
        retained = self._decoded_retained(record)
        scalar = record.proposal.votes
        if not retained:
            return scalar
        n_pre = len(scalar) - len(record.scalar_seqs)
        items: list[tuple[int, list]] = [(-1, scalar[:n_pre])] if n_pre else []
        items.extend(
            (seq, [vote])
            for seq, vote in zip(record.scalar_seqs, scalar[n_pre:])
        )
        items.extend(retained)
        items.sort(key=lambda t: t[0])
        return [vote for _, votes in items for vote in votes]

    def _resync_wire_chain(self, record: "SessionRecord[Scope]") -> None:
        """Rebuild the wire-guard continuity state from the merged
        accepted chain (after scalar accepts or a watermark extension
        touched a wire_only record)."""
        chain = self._accepted_vote_chain(record)
        record.wire_seen = {vote.vote_owner for vote in chain}
        record.wire_tail = chain[-1].vote_hash if chain else b""
        record.wire_sync = (
            len(record.retained_wire), len(record.scalar_seqs)
        )

    def _wire_track_chain(self, buf, cols, slots, offsets, statuses) -> None:
        """Post-apply continuity update: fold each slot's ACCEPTED rows
        (frame order) into the record's wire chain state — effective
        tail hash, accepted-owner set, and the sync stamp that proves no
        other path touched the record since."""
        from ..bridge import columnar as C

        ok_rows = np.nonzero(statuses == int(StatusCode.OK))[0]
        if ok_rows.size == 0:
            return
        order = np.argsort(slots[ok_rows], kind="stable")
        for i in ok_rows[order].tolist():
            record = self._records[int(slots[i])]
            if record.wire_seen is None:
                record.wire_seen = set(record.votes)
            c = cols[i]
            record.wire_seen.add(
                buf[c[C.COL_OWNER_OFF]:c[C.COL_OWNER_OFF] + c[C.COL_OWNER_LEN]]
            )
            record.wire_tail = (
                buf[c[C.COL_HASH_OFF]:c[C.COL_HASH_OFF] + c[C.COL_HASH_LEN]]
            )
            record.wire_sync = (
                len(record.retained_wire), len(record.scalar_seqs)
            )

    def _wire_intern_gids(self, buf, cols, valid) -> np.ndarray:
        """gid column for the apply stage: unique owners interned once
        each. Fixed-width identities (every real scheme) dedupe in one
        vectorized np.unique over an [N, L] byte matrix; mixed widths
        fall back to a memo dict."""
        from ..bridge import columnar as C

        batch = len(cols)
        gids = np.zeros(batch, np.int64)
        rows = np.nonzero(valid)[0]
        if rows.size == 0:
            return gids
        lens = cols[rows, C.COL_OWNER_LEN]
        width = int(lens[0])
        if (lens == width).all():
            data_arr = np.frombuffer(buf, np.uint8)
            gather = (
                cols[rows, C.COL_OWNER_OFF, None]
                + np.arange(width, dtype=np.int64)
            )
            matrix = data_arr[gather]
            uniq, inverse = np.unique(matrix, axis=0, return_inverse=True)
            uniq_gids = np.array(
                [self._pool.voter_gid(row.tobytes()) for row in uniq],
                np.int64,
            )
            gids[rows] = uniq_gids[inverse.reshape(-1)]
        else:
            memo: dict[bytes, int] = {}
            for i in rows.tolist():
                c = cols[i]
                owner = buf[c[C.COL_OWNER_OFF]:c[C.COL_OWNER_OFF] + c[C.COL_OWNER_LEN]]
                gid = memo.get(owner)
                if gid is None:
                    gid = memo[owner] = self._pool.voter_gid(owner)
                gids[i] = gid
        return gids

    def _wire_admit_health(
        self, buf, cols, scopes, scope_idx, slots, offsets, statuses,
        admit_timeout, now,
    ) -> None:
        """Post-apply health flush for the wire path: batched admission
        counts for accepted rows (the object path's ``note_admitted``)
        plus the equivocation probe over duplicate-shaped rejections —
        a differing vote_hash from an owner the session already tallied
        becomes a retained evidence pair, with the prior vote recovered
        from the session's scalar votes or its retained wire chunks."""
        if not self._health_live:
            return
        from ..bridge import columnar as C

        ok = statuses == int(StatusCode.OK)
        if ok.any():
            admit_counts: dict[bytes, int] = {}
            for row in np.nonzero(ok)[0].tolist():
                c = cols[row]
                owner = buf[c[C.COL_OWNER_OFF]:c[C.COL_OWNER_OFF] + c[C.COL_OWNER_LEN]]
                admit_counts[owner] = admit_counts.get(owner, 0) + 1
            self.health.note_admitted(
                admit_counts, now, timeout_hint=admit_timeout
            )
        cand = statuses == self._EQUIVOCATION_PROBE_CODES[0]
        for code in self._EQUIVOCATION_PROBE_CODES[1:]:
            cand |= statuses == code
        for row in np.nonzero(cand)[0].tolist():
            slot = int(slots[row])
            record = self._records[slot]
            c = cols[row]
            owner = buf[c[C.COL_OWNER_OFF]:c[C.COL_OWNER_OFF] + c[C.COL_OWNER_LEN]]
            vote_hash = buf[c[C.COL_HASH_OFF]:c[C.COL_HASH_OFF] + c[C.COL_HASH_LEN]]
            prior = record.votes.get(owner)
            prior_bytes = None
            if prior is not None and prior.vote_hash != vote_hash:
                prior_bytes = prior.encode()
            elif prior is None:
                for _seq, chunk in self._decoded_retained(record):
                    for v in chunk:
                        if v.vote_owner == owner:
                            if v.vote_hash != vote_hash:
                                prior_bytes = v.encode()
                            break
                    if prior_bytes is not None:
                        break
            if prior_bytes is not None:
                self.health.note_equivocation(
                    scopes[int(scope_idx[row])],
                    int(cols[row, C.COL_PID]),
                    prior_bytes,
                    buf[int(offsets[row]):int(offsets[row + 1])],
                    owner,
                    now,
                )

    def _columnar_apply(
        self,
        slots: np.ndarray,
        found: np.ndarray,
        voter_gids: np.ndarray,
        values: np.ndarray,
        now: int,
        max_depth: int,
        statuses: np.ndarray,
    ) -> np.ndarray:
        """Slot-resolved columnar pipeline shared by the single- and
        multi-scope entry points: gid/locality filters, host-spill tallies,
        lane resolution, bounded-depth pipelined device dispatches, round
        bookkeeping, and event emission."""
        # Gids must be LIVE current-generation identities (voter_gid):
        # out-of-range, freed, and stale-generation ids (held across a
        # release, even after the index was recycled to a new owner) all get
        # a typed per-row status on BOTH substrates — previously the spill
        # path raised IndexError mid-batch while the device path silently
        # accepted any integer as a fresh voter.
        if self._multihost:
            # Misrouted rows (device slots another process owns) report the
            # session as not found on this host; the relay routes by
            # is_local(). Host-spilled rows (slots < 0) are replicated
            # control-plane state and apply everywhere. This runs BEFORE the
            # gid check: a misrouted voter is typically not interned here,
            # and the relay must see the routing status, not an identity one.
            lo, hi = self._pool.local_slots()
            non_local = found & (slots >= 0) & ((slots < lo) | (slots >= hi))
            if non_local.any():
                statuses[non_local] = int(StatusCode.SESSION_NOT_FOUND)
                found = found & ~non_local

        bad_gid = ~self._pool.gids_live(voter_gids)
        if bad_gid.any():
            statuses[found & bad_gid] = int(StatusCode.EMPTY_VOTE_OWNER)
            found = found & ~bad_gid

        # Host-spilled sessions (negative slots): rare scalar fallback,
        # applied tally-only — fabricating unsigned Vote objects here would
        # poison the session's exportable chain (advisor r2 medium).
        wall = time.monotonic()
        host_rows = np.nonzero(found & (slots < 0))[0]
        for i in host_rows:
            slot = int(slots[i])
            record = self._records[slot]
            owner = self._pool.owner_of_gid(int(voter_gids[i]))
            was_active = record.session.state.is_active
            code, event = self._host_add_tally(
                record, owner, bool(values[i]), now
            )
            statuses[i] = code
            if code == int(StatusCode.OK):
                record.last_activity = now
                self._timelines.voted(slot, now, wall)
                self._m_votes_accepted.inc()
            self.tracer.count(
                "engine.votes_accepted", int(code == int(StatusCode.OK))
            )
            if was_active and not record.session.state.is_active:
                # Ownership-gated like events: host-spilled sessions are
                # replicated fleet-wide, decision metrics must not be.
                owned = self._owns_slot(slot)
                self._timelines.decided(
                    slot,
                    _OUTCOME_OF_STATE[state_code_of(record.session.state)],
                    now,
                    wall,
                    observe=owned,
                )
                if owned:
                    self._m_decisions.inc()
            self.tracer.count(
                "engine.transitions",
                int(was_active and not record.session.state.is_active),
            )
            if event is not None and self._owns_slot(slot):
                self._emit(record.scope, event)

        dev_mask = found & (slots >= 0)
        # Identity fast path: when EVERY row reaches the device (the
        # streaming steady state — no unknown sessions, no stale gids, no
        # spills), skip materializing the row-index array and the gathers
        # through it; ``sel`` below is then just ``order``.
        dev_rows = (
            None
            if len(dev_mask) and dev_mask.all()
            else np.nonzero(dev_mask)[0]
        )

        # ── Fused sorted-domain pipeline ───────────────────────────────
        # ONE stable slot-sort of the batch; grouping, lane assignment,
        # depth segmentation, and round bookkeeping all derive from the
        # sorted domain. (Previously each stage re-sorted: lanes_for_batch
        # unique+lexsort, group_batch argsort, one more argsort per depth
        # segment — ~3x the host time on multi-million-row batches.)
        def _group(s_sorted: np.ndarray):
            b = len(s_sorted)
            is_start = np.empty(b, bool)
            is_start[0] = True
            np.not_equal(s_sorted[1:], s_sorted[:-1], out=is_start[1:])
            starts_idx = np.nonzero(is_start)[0]
            grp = np.cumsum(is_start) - 1
            col = np.arange(b) - starts_idx[grp]
            counts = np.diff(np.append(starts_idx, b))
            return s_sorted[starts_idx], starts_idx, grp, col, counts

        order = np.empty(0, np.int64)
        sel = order  # statuses-row index per sorted item (= dev_rows[order])
        lanes_sorted = np.empty(0, np.int32)
        vals_sorted = np.empty(0, bool)
        uniq = starts_idx = grp_sorted = col_sorted = counts = None
        fast_lanes = False
        if dev_rows is None or dev_rows.size:
            dslots = slots if dev_rows is None else slots[dev_rows]
            dgids = voter_gids if dev_rows is None else voter_gids[dev_rows]
            # Grouped-stream fast path: a proposal-major batch (each slot's
            # rows contiguous, checked as "no slot starts two runs") is
            # already a valid sorted-domain order — the O(B log B) argsort
            # and its gathers vanish. Only probed when runs are few (the
            # run-start values' unique() would itself be a sort otherwise).
            ordered = False
            if len(dslots) > 1:
                run_starts = np.empty(len(dslots), bool)
                run_starts[0] = True
                np.not_equal(dslots[1:], dslots[:-1], out=run_starts[1:])
                n_runs = int(run_starts.sum())
                if n_runs * 4 <= len(dslots):
                    start_vals = dslots[run_starts]
                    ordered = len(np.unique(start_vals)) == n_runs
            else:
                ordered = True
            if ordered:
                order = np.arange(len(dslots), dtype=np.int64)
                sel = order if dev_rows is None else dev_rows
                s_sorted = dslots
            else:
                order = np.argsort(dslots, kind="stable")
                sel = order if dev_rows is None else dev_rows[order]
                s_sorted = dslots[order]
            uniq, starts_idx, grp_sorted, col_sorted, counts = _group(s_sorted)
            # ordered: dgids is already in sorted-domain order — masking it
            # avoids re-gathering what's in hand.
            gid_idx_sorted = (
                dgids & 0xFFFFFFFF if ordered else voter_gids[sel] & 0xFFFFFFFF
            )
            lanes_sorted = self._pool.fresh_lanes_grouped(
                s_sorted, gid_idx_sorted, col_sorted, uniq, counts
            )
            fast_lanes = lanes_sorted is not None
            if lanes_sorted is None:
                # General path (pre-voted slots or an in-batch duplicate
                # voter); assume_live: the gids_live gate above ran.
                lanes_sorted = self._pool.lanes_for_batch(
                    dslots, dgids, assume_live=True
                )[order]
            no_lane = lanes_sorted < 0
            if no_lane.any():
                statuses[sel[no_lane]] = int(
                    StatusCode.VOTER_CAPACITY_EXCEEDED
                )
                keep = ~no_lane
                order = order[keep]
                sel = sel[keep]
                s_sorted = s_sorted[keep]
                lanes_sorted = lanes_sorted[keep]
                if len(order):
                    uniq, starts_idx, grp_sorted, col_sorted, counts = _group(
                        s_sorted
                    )
            vals_sorted = (
                values
                if ordered and dev_rows is None and len(sel) == len(values)
                else values[sel]
            )

        # Dispatch plan. Preferred: ONE closed-form (scan-free) dispatch for
        # the whole batch — valid exactly when the fast lane path ran (fresh
        # slots, no duplicate voters) and every touched slot is still ACTIVE
        # (rare non-ACTIVE fresh slots: empty sessions decided by timeout).
        # The grid is [S, depth]-padded, so a cell-budget guard falls back
        # to the segmented scan when padding would blow up (one slot with a
        # huge chain amid many shallow ones). Fallback: bounded-depth scan
        # segmentation — in the sorted domain each slot's items are
        # contiguous and arrival-ordered, so segment k (votes
        # [k*D, (k+1)*D) of every slot) is a repeat/arange gather with no
        # per-segment re-sort.
        segs: list[tuple] = []  # (uniq_k, rows_k, cols_k, depth_k, idx_k, fresh)
        use_fresh = (
            fast_lanes
            and len(order) > 0
            and self._pool.fresh_ingest_viable(
                uniq, int(counts.max()), len(order)
            )
        )
        fleet_fresh = False
        if self._multihost:
            # Fleet agreement on the dispatch PLAN, not just the count: the
            # fresh and scan kernels are different global programs, so the
            # path is taken only when EVERY process votes yes (an empty
            # local batch votes yes if its pool supports the kernel — it
            # then dispatches one empty fresh call to hold the collective
            # cadence), AND the fleet-max grid shapes — which the dispatch
            # pads every process to — fit the cell budget.
            from jax.experimental import multihost_utils

            fresh_ok = (use_fresh or len(order) == 0) and getattr(
                self._pool, "supports_fresh_ingest", False
            )
            plan = np.array(
                [
                    1 if fresh_ok else 0,
                    len(uniq) if len(order) else 0,
                    int(counts.max()) if len(order) else 0,
                ],
                np.int64,
            )
            agreed_plan = multihost_utils.process_allgather(plan)
            use_fresh = bool(
                np.min(agreed_plan[..., 0])
            ) and self._pool.fresh_grid_within_budget(
                int(np.max(agreed_plan[..., 1])),
                int(np.max(agreed_plan[..., 2])),
            )
            fleet_fresh = use_fresh
            if use_fresh and len(order) == 0:
                empty = np.empty(0, np.int64)
                segs.append((empty, empty, empty, 0, empty, True))
        if use_fresh and len(order) > 0:
            self.tracer.count("engine.fresh_dispatches")
            segs.append(
                (
                    uniq,
                    grp_sorted,
                    col_sorted,
                    int(counts.max()),
                    np.arange(len(order), dtype=np.int64),
                    True,
                )
            )
        elif len(order):
            depth = int(counts.max())
            if depth > max_depth:
                d = max_depth
                for k in range(-(-depth // d)):
                    seg_mask = counts > k * d
                    g_starts = starts_idx[seg_mask] + k * d
                    g_lens = np.minimum(counts[seg_mask] - k * d, d)
                    m = int(g_lens.sum())
                    off = np.zeros(len(g_lens) + 1, np.int64)
                    np.cumsum(g_lens, out=off[1:])
                    local = np.arange(m, dtype=np.int64) - np.repeat(
                        off[:-1], g_lens
                    )
                    idx_k = np.repeat(g_starts, g_lens) + local
                    rows_k = np.repeat(
                        np.arange(int(seg_mask.sum()), dtype=np.int64), g_lens
                    )
                    # Uniform depth d (not g_lens.max()): a shallower final
                    # segment would give its output a different shape,
                    # splitting complete_all's single stacked readback into
                    # two transfers. Pad columns are valid=0, inert.
                    segs.append(
                        (uniq[seg_mask], rows_k, local, d, idx_k, False)
                    )
            else:
                segs.append(
                    (
                        uniq,
                        grp_sorted,
                        col_sorted,
                        depth,
                        np.arange(len(order), dtype=np.int64),
                        False,
                    )
                )
        if self._multihost and not fleet_fresh:
            # Collective cadence for the scan plan: every process must
            # issue the same number of dispatches this call, empty ones
            # included. (The fresh plan is exactly one dispatch per process
            # by construction, so it needs no second collective.)
            from jax.experimental import multihost_utils

            agreed = multihost_utils.process_allgather(
                np.array([len(segs)], np.int64)
            )
            empty = np.empty(0, np.int64)
            for _ in range(int(np.max(agreed)) - len(segs)):
                segs.append((empty, empty, empty, 0, empty, False))
        if not segs:
            return statuses

        pendings = []
        orig_of = []  # statuses rows per pending, in dispatch item order
        for uniq_k, rows_k, cols_k, depth_k, idx_k, fresh_k in segs:
            pendings.append(
                self._pool.ingest_async_grouped(
                    uniq_k,
                    rows_k,
                    cols_k,
                    depth_k,
                    lanes_sorted[idx_k],
                    vals_sorted[idx_k],
                    now,
                    fresh=fresh_k,
                )
            )
            orig_of.append(sel[idx_k])
        with observed_span(
            self.tracer,
            "engine.device_ingest",
            self._m_device,
            votes=int(len(order)),
        ):
            results = self._pool.complete_all(pendings)

        wall = time.monotonic()
        accepted = 0
        reached_transitions: list[tuple[int, int]] = []
        n_transitions = 0
        for orig_rows, (seg_statuses, transitions) in zip(orig_of, results):
            statuses[orig_rows] = seg_statuses
            accepted += int(np.sum(seg_statuses == int(StatusCode.OK)))
            n_transitions += len(transitions)
            for slot, new_state in transitions:
                if new_state in (STATE_REACHED_YES, STATE_REACHED_NO):
                    reached_transitions.append((slot, new_state))
                outcome = _OUTCOME_OF_STATE.get(new_state)
                if outcome is not None:
                    self._timelines.decided(slot, outcome, now, wall)
        self.tracer.count("engine.votes_accepted", accepted)
        self.tracer.count("engine.transitions", n_transitions)
        self._m_votes_accepted.inc(accepted)
        self._m_decisions.inc(n_transitions)

        # Round + late-vote bookkeeping per touched slot, via bincount over
        # the sorted-domain group index (no re-sort; totals are
        # order-independent).
        if len(orig_of) == 1 and len(orig_of[0]) == len(order):
            # Single dispatch covering the whole sorted domain (the fresh
            # fast path): its output IS the sorted-domain statuses — skip
            # the O(B) re-gather through statuses.
            sorted_statuses = results[0][0]
        else:
            sorted_statuses = (
                statuses[sel] if len(order) else np.empty(0, np.int32)
            )
        if len(order):
            ok_m = sorted_statuses == int(StatusCode.OK)
            if ok_m.any():
                cnt = np.bincount(grp_sorted[ok_m], minlength=len(uniq))
                for g in np.nonzero(cnt)[0].tolist():
                    slot = int(uniq[g])
                    record = self._records[slot]
                    record.bump_round(int(cnt[g]))
                    record.last_activity = now
                    self._timelines.voted(slot, now, wall)

        # Events: one ConsensusReached per deciding transition plus one per
        # late (ALREADY_REACHED) vote — same per-session counts as the
        # scalar path; cross-session order is per-slot grouped.
        for slot, new_state in reached_transitions:
            record = self._records[slot]
            self._emit(
                record.scope,
                ConsensusReached(
                    proposal_id=record.proposal.proposal_id,
                    result=new_state == STATE_REACHED_YES,
                    timestamp=now,
                ),
            )
        if len(order):
            ar_m = sorted_statuses == int(StatusCode.ALREADY_REACHED)
            if ar_m.any():
                cnt = np.bincount(grp_sorted[ar_m], minlength=len(uniq))
                for g in np.nonzero(cnt)[0].tolist():
                    slot = int(uniq[g])
                    record = self._records[slot]
                    state = self._pool.state_of(slot)
                    event = ConsensusReached(
                        proposal_id=record.proposal.proposal_id,
                        result=state == STATE_REACHED_YES,
                        timestamp=now,
                    )
                    for _ in range(int(cnt[g])):
                        self._emit(record.scope, event)
        return statuses

    def _drop_pid_cache(self, scope: Scope) -> None:
        """Invalidate pid-resolution caches after a membership change in
        ``scope`` (register/evict/delete). The fused multi-scope cache is
        cleared outright — its tuples may span any scopes."""
        self._pid_tables.pop(scope, None)
        self._pid_hashes.pop(scope, None)
        self._fused_pid_cache.clear()

    def _fused_pid_lookup(self, scopes: list) -> "_PidLookup | None":
        """One composite-key hash for a whole multi-scope resolution:
        key = scope_ordinal << 32 | pid. Registered pids always fit u32
        (generate_id / batch draw / wire decode all mask to 32 bits), so
        the composite is injective; if a table somehow holds a wider pid,
        returns None and the caller falls back to per-scope probing.
        One build pass + one probe pass replaces len(scopes) of each —
        at the 256-scope churn shape that is ~100ms/wave of numpy
        fixed-overhead eliminated."""
        cache_key = tuple(scopes)
        cached = self._fused_pid_cache.get(cache_key)
        if cached is not None:
            return cached
        key_parts: list[np.ndarray] = []
        val_parts: list[np.ndarray] = []
        for k, scope in enumerate(scopes):
            pids, slot_arr = self._pid_table(scope)
            if len(pids) and (
                int(pids.min()) < 0 or (int(pids.max()) >> 32) != 0
            ):
                return None
            key_parts.append(pids | (np.int64(k) << np.int64(32)))
            val_parts.append(slot_arr)
        lookup = _PidLookup(
            np.concatenate(key_parts) if key_parts else np.empty(0, np.int64),
            np.concatenate(val_parts) if val_parts else np.empty(0, np.int64),
        )
        if len(self._fused_pid_cache) >= 8:  # bound distinct tuples per epoch
            self._fused_pid_cache.clear()
        self._fused_pid_cache[cache_key] = lookup
        return lookup

    def _pid_lookup(self, scope: Scope) -> "_PidLookup":
        """Vectorized pid -> slot hash for one scope (lazily rebuilt with
        the sorted table). Columnar resolution uses this instead of
        searchsorted: numpy's searchsorted walks O(log P) scalar probes per
        row (~70 ms for the 655k-row config-3 batch), while the
        open-addressing probe loop is ~1.3 vectorized gathers per row."""
        lookup = self._pid_hashes.get(scope)
        if lookup is None:
            pids_sorted, slots_sorted = self._pid_table(scope)
            lookup = _PidLookup(pids_sorted, slots_sorted)
            self._pid_hashes[scope] = lookup
        return lookup

    def _pid_table(self, scope: Scope) -> tuple[np.ndarray, np.ndarray]:
        """(proposal_ids, slots) membership arrays for one scope — the
        vectorized replacement for per-vote dict lookups; rebuilt lazily
        after any membership change. Unordered: both consumers
        (_pid_lookup's hash build, _draw_unique_pids' np.isin) are
        order-independent, so the old O(P log P) sort was dead weight."""
        table = self._pid_tables.get(scope)
        if table is None:
            scope_slots = self._scopes.get(scope, [])
            pids = np.fromiter(
                (
                    self._records[s].proposal.proposal_id
                    for s in scope_slots
                ),
                np.int64,
                len(scope_slots),
            )
            slot_arr = np.fromiter(scope_slots, np.int64, len(scope_slots))
            table = (pids, slot_arr)
            self._pid_tables[scope] = table
        return table

    def _host_add_vote(
        self, record: SessionRecord[Scope], vote: Vote, now: int
    ) -> tuple[int, ConsensusEvent | None]:
        """Apply one validated vote to a host-spilled session, mapping scalar
        outcomes to the same status codes the device path produces (parity:
        the scalar session IS the oracle the kernels are fuzzed against).
        Returns (status code, event-to-emit-or-None); the caller queues the
        event so emission order follows per-vote arrival order even when a
        batch mixes substrates."""
        code, event = self._host_apply(record, lambda s: s.add_vote(vote, now), now)
        if code == int(StatusCode.OK):
            # add_vote appended to the shared proposal's vote list.
            record.scalar_seqs.append(record.next_arrival_seq())
        return code, event

    def _host_add_tally(
        self, record: SessionRecord[Scope], owner: bytes, value: bool, now: int
    ) -> tuple[int, ConsensusEvent | None]:
        """Columnar counterpart of _host_add_vote: apply one tally to a
        host-spilled session (session.add_tally — no Vote object is
        fabricated, so the session's exportable chain stays valid)."""
        return self._host_apply(
            record, lambda s: s.add_tally(owner, value, now), now
        )

    def _host_apply(
        self, record: SessionRecord[Scope], mutate, now: int
    ) -> tuple[int, ConsensusEvent | None]:
        """Shared outcome mapping for host-spilled mutations: run the
        session mutation, translate scalar outcomes to the device path's
        status codes, and surface the transition event (if any) for the
        caller to queue in arrival order."""
        session = record.session
        already = session.state.is_reached
        try:
            transition = mutate(session)
        except ConsensusError as exc:
            return int(exc.code), None
        event = None
        if transition.is_reached:
            event = ConsensusReached(
                proposal_id=record.proposal.proposal_id,
                result=transition.reached,
                timestamp=now,
            )
        return (
            int(StatusCode.ALREADY_REACHED) if already else int(StatusCode.OK),
            event,
        )

    def _host_timeout(self, record: SessionRecord[Scope], now: int) -> int:
        """Timeout decision for a host-spilled session; returns the new dense
        state code (same contract as pool.timeout rows). Mirrors the scalar
        service (reference: src/service.rs:323-373): idempotent for decided
        sessions, Failed sessions stay Failed."""
        session = record.session
        if session.state.is_active:
            result = session.decide_now(True)
            session.state = (
                ConsensusState.reached(result)
                if result is not None
                else ConsensusState.failed()
            )
        return state_code_of(session.state)

    # ── Timeouts ───────────────────────────────────────────────────────

    def handle_consensus_timeout(self, scope: Scope, proposal_id: int, now: int) -> bool:
        """App-driven timeout for one session
        (reference: src/service.rs:323-373). Idempotent for decided sessions;
        raises InsufficientVotesAtTimeout (after emitting ConsensusFailed)
        when undecidable."""
        slot = self._index.get((scope, proposal_id))
        if slot is None:
            # A demoted (idle) session can still be timed out by the
            # embedder: page it back in and fire as if it never left.
            slot = self._tier_lookup_promote(scope, proposal_id)
            if slot is None:
                raise SessionNotFound()
        # Timeout calls carry the embedder's clock even when vote traffic
        # has stopped — exactly when the liveness watchdog needs a
        # current tick to measure silence against.
        self.health.tick(now)
        record = self._records[slot]
        owned = self._owns_slot(slot)
        was_active = self._state_code(record) == STATE_ACTIVE
        if was_active:
            # A fired timeout is the session's deciding activity: the GC
            # TTL for decided sessions measures from here.
            record.last_activity = now
        if record.session is not None:
            new_state = self._host_timeout(record, now)
        else:
            transitions = self._pool.timeout([slot])
            if transitions:
                [(_, new_state)] = transitions
            else:
                # Multi-host collective: this process joined the dispatch
                # but another process owns the slot; pool.timeout synced the
                # state mirror, so the result is readable (and the owner
                # emitted the event).
                new_state = self._pool.state_of(slot)
        if was_active and owned:
            # Only count timeouts that actually fired, on the owning
            # process only: the call is idempotent for already-decided
            # sessions (polls must not inflate the counter), and in a
            # multi-host fleet every process runs this collective — a
            # metrics sum across processes must report one firing.
            self._m_timeouts.inc()
        if was_active and self._health_live:
            # Actually-fired timeout: back off the scope's learned
            # timeout. Ownership-independent — each process keeps its own
            # advisory book, and identical collectives keep them aligned.
            self._adaptive.on_timeout(scope, self._scope_configs.get(scope))
        outcome = _OUTCOME_OF_STATE.get(new_state)
        if outcome is not None:
            # Idempotent for sessions that already decided by votes (the
            # store ignores a second outcome); the latency observation is
            # ownership-gated like events, the timeline stamp is not.
            self._timelines.decided(
                slot, outcome, now, time.monotonic(), by_timeout=True,
                observe=owned,
            )
            if trace_store.enabled and was_active and record.trace is not None:
                trace_store.instant(
                    "consensus.timeout_decided",
                    record.trace,
                    peer=self._trace_peer,
                    attrs={"outcome": outcome},
                )
        if new_state in (STATE_REACHED_YES, STATE_REACHED_NO):
            result = new_state == STATE_REACHED_YES
            if owned:
                self._emit(
                    scope,
                    ConsensusReached(
                        proposal_id=proposal_id, result=result, timestamp=now
                    ),
                )
            return result
        if owned:
            self._emit(
                scope, ConsensusFailedEvent(proposal_id=proposal_id, timestamp=now)
            )
        raise InsufficientVotesAtTimeout()

    def sweep_timeouts(
        self, now: int, _gc_sink: "list | None" = None
    ) -> list[tuple[Scope, int, bool | None]]:
        """Engine-level convenience absent from the reference (its embedder
        schedules per-proposal timers): fire the timeout decision for every
        still-undecided session whose expiration has passed, in one device
        dispatch. Returns (scope, proposal_id, result-or-None) per swept
        session and emits the same events as per-session timeouts. Only
        ACTIVE sessions are swept: a FAILED session's tallies are frozen (the
        ingest kernel rejects votes on non-ACTIVE slots) so re-sweeping it
        would deterministically re-fail and re-emit forever.

        Multi-host: collective (same cadence everywhere). The state mirror
        is synced first so every process computes the IDENTICAL expired set
        — remote slots' mirrored states lag between collectives by design
        (zero DCN on the ingest path)."""
        if self._multihost:
            self._pool.sync_states()
        # Expired idle sessions sleeping in the demoted tier must fire
        # their timeouts exactly like live ones: page them in first.
        self._promote_expired_tier(now)
        expired: list[int] = []
        host_expired: list[int] = []
        for slot, record in self._records.items():
            if record.session is not None:
                if (
                    record.session.state.is_active
                    and record.proposal.expiration_timestamp <= now
                ):
                    host_expired.append(slot)
            elif self._pool.state_of(slot) == STATE_ACTIVE:
                if self._pool.meta(slot).expiry <= now:
                    expired.append(slot)
        self.tracer.count("engine.timeout_sweeps")
        self.tracer.count("engine.timeouts_fired", len(expired) + len(host_expired))
        self.health.tick(now)  # watchdog clock advances with the sweep cadence
        if expired or host_expired:
            flight_recorder.record(
                "engine.sweep", fired=len(expired) + len(host_expired)
            )
        wall = time.monotonic()
        out: list[tuple[Scope, int, bool | None]] = []
        # pool.timeout is collective on a multi-host pool and returns only
        # this process's slots; host-spilled sessions advance identically on
        # every process but their events/results belong to process 0.
        swept = [(slot, st, True) for slot, st in self._pool.timeout(expired)] + [
            (
                slot,
                self._host_timeout(self._records[slot], now),
                self._owns_slot(slot),
            )
            for slot in host_expired
        ]
        # Fired count and latency observations are ownership-gated like
        # events: a multi-host fleet's metrics sum must report each swept
        # session once, not once per process.
        self._m_timeouts.inc(sum(1 for _, _, owned in swept if owned))
        for slot, new_state, owned in swept:
            # The fired timeout is the session's deciding activity (GC
            # TTLs measure from it); ownership-independent like the
            # timeline stamp.
            self._records[slot].last_activity = now
            if self._health_live:
                swept_scope = self._records[slot].scope
                self._adaptive.on_timeout(
                    swept_scope, self._scope_configs.get(swept_scope)
                )
            outcome = _OUTCOME_OF_STATE.get(new_state)
            if outcome is not None:
                self._timelines.decided(
                    slot, outcome, now, wall, by_timeout=True, observe=owned
                )
                if trace_store.enabled:
                    tctx = self._records[slot].trace
                    if tctx is not None:
                        trace_store.instant(
                            "consensus.timeout_decided",
                            tctx,
                            peer=self._trace_peer,
                            attrs={"outcome": outcome},
                        )
            if not owned:
                continue
            record = self._records[slot]
            pid = record.proposal.proposal_id
            if new_state in (STATE_REACHED_YES, STATE_REACHED_NO):
                result = new_state == STATE_REACHED_YES
                self._emit(
                    record.scope,
                    ConsensusReached(proposal_id=pid, result=result, timestamp=now),
                )
                out.append((record.scope, pid, result))
            else:
                self._emit(
                    record.scope,
                    ConsensusFailedEvent(proposal_id=pid, timestamp=now),
                )
                out.append((record.scope, pid, None))
        # The engine-wide tier cadence rides the sweep the embedder
        # already drives: demote idle sessions, GC decided ones past
        # their per-scope TTLs (no-op without ScopeConfig tier knobs).
        self.lifecycle_sweep(now, _gc_sink=_gc_sink)
        return out

    # ── Queries (reference: src/storage.rs:112-180 derived helpers) ────

    def _decoded_retained(
        self, record: SessionRecord[Scope]
    ) -> list[tuple[int, list[Vote]]]:
        """Decode a record's retained wire bytes once per growth, keeping
        each chunk's arrival seq; exports clone the cached Vote objects so
        callers can't mutate the cache."""
        n = len(record.retained_wire)
        if n == 0:
            return []
        if record.retained_cache is None or record.retained_cache[0] != n:
            chunks: list[tuple[int, list[Vote]]] = []
            for seq, data, offs in record.retained_wire:
                chunks.append(
                    (
                        seq,
                        [
                            Vote.decode(data[offs[k] : offs[k + 1]])
                            for k in range(len(offs) - 1)
                        ],
                    )
                )
            record.retained_cache = (n, chunks)
        return record.retained_cache[1]

    def _materialized_proposal(self, record: SessionRecord[Scope]) -> Proposal:
        """Export view of a record's proposal: retained columnar wire bytes
        (if any) are decoded and merged with the scalar-ingested votes in
        TRUE arrival order (per-record seq: one tick per scalar accept, one
        per retained chunk), so a session fed through both paths still
        re-gossips a chain-valid vote list."""
        proposal = record.proposal.clone()
        retained = self._decoded_retained(record)
        if retained:
            scalar = proposal.votes
            # Votes embedded at registration predate the arrival clock and
            # keep their leading position (seq -1, stable sort).
            n_pre = len(scalar) - len(record.scalar_seqs)
            items: list[tuple[int, list[Vote]]] = [
                (-1, scalar[:n_pre])
            ] if n_pre else []
            items.extend(
                (seq, [vote])
                for seq, vote in zip(record.scalar_seqs, scalar[n_pre:])
            )
            items.extend(
                (seq, [v.clone() for v in votes]) for seq, votes in retained
            )
            items.sort(key=lambda t: t[0])
            proposal.votes = [v for _, votes in items for v in votes]
        return proposal

    def get_proposal(self, scope: Scope, proposal_id: int) -> Proposal:
        return self._materialized_proposal(self._get_record(scope, proposal_id))

    def get_consensus_result(self, scope: Scope, proposal_id: int) -> bool | None:
        """None while active; raises ConsensusFailed for a failed session —
        the same contract as the storage derived helper
        (reference: src/storage.rs:112-126), so the framework's two front
        doors agree."""
        record = self._get_record(scope, proposal_id)
        state = self._state_code(record)
        if state == STATE_REACHED_YES:
            return True
        if state == STATE_REACHED_NO:
            return False
        if state == STATE_FAILED:
            raise ConsensusFailed()
        return None

    def _tier_sessions_where(self, scope: Scope, want_state: "int | None"):
        """Decode a scope's demoted sessions (``want_state`` filters on
        the stored snapshot state code; None = all) WITHOUT promoting —
        enumeration reads pass through the tier, only point reads and
        mutations page sessions back in."""
        entries = self._tier.get(scope)
        if not entries:
            return
        from ..sync.snapshot import decode_session_item

        for entry in entries.values():
            if want_state is not None and entry.state != want_state:
                continue
            _, session = decode_session_item(entry.item)
            yield entry, session

    def get_active_proposals(self, scope: Scope) -> list[Proposal]:
        out = [
            self._materialized_proposal(r)
            for r in self._scope_records(scope)
            if self._state_code(r) == STATE_ACTIVE
        ]
        out.extend(
            session.proposal
            for _, session in self._tier_sessions_where(scope, 0)
        )
        return out

    def get_reached_proposals(self, scope: Scope) -> list[tuple[Proposal, bool]]:
        out = []
        for r in self._scope_records(scope):
            state = self._state_code(r)
            if state in (STATE_REACHED_YES, STATE_REACHED_NO):
                out.append((self._materialized_proposal(r), state == STATE_REACHED_YES))
        out.extend(
            (session.proposal, bool(entry.result))
            for entry, session in self._tier_sessions_where(scope, 1)
        )
        return out

    def get_scope_stats(self, scope: Scope) -> ConsensusStats:
        """reference: src/service_stats.rs:32-59 (zeros for unknown scope).
        Demoted sessions count from their stored state metadata — no
        decode, no promotion."""
        stats = ConsensusStats()
        for r in self._scope_records(scope):
            stats.total_sessions += 1
            state = self._state_code(r)
            if state == STATE_ACTIVE:
                stats.active_sessions += 1
            elif state == STATE_FAILED:
                stats.failed_sessions += 1
            else:
                stats.consensus_reached += 1
        entries = self._tier.get(scope)
        if entries:
            for entry in entries.values():
                stats.total_sessions += 1
                if entry.state == 0:
                    stats.active_sessions += 1
                elif entry.state == 2:
                    stats.failed_sessions += 1
                else:
                    stats.consensus_reached += 1
        return stats

    def proposal_timeline(self, scope: Scope, proposal_id: int) -> dict | None:
        """Lifecycle timeline readout for one proposal: created /
        first_vote / quorum / decided logical timestamps, outcome
        (yes/no/failed + by_timeout), and the derived wall-clock latencies
        (``decision_latency_s`` is what feeds the
        ``hashgraph_decision_latency_seconds`` histogram). Falls back to
        the bounded finished-timeline ring for recently deleted/evicted
        sessions; None when the proposal was never seen (or aged out)."""
        slot = self._index.get((scope, proposal_id))
        if slot is not None:
            tl = self._timelines.get(slot)
            if tl is not None and tl.proposal_id == proposal_id:
                return tl.as_dict()
        tl = self._timelines.find(scope, proposal_id)
        return tl.as_dict() if tl is not None else None

    def trace_context_of(self, scope: Scope, proposal_id: int):
        """The distributed :class:`~hashgraph_tpu.obs.trace.TraceContext`
        bound to a live session (None when untracked/untraced). The bridge
        serializes this onto CREATE_PROPOSAL / CAST_VOTE responses so
        embedders can carry it to the peers they gossip to."""
        slot = self._index.get((scope, proposal_id))
        if slot is None:
            return None
        return self._records[slot].trace

    def explain_decision(self, scope: Scope, proposal_id: int) -> dict:
        """Decision provenance: one JSON-ready verdict answering *why and
        how* this proposal is in its current state.

        Reconstructs the accepted vote chain (chain order, per-peer
        contributions — columnar tallies included), the quorum arithmetic
        (``div_ceil(2n, 3)`` exact path / ``ceil(n·t)`` general path /
        n≤2 unanimity, with the observed yes/no/silent counts and an
        independent re-run of the decision kernel as a cross-check), the
        lifecycle timeline phases, and the bound distributed-trace
        identity. Raises SessionNotFound for unknown proposals; a
        :class:`~hashgraph_tpu.wal.DurableEngine` overlays the WAL LSN
        watermark. Exposed over the bridge as ``OP_EXPLAIN``
        (``BridgeClient.explain``)."""
        record = self._get_record(scope, proposal_id)
        session = self.export_session(scope, proposal_id)
        proposal = session.proposal
        n = proposal.expected_voters_count
        thr = session.config.consensus_threshold
        state = self._state_code(record)
        status = {
            STATE_ACTIVE: "active",
            STATE_FAILED: "failed",
            STATE_REACHED_YES: "reached",
            STATE_REACHED_NO: "reached",
        }[state]
        result = (
            state == STATE_REACHED_YES
            if state in (STATE_REACHED_YES, STATE_REACHED_NO)
            else None
        )
        timeline = self.proposal_timeline(scope, proposal_id)
        by_timeout = bool(timeline and timeline.get("by_timeout"))
        yes, total = session.tally_counts()
        if n <= 2:
            # Unanimity rule (reference: src/utils.rs:239-244).
            rule = "unanimity (n <= 2)"
            required = choice_required = n
        else:
            required = calculate_required_votes(n, thr)
            choice_required = calculate_threshold_based_value(n, thr)
            # EXACTLY the comparison calculate_threshold_based_value
            # makes, so the stated rule always names the path that
            # produced the numbers beside it.
            rule = (
                "div_ceil(2n, 3)"
                if abs(thr - _TWO_THIRDS) < _F64_EPSILON
                else f"ceil(n * {thr!r})"
            )
        # Independent re-run of the decision kernel over the reconstructed
        # session (the same decide_now the scalar substrate runs, so the
        # cross-check can never drift from the real semantics): must agree
        # with the recorded outcome for vote-decided sessions (None for
        # still-active / failed ones).
        recomputed = session.decide_now(by_timeout)
        chain = [
            {
                "position": i,
                "owner": v.vote_owner.hex(),
                "vote": v.vote,
                "vote_id": v.vote_id,
                "timestamp": v.timestamp,
                "parent_hash": v.parent_hash.hex(),
                "vote_hash": v.vote_hash.hex(),
            }
            for i, v in enumerate(proposal.votes)
        ]
        contributions = {
            v.vote_owner.hex(): {"vote": v.vote, "via": "vote"}
            for v in session.votes.values()
        }
        for owner, value in session.tallies.items():
            contributions[owner.hex()] = {"vote": value, "via": "tally"}
        trace = None
        if record.trace is not None:
            trace = {
                "traceparent": record.trace.to_traceparent(),
                "trace_id": record.trace.trace_id.hex(),
                "span_id": record.trace.span_id.hex(),
            }
        return {
            "scope": str(scope),
            "proposal_id": proposal.proposal_id,
            "status": status,
            "result": result,
            "by_timeout": by_timeout,
            "proposal": {
                "name": proposal.name,
                "owner": proposal.proposal_owner.hex(),
                "round": proposal.round,
                "created_at": record.created_at,
                "expiration_timestamp": proposal.expiration_timestamp,
                "liveness_criteria_yes": proposal.liveness_criteria_yes,
            },
            "quorum": {
                "expected_voters": n,
                "threshold": thr,
                "rule": rule,
                "required_votes": required,
                "required_choice_votes": choice_required,
                "yes": yes,
                "no": total - yes,
                "total": total,
                "silent": max(n - total, 0),
                "reached": status == "reached",
                "recomputed_result": recomputed,
            },
            "vote_chain": chain,
            "contributions": contributions,
            "timeline": timeline,
            "trace": trace,
        }

    def health_report(self, now: int | None = None) -> dict:
        """Consensus-health snapshot: per-peer scorecards (graded), the
        retained equivocation/fork evidence, liveness-watchdog state, and
        the firing alert rules — :meth:`HealthMonitor.snapshot` plus this
        engine's signer identity. ``now`` is the embedder's logical tick
        (default: the latest tick the monitor has seen — HTTP scrapes
        have no embedder clock). Exposed over the bridge as ``OP_HEALTH``
        (``BridgeClient.health``); a
        :class:`~hashgraph_tpu.wal.DurableEngine` overlays the WAL LSN
        watermark. Deliberately NOT engine-locked: the monitor has its
        own lock, so scrape threads never contend with ingest."""
        out = self.health.snapshot(now)
        out["identity"] = self._signer.identity().hex()
        return out

    def occupancy(self) -> dict:
        """Capacity snapshot: live sessions, device slots claimed vs pool
        capacity, and host-spilled sessions (negative synthetic ids hold
        no pool row). The same numbers the scrape-time gauges sample,
        exposed as one consistent read for fleet routers and capacity
        planners (parallel.fleet's per-shard breakdown)."""
        with self._lock:
            slots = list(self._records)
            tier_sessions = self._tier_count
            tier_bytes = self._tier_bytes
            demotions = self._tier_demotions
            promotions = self._tier_promotions
            gc = self._tier_gc
        device_used = sum(1 for s in slots if s >= 0)
        return {
            "live_sessions": len(slots),
            "device_slots_used": device_used,
            "host_spilled": len(slots) - device_used,
            "capacity": self._pool.capacity,
            "voter_capacity": self._pool.voter_capacity,
            # Demoted tier: population + serialized footprint, and this
            # engine's lifetime demote/promote/GC traffic.
            "tier_sessions": tier_sessions,
            "tier_bytes": tier_bytes,
            "tier_demotions_total": demotions,
            "tier_promotions_total": promotions,
            "tier_gc_total": gc,
        }

    def session_keys(self) -> "list[tuple[Scope, int]]":
        """Every tracked ``(scope, proposal_id)`` in one consistent read —
        the enumeration a gossip node needs to bootstrap its anti-entropy
        bookkeeping after installing state it did not ingest itself
        (catch-up, storage load). Demoted sessions are tracked sessions:
        their keys enumerate too (anti-entropy watermarks must cover
        them, or a peer would re-push state this engine already holds)."""
        with self._lock:
            keys = list(self._index.keys())
            for scope, entries in self._tier.items():
                keys.extend((scope, pid) for pid in entries)
            return keys

    def export_session(self, scope: Scope, proposal_id: int) -> ConsensusSession:
        """Materialise a scalar ConsensusSession from the pooled state —
        the bridge back to ConsensusStorage backends (checkpoint/interop).

        Pooled sessions read their columnar tallies back from the device
        (lane -> owner via the gid registry); rows whose verbatim wire bytes
        were retained export as real signed votes instead of tallies, so the
        re-gossip capability survives a save/load round-trip."""
        return self._export_record(self._get_record(scope, proposal_id))

    def _export_record(
        self, record: SessionRecord[Scope], row: "dict | None" = None
    ) -> ConsensusSession:
        """Body of :meth:`export_session` over an already-resolved record.
        ``row`` optionally injects the slot's device row (vote_mask /
        vote_val) pre-fetched by a batched ``pool.read_slots`` gather — the
        demotion path exports many sessions per device round-trip."""
        retained_votes = [
            vote for _, votes in self._decoded_retained(record) for vote in votes
        ]
        if record.session is not None:
            session = record.session.clone()
            if retained_votes:
                # The materialized proposal merges both paths' votes in
                # arrival order; the dict/tally bookkeeping follows.
                session.proposal = self._materialized_proposal(record)
                for vote in retained_votes:
                    # A retained signed vote supersedes its tally entry.
                    session.tallies.pop(vote.vote_owner, None)
                    if vote.vote_owner not in session.votes:
                        session.votes[vote.vote_owner] = vote.clone()
            return session
        votes = {k: v.clone() for k, v in record.votes.items()}
        tallies: dict[bytes, bool] = {}
        if row is None:
            row = self._pool.read_slot(record.slot)
        lane_owners = self._pool.lane_owners(record.slot)
        for lane in np.nonzero(row["vote_mask"])[0]:
            owner = lane_owners.get(int(lane))
            if owner is None or owner in votes:
                continue  # scalar votes already carry this participant
            tallies[owner] = bool(row["vote_val"][lane])
        for vote in retained_votes:
            tallies.pop(vote.vote_owner, None)
            votes.setdefault(vote.vote_owner, vote.clone())
        return ConsensusSession(
            # The materialized proposal embeds retained votes in chain
            # order, so re-gossip capability survives save -> load.
            proposal=self._materialized_proposal(record),
            state=_STATE_TO_SCALAR[self._pool.state_of(record.slot)],
            votes=votes,
            created_at=record.created_at,
            config=record.config,
            tallies=tallies,
        )

    # ── Checkpoint / resume (SURVEY §5: host storage is the source of
    #    truth; device tensors are a rebuildable cache) ─────────────────

    def save_to_storage(self, storage) -> int:
        """Persist every tracked session (and scope configs) into a
        ConsensusStorage backend — the reference's durability abstraction
        (src/storage.rs:18-22). Returns the number of sessions written.

        Demoted sessions are persisted too, decoded straight from their
        canonical tier bytes — snapshot builds and fingerprints therefore
        carry the identical session items whether a session is live or
        demoted (the codec round-trips byte-identically; the tier/untier'd
        fingerprint-equality property pins it)."""
        count = 0
        for scope, slots in self._scopes.items():
            for slot in slots:
                record = self._records[slot]
                storage.save_session(
                    scope, self.export_session(scope, record.proposal.proposal_id)
                )
                count += 1
        for scope in self._tier:
            for _, session in self._tier_sessions_where(scope, None):
                storage.save_session(scope, session)
                count += 1
        for scope, config in self._scope_configs.items():
            storage.set_scope_config(scope, config.clone())
        return count

    def load_from_storage(self, storage) -> int:
        """Rebuild pool state from a ConsensusStorage backend: every stored
        session is loaded into a fresh slot with its original created_at,
        tallies, lanes, and lifecycle state (no re-validation — storage is
        trusted, exactly as the reference trusts its own persisted sessions).
        Returns the number of sessions loaded."""
        count = 0
        scopes = storage.list_scopes() or []
        for scope in scopes:
            config = storage.get_scope_config(scope)
            if config is not None:
                self._scope_configs[scope] = config.clone()
            sessions = storage.list_scope_sessions(scope) or []
            for session in sorted(sessions, key=lambda s: s.created_at):
                if (scope, session.proposal.proposal_id) in self._index or (
                    self._tier_has(scope, session.proposal.proposal_id)
                ):
                    continue  # already tracked (idempotent restore)
                self._register_session(scope, session.clone(), session.created_at)
                count += 1
        return count

    def delete_scope(self, scope: Scope) -> None:
        """Drop every session and the config of a scope
        (reference: src/storage.rs:92 delete_scope semantics)."""
        self.delete_scopes([scope])

    def delete_scopes(self, scopes: "list[Scope]") -> None:
        """Batched delete_scope: ONE pool release dispatch (and one lane
        retirement pass) covers every scope's sessions — the teardown half
        of the config-5 churn shape (mirror of create_proposals_multi,
        which batches the registration half). Observable semantics are
        identical to calling delete_scope once per scope."""
        all_slots: list[int] = []
        for scope in scopes:
            slots = self._scopes.pop(scope, [])
            for slot in slots:
                record = self._records.pop(slot)
                del self._index[(scope, record.proposal.proposal_id)]
                self._timelines.forget(slot)
            # Host spills (slot < 0) have no pool slot to release.
            all_slots.extend(s for s in slots if s >= 0)
            self._scope_configs.pop(scope, None)
            self._drop_pid_cache(scope)
            # The demoted tier drops with the scope, like live sessions.
            entries = self._tier.pop(scope, None)
            if entries:
                self._tier_count -= len(entries)
                self._tier_bytes -= sum(len(e.item) for e in entries.values())
                for pid, entry in entries.items():
                    if entry.state == 0:
                        self._tier_active.pop((scope, pid), None)
                self._tier_pid_arrays.pop(scope, None)
            self._pinned_scopes.discard(scope)
            self._scope_seq.pop(scope, None)
        self._pool.release(all_slots)

    # ── Tiered session lifecycle (demote / demand-page / GC) ───────────
    #
    # The ARIES / Raft log-compaction frame (PAPERS.md): the WAL already
    # makes any in-memory representation a rebuildable cache, so a
    # decided/idle session can drop its device slot and host record and
    # live on as its canonical serialized bytes (the PR-8 snapshot item
    # format — the exact signed wire, so promotion re-registers without
    # re-signing and fingerprints hash the same items either way). Every
    # public surface reads through the tier: point reads and mutations
    # page the session back in, enumerations/stats/save_to_storage read
    # the tier without promoting — callers observe an untier'd engine.

    def _tier_has(self, scope: Scope, proposal_id: int) -> bool:
        entries = self._tier.get(scope)
        return entries is not None and proposal_id in entries

    def _tier_lookup_promote(self, scope: Scope, proposal_id: int) -> "int | None":
        """Slot of a demoted session after paging it back in; None when
        the session is not in the tier (the caller's miss is real)."""
        entries = self._tier.get(scope)
        if entries is None or proposal_id not in entries:
            return None
        return self._promote_key(scope, proposal_id)

    def demote_session(self, scope: Scope, proposal_id: int) -> bool:
        """Move one session out of its device slot / host record into the
        compact serialized tier. Idempotent: False when already demoted.
        Raises SessionNotFound for unknown sessions. The session stays
        fully addressable — any read or late vote transparently promotes
        it back (see the section comment)."""
        if self._multihost:
            raise RuntimeError(
                "session tiering is not supported on multi-host pools"
            )
        if self._tier_has(scope, proposal_id):
            return False
        slot = self._index.get((scope, proposal_id))
        if slot is None:
            raise SessionNotFound()
        self._demote_records(scope, [slot])
        return True

    # Pool lifecycle code -> (snapshot state code, result).
    _POOL_TO_SNAP = {
        STATE_ACTIVE: (0, False),
        STATE_REACHED_YES: (1, True),
        STATE_REACHED_NO: (1, False),
        STATE_FAILED: (2, False),
    }

    def _demote_records(self, scope: Scope, slots: "list[int]") -> int:
        """Batched demotion of live slots belonging to one scope: ONE
        device gather for every pooled slot's tally row, one pool release
        dispatch, one pid-cache drop. Plain pooled sessions (the churn
        steady state) encode field-direct — per-call memoized scope/config
        bytes, tallies straight off the gathered row, the live proposal's
        wire bytes — with no intermediate ConsensusSession; byte-identity
        with the session-object codec is pinned by the tier fingerprint
        property suite."""
        from ..sync.snapshot import (
            _STATE_CODE,
            encode_session_fields,
            encode_session_item,
        )
        from ..wal import format as F

        records = [self._records[s] for s in slots]
        rows: dict[int, dict] = {}
        pool_states: dict[int, int] = {}
        pooled = [r for r in records if r.session is None]
        if pooled:
            pooled_slots = [r.slot for r in pooled]
            batch = self._pool.read_slots(pooled_slots)
            states = self._pool.states_of(pooled_slots).tolist()
            masks = batch["vote_mask"]
            vals = batch["vote_val"]
            for k, r in enumerate(pooled):
                rows[r.slot] = {
                    "vote_mask": masks[k],
                    "vote_val": vals[k],
                }
                pool_states[r.slot] = states[k]
        entries = self._tier.setdefault(scope, {})
        scope_bytes = F.encode_scope(scope)
        cfg_bytes: dict[int, bytes] = {}  # id(config) -> canonical encode
        # Vote-free proposals sharing every field but the id (the churn
        # steady state: whole waves minted from one request shape) encode
        # via ONE cached (head, tail) split per shape + a per-item id
        # varint — Proposal.encode's nine-field walk was the single
        # biggest demotion cost.
        split_cache: dict[tuple, tuple[bytes, bytes]] = {}
        from ..wire import _U32_MASK as _PIDM
        from ..wire import _encode_uint_field
        for record in records:
            pid = record.proposal.proposal_id
            if record.session is None and not record.retained_wire:
                # Fast path: encode from the record's parts directly.
                state, result = self._POOL_TO_SNAP[pool_states[record.slot]]
                row = rows[record.slot]
                lane_owners = self._pool.lane_owners(record.slot)
                votes = record.votes
                tallies: dict[bytes, bool] = {}
                # Voter lanes are few (<= voter_capacity): a plain list
                # walk beats np.nonzero on tiny rows.
                val_row = row["vote_val"].tolist()
                for lane, on in enumerate(row["vote_mask"].tolist()):
                    if not on:
                        continue
                    owner = lane_owners.get(lane)
                    if owner is None or owner in votes:
                        continue
                    tallies[owner] = bool(val_row[lane])
                config_bytes = cfg_bytes.get(id(record.config))
                if config_bytes is None:
                    config_bytes = F.encode_consensus_config(record.config)
                    cfg_bytes[id(record.config)] = config_bytes
                p = record.proposal
                if not p.votes:
                    shape = (
                        p.name,
                        p.payload,
                        p.proposal_owner,
                        p.expected_voters_count,
                        p.round,
                        p.timestamp,
                        p.expiration_timestamp,
                        p.liveness_criteria_yes,
                    )
                    parts = split_cache.get(shape)
                    if parts is None:
                        parts = p.encode_split()
                        split_cache[shape] = parts
                    buf = bytearray(parts[0])
                    _encode_uint_field(buf, 12, p.proposal_id & _PIDM)
                    buf += parts[1]
                    proposal_wire = bytes(buf)
                else:
                    proposal_wire = p.encode()
                item = encode_session_fields(
                    scope_bytes,
                    state,
                    result,
                    record.created_at,
                    config_bytes,
                    tallies,
                    proposal_wire,
                )
            else:
                session = self._export_record(record, row=rows.get(record.slot))
                item = encode_session_item(scope, session)
                state = _STATE_CODE[session.state.kind]
                result = bool(session.state.result)
            entries[pid] = _TierEntry(
                item,
                state,
                result,
                record.created_at,
                record.seq,
                record.proposal.expiration_timestamp,
                record.last_activity,
            )
            self._tier_count += 1
            self._tier_bytes += len(item)
            if state == 0:
                # Idle-but-active: the timeout sweep must still find it.
                self._tier_active[(scope, pid)] = (
                    record.proposal.expiration_timestamp
                )
        self._drop_live_slots(scope, slots)
        self._tier_pid_arrays.pop(scope, None)
        n = len(records)
        self._tier_demotions += n
        self._m_tier_demotions.inc(n)
        self.tracer.count("engine.tier_demotions", n)
        return n

    def _promote_key(self, scope: Scope, proposal_id: int) -> "int | None":
        """Page one demoted session back in: decode the stored item bytes
        and re-register on the live substrate (device slot, or the
        host-spilled negative-slot path for sessions the pool geometry
        cannot hold — tally-carrying ones included). The session keeps its
        original created_at / LRU rank / idle clock, so demote→promote is
        invisible to eviction and TTL policies."""
        from ..sync.snapshot import decode_session_item

        entries = self._tier[scope]
        entry = entries.pop(proposal_id)
        if not entries:
            del self._tier[scope]
        self._tier_count -= 1
        self._tier_bytes -= len(entry.item)
        if entry.state == 0:
            self._tier_active.pop((scope, proposal_id), None)
        self._tier_pid_arrays.pop(scope, None)
        _, session = decode_session_item(entry.item)
        self._promoting = True
        try:
            self._register_session(scope, session, entry.created_at)
        finally:
            self._promoting = False
        self._tier_promotions += 1
        self._m_tier_promotions.inc()
        self.tracer.count("engine.tier_promotions")
        slot = self._index.get((scope, proposal_id))
        if slot is None:
            return None  # lost the per-scope LRU ranking outright
        record = self._records[slot]
        record.last_activity = entry.last_activity
        record.seq = entry.seq
        return slot

    def _promote_expired_tier(self, now: int) -> None:
        """Page back every ACTIVE demoted session whose expiry has passed
        so the timeout sweep fires it exactly as if it had never left.
        Scans only the (small) active-tier side map, never the decided
        mass."""
        if not self._tier_active:
            return
        due = [
            key for key, expiry in self._tier_active.items() if expiry <= now
        ]
        for scope, pid in due:
            if self._tier_has(scope, pid):
                self._promote_key(scope, pid)

    def _promote_columnar_misses(
        self, scopes: list, scope_idx, proposal_ids: np.ndarray,
        found: np.ndarray,
    ) -> bool:
        """Demand-page demoted sessions hit by a columnar batch: check the
        unresolved rows against the tier and promote any hits. Returns
        True when a promotion happened (the caller re-resolves — the pid
        caches were rebuilt by registration). Free when the tier is empty;
        otherwise per-MISS Python only, never per-row."""
        if not self._tier:
            return False
        miss = np.nonzero(~found)[0]
        if miss.size == 0:
            return False
        promoted = False
        seen: set = set()
        idx_list = None if scope_idx is None else scope_idx
        for i in miss.tolist():
            scope = scopes[0] if idx_list is None else scopes[int(idx_list[i])]
            pid = int(proposal_ids[i])
            key = (scope, pid)
            if key in seen:
                continue
            seen.add(key)
            entries = self._tier.get(scope)
            if entries is not None and pid in entries:
                self._promote_key(scope, pid)
                promoted = True
        return promoted

    def _drop_live_slots(self, scope: Scope, slots: "list[int]") -> None:
        """Shared live-slot teardown (cap eviction / TTL GC / demotion):
        untrack records, forget timelines, filter the scope list, release
        pool slots, drop the pid caches — ONE copy of the sequence, so a
        future bookkeeping field cannot be dropped from just one site."""
        gone = set(slots)
        for slot in slots:
            record = self._records.pop(slot)
            del self._index[(scope, record.proposal.proposal_id)]
            self._timelines.forget(slot)
        live = self._scopes.get(scope)
        if live is not None:
            self._scopes[scope] = [s for s in live if s not in gone]
        release = [s for s in slots if s >= 0]
        if release:
            self._pool.release(release)
        self._drop_pid_cache(scope)

    def _gc_live(self, scope: Scope, slots: "list[int]") -> int:
        """Garbage-collect decided live sessions past their per-scope
        ``evict_decided_after`` TTL: dropped outright (session, slot,
        timeline), exactly like a per-scope-cap eviction but policy-driven."""
        self._drop_live_slots(scope, slots)
        n = len(slots)
        self._tier_gc += n
        self._m_tier_gc.inc(n)
        self.tracer.count("engine.tier_gc", n)
        return n

    def _gc_tier(self, scope: Scope, pids: "list[int]") -> int:
        """Garbage-collect demoted decided sessions past the TTL."""
        entries = self._tier[scope]
        for pid in pids:
            entry = entries.pop(pid)
            self._tier_count -= 1
            self._tier_bytes -= len(entry.item)
        if not entries:
            del self._tier[scope]
        self._tier_pid_arrays.pop(scope, None)
        n = len(pids)
        self._tier_gc += n
        self._m_tier_gc.inc(n)
        self.tracer.count("engine.tier_gc", n)
        return n

    def lifecycle_sweep(self, now: int, _gc_sink: "list | None" = None) -> dict:
        """Apply every scope's tier TTL policies (ScopeConfig
        ``demote_after`` / ``evict_decided_after``) at the embedder's
        logical clock: GC decided/failed sessions past the eviction TTL
        (live or already demoted), then demote sessions idle past the
        demotion TTL. Runs automatically at the end of every
        :meth:`sweep_timeouts` (the engine-wide cadence embedders already
        drive); callable standalone for a custom cadence. Pinned scopes
        (:meth:`pin_scope` — fleet migration freeze) and scopes without
        TTL knobs are untouched. Returns ``{demoted, gc_live, gc_tier}``.

        ``_gc_sink`` (private) collects the GC'd ``(scope, pid)`` keys —
        a DurableEngine logs them as the KIND_GC outcome record. During
        WAL replay the whole sweep is a no-op (set_replay_mode): TTL
        decisions ride idle clocks a snapshot restore does not carry, so
        recovery applies the live run's logged outcome instead of
        re-deriving the policy. A freshly recovered engine's sessions
        restart their idle clocks from created_at (or their replayed
        activity) — demotion may then run early, which is invisible, and
        decided-session GC may collect somewhat earlier than the
        pre-crash clock would have, which is the documented
        retention-policy semantics across restarts."""
        out = {"demoted": 0, "gc_live": 0, "gc_tier": 0}
        if self._multihost or not self._lifecycle_live:
            return out  # replicated control plane / WAL replay
        for scope, config in list(self._scope_configs.items()):
            demote_after = config.demote_after
            evict_after = config.evict_decided_after
            if (demote_after is None and evict_after is None) or (
                scope in self._pinned_scopes
            ):
                continue
            records = self._records
            if evict_after is not None:
                # Cheap TTL filter first (one attribute compare per live
                # record); the state check — a batched host-mirror gather
                # for pooled records — runs on the survivors only.
                cutoff = now - evict_after
                cand = [
                    s
                    for s in self._scopes.get(scope, [])
                    if records[s].last_activity <= cutoff
                ]
                gc_slots = []
                if cand:
                    pooled = [s for s in cand if records[s].session is None]
                    pooled_state = (
                        dict(zip(pooled, self._pool.states_of(pooled).tolist()))
                        if pooled
                        else {}
                    )
                    for s in cand:
                        state = pooled_state.get(s)
                        if state is None:
                            state = state_code_of(records[s].session.state)
                        if state != STATE_ACTIVE:
                            gc_slots.append(s)
                if gc_slots:
                    if _gc_sink is not None:
                        _gc_sink.extend(
                            (scope, records[s].proposal.proposal_id)
                            for s in gc_slots
                        )
                    out["gc_live"] += self._gc_live(scope, gc_slots)
                entries = self._tier.get(scope)
                if entries:
                    dead = [
                        pid
                        for pid, e in entries.items()
                        if e.state != 0 and e.last_activity <= cutoff
                    ]
                    if dead:
                        if _gc_sink is not None:
                            _gc_sink.extend((scope, pid) for pid in dead)
                        out["gc_tier"] += self._gc_tier(scope, dead)
            if demote_after is not None:
                cutoff = now - demote_after
                idle = [
                    s
                    for s in self._scopes.get(scope, [])
                    if records[s].last_activity <= cutoff
                ]
                if idle:
                    out["demoted"] += self._demote_records(scope, idle)
        if out["demoted"] or out["gc_live"] or out["gc_tier"]:
            flight_recorder.record("engine.lifecycle_sweep", **out)
        return out

    def gc_sessions(self, keys: "list[tuple[Scope, int]]") -> int:
        """Apply an exact GC outcome: drop each ``(scope, pid)`` — live
        or demoted — counting it as tier GC. Unknown keys are skipped
        (idempotent). This is the replay entry point for KIND_GC records
        (the live sweep's logged outcome), usable by embedders as an
        explicit per-session retirement too."""
        applied = 0
        by_scope_live: dict[Scope, list[int]] = {}
        by_scope_tier: dict[Scope, list[int]] = {}
        for scope, pid in keys:
            slot = self._index.get((scope, pid))
            if slot is not None:
                by_scope_live.setdefault(scope, []).append(slot)
            elif self._tier_has(scope, pid):
                by_scope_tier.setdefault(scope, []).append(pid)
        for scope, slots in by_scope_live.items():
            applied += self._gc_live(scope, slots)
        for scope, pids in by_scope_tier.items():
            applied += self._gc_tier(scope, pids)
        return applied

    def pin_scope(self, scope: Scope) -> None:
        """Exclude a scope from the lifecycle sweep's demote/GC policies
        (idempotent). The fleet/federation routers pin a shard's scopes
        for the duration of a live migration so nothing pages mid-flip."""
        self._pinned_scopes.add(scope)

    def unpin_scope(self, scope: Scope) -> None:
        self._pinned_scopes.discard(scope)

    def _taken_pids(self, scope: Scope) -> np.ndarray:
        """Every proposal id currently claimed in ``scope`` — live AND
        demoted — for batch id draws (a fresh id colliding with a demoted
        session would alias two sessions onto one key at promotion)."""
        live = self._pid_table(scope)[0]
        entries = self._tier.get(scope)
        if not entries:
            return live
        tier = self._tier_pid_arrays.get(scope)
        if tier is None:
            tier = np.fromiter(entries.keys(), np.int64, len(entries))
            self._tier_pid_arrays[scope] = tier
        return np.concatenate([live, tier])

    # ── Scope config (reference: src/service.rs:375-484) ───────────────

    def scope(self, scope: Scope):
        """Fluent per-scope configuration builder, same surface as the
        scalar service (reference: src/service.rs:558-668)."""
        from ..service import ScopeConfigBuilderWrapper

        existing = self._scope_configs.get(scope)
        builder = (
            ScopeConfigBuilder.from_existing(existing)
            if existing is not None
            else ScopeConfigBuilder()
        )
        return ScopeConfigBuilderWrapper(self, scope, builder)

    def set_scope_config(self, scope: Scope, config: ScopeConfig) -> None:
        config.validate()
        self._scope_configs[scope] = config

    def get_scope_config(self, scope: Scope) -> ScopeConfig | None:
        return self._scope_configs.get(scope)

    def adaptive_timeout(self, scope: Scope) -> float:
        """The consensus timeout the embedder should schedule next for
        ``scope``, in seconds: the learned value when the scope declared
        ``timeout_min``/``timeout_max`` bounds, else the scope's static
        ``default_timeout`` (or the gossipsub default) — exactly the
        reference behavior. Advisory only: timers stay embedder-owned
        (reference: src/lib.rs:15-34)."""
        cfg = self._scope_configs.get(scope)
        learned = self._adaptive.current(scope, cfg)
        if learned is not None:
            return learned
        return cfg.default_timeout if cfg is not None else DEFAULT_TIMEOUT_SECONDS

    def adaptive_timeout_snapshot(self) -> dict:
        """Learner introspection (per-scope learned values + counters)."""
        return self._adaptive.snapshot()

    # ScopeConfigBuilderWrapper terminal hooks (shared with the service).
    def _initialize_scope(self, scope: Scope, config: ScopeConfig) -> None:
        self.set_scope_config(scope, config)

    def _update_scope_config(self, scope: Scope, config: ScopeConfig) -> None:
        """Create-default-then-mutate-then-validate, matching
        InMemoryConsensusStorage.update_scope_config
        (reference: src/storage.rs:366-375)."""
        existing = self._scope_configs.get(scope, ScopeConfig())
        existing.network_type = config.network_type
        existing.default_consensus_threshold = config.default_consensus_threshold
        existing.default_timeout = config.default_timeout
        existing.default_liveness_criteria_yes = config.default_liveness_criteria_yes
        existing.max_rounds_override = config.max_rounds_override
        existing.demote_after = config.demote_after
        existing.evict_decided_after = config.evict_decided_after
        existing.decide_p99_ms = config.decide_p99_ms
        existing.timeout_min = config.timeout_min
        existing.timeout_max = config.timeout_max
        existing.validate()
        self._scope_configs[scope] = existing

    def _resolve_config(
        self,
        scope: Scope,
        proposal_override: ConsensusConfig | None,
        proposal: Proposal | None,
    ) -> ConsensusConfig:
        """Same precedence as the service: explicit override > scope config >
        gossipsub default; timeout from the proposal's expiration window
        unless overridden; liveness always from the proposal
        (reference: src/service.rs:440-484)."""
        has_override = proposal_override is not None
        if proposal_override is not None:
            base = proposal_override
        else:
            scope_config = self._scope_configs.get(scope)
            base = (
                ConsensusConfig.from_scope_config(scope_config)
                if scope_config is not None
                else ConsensusConfig.gossipsub()
            )
        if proposal is None:
            return base
        if has_override:
            timeout_seconds = base.consensus_timeout
        elif proposal.expiration_timestamp > proposal.timestamp:
            timeout_seconds = float(proposal.expiration_timestamp - proposal.timestamp)
        else:
            timeout_seconds = base.consensus_timeout
        return ConsensusConfig(
            consensus_threshold=base.consensus_threshold,
            consensus_timeout=timeout_seconds,
            max_rounds=base.max_rounds,
            use_gossipsub_rounds=base.use_gossipsub_rounds,
            liveness_criteria=proposal.liveness_criteria_yes,
        )

    # ── Internals ──────────────────────────────────────────────────────

    def _state_code(self, record: SessionRecord[Scope]) -> int:
        """Dense lifecycle state regardless of substrate: host-mirrored pool
        state for pooled records, scalar state for host-spilled ones."""
        if record.session is not None:
            return state_code_of(record.session.state)
        return self._pool.state_of(record.slot)

    def _get_record(self, scope: Scope, proposal_id: int) -> SessionRecord[Scope]:
        slot = self._index.get((scope, proposal_id))
        if slot is None:
            # Demand-page: a point read on a demoted session promotes it
            # back transparently (get_result / EXPLAIN / export / gossip
            # reconstruction all land here).
            slot = self._tier_lookup_promote(scope, proposal_id)
            if slot is None:
                raise SessionNotFound()
        return self._records[slot]

    def _scope_records(self, scope: Scope) -> list[SessionRecord[Scope]]:
        return [self._records[s] for s in self._scopes.get(scope, [])]

    def _evict_for(self, scope: Scope, now: int) -> bool:
        """LRU-by-created_at eviction beyond the per-scope cap
        (reference: src/service.rs:512-522), applied for an incoming session
        stamped ``created_at=now`` *before* it is allocated: keep the newest
        ``max`` of incumbents+newcomer (ties favor incumbents, matching the
        insert-then-trim stable sort). Evicts surplus incumbents; returns
        True when the newcomer itself loses the ranking and must not be
        tracked.

        Demoted sessions are incumbents too: they count against the cap
        and evict on the same ranking (ordered by their per-scope ``seq``,
        which reconstructs the original insertion order even after a
        demote→promote round-trip re-appended a record), so a tiered
        engine evicts exactly the sessions its untier'd twin would."""
        slots = self._scopes.get(scope, [])
        tier_entries = self._tier.get(scope)
        n_tier = len(tier_entries) if tier_entries else 0
        if len(slots) + n_tier + 1 <= self._max_sessions_per_scope:
            return False
        # (created_at, seq, is_tier, key): seq-ascending reproduces the
        # per-scope insertion order; the newcomer's infinite seq loses
        # created_at ties to every incumbent (insert-then-trim order).
        items = [
            (self._records[s].created_at, self._records[s].seq, False, s)
            for s in slots
        ]
        if tier_entries:
            items.extend(
                (e.created_at, e.seq, True, pid)
                for pid, e in tier_entries.items()
            )
        newcomer = (now, float("inf"), False, None)
        items.append(newcomer)
        items.sort(key=lambda t: t[1])
        items.sort(key=lambda t: t[0], reverse=True)
        keep = items[: self._max_sessions_per_scope]
        evicted = items[self._max_sessions_per_scope :]
        evicted_slots = [k for _, _, is_tier, k in evicted if not is_tier and k is not None]
        evicted_pids = [k for _, _, is_tier, k in evicted if is_tier]
        if evicted_slots:
            self._drop_live_slots(scope, evicted_slots)
        if evicted_pids:
            for pid in evicted_pids:
                entry = tier_entries.pop(pid)
                self._tier_count -= 1
                self._tier_bytes -= len(entry.item)
                if entry.state == 0:
                    self._tier_active.pop((scope, pid), None)
            if not tier_entries:
                del self._tier[scope]
            self._tier_pid_arrays.pop(scope, None)
        return newcomer not in keep

    def _emit(self, scope: Scope, event: ConsensusEvent) -> None:
        self._event_bus.publish(scope, event)

    # ── Multi-host ownership (parallel/multihost.py contract) ──────────

    def _owns_replicated_event(self) -> bool:
        """Events arising from replicated, not-slot-owned work — proposal
        loads and host-spilled sessions — are emitted by process 0 only in
        multi-host mode, so a fleet of engine front-ends never
        double-publishes."""
        return self._process_zero

    def _owns_slot(self, slot: int) -> bool:
        """EVENT-emission ownership of one session. Single-host pools own
        everything. On a multi-host pool a device slot belongs to the
        process whose local range holds it; host-spilled sessions
        (replicated on every process) belong to process 0."""
        if not self._multihost:
            return True
        if slot < 0:
            return self._process_zero
        lo, hi = self._pool.local_slots()
        return lo <= slot < hi

    def is_local(self, scope: Scope, proposal_id: int) -> bool:
        """Routing query for multi-host embedders: should THIS process
        apply the session's votes? Device-pooled sessions: the slot-owning
        process only (route to it). Host-spilled sessions are replicated
        control-plane state: True on EVERY process — the relay must deliver
        their votes fleet-wide (like proposals) so the replicas advance
        identically; their events still come from process 0 only."""
        slot = self._index.get((scope, proposal_id))
        if slot is None:
            raise SessionNotFound()
        if slot < 0:
            return True
        return self._owns_slot(slot)


class _PidLookup:
    """Open-addressing proposal-id -> slot hash with fully vectorized
    probing. Fibonacci hashing, power-of-two size, load factor <= 0.5, so
    probe chains are short; both build and lookup run as numpy passes over
    shrinking active sets (no per-row Python)."""

    _GOLDEN = np.uint64(0x9E3779B97F4A7C15)

    def __init__(self, pids: np.ndarray, slots: np.ndarray):
        n = max(len(pids), 1)
        size = 1
        while size < 2 * n:
            size <<= 1
        self._size = size
        self._shift = np.uint64(64 - (size.bit_length() - 1))
        self._mask = np.int64(size - 1)
        self.keys = np.full(size, -1, np.int64)
        self.vals = np.zeros(size, np.int64)
        if len(pids) == 0:
            return
        rem_pids = np.asarray(pids, np.int64)
        rem_slots = np.asarray(slots, np.int64)
        if (rem_pids == -1).any():
            raise ValueError("proposal id -1 collides with the hash sentinel")
        h = self._bucket(rem_pids)
        while rem_pids.size:
            # A bucket can be contested by several pending keys: the first
            # occupant wins, the rest advance one step (linear probing).
            empty = self.keys[h] == -1
            _, first = np.unique(h, return_index=True)
            win = np.zeros(len(h), bool)
            win[first] = True
            place = empty & win
            self.keys[h[place]] = rem_pids[place]
            self.vals[h[place]] = rem_slots[place]
            rest = ~place
            h = (h[rest] + 1) & self._mask
            rem_pids = rem_pids[rest]
            rem_slots = rem_slots[rest]

    def _bucket(self, q: np.ndarray) -> np.ndarray:
        return (
            (q.astype(np.uint64) * self._GOLDEN) >> self._shift
        ).astype(np.int64)

    def lookup(self, q: np.ndarray) -> tuple[np.ndarray, np.ndarray]:
        """Returns (found bool[B], slot int64[B]; 0 where not found)."""
        q = np.asarray(q, np.int64)
        batch = len(q)
        if batch >= 512:
            # Fused native probe (one C pass per query, GIL released) —
            # the numpy loop below pays ~12 array passes per probe round.
            from .. import native as _native

            res = _native.pid_lookup(self.keys, self.vals, int(self._shift), q)
            if res is not None:
                return res
        found = np.zeros(batch, bool)
        out = np.zeros(batch, np.int64)
        # Any int64 key hashes fine (uint64 cast); only -1 must be excluded
        # or it would match the empty-bucket sentinel and "resolve" to
        # slot 0. (-1 is also rejected at build, so it can never be stored.)
        active = np.nonzero(q != -1)[0]
        h = self._bucket(q[active])
        while active.size:
            k = self.keys[h]
            hit = k == q[active]
            hit &= k != -1  # never match the empty sentinel
            if hit.any():
                rows = active[hit]
                found[rows] = True
                out[rows] = self.vals[h[hit]]
            cont = ~hit & (k != -1)
            active = active[cont]
            h = (h[cont] + 1) & self._mask
        return found, out


def _synchronized(fn):
    @functools.wraps(fn)
    def wrapper(self, *args, **kwargs):
        with self._lock:
            try:
                return fn(self, *args, **kwargs)
            except ConsensusError:
                # The engine's caller-facing contract: typed rejections,
                # not faults — no flight dump for them.
                raise
            except Exception as exc:
                # Anything else — including a bare KeyError/ValueError from
                # internal bookkeeping, which is almost always an invariant
                # break, not an API rejection — is a fault: preserve the
                # evidence. The ring already holds the recent
                # batch/creation/sweep events; dumping is rate-limited
                # inside the recorder, so a crash loop (or a caller
                # hammering a malformed-argument path) costs one file per
                # second, not one per call.
                flight_recorder.record(
                    "engine.fault", api=fn.__name__, error=repr(exc)
                )
                flight_recorder.dump(f"engine-fault:{fn.__name__}")
                raise

    return wrapper


# Public API surface runs under the engine lock (reentrant: scalar entry
# points funnel into ingest_votes). Event-bus publishes are non-blocking
# (bounded queues, silent drop), so holding the lock across them is safe.
for _name in (
    "create_proposal",
    "create_proposals",
    "create_proposals_multi",
    "process_incoming_proposal",
    "ingest_proposals",
    "deliver_proposal",
    "deliver_proposals",
    "ingest_columnar",
    "ingest_columnar_multi",
    "ingest_wire_columnar",
    "voter_gid",
    "cast_vote",
    "cast_vote_and_get_proposal",
    "process_incoming_vote",
    "ingest_votes",
    "handle_consensus_timeout",
    "sweep_timeouts",
    "demote_session",
    "lifecycle_sweep",
    "gc_sessions",
    "pin_scope",
    "unpin_scope",
    "get_proposal",
    "get_consensus_result",
    "get_active_proposals",
    "get_reached_proposals",
    "get_scope_stats",
    "proposal_timeline",
    "trace_context_of",
    "explain_decision",
    "set_replay_mode",
    "export_session",
    "save_to_storage",
    "load_from_storage",
    "delete_scope",
    "delete_scopes",
    "set_scope_config",
    "get_scope_config",
    "_initialize_scope",
    "_update_scope_config",
):
    setattr(
        TpuConsensusEngine,
        _name,
        _synchronized(getattr(TpuConsensusEngine, _name)),
    )
