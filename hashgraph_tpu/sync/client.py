"""CatchUpClient: joiner-side snapshot install + WAL tailing.

The O(suffix) catch-up recipe (ARIES / Raft InstallSnapshot) over the
bridge's sync opcodes:

1. **Manifest** — fetch the source peer's snapshot manifest (watermark
   LSN, chunk count, per-chunk digests).
2. **Chunks** — download each chunk, verifying its SHA-256 against the
   manifest AS IT ARRIVES. Interrupted transfers resume: the
   :class:`CatchUpState` remembers verified chunks, and a fresh client
   handed the same state re-downloads only what is missing (or restarts
   cleanly if the source rebuilt its snapshot in the meantime).
3. **Verify** — decode the snapshot and verify every session's signed
   vote chain in ONE batched pass through the scheme's
   ``verify_batch_submit`` (the persistent native verify pool for
   Ethereum/Ed25519): this is where catch-up beats full replay — replay
   pays per-record crypto at gossip batch sizes, the snapshot pays one
   pool-wide batch. ``trust_snapshot=True`` skips the crypto for
   operator-trusted sources (a replica restored from its own blessed
   backup) — the structural decode still runs.
4. **Install** — load the verified sessions into the joiner in one
   atomic ``load_from_storage`` (nothing is installed unless the whole
   snapshot verified).
5. **Tail** — stream WAL records after the watermark and apply each
   through the engine's live entry points
   (:func:`hashgraph_tpu.wal.recovery.apply_record`): ``KIND_DELIVER``
   records run the validated-chain watermark path, so only the suffix is
   chain-checked; forked or replayed suffixes settle through the
   engine's existing fork handling, never a blind install. LSN
   continuity is enforced — a gap raises :class:`TailGapError` instead
   of replaying around a hole.

The whole catch-up runs under ``set_replay_mode`` (when the engine has
one): the suffix is history, and history must not re-feed the health
scorecards or decision-latency histograms.

Durability note: ``load_from_storage`` is deliberately NOT logged by a
durable joiner (snapshot-shaped state, not traffic — see
``DurableEngine.load_from_storage``), while tailed records ARE logged.
A durable joiner that must survive its own crash after catch-up should
checkpoint to its storage backend once catch-up completes; until then
its local WAL covers only the tailed suffix.
"""

from __future__ import annotations

import hashlib
import time
from dataclasses import dataclass, field

from ..bridge import protocol as P
from ..bridge.client import BridgeClient
from ..errors import ConsensusError
from ..obs import (
    SYNC_CATCHUP_SECONDS,
    SYNC_CHUNKS_RECEIVED_TOTAL,
    SYNC_TAIL_RECORDS_TOTAL,
    flight_recorder,
)
from ..obs import registry as default_registry
from ..protocol import compute_vote_hash, validate_vote_chain
from ..storage import InMemoryConsensusStorage
from ..wal.recovery import ReplayStats, apply_record
from .errors import (
    SnapshotDigestError,
    SyncStateError,
    SyncTimeoutError,
    SyncVerificationError,
    TailGapError,
    TailRecordError,
)
from .snapshot import decode_snapshot


def verify_sessions(sessions, scheme) -> int:
    """Verify every session's signed vote chain: per-vote proposal-id
    binding and vote-hash recomputation, per-session hashgraph chain
    linkage (:func:`~hashgraph_tpu.protocol.validate_vote_chain`), ALL
    signatures in one ``verify_batch_submit`` batch — the snapshot's
    crypto cost is one pool-wide pass, not one verify per vote per
    record — and, for sessions claiming a decided outcome, that the
    claimed result is PRODUCIBLE by the decision kernel from the
    verified participants under the shipped config (some admissible
    timing — decide-on-vote or decide-at-timeout — must yield it).
    Returns the number of signatures verified; raises
    :class:`SyncVerificationError` on the first failure (nothing should
    be installed).

    Trust boundary, stated precisely: signatures, hashes, and chain
    structure are cryptographically verified; the per-session scalar
    fields the wire does not sign — config, created_at, columnar tallies
    (the documented columnar trade-off), and the exact decision *timing*
    — are source-asserted, exactly as the reference trusts its own
    persisted sessions (src/storage.rs load semantics). The producibility
    check above caps a hostile source's power at that of an attacker who
    controls message timing and local config, which the BFT model already
    grants; catch up from sources whose gossip you would accept, and the
    health/evidence layer keeps scoring them afterwards."""
    identities: list[bytes] = []
    payloads: list[bytes] = []
    signatures: list[bytes] = []
    refs: list[tuple] = []
    for scope, session in sessions:
        proposal = session.proposal
        for vote in proposal.votes:
            if vote.proposal_id != proposal.proposal_id:
                raise SyncVerificationError(
                    f"snapshot session {scope!r}/{proposal.proposal_id}: "
                    f"embedded vote bound to proposal {vote.proposal_id}"
                )
            if compute_vote_hash(vote) != vote.vote_hash:
                raise SyncVerificationError(
                    f"snapshot session {scope!r}/{proposal.proposal_id}: "
                    f"vote hash mismatch for owner {vote.vote_owner.hex()}"
                )
            identities.append(vote.vote_owner)
            payloads.append(vote.signing_payload())
            signatures.append(vote.signature)
            refs.append((scope, proposal.proposal_id, vote.vote_owner))
        try:
            validate_vote_chain(proposal.votes)
        except ConsensusError as exc:
            raise SyncVerificationError(
                f"snapshot session {scope!r}/{proposal.proposal_id}: "
                f"vote chain invalid ({type(exc).__name__})"
            ) from exc
        if session.state.is_reached:
            claimed = bool(session.state.result)
            if (
                session.decide_now(False) != claimed
                and session.decide_now(True) != claimed
            ):
                raise SyncVerificationError(
                    f"snapshot session {scope!r}/{proposal.proposal_id}: "
                    f"claimed decided result {claimed} is not producible "
                    f"from its verified participants under the shipped "
                    f"config (neither the vote nor the timeout decision "
                    f"path yields it)"
                )
    if identities:
        verdicts = scheme.verify_batch_submit(
            identities, payloads, signatures
        ).collect()
        for verdict, (scope, pid, owner) in zip(verdicts, refs):
            if verdict is not True:
                raise SyncVerificationError(
                    f"snapshot session {scope!r}/{pid}: signature by "
                    f"{owner.hex()} failed verification ({verdict!r})"
                )
    return len(identities)


class CatchUpState:
    """Resumable progress of one catch-up: the manifest being
    transferred, the chunks already received AND digest-verified, whether
    the snapshot was installed into the target engine, and the last WAL
    LSN applied. Hand the same state (and the same engine) to a fresh
    :class:`CatchUpClient` after a connection drop and it continues where
    the old one stopped — mid-download resumes missing chunks,
    post-install resumes the tail."""

    def __init__(self):
        self.manifest: dict | None = None
        self.chunks: dict[int, bytes] = {}
        self.installed = False
        self.applied_lsn = 0


@dataclass
class CatchUpReport:
    """What one catch-up did, for logs/benchmarks."""

    watermark: int = 0
    chunks_fetched: int = 0
    snapshot_bytes: int = 0
    sessions_installed: int = 0
    votes_verified: int = 0
    tail_records: int = 0
    tail_votes: int = 0
    trust_snapshot: bool = False
    resumed: bool = False
    seconds: float = 0.0
    tail_stats: ReplayStats = field(default_factory=ReplayStats)

    @property
    def verified_votes_per_sec(self) -> float:
        total = self.votes_verified + self.tail_votes
        return round(total / self.seconds, 1) if self.seconds else 0.0


class CatchUpClient:
    """One catch-up connection to a source peer's bridge.

    ``state`` (default: fresh) carries resumable progress — see
    :class:`CatchUpState`. The client owns its bridge connection; close it
    (or use as a context manager) when done.

    ``timeout`` is the wall-clock bound on EVERY network operation
    (manifest, chunk, tail request): a source that stalls mid-transfer
    raises the typed :class:`SyncTimeoutError` instead of hanging the
    joiner thread on a silent socket forever — verified progress stays
    in ``state`` for a resume against the same or another source.

    ``bridge`` (advanced) injects the transport: any object with the
    BridgeClient ``sync_manifest``/``sync_chunk``/``wal_tail``/``close``
    surface serves — the deterministic simulator routes catch-up over
    its in-process fabric this way. The client closes whatever bridge it
    holds, injected or not.
    """

    # How many times a stale-snapshot response mid-download triggers a
    # manifest refresh before giving up (a source checkpointing faster
    # than the joiner downloads would otherwise livelock).
    _STALE_RETRIES = 3

    def __init__(
        self,
        host: str,
        port: int,
        source_peer: int,
        *,
        timeout: float = 30.0,
        state: CatchUpState | None = None,
        bridge=None,
    ):
        self._bridge = bridge if bridge is not None else BridgeClient(
            host, port, timeout
        )
        self._timeout = timeout
        self.source_peer = source_peer
        self.state = state if state is not None else CatchUpState()
        self._m_chunks = default_registry.counter(SYNC_CHUNKS_RECEIVED_TOTAL)
        self._m_tail = default_registry.counter(SYNC_TAIL_RECORDS_TOTAL)
        self._m_seconds = default_registry.histogram(SYNC_CATCHUP_SECONDS)

    def _netop(self, operation: str, call):
        """Run one network operation under the typed-timeout contract:
        the socket's wall-clock timeout (set at connect) surfaces as
        :class:`SyncTimeoutError` naming the stalled step, never a raw
        ``socket.timeout`` the joiner's supervisor cannot route."""
        try:
            return call()
        except TimeoutError as exc:  # socket.timeout is a subclass
            raise SyncTimeoutError(operation, self._timeout) from exc

    def close(self) -> None:
        self._bridge.close()

    def __enter__(self) -> "CatchUpClient":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    # ── Public entry points ────────────────────────────────────────────

    def catch_up(
        self,
        engine,
        *,
        trust_snapshot: bool = False,
        max_chunk_bytes: int = 0,
        tail_max_bytes: int = 0,
    ) -> CatchUpReport:
        """Snapshot + tail catch-up of ``engine`` from the source peer.
        The engine must be fresh (no tracked sessions) unless resuming a
        state whose snapshot already installed into it. Returns a
        :class:`CatchUpReport`; failures raise the typed
        :mod:`hashgraph_tpu.sync.errors` with nothing partially
        installed."""
        t0 = time.perf_counter()
        report = CatchUpReport(trust_snapshot=trust_snapshot)
        st = self.state
        report.resumed = bool(st.chunks or st.installed)
        try:
            if not st.installed:
                self._guard_fresh(engine)
                self._download_snapshot(report, max_chunk_bytes)
                self._verify_and_install(engine, report, trust_snapshot)
            else:
                report.watermark = st.applied_lsn if st.manifest is None else (
                    st.manifest["watermark"]
                )
            self._tail(engine, report, tail_max_bytes)
        except BaseException as exc:
            flight_recorder.record(
                "sync.failed",
                source_peer=self.source_peer,
                error=repr(exc),
                installed=st.installed,
                applied_lsn=st.applied_lsn,
            )
            raise
        report.seconds = round(time.perf_counter() - t0, 6)
        self._m_seconds.observe(report.seconds)
        flight_recorder.record(
            "sync.catchup",
            source_peer=self.source_peer,
            watermark=report.watermark,
            sessions=report.sessions_installed,
            votes_verified=report.votes_verified,
            tail_records=report.tail_records,
            seconds=report.seconds,
            resumed=report.resumed,
            trust_snapshot=trust_snapshot,
        )
        return report

    def full_replay(self, engine, *, tail_max_bytes: int = 0) -> CatchUpReport:
        """Catch up by streaming and applying the source's ENTIRE WAL —
        no snapshot, per-record validation all the way (the O(history)
        baseline ``bench.py catchup`` measures snapshot+tail against).
        Only possible while the source's log is uncompacted from LSN 1;
        a compacted source raises :class:`TailGapError` — the signal that
        a snapshot is required."""
        t0 = time.perf_counter()
        report = CatchUpReport()
        try:
            self._tail(engine, report, tail_max_bytes)
        except BaseException as exc:
            flight_recorder.record(
                "sync.failed",
                source_peer=self.source_peer,
                error=repr(exc),
                installed=False,
                applied_lsn=self.state.applied_lsn,
            )
            raise
        report.seconds = round(time.perf_counter() - t0, 6)
        self._m_seconds.observe(report.seconds)
        flight_recorder.record(
            "sync.catchup",
            source_peer=self.source_peer,
            watermark=0,
            sessions=0,
            votes_verified=0,
            tail_records=report.tail_records,
            seconds=report.seconds,
            resumed=report.resumed,
            trust_snapshot=False,
        )
        return report

    # ── Steps ──────────────────────────────────────────────────────────

    @staticmethod
    def _guard_fresh(engine) -> None:
        occupancy = getattr(engine, "occupancy", None)
        if occupancy is not None and occupancy().get("live_sessions", 0):
            raise SyncStateError(
                "snapshot install requires a fresh engine (this one "
                "already tracks sessions); build a new engine, or resume "
                "with the CatchUpState that installed into it"
            )

    def _download_snapshot(self, report: CatchUpReport, max_chunk_bytes: int) -> None:
        st = self.state
        for attempt in range(self._STALE_RETRIES + 1):
            manifest = self._netop(
                "manifest request",
                lambda: self._bridge.sync_manifest(
                    self.source_peer, max_chunk_bytes
                ),
            )
            if (
                st.manifest is not None
                and st.manifest["snapshot_id"] != manifest["snapshot_id"]
            ):
                # The source's state moved on and its snapshot was
                # rebuilt: previously downloaded chunks belong to a dead
                # artifact.
                st.chunks.clear()
            st.manifest = manifest
            try:
                for index in range(manifest["chunk_count"]):
                    if index in st.chunks:
                        continue
                    data = self._netop(
                        f"chunk {index} request",
                        lambda: self._bridge.sync_chunk(
                            self.source_peer, manifest["snapshot_id"], index
                        ),
                    )
                    self._check_chunk(manifest, index, data)
                    st.chunks[index] = data
                    report.chunks_fetched += 1
                    self._m_chunks.inc()
                return
            except Exception as exc:
                stale = (
                    getattr(exc, "status", None) == P.STATUS_SYNC_STALE
                )
                if not stale or attempt >= self._STALE_RETRIES:
                    raise
                # Keep st.manifest (the now-dead snapshot's): the next
                # loop's id comparison against the freshly fetched
                # manifest is what clears the dead snapshot's chunks —
                # nulling it here would let them survive into the new
                # transfer and corrupt the reassembled stream.

    @staticmethod
    def _check_chunk(manifest: dict, index: int, data: bytes) -> None:
        last = manifest["chunk_count"] - 1
        expected_len = (
            manifest["chunk_bytes"]
            if index < last
            else manifest["total_bytes"] - manifest["chunk_bytes"] * last
        )
        if len(data) != expected_len:
            raise SnapshotDigestError(
                f"chunk {index}: got {len(data)} bytes, manifest says "
                f"{expected_len}"
            )
        if hashlib.sha256(data).digest() != manifest["digests"][index]:
            raise SnapshotDigestError(
                f"chunk {index}: SHA-256 mismatch against the manifest — "
                "corrupt transfer or hostile source; nothing installed"
            )

    def _verify_and_install(
        self, engine, report: CatchUpReport, trust_snapshot: bool
    ) -> None:
        st = self.state
        manifest = st.manifest
        chunks = (st.chunks[i] for i in range(manifest["chunk_count"]))
        watermark, sessions, configs = decode_snapshot(chunks)
        if watermark != manifest["watermark"]:
            raise SyncVerificationError(
                f"snapshot header watermark {watermark} disagrees with "
                f"the manifest's {manifest['watermark']}"
            )
        if not trust_snapshot:
            report.votes_verified = verify_sessions(
                sessions, type(engine.signer())
            )
        storage = InMemoryConsensusStorage()
        for scope, config in configs:
            storage.set_scope_config(scope, config)
        for scope, session in sessions:
            storage.save_session(scope, session)
        set_mode = getattr(engine, "set_replay_mode", None)
        if set_mode is not None:
            set_mode(True)
        try:
            # Configs first, and EXPLICITLY: load_from_storage only walks
            # scopes that hold sessions, which would drop a configured-
            # but-empty scope from the install (and catch-up must land on
            # the source's exact state, configs included).
            for scope, config in configs:
                engine.set_scope_config(scope, config)
            report.sessions_installed = engine.load_from_storage(storage)
        finally:
            if set_mode is not None:
                set_mode(False)
        report.watermark = watermark
        report.snapshot_bytes = manifest["total_bytes"]
        st.installed = True
        st.applied_lsn = watermark
        st.chunks.clear()  # transferred and installed; free the memory

    def _tail(self, engine, report: CatchUpReport, tail_max_bytes: int) -> None:
        st = self.state
        set_mode = getattr(engine, "set_replay_mode", None)
        if set_mode is not None:
            set_mode(True)
        try:
            while True:
                records, more = self._netop(
                    "tail request",
                    lambda: self._bridge.wal_tail(
                        self.source_peer, st.applied_lsn, tail_max_bytes
                    ),
                )
                for lsn, kind, payload in records:
                    if lsn != st.applied_lsn + 1:
                        raise TailGapError(st.applied_lsn + 1, lsn)
                    before = report.tail_stats.votes_replayed
                    apply_record(
                        engine, kind, payload, report.tail_stats, lsn=lsn
                    )
                    if report.tail_stats.errors:
                        # Local crash replay tolerates decode faults
                        # (surfaced in stats, keep going); a REMOTE
                        # catch-up must not — skipping a record means
                        # silent divergence from the source.
                        raise TailRecordError(
                            f"tail record lsn {lsn} failed to decode: "
                            f"{report.tail_stats.errors[0][1]}"
                        )
                    st.applied_lsn = lsn
                    report.tail_records += 1
                    report.tail_votes += (
                        report.tail_stats.votes_replayed - before
                    )
                    self._m_tail.inc()
                if not more:
                    return
        finally:
            if set_mode is not None:
                set_mode(False)
