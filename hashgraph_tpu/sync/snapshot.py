"""Canonical chunked snapshot wire format for state sync.

A snapshot is the byte-serialized form of an engine's full tracked state —
every session (as the exact canonical proposal/vote wire bytes the
signatures cover, plus the scalar lifecycle fields the wire does not
carry) and every scope config — captured at a WAL LSN *watermark*: the
state contains exactly the effects of records with ``lsn <= watermark``,
so a joiner that installs it and then applies the WAL suffix after the
watermark converges to the source's state (the ARIES / Raft
InstallSnapshot recipe).

Layout: a flat stream of CRC-framed items, byte-split into fixed-size
chunks for transfer (chunk boundaries are arbitrary byte offsets — the
frame parser is incremental, so a multi-GB snapshot never materializes in
one buffer on either side)::

    frame := u32 body_len | u32 crc32(body) | body
    body  := u8 item_kind | payload

    ITEM_HEADER        MAGIC(8) | u32 version | u64 watermark
    ITEM_SESSION       scope | u8 state | u8 result | u64 created_at |
                       consensus_config | u32 n_tallies |
                       n × (blob owner | u8 value) | blob proposal_wire
    ITEM_SCOPE_CONFIG  scope | scope_config
    ITEM_END           u32 session_count | u32 config_count

Scope / config codecs are the WAL's (:mod:`hashgraph_tpu.wal.format`):
one canonical cross-process encoding per concept, not two. The embedded
``proposal_wire`` is the prost-compatible protobuf encoding carrying the
full vote chain — the same bytes the votes' signatures cover, which is
what lets a joiner verify the whole snapshot cryptographically
(:func:`hashgraph_tpu.sync.client.verify_sessions`) before trusting it.
"""

from __future__ import annotations

import hashlib
import os
import struct
import zlib
from dataclasses import dataclass

from ..session import ConsensusSession, ConsensusState, ConsensusStateKind
from ..wal import format as F
from ..wire import Proposal
from .errors import SnapshotDecodeError

MAGIC = b"HGSYNC01"
VERSION = 1

ITEM_HEADER = 1
ITEM_SESSION = 2
ITEM_SCOPE_CONFIG = 3
ITEM_END = 4

_HEADER = struct.Struct("<II")  # body_len | crc32
HEADER_BYTES = _HEADER.size
# Hard cap against garbage length prefixes (the WAL / bridge rationale).
MAX_FRAME = 64 * 1024 * 1024

DEFAULT_CHUNK_BYTES = 4 * 1024 * 1024

_STATE_CODE = {
    ConsensusStateKind.ACTIVE: 0,
    ConsensusStateKind.CONSENSUS_REACHED: 1,
    ConsensusStateKind.FAILED: 2,
}


def _u8(v: int) -> bytes:
    return struct.pack("<B", v)


def _u32(v: int) -> bytes:
    return struct.pack("<I", v)


def _u64(v: int) -> bytes:
    return struct.pack("<Q", v)


def _blob(b: bytes) -> bytes:
    return _u32(len(b)) + bytes(b)


@dataclass(frozen=True)
class SnapshotManifest:
    """What a joiner needs BEFORE transferring a snapshot: identity,
    integrity, and resume geometry. ``snapshot_id`` identifies one BUILD
    artifact — the exact (file bytes, chunk geometry) pair chunks are
    served from; it defaults to the watermark, but a server that can
    rebuild (new watermark, or a different requested chunk size over the
    same state) must mint a fresh unique id per build so a client holding
    a stale manifest gets a typed stale signal instead of chunks read at
    the wrong offsets. ``digests`` are per-chunk SHA-256 over the raw
    chunk bytes, verified as each chunk arrives so a corrupt transfer is
    caught per-chunk, not after gigabytes."""

    snapshot_id: int
    watermark: int
    total_bytes: int
    chunk_bytes: int
    session_count: int
    config_count: int
    digests: "tuple[bytes, ...]"

    @property
    def chunk_count(self) -> int:
        return len(self.digests)

    def chunk_size(self, index: int) -> int:
        if index < 0 or index >= len(self.digests):
            raise IndexError(f"chunk {index} out of range")
        if index < len(self.digests) - 1:
            return self.chunk_bytes
        return self.total_bytes - self.chunk_bytes * (len(self.digests) - 1)


# ── Frame + item codecs ────────────────────────────────────────────────


def encode_frame(item_kind: int, payload: bytes) -> bytes:
    body = _u8(item_kind) + payload
    return _HEADER.pack(len(body), zlib.crc32(body)) + body


# C-level packers for the bulk path (byte-identical to _u8/_u64/_u32
# sequences: little-endian "<" structs never pad).
_SRC_PACK = struct.Struct("<BBQ").pack  # state | result | created_at
_U32_PACK = struct.Struct("<I").pack


def encode_session_fields(
    scope_bytes: bytes,
    state: int,
    result: bool,
    created_at: int,
    config_bytes: bytes,
    tallies,
    proposal_wire: bytes,
) -> bytes:
    """ITEM_SESSION payload from pre-resolved components — the layout
    :func:`encode_session_item` delegates to. Callers that already hold
    the canonical pieces (the engine's bulk demotion path: per-call
    memoized scope/config encodes, tallies straight off the device row,
    the live proposal's wire bytes) skip materializing a scalar
    ConsensusSession per item; byte-identity with the session-object
    path is pinned by the tier fingerprint property suite."""
    out = [
        scope_bytes,
        _SRC_PACK(state, 1 if result else 0, created_at),
        config_bytes,
        _U32_PACK(len(tallies)),
    ]
    append = out.append
    for owner, value in tallies.items():
        append(_U32_PACK(len(owner)))
        append(bytes(owner))
        append(b"\x01" if value else b"\x00")
    append(_U32_PACK(len(proposal_wire)))
    append(proposal_wire)
    return b"".join(out)


def encode_session_item(scope, session: ConsensusSession) -> bytes:
    return encode_session_fields(
        F.encode_scope(scope),
        _STATE_CODE[session.state.kind],
        bool(session.state.result),
        session.created_at,
        F.encode_consensus_config(session.config),
        session.tallies,
        session.proposal.encode(),
    )


def decode_session_item(payload: bytes) -> "tuple[object, ConsensusSession]":
    r = F.Reader(payload)
    scope = F.decode_scope(r)
    state_code = r.u8()
    result = bool(r.u8())
    created_at = r.u64()
    config = F.decode_consensus_config(r)
    tallies = {}
    for _ in range(r.u32()):
        owner = r.blob()
        tallies[owner] = bool(r.u8())
    proposal = Proposal.decode(r.blob())
    if state_code == 0:
        state = ConsensusState.active()
    elif state_code == 1:
        state = ConsensusState.reached(result)
    elif state_code == 2:
        state = ConsensusState.failed()
    else:
        raise ValueError(f"unknown session state code {state_code}")
    # ``votes`` is derived state: one vote per owner, and the proposal's
    # embedded chain is the canonical (signed) record of exactly those
    # votes — the scalar session maintains the two in lockstep.
    votes = {v.vote_owner: v for v in proposal.votes}
    session = ConsensusSession(
        proposal=proposal,
        state=state,
        votes=votes,
        created_at=created_at,
        config=config,
        tallies=tallies,
    )
    return scope, session


def encode_scope_config_item(scope, config) -> bytes:
    return F.encode_scope(scope) + F.encode_scope_config(config)


def decode_scope_config_item(payload: bytes):
    r = F.Reader(payload)
    return F.decode_scope(r), F.decode_scope_config(r)


# ── Building (source side) ─────────────────────────────────────────────


class _SnapshotSink:
    """ConsensusStorage-shaped collector framing sessions/configs straight
    to a byte sink. Only the two methods ``save_to_storage`` drives exist:
    the engine streams one materialized session at a time through
    ``save_session``, so the build holds one session in memory, never the
    whole state."""

    def __init__(self, write):
        self._write = write
        self.sessions = 0
        self.configs = 0

    def save_session(self, scope, session) -> None:
        self._write(encode_frame(ITEM_SESSION, encode_session_item(scope, session)))
        self.sessions += 1

    def set_scope_config(self, scope, config) -> None:
        self._write(
            encode_frame(ITEM_SCOPE_CONFIG, encode_scope_config_item(scope, config))
        )
        self.configs += 1


def build_snapshot(
    engine,
    path: str,
    *,
    chunk_bytes: int = DEFAULT_CHUNK_BYTES,
    snapshot_id: "int | None" = None,
) -> SnapshotManifest:
    """Serialize ``engine``'s tracked state to ``path`` and return the
    manifest. A :class:`~hashgraph_tpu.wal.DurableEngine` is captured
    under its mutator lock via ``capture_consistent``, so the file's
    watermark is exactly consistent with its contents (mutators stall for
    the duration of the capture — the price of a consistent cut); a bare
    engine snapshots with watermark 0 (no WAL position to tail from).

    The file is written to ``path + ".tmp"`` and renamed into place, so a
    crashed build never leaves a half-snapshot under the served name.
    Chunk digests are computed in a second streaming pass over the file.
    """
    if chunk_bytes <= 0 or chunk_bytes > MAX_FRAME:
        raise ValueError(f"chunk_bytes must be in (0, {MAX_FRAME}]")
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    tmp = path + ".tmp"
    info: dict = {}
    with open(tmp, "wb") as fh:
        def run(inner, watermark: int) -> None:
            fh.write(
                encode_frame(
                    ITEM_HEADER, MAGIC + _u32(VERSION) + _u64(watermark)
                )
            )
            sink = _SnapshotSink(fh.write)
            inner.save_to_storage(sink)
            fh.write(
                encode_frame(ITEM_END, _u32(sink.sessions) + _u32(sink.configs))
            )
            info.update(
                watermark=watermark,
                sessions=sink.sessions,
                configs=sink.configs,
            )

        capture = getattr(engine, "capture_consistent", None)
        if capture is not None:
            capture(run)
        else:
            run(engine, 0)
        fh.flush()
        os.fsync(fh.fileno())
    os.replace(tmp, path)
    digests: list[bytes] = []
    total = 0
    with open(path, "rb") as fh:
        while True:
            block = fh.read(chunk_bytes)
            if not block:
                break
            digests.append(hashlib.sha256(block).digest())
            total += len(block)
    return SnapshotManifest(
        snapshot_id=(
            info["watermark"] if snapshot_id is None else snapshot_id
        ),
        watermark=info["watermark"],
        total_bytes=total,
        chunk_bytes=chunk_bytes,
        session_count=info["sessions"],
        config_count=info["configs"],
        digests=tuple(digests),
    )


# ── Parsing (joiner side) ──────────────────────────────────────────────


def iter_snapshot_frames(chunks):
    """Yield ``(item_kind, payload)`` from an iterable of byte blocks with
    ARBITRARY boundaries (transfer chunks). Incremental: memory is bounded
    by one frame plus one chunk, not the snapshot. Raises
    :class:`SnapshotDecodeError` on any malformed frame — unlike the WAL's
    torn-tail tolerance, a snapshot is a complete artifact whose length
    and digests the manifest pinned, so truncation IS corruption."""
    buf = bytearray()
    pos = 0
    for chunk in chunks:
        buf += chunk
        while True:
            if len(buf) - pos < HEADER_BYTES:
                break
            body_len, crc = _HEADER.unpack_from(buf, pos)
            if body_len < 1 or body_len > MAX_FRAME:
                raise SnapshotDecodeError(
                    f"snapshot frame with invalid body length {body_len}"
                )
            end = pos + HEADER_BYTES + body_len
            if end > len(buf):
                break
            body = bytes(buf[pos + HEADER_BYTES : end])
            if zlib.crc32(body) != crc:
                raise SnapshotDecodeError("snapshot frame CRC mismatch")
            yield body[0], body[1:]
            pos = end
        if pos:
            del buf[:pos]
            pos = 0
    if len(buf) - pos:
        raise SnapshotDecodeError(
            f"snapshot stream ends with {len(buf) - pos} trailing bytes "
            "inside an incomplete frame"
        )


def decode_snapshot(chunks):
    """Parse a full snapshot byte stream into ``(watermark, sessions,
    configs)`` where sessions are ``(scope, ConsensusSession)`` and
    configs are ``(scope, ScopeConfig)``. Validates the header
    magic/version, the trailer's item counts, and every frame's CRC."""
    watermark = None
    sessions: list = []
    configs: list = []
    ended = False
    for item, payload in iter_snapshot_frames(chunks):
        if ended:
            raise SnapshotDecodeError("snapshot frames after the END trailer")
        if watermark is None:
            if item != ITEM_HEADER:
                raise SnapshotDecodeError("snapshot does not start with a header")
            r = F.Reader(payload)
            magic = r.raw(len(MAGIC))
            if magic != MAGIC:
                raise SnapshotDecodeError(f"bad snapshot magic {magic!r}")
            version = r.u32()
            if version != VERSION:
                raise SnapshotDecodeError(f"unsupported snapshot version {version}")
            watermark = r.u64()
            continue
        try:
            if item == ITEM_SESSION:
                sessions.append(decode_session_item(payload))
            elif item == ITEM_SCOPE_CONFIG:
                configs.append(decode_scope_config_item(payload))
            elif item == ITEM_END:
                r = F.Reader(payload)
                want_sessions, want_configs = r.u32(), r.u32()
                if want_sessions != len(sessions) or want_configs != len(configs):
                    raise SnapshotDecodeError(
                        f"snapshot trailer claims {want_sessions} sessions / "
                        f"{want_configs} configs, stream carried "
                        f"{len(sessions)} / {len(configs)}"
                    )
                ended = True
            else:
                raise SnapshotDecodeError(f"unknown snapshot item kind {item}")
        except ValueError as exc:
            raise SnapshotDecodeError(
                f"snapshot item payload undecodable: {exc}"
            ) from exc
    if watermark is None:
        raise SnapshotDecodeError("empty snapshot stream")
    if not ended:
        raise SnapshotDecodeError("snapshot stream missing the END trailer")
    return watermark, sessions, configs


# ── State equality ─────────────────────────────────────────────────────


def state_fingerprint(engine) -> str:
    """Order-insensitive content digest of an engine's full tracked state
    (sessions + scope configs), built from the same canonical item frames
    the snapshot ships. Two engines fingerprint equal iff their
    ``save_to_storage`` dumps carry byte-identical session/config items —
    the acceptance criterion for catch-up convergence. DurableEngine
    wrappers are unwrapped first (the wrapper's own ``save_to_storage``
    appends a checkpoint mark; a read-only fingerprint must not)."""
    target = getattr(engine, "engine", engine)
    frames: list[bytes] = []
    target.save_to_storage(_SnapshotSink(frames.append))
    item_digests = sorted(hashlib.sha256(f).digest() for f in frames)
    return hashlib.sha256(b"".join(item_digests)).hexdigest()
