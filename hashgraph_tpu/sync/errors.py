"""Typed state-sync failures.

Every failure mode of the catch-up path gets its own type so embedders can
route them: a digest mismatch is a corrupt/hostile source (retry another
peer), a verification failure is a hostile snapshot (never install), a
tail gap is a source whose log no longer covers the requested suffix
(refresh the manifest and re-snapshot), and a state error is a caller bug
(catch-up targets a fresh engine). All of them guarantee NO PARTIAL
INSTALL: the joiner engine is untouched unless the whole snapshot
verified and decoded.
"""

from __future__ import annotations


class SyncError(RuntimeError):
    """Base class for state-sync failures."""


class SnapshotDecodeError(SyncError):
    """Snapshot byte stream is malformed (bad magic/version, truncated or
    CRC-invalid frame, item counts disagreeing with the trailer)."""


class SnapshotDigestError(SyncError):
    """A received chunk's bytes do not match the manifest's digest — the
    transfer was corrupted or the source is serving hostile bytes. Nothing
    was installed; re-request the chunk or pick another source."""


class SyncVerificationError(SyncError):
    """The snapshot's signed vote chains failed verification (bad
    signature, wrong vote hash, broken chain link, proposal-id mismatch).
    Nothing was installed. ``trust_snapshot=True`` bypasses this check for
    operator-trusted sources."""


class TailGapError(SyncError):
    """The served WAL tail is not contiguous with the requested position:
    the source compacted past the snapshot watermark (re-fetch a fresh
    manifest) or lost records to mid-log corruption. Applying around a gap
    could replay a vote before its proposal, so the catch-up refuses."""

    def __init__(self, expected_lsn: int, got_lsn: int):
        super().__init__(
            f"WAL tail gap: expected lsn {expected_lsn}, source served "
            f"{got_lsn} — the log no longer covers the requested suffix "
            f"(compacted past the watermark, or mid-log corruption)"
        )
        self.expected_lsn = expected_lsn
        self.got_lsn = got_lsn


class TailRecordError(SyncError):
    """A served WAL tail record's payload failed to decode. Local crash
    recovery tolerates this (it surfaces the fault in ReplayStats and
    keeps replaying — the frame layer guarantees record boundaries), but
    a remote catch-up must not: a joiner that silently skips a record
    diverges from the source, so the sync path fails typed instead."""


class SyncStateError(SyncError):
    """The joiner engine is not in a state catch-up supports (e.g. it
    already tracks sessions and no snapshot was installed through this
    catch-up state — a snapshot install must target a fresh engine)."""


class SyncTimeoutError(SyncError):
    """A catch-up network operation (manifest, chunk, or tail request)
    exceeded the client's wall-clock timeout — the source stalled
    mid-transfer. Distinct from a dead connection (``ConnectionError``):
    the socket is up but the peer stopped answering, so a joiner thread
    must not hang on it forever. Progress already verified stays in the
    :class:`~hashgraph_tpu.sync.CatchUpState`; hand it to a fresh client
    (same or different source) to resume."""

    def __init__(self, operation: str, timeout: float):
        super().__init__(
            f"state-sync {operation} timed out after {timeout:g}s — the "
            f"source stalled; resume with the same CatchUpState on a "
            f"fresh client or pick another source"
        )
        self.operation = operation
        self.timeout = timeout
