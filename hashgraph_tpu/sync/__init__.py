"""hashgraph_tpu.sync — state sync: snapshot shipping + WAL tailing.

Turns cold-peer catch-up from O(full history × crypto) into O(suffix):
the source serves a consistent snapshot of its engine state at a WAL LSN
watermark (:mod:`.snapshot`, built under the DurableEngine's mutator
lock, chunked and digest-pinned for transfer), and the joiner
(:class:`.client.CatchUpClient`) verifies the snapshot's signed vote
chains in one batched pass through the native verify pool, installs it
atomically, then tails and applies only the WAL records past the
watermark through the engine's live entry points — ``deliver`` records
ride the validated-chain watermark, so even the tail's redeliveries
verify only their suffixes.

This is an embedder-layer construct over the reference's storage
contract (src/storage.rs save/load semantics), not a protocol
divergence: the snapshot carries exactly the canonical session/vote wire
bytes the reference persists, plus the scalar lifecycle fields its
storage trait round-trips. See PARITY.md.
"""

from .client import CatchUpClient, CatchUpReport, CatchUpState, verify_sessions
from .errors import (
    SnapshotDecodeError,
    SnapshotDigestError,
    SyncError,
    SyncStateError,
    SyncTimeoutError,
    SyncVerificationError,
    TailGapError,
    TailRecordError,
)
from .snapshot import (
    DEFAULT_CHUNK_BYTES,
    SnapshotManifest,
    build_snapshot,
    decode_snapshot,
    state_fingerprint,
)

__all__ = [
    "CatchUpClient",
    "CatchUpReport",
    "CatchUpState",
    "DEFAULT_CHUNK_BYTES",
    "SnapshotDecodeError",
    "SnapshotDigestError",
    "SnapshotManifest",
    "SyncError",
    "SyncStateError",
    "SyncTimeoutError",
    "SyncVerificationError",
    "TailGapError",
    "TailRecordError",
    "build_snapshot",
    "decode_snapshot",
    "state_fingerprint",
    "verify_sessions",
]
