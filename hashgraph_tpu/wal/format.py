"""WAL record framing and payload codecs.

One record is one durable unit, framed as (all integers little-endian)::

    u32 body_len | u32 crc32 | body
    body = u64 lsn | u8 kind | payload

``body_len`` counts the body (lsn + kind + payload, so ``9 + len(payload)``);
``crc32`` is :func:`zlib.crc32` over the body. A reader accepts a record only
when the full frame is present AND the CRC matches, so a torn tail — a crash
mid-``write(2)``, a short frame, or garbage after a partially-flushed page —
is detected at the first bad frame and everything before it stays usable.
This is the classic ARIES/Raft log-framing discipline; see
:mod:`hashgraph_tpu.wal.recovery` for the truncate-at-first-bad-frame rule.

Payloads reuse the framework's canonical byte encodings: ``Proposal`` /
``Vote`` records embed the exact prost-compatible wire bytes of
:mod:`hashgraph_tpu.wire` (no second serialization format — the bytes that
were validated/signed are the bytes that are logged), and scopes use the
same canonical str/bytes/int encoding the multi-host control plane requires
(engine._canonical_scope_bytes rationale: arbitrary ``repr`` is not stable
across processes, and a WAL must be readable by a different process than
the one that wrote it).
"""

from __future__ import annotations

import struct
import zlib

import numpy as np

from ..scope_config import NetworkType, ScopeConfig
from ..session import ConsensusConfig

# ── Record kinds ───────────────────────────────────────────────────────

KIND_PROPOSALS = 1  # batch of (scope, proposal wire bytes, optional config)
KIND_VOTES = 2  # batch of (scope, vote wire bytes) + pre_validated flag
KIND_COLUMNAR = 3  # columnar vote batch: scopes + packed wire bytes
KIND_SCOPE_CONFIG = 4  # scope config set/initialize/update
KIND_SCOPE_DELETE = 5  # batch of scopes dropped
KIND_TIMEOUT = 6  # app-driven per-session timeout decision
KIND_SWEEP = 7  # engine-level timeout sweep
KIND_SNAPSHOT = 8  # snapshot watermark: records with lsn <= mark are covered
# Gossip create-or-extend delivery (engine.deliver_proposals). Payload is
# the KIND_PROPOSALS encoding verbatim; the kind byte alone routes replay
# through the watermark path, because the same proposal bytes mean
# different state transitions under deliver (extension applies a suffix)
# vs ingest (redelivery rejects) — replay must re-run the call that was
# acked, not a lookalike.
KIND_DELIVER = 9
# Wire-columnar ingest (engine.ingest_wire_columnar). Payload is the
# KIND_COLUMNAR encoding verbatim; the kind byte alone routes replay back
# through the wire path (crypto skipped — only accepted rows are logged),
# because the wire path RETAINS its chains wire-validated: replaying
# through plain columnar ingest would demote ``wire_only`` and a
# recovered peer would silently drop the cross-frame dangling-vote guard
# its non-crashed twins keep.
KIND_WIRE_COLUMNAR = 10
# Standalone engine.lifecycle_sweep call (tier demote/GC at the
# embedder's clock). Demotion alone would be replay-neutral (the tier is
# a cache), but the sweep's TTL GC is semantic — sessions past
# evict_decided_after cease to exist — so an unlogged sweep would let a
# crash resurrect sessions the live engine already dropped. Sweeps that
# ride sweep_timeouts are covered by KIND_SWEEP.
KIND_LIFECYCLE = 11
# TTL-GC OUTCOME of the immediately preceding KIND_SWEEP/KIND_LIFECYCLE
# record: the exact (scope, pid) set the live sweep collected. Logged
# after the apply, before the ack (the columnar discipline), because the
# GC decision depends on per-session idle clocks a SNAPSHOT restore does
# not carry (last_activity is deliberately absent from the fingerprinted
# session item — two converged peers with different local clocks must
# not fingerprint-diverge): re-deriving the TTL policy on replay over
# restored clocks could collect a different set than the live engine
# did. Replay therefore applies the logged outcome verbatim
# (engine.gc_sessions), while recovery's replay mode suppresses the
# re-derived lifecycle policy inside KIND_SWEEP/KIND_LIFECYCLE.
KIND_GC = 12

KIND_NAMES = {
    KIND_PROPOSALS: "proposals",
    KIND_VOTES: "votes",
    KIND_COLUMNAR: "columnar",
    KIND_SCOPE_CONFIG: "scope_config",
    KIND_SCOPE_DELETE: "scope_delete",
    KIND_TIMEOUT: "timeout",
    KIND_SWEEP: "sweep",
    KIND_SNAPSHOT: "snapshot",
    KIND_DELIVER: "deliver",
    KIND_WIRE_COLUMNAR: "wire_columnar",
    KIND_LIFECYCLE: "lifecycle",
    KIND_GC: "gc",
}

# Scope-config record modes (the engine has three distinct mutation
# semantics; replay must re-run the SAME one).
SCOPE_CONFIG_SET = 0
SCOPE_CONFIG_INITIALIZE = 1
SCOPE_CONFIG_UPDATE = 2

_HEADER = struct.Struct("<II")  # body_len | crc32
_BODY_LEAD = struct.Struct("<QB")  # lsn | kind
HEADER_BYTES = _HEADER.size
BODY_LEAD_BYTES = _BODY_LEAD.size

# Hard cap against garbage length prefixes (same rationale as the bridge's
# MAX_FRAME): a corrupt length must not trigger a giant allocation.
MAX_RECORD = 64 * 1024 * 1024


# ── Framing ────────────────────────────────────────────────────────────


def encode_record(lsn: int, kind: int, payload: bytes) -> bytes:
    """Frame one record. ``len(result)`` is the on-disk footprint."""
    body = _BODY_LEAD.pack(lsn, kind) + payload
    return _HEADER.pack(len(body), zlib.crc32(body)) + body


def scan_buffer(
    data: bytes, pos: int = 0
) -> tuple[list[tuple[int, int, bytes]], int]:
    """Parse consecutive records from ``data`` starting at ``pos``.

    Returns ``(records, valid_end)`` where records are ``(lsn, kind,
    payload)`` tuples and ``valid_end`` is the offset just past the last
    intact record. ``valid_end < len(data)`` means a torn tail: a short
    header, an out-of-range length, a truncated body, or a CRC mismatch.
    The scan never raises on malformed input — torn tails are an expected
    crash artifact, not an error.
    """
    records: list[tuple[int, int, bytes]] = []
    n = len(data)
    while True:
        if n - pos < HEADER_BYTES:
            return records, pos
        body_len, crc = _HEADER.unpack_from(data, pos)
        if body_len < BODY_LEAD_BYTES or body_len > MAX_RECORD:
            return records, pos
        end = pos + HEADER_BYTES + body_len
        if end > n:
            return records, pos
        body = data[pos + HEADER_BYTES : end]
        if zlib.crc32(body) != crc:
            return records, pos
        lsn, kind = _BODY_LEAD.unpack_from(body, 0)
        records.append((lsn, kind, body[BODY_LEAD_BYTES:]))
        pos = end


# ── Payload reader ─────────────────────────────────────────────────────


class Reader:
    """Sequential reader over one record's payload. Raises ValueError on
    overrun — a record that passed its CRC but fails payload decode is
    corruption beyond what framing can mask, and recovery surfaces it.

    Deliberately mirrors (not reuses) ``bridge/protocol.Cursor``: the
    durability layer must not depend on the bridge transport, and the two
    formats genuinely differ (u32 blob prefixes here vs u16 strings there,
    f64 fields here) — sharing the core would couple the WAL's on-disk
    layout to a network protocol that evolves on its own schedule."""

    __slots__ = ("_data", "_pos")

    def __init__(self, data: bytes):
        self._data = data
        self._pos = 0

    def _take(self, n: int) -> bytes:
        end = self._pos + n
        if end > len(self._data):
            raise ValueError("WAL payload truncated inside a CRC-valid record")
        out = self._data[self._pos : end]
        self._pos = end
        return out

    def u8(self) -> int:
        return self._take(1)[0]

    def u32(self) -> int:
        return struct.unpack("<I", self._take(4))[0]

    def u64(self) -> int:
        return struct.unpack("<Q", self._take(8))[0]

    def f64(self) -> float:
        return struct.unpack("<d", self._take(8))[0]

    def blob(self) -> bytes:
        return self._take(self.u32())

    def raw(self, n: int) -> bytes:
        """``n`` raw bytes — fixed-width arrays whose length the caller
        derives from earlier fields (no length prefix of their own)."""
        return self._take(n)

    def done(self) -> bool:
        return self._pos == len(self._data)


def _u8(v: int) -> bytes:
    return struct.pack("<B", v)


def _u32(v: int) -> bytes:
    return struct.pack("<I", v)


def _u64(v: int) -> bytes:
    return struct.pack("<Q", v)


def _f64(v: float) -> bytes:
    return struct.pack("<d", v)


def _blob(b: bytes) -> bytes:
    return _u32(len(b)) + bytes(b)


# ── Scope codec ────────────────────────────────────────────────────────

_SCOPE_STR = 0x73  # 's'
_SCOPE_BYTES = 0x62  # 'b'
_SCOPE_INT = 0x69  # 'i'


def encode_scope(scope) -> bytes:
    """Canonical scope encoding — str/bytes/int only (same restriction and
    rationale as the engine's multi-host scope canonicalization: the WAL is
    read by a different process, so the encoding must be process-independent
    and round-trippable)."""
    if isinstance(scope, str):
        return _u8(_SCOPE_STR) + _blob(scope.encode("utf-8"))
    if isinstance(scope, (bytes, bytearray)):
        return _u8(_SCOPE_BYTES) + _blob(bytes(scope))
    if isinstance(scope, int):
        # int(scope) so bool encodes identically to the int it equals.
        return _u8(_SCOPE_INT) + _blob(str(int(scope)).encode())
    raise TypeError(
        f"durable logging requires str/bytes/int scopes (canonical "
        f"cross-process encoding); got {type(scope).__name__}"
    )


def decode_scope(r: Reader):
    tag = r.u8()
    raw = r.blob()
    if tag == _SCOPE_STR:
        return raw.decode("utf-8")
    if tag == _SCOPE_BYTES:
        return raw
    if tag == _SCOPE_INT:
        return int(raw.decode())
    raise ValueError(f"unknown scope tag {tag:#x}")


# ── Config codecs ──────────────────────────────────────────────────────

_NT_GOSSIPSUB = 0
_NT_P2P = 1


def encode_scope_config(config: ScopeConfig) -> bytes:
    override = config.max_rounds_override
    # Tier-TTL presence flags: absent TTLs encode as flag 0 + value 0.0 so
    # the layout stays fixed-width and canonical (fingerprints hash these
    # bytes — two equal configs must encode identically).
    tier_flags = (0 if config.demote_after is None else 1) | (
        0 if config.evict_decided_after is None else 2
    )
    # Adaptive-timeout bounds follow the same fixed-width presence-flag
    # pattern (bit 1 = timeout_min, bit 2 = timeout_max; validate() makes
    # them all-or-nothing, but the bits stay independent for symmetry).
    adaptive_flags = (0 if config.timeout_min is None else 1) | (
        0 if config.timeout_max is None else 2
    )
    return b"".join(
        (
            _u8(_NT_P2P if config.network_type == NetworkType.P2P else _NT_GOSSIPSUB),
            _f64(config.default_consensus_threshold),
            _f64(config.default_timeout),
            _u8(1 if config.default_liveness_criteria_yes else 0),
            _u8(0 if override is None else 1),
            _u32(override or 0),
            _u8(tier_flags),
            _f64(config.demote_after or 0.0),
            _f64(config.evict_decided_after or 0.0),
            _u8(adaptive_flags),
            _f64(config.timeout_min or 0.0),
            _f64(config.timeout_max or 0.0),
        )
    )


def decode_scope_config(r: Reader) -> ScopeConfig:
    nt = NetworkType.P2P if r.u8() == _NT_P2P else NetworkType.GOSSIPSUB
    threshold = r.f64()
    timeout = r.f64()
    liveness = bool(r.u8())
    has_override = bool(r.u8())
    override = r.u32()
    tier_flags = r.u8()
    demote_after = r.f64()
    evict_decided_after = r.f64()
    adaptive_flags = r.u8()
    timeout_min = r.f64()
    timeout_max = r.f64()
    return ScopeConfig(
        network_type=nt,
        default_consensus_threshold=threshold,
        default_timeout=timeout,
        default_liveness_criteria_yes=liveness,
        max_rounds_override=override if has_override else None,
        demote_after=demote_after if tier_flags & 1 else None,
        evict_decided_after=evict_decided_after if tier_flags & 2 else None,
        timeout_min=timeout_min if adaptive_flags & 1 else None,
        timeout_max=timeout_max if adaptive_flags & 2 else None,
    )


def encode_consensus_config(config: ConsensusConfig) -> bytes:
    return b"".join(
        (
            _f64(config.consensus_threshold),
            _f64(config.consensus_timeout),
            _u32(config.max_rounds),
            _u8(1 if config.use_gossipsub_rounds else 0),
            _u8(1 if config.liveness_criteria else 0),
        )
    )


def decode_consensus_config(r: Reader) -> ConsensusConfig:
    return ConsensusConfig(
        consensus_threshold=r.f64(),
        consensus_timeout=r.f64(),
        max_rounds=r.u32(),
        use_gossipsub_rounds=bool(r.u8()),
        liveness_criteria=bool(r.u8()),
    )


# ── Per-item payload footprints ────────────────────────────────────────
# Used by DurableEngine's record splitting to pick chunk boundaries
# arithmetically, so each byte is encoded exactly once (no trial encodes
# of payloads that turn out oversized). Keep in lockstep with the encoders
# below — every field is fixed-width except the scope and the wire blob.

PROPOSALS_LEAD_BYTES = 12  # u64 now + u32 count
VOTES_LEAD_BYTES = 13  # u64 now + u8 pre_validated + u32 count
CONSENSUS_CONFIG_BYTES = 22  # 2 × f64 + u32 + 2 × u8 (encode_consensus_config)


def sizeof_proposal_item(item) -> int:
    """Encoded footprint of one ``encode_proposals`` item."""
    scope, wire, config = item
    return (
        len(encode_scope(scope))
        + 1  # has-config flag
        + (CONSENSUS_CONFIG_BYTES if config is not None else 0)
        + 4  # wire length prefix
        + len(wire)
    )


def sizeof_vote_item(item) -> int:
    """Encoded footprint of one ``encode_votes`` item."""
    scope, wire = item
    return len(encode_scope(scope)) + 4 + len(wire)


# ── Record payloads ────────────────────────────────────────────────────


def encode_proposals(
    now: int, items: "list[tuple[object, bytes, ConsensusConfig | None]]"
) -> bytes:
    """items: (scope, Proposal wire bytes, optional per-item config
    override). The override preserves create_proposal's explicit-config
    precedence across replay."""
    out = [_u64(now), _u32(len(items))]
    for scope, wire, config in items:
        out.append(encode_scope(scope))
        if config is None:
            out.append(_u8(0))
        else:
            out.append(_u8(1))
            out.append(encode_consensus_config(config))
        out.append(_blob(wire))
    return b"".join(out)


def decode_proposals(
    payload: bytes,
) -> "tuple[int, list[tuple[object, bytes, ConsensusConfig | None]]]":
    r = Reader(payload)
    now = r.u64()
    items = []
    for _ in range(r.u32()):
        scope = decode_scope(r)
        config = decode_consensus_config(r) if r.u8() else None
        items.append((scope, r.blob(), config))
    return now, items


def encode_votes(
    now: int, pre_validated: bool, items: "list[tuple[object, bytes]]"
) -> bytes:
    """items: (scope, Vote wire bytes). ``pre_validated`` mirrors the live
    ingest_votes flag so replay repeats the exact validation the live call
    performed (locally-built votes skip it; network votes re-validate)."""
    out = [_u64(now), _u8(1 if pre_validated else 0), _u32(len(items))]
    for scope, wire in items:
        out.append(encode_scope(scope))
        out.append(_blob(wire))
    return b"".join(out)


def decode_votes(payload: bytes) -> "tuple[int, bool, list[tuple[object, bytes]]]":
    r = Reader(payload)
    now = r.u64()
    pre_validated = bool(r.u8())
    items = [(decode_scope(r), r.blob()) for _ in range(r.u32())]
    return now, pre_validated, items


def encode_columnar(
    now: int,
    scopes: list,
    scope_idx: "np.ndarray | None",
    blob: bytes,
    offsets: np.ndarray,
) -> bytes:
    """Columnar batch: the record stores the verbatim wire bytes of the
    rows the live engine ACCEPTED (DurableEngine filters by status before
    logging — the live call trusts the caller's gid column, which replay
    cannot reproduce: gid interning is process-local, so recovery re-derives
    the pid/gid/value columns from the wire bytes with fresh interning)."""
    count = len(offsets) - 1
    out = [_u64(now), _u32(len(scopes))]
    for scope in scopes:
        out.append(encode_scope(scope))
    out.append(_u32(count))
    if len(scopes) > 1:
        idx = np.asarray(scope_idx, np.uint32)
        if len(idx) != count:
            raise ValueError("scope_idx must supply one entry per batch row")
        out.append(idx.astype("<u4").tobytes())
    out.append(_blob(blob))
    out.append(np.asarray(offsets, np.int64).astype("<u4").tobytes())
    return b"".join(out)


def decode_columnar(
    payload: bytes,
) -> "tuple[int, list, np.ndarray | None, bytes, np.ndarray]":
    r = Reader(payload)
    now = r.u64()
    scopes = [decode_scope(r) for _ in range(r.u32())]
    count = r.u32()
    scope_idx = None
    if len(scopes) > 1:
        scope_idx = np.frombuffer(r.raw(4 * count), "<u4").astype(np.int64)
    blob = r.blob()
    offsets = np.frombuffer(r.raw(4 * (count + 1)), "<u4").astype(np.int64)
    return now, scopes, scope_idx, blob, offsets


def encode_scope_config_record(mode: int, scope, config: ScopeConfig) -> bytes:
    return _u8(mode) + encode_scope(scope) + encode_scope_config(config)


def decode_scope_config_record(payload: bytes) -> tuple[int, object, ScopeConfig]:
    r = Reader(payload)
    mode = r.u8()
    return mode, decode_scope(r), decode_scope_config(r)


def encode_scope_delete(scopes: list) -> bytes:
    return _u32(len(scopes)) + b"".join(encode_scope(s) for s in scopes)


def decode_scope_delete(payload: bytes) -> list:
    r = Reader(payload)
    return [decode_scope(r) for _ in range(r.u32())]


def encode_timeout(scope, proposal_id: int, now: int) -> bytes:
    # Full u64, NOT masked to the engine's u32 pid space: the record must
    # reproduce the argument the live call received, so a bogus >u32 pid
    # that raised SessionNotFound live raises identically on replay
    # (masking would silently retarget the timeout at a different pid).
    return encode_scope(scope) + _u64(proposal_id) + _u64(now)


def decode_timeout(payload: bytes) -> tuple[object, int, int]:
    r = Reader(payload)
    return decode_scope(r), r.u64(), r.u64()


def encode_sweep(now: int) -> bytes:
    return _u64(now)


# KIND_LIFECYCLE shares the sweep payload: one u64 logical timestamp.
encode_lifecycle = encode_sweep


def encode_gc(keys: list) -> bytes:
    return _u32(len(keys)) + b"".join(
        encode_scope(scope) + _u64(pid) for scope, pid in keys
    )


def decode_gc(payload: bytes) -> list:
    r = Reader(payload)
    return [(decode_scope(r), r.u64()) for _ in range(r.u32())]


def decode_sweep(payload: bytes) -> int:
    return Reader(payload).u64()


def encode_snapshot(watermark: int) -> bytes:
    return _u64(watermark)


def decode_snapshot(payload: bytes) -> int:
    return Reader(payload).u64()
