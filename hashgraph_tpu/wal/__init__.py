"""Durability subsystem: segmented write-ahead log + crash recovery.

The library core performs no I/O by contract; this package is the optional
durability layer an embedder composes around an engine:

- :mod:`.format` — CRC32-framed record layout over the canonical
  ``wire.py`` Proposal/Vote bytes (no second serialization format);
- :mod:`.segment` — ``wal-<base_lsn>.seg`` segmented files, sealed on
  rotation, torn-tail repair confined to the active segment;
- :mod:`.writer` — :class:`WalWriter` with per-record / batched-every-N /
  off fsync policies, rotation, and snapshot-anchored compaction;
- :mod:`.recovery` — :func:`scan` + :func:`replay` through the engine's
  own batch ingest paths (recovered traffic is validated like live
  traffic, torn tails truncate at the first bad frame);
- :mod:`.durable` — :class:`DurableEngine`, the log-before-acknowledge
  engine wrapper with :meth:`~DurableEngine.recover` and
  :meth:`~DurableEngine.checkpoint`.

Quick start::

    from hashgraph_tpu.engine import TpuConsensusEngine
    from hashgraph_tpu.wal import DurableEngine

    durable = DurableEngine(engine, "/var/lib/app/wal", fsync_policy="batch")
    durable.recover(storage)          # snapshot + WAL tail -> warm engine
    durable.create_proposal(...)      # logged before acknowledged
    durable.checkpoint(storage)       # snapshot, mark, drop covered segments

Tracing: the subsystem emits ``wal.append_records`` / ``wal.append_bytes``
/ ``wal.fsync`` / ``wal.rotate`` / ``wal.recover.records`` /
``wal.compact.segments`` / ``wal.repair.truncated_bytes`` counters, plus
the recovery-loss counters ``wal.recover.torn_bytes`` /
``wal.recover.dropped_segments`` / ``wal.recover.decode_errors``, through
:mod:`hashgraph_tpu.tracing` (no-ops until the tracer is enabled).
"""

from . import format, recovery, segment
from .durable import DurableEngine
from .recovery import ReplayStats, WalScan, replay, scan
from .writer import (
    CRASH_POINTS,
    FSYNC_ALWAYS,
    FSYNC_BATCH,
    FSYNC_OFF,
    SimulatedCrash,
    WalWriter,
)

__all__ = [
    "DurableEngine",
    "WalWriter",
    "ReplayStats",
    "WalScan",
    "replay",
    "scan",
    "FSYNC_ALWAYS",
    "FSYNC_BATCH",
    "FSYNC_OFF",
    "CRASH_POINTS",
    "SimulatedCrash",
    "format",
    "recovery",
    "segment",
]
