"""WalWriter: append-side of the durability subsystem.

Responsibilities: frame records (:mod:`.format`), assign monotonically
increasing LSNs, rotate segments at a size threshold (:mod:`.segment`),
run the configured fsync policy, repair a torn tail left by a previous
crash on open, and drop snapshot-covered segments on compaction.

Fsync policies (the durability/throughput dial — see README "Durability &
recovery" for the guarantee each level buys):

- ``"always"``: fsync after every append. An acknowledged record survives
  OS/power failure. Slowest — one fsync per record.
- ``"batch"``: fsync every ``fsync_interval`` appends (and on rotation,
  ``sync()`` and ``close()``). An acknowledged record survives *process*
  crash immediately (the bytes are in the page cache) and OS/power failure
  up to the last interval boundary.
- ``"off"``: never fsync (the OS flushes on its own schedule). Survives
  process crash; OS/power failure may lose the page-cache tail.

Every policy keeps the framing invariant: a record is written with one
buffered ``write`` call and the frame CRC covers the whole body, so a
partially-persisted record is detected and truncated at recovery — the WAL
never replays garbage, it only ever loses an un-fsynced suffix.
"""

from __future__ import annotations

import errno
import os
import threading
import time
import weakref

from ..obs import (
    WAL_FSYNC_SECONDS,
    WAL_SEGMENT_BYTES,
    WAL_SEGMENT_COUNT,
)
from ..obs import registry as default_registry
from ..tracing import tracer as default_tracer
from . import format as F
from .segment import (
    DEFAULT_SEGMENT_BYTES,
    list_segments,
    scan_segment,
    segment_name,
    truncate_segment,
)

FSYNC_ALWAYS = "always"
FSYNC_BATCH = "batch"
FSYNC_OFF = "off"
_POLICIES = (FSYNC_ALWAYS, FSYNC_BATCH, FSYNC_OFF)

# Writer-liveness lock file. Does not parse as a segment (no ``wal-``
# prefix / ``.seg`` suffix), so listing/compaction ignore it.
LOCK_FILENAME = "wal.lock"

# Crash-point labels a ``crash_hook`` observes, in the order one append
# can traverse them. "append" fires with the encoded frame about to be
# written (a hook raising SimulatedCrash(torn_bytes=k) leaves the first k
# bytes of that frame on disk — a torn write); "append.flushed" fires
# after the frame reached the OS; "fsync"/"fsync.done" bracket each fsync
# syscall; "rotate"/"rotate.done" bracket a segment roll.
CRASH_POINTS = (
    "append",
    "append.flushed",
    "fsync",
    "fsync.done",
    "rotate",
    "rotate.done",
)


class SimulatedCrash(RuntimeError):
    """Raised by a WAL ``crash_hook`` to simulate ``kill -9`` at a chosen
    boundary. The writer dies exactly as a killed process would: file
    handles and the cross-process flock are released WITHOUT the close
    path's final fsync, on-disk bytes stay whatever previous flushes left
    (plus, for ``torn_bytes > 0`` at an "append" point, a partial frame —
    the torn tail recovery must truncate). The exception propagates to
    the caller, which treats the engine as dead and recovers through
    :meth:`~hashgraph_tpu.wal.DurableEngine.recover` on a fresh writer."""

    def __init__(self, point: str, torn_bytes: int = 0):
        super().__init__(
            f"simulated crash at WAL point {point!r}"
            + (f" (torn after {torn_bytes} bytes)" if torn_bytes else "")
        )
        self.point = point
        self.torn_bytes = torn_bytes


def _fsync_dir(path: str) -> None:
    """Persist directory-entry changes. fsync on a segment file makes its
    DATA durable but not its EXISTENCE — after a power failure a freshly
    created file can vanish from the directory even though its blocks were
    synced, silently losing acknowledged records in a just-rotated segment.
    Best-effort on platforms without directory fds."""
    try:
        fd = os.open(path, os.O_RDONLY)
    except OSError:
        return
    try:
        os.fsync(fd)
    finally:
        os.close(fd)


class WalWriter:
    """Segmented append-only record log. Thread-safe (one internal lock);
    appends are strictly serialized so LSN order is write order."""

    def __init__(
        self,
        directory: str | os.PathLike,
        *,
        segment_bytes: int = DEFAULT_SEGMENT_BYTES,
        fsync_policy: str = FSYNC_BATCH,
        fsync_interval: int = 256,
        tracer=None,
        crash_hook=None,
    ):
        if fsync_policy not in _POLICIES:
            raise ValueError(
                f"fsync_policy must be one of {_POLICIES}, got {fsync_policy!r}"
            )
        if segment_bytes <= 0:
            raise ValueError("segment_bytes must be positive")
        if fsync_interval <= 0:
            raise ValueError("fsync_interval must be positive")
        self._dir = os.fspath(directory)
        self._segment_bytes = segment_bytes
        self._policy = fsync_policy
        self._interval = fsync_interval
        self._tracer = tracer if tracer is not None else default_tracer
        self._lock = threading.Lock()
        self._since_fsync = 0
        self._closed = False
        # ``crash_hook(point)`` fires at every CRASH_POINTS boundary; it
        # may raise SimulatedCrash to kill the writer there (see _crash).
        # Deterministic-chaos seam — None in production.
        self._crash_hook = crash_hook
        os.makedirs(self._dir, exist_ok=True)

        # Cross-process exclusivity: two writers on one directory would
        # scan the same tail, mint duplicate LSNs, and interleave frames —
        # exactly the corruption the in-process reuse caches prevent, but
        # across processes (e.g. a supervisor restarting a server before
        # the old process finishes closing). flock is advisory and dies
        # with the process, so a crashed writer never wedges the lock.
        self._lock_file = open(os.path.join(self._dir, LOCK_FILENAME), "ab")
        try:
            import fcntl

            fcntl.flock(self._lock_file.fileno(), fcntl.LOCK_EX | fcntl.LOCK_NB)
        except ImportError:
            pass  # non-POSIX: best-effort, in-process reuse still guarded
        except OSError as exc:
            if exc.errno in (errno.EAGAIN, errno.EWOULDBLOCK, errno.EACCES):
                self._lock_file.close()
                raise ValueError(
                    f"WAL directory {self._dir!r} is locked by another live "
                    f"writer; a second writer would corrupt the log"
                ) from None
            # Any other errno means the filesystem cannot take the lock at
            # all (ENOTSUP/ENOLCK on some FUSE/network mounts) — degrade to
            # best-effort like the no-fcntl path rather than misreport an
            # unsupported mount as a live contending writer.

        segments = list_segments(self._dir)
        if segments:
            # Tail repair is confined to the ACTIVE (last) segment: sealed
            # segments were fully written before rotation fsynced them.
            base, path = segments[-1]
            records, valid_end, size = scan_segment(path)
            if valid_end < size:
                removed = truncate_segment(path, valid_end)
                self._tracer.count("wal.repair.truncated_bytes", removed)
            last_lsn = records[-1][0] if records else base - 1
            self._segment_base = base
            self._segment_size = valid_end
            self._next_lsn = last_lsn + 1
            self._file = open(path, "ab")
            self._segment_count = len(segments)
            self._total_bytes = valid_end + sum(
                os.path.getsize(p) for _, p in segments[:-1]
            )
        else:
            self._next_lsn = 1
            self._segment_base = 1
            self._segment_size = 0
            self._file = open(
                os.path.join(self._dir, segment_name(1)), "ab"
            )
            self._segment_count = 1
            self._total_bytes = 0
        # Scrape-time gauges for this writer's on-disk footprint; providers
        # sum across writers (one per durable peer), are unregistered on
        # close, and hold only a weakref so an abandoned writer can still
        # be collected.
        self._m_fsync = default_registry.histogram(WAL_FSYNC_SECONDS)
        ref = weakref.ref(self)

        def _segments() -> int:
            writer = ref()
            return writer._segment_count if writer is not None else 0

        def _bytes() -> int:
            writer = ref()
            return writer._total_bytes if writer is not None else 0

        self._gauge_handles = [
            default_registry.register_gauge(WAL_SEGMENT_COUNT, _segments, owner=self),
            default_registry.register_gauge(WAL_SEGMENT_BYTES, _bytes, owner=self),
        ]
        # The directory entries created above (the dir itself, the lock
        # file, a possibly-new active segment) must be durable before any
        # append is acknowledged.
        _fsync_dir(self._dir)

    # ── Introspection ──────────────────────────────────────────────────

    @property
    def directory(self) -> str:
        return self._dir

    @property
    def last_lsn(self) -> int:
        """LSN of the most recently appended record (0 = nothing logged)."""
        return self._next_lsn - 1

    @property
    def fsync_policy(self) -> str:
        return self._policy

    # ── Appending ──────────────────────────────────────────────────────

    def append(self, kind: int, payload: bytes) -> int:
        """Frame and write one record; returns its LSN. Runs the fsync
        policy and rotates the segment when the size threshold is crossed."""
        if F.BODY_LEAD_BYTES + len(payload) > F.MAX_RECORD:
            # Refuse BEFORE acknowledging: a frame whose body_len exceeds
            # MAX_RECORD is indistinguishable from garbage to the reader
            # (scan_buffer treats it as a torn tail), so writing it would
            # silently destroy this record and everything after it at
            # recovery. Callers with oversized batches must split them
            # (DurableEngine does).
            raise ValueError(
                f"WAL record body would be {F.BODY_LEAD_BYTES + len(payload)} "
                f"bytes, over the MAX_RECORD cap ({F.MAX_RECORD}); split the "
                f"batch across records"
            )
        with self._lock:
            if self._closed:
                raise ValueError("WalWriter is closed")
            lsn = self._next_lsn
            frame = F.encode_record(lsn, kind, payload)
            self._crash("append", frame)
            self._file.write(frame)
            # Flush to the page cache on EVERY append: the policy dial is
            # fsync (durability vs the OS/power failure), not write(2) —
            # an acknowledged record must survive a *process* crash under
            # every policy, and user-space buffering would break that.
            self._file.flush()
            self._crash("append.flushed")
            self._next_lsn = lsn + 1
            self._segment_size += len(frame)
            self._total_bytes += len(frame)
            self._tracer.count("wal.append_records")
            self._tracer.count("wal.append_bytes", len(frame))
            self._since_fsync += 1
            if self._policy == FSYNC_ALWAYS or (
                self._policy == FSYNC_BATCH and self._since_fsync >= self._interval
            ):
                self._fsync_locked()
            if self._segment_size >= self._segment_bytes:
                self._rotate_locked()
            return lsn

    def append_snapshot_mark(self, watermark: int | None = None) -> int:
        """Record that a snapshot now covers every record with
        ``lsn <= watermark`` (default: everything appended so far). The mark
        is always fsynced — compaction deletes data on its authority, so it
        must never be the record a crash loses."""
        with self._lock:
            if watermark is None:
                watermark = self._next_lsn - 1
        lsn = self.append(F.KIND_SNAPSHOT, F.encode_snapshot(watermark))
        self.sync()
        return lsn

    def sync(self) -> None:
        """Flush buffered frames and fsync, regardless of policy."""
        with self._lock:
            if not self._closed:
                self._fsync_locked()

    def rotate(self) -> None:
        """Seal the active segment now (no-op when it's empty). Checkpoints
        rotate before marking so the whole pre-snapshot history lives in
        sealed segments and compaction can drop all of it."""
        with self._lock:
            if self._closed:
                raise ValueError("WalWriter is closed")
            if self._segment_size:
                self._rotate_locked()

    def close(self) -> None:
        with self._lock:
            if self._closed:
                return
            self._fsync_locked()
            self._file.close()
            self._lock_file.close()  # releases the cross-process flock
            self._closed = True
            for handle in self._gauge_handles:
                handle.unregister()

    def abandon(self) -> None:
        """Simulated ``kill -9``: release the file handles and the
        cross-process flock WITHOUT the close path's final fsync. On-disk
        bytes stay exactly what previous flushes left (every append
        flushes to the page cache, so only an in-progress torn write —
        see :class:`SimulatedCrash` — can leave a partial frame). A fresh
        writer can then reopen the directory, which is how the chaos
        harness restarts a crashed peer in-process."""
        with self._lock:
            if self._closed:
                return
            self._closed = True
            for handle in (self._file, self._lock_file):
                try:
                    handle.close()
                except OSError:
                    pass
            for handle in self._gauge_handles:
                handle.unregister()

    def set_crash_hook(self, hook) -> None:
        """Install/replace the crash hook (``None`` removes it)."""
        self._crash_hook = hook

    def _crash(self, point: str, frame: bytes | None = None) -> None:
        """Fire the crash hook at ``point`` (lock held). A raised
        :class:`SimulatedCrash` kills the writer in place: for a
        ``torn_bytes``-carrying crash at an "append" point the first k
        bytes of the un-written frame land on disk first (the torn write
        the recovery scan must detect and truncate), then handles and
        the flock are released crash-style and the exception
        propagates."""
        hook = self._crash_hook
        if hook is None:
            return
        try:
            hook(point)
        except SimulatedCrash as crash:
            if frame is not None and crash.torn_bytes > 0:
                self._file.write(frame[: min(crash.torn_bytes, len(frame))])
            try:
                self._file.close()  # flushes buffered bytes; no fsync
            except OSError:
                pass
            try:
                self._lock_file.close()
            except OSError:
                pass
            self._closed = True
            for handle in self._gauge_handles:
                handle.unregister()
            raise

    def __enter__(self) -> "WalWriter":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    # ── Compaction ─────────────────────────────────────────────────────

    def compact(self, watermark: int) -> int:
        """Delete every SEALED segment fully covered by ``watermark`` (all
        its records have lsn <= watermark — equivalently, the next segment's
        base_lsn - 1 <= watermark). The active segment is never deleted,
        so the log always retains the latest snapshot mark. Returns the
        number of segments removed."""
        with self._lock:
            if self._closed:
                raise ValueError("WalWriter is closed")
            segments = list_segments(self._dir)
            removed = 0
            for (base, path), (next_base, _) in zip(segments, segments[1:]):
                if next_base - 1 <= watermark:
                    try:
                        dropped_bytes = os.path.getsize(path)
                    except OSError:
                        dropped_bytes = 0
                    os.remove(path)
                    removed += 1
                    self._segment_count -= 1
                    self._total_bytes -= dropped_bytes
            if removed:
                self._tracer.count("wal.compact.segments", removed)
            return removed

    # ── Internals ──────────────────────────────────────────────────────

    def _fsync_locked(self) -> None:
        self._crash("fsync")
        self._file.flush()
        start = time.perf_counter()
        os.fsync(self._file.fileno())
        self._crash("fsync.done")
        # wal_fsync_seconds is THE durability/throughput dial's price tag:
        # one observation per fsync syscall, always on.
        self._m_fsync.observe(time.perf_counter() - start)
        self._tracer.count("wal.fsync")
        self._since_fsync = 0

    def _rotate_locked(self) -> None:
        """Seal the current segment (flush + fsync so sealed segments are
        durable and repair stays confined to the active one) and open a new
        segment based at the next LSN."""
        self._crash("rotate")
        self._fsync_locked()
        self._file.close()
        self._segment_base = self._next_lsn
        self._segment_size = 0
        self._segment_count += 1
        self._file = open(
            os.path.join(self._dir, segment_name(self._segment_base)), "ab"
        )
        # Make the new segment's directory entry durable before records in
        # it are acknowledged (file fsync alone doesn't persist existence).
        _fsync_dir(self._dir)
        self._tracer.count("wal.rotate")
        self._crash("rotate.done")
