"""Segmented log files: naming, listing, scanning, tail repair.

A WAL directory holds segments named ``wal-<base_lsn>.seg`` where
``base_lsn`` (zero-padded, 20 digits so lexicographic order == numeric
order) is the LSN of the first record the segment holds (for an empty
just-rotated segment: the next LSN to be written). Segments are strictly
append-only; once the writer rotates past one it is *sealed* and never
modified again. That gives compaction a trivial correctness rule — a sealed
segment's records all have ``lsn < next_segment.base_lsn`` — and confines
torn-tail repair to the single active (last) segment.
"""

from __future__ import annotations

import os

from . import format as F

SEGMENT_PREFIX = "wal-"
SEGMENT_SUFFIX = ".seg"
_LSN_DIGITS = 20

DEFAULT_SEGMENT_BYTES = 64 * 1024 * 1024


def segment_name(base_lsn: int) -> str:
    return f"{SEGMENT_PREFIX}{base_lsn:0{_LSN_DIGITS}d}{SEGMENT_SUFFIX}"


def base_lsn_of(name: str) -> int | None:
    """Parse a segment filename; None for non-segment directory entries."""
    if not (name.startswith(SEGMENT_PREFIX) and name.endswith(SEGMENT_SUFFIX)):
        return None
    digits = name[len(SEGMENT_PREFIX) : -len(SEGMENT_SUFFIX)]
    if not digits.isdigit():
        return None
    return int(digits)


def list_segments(directory: str) -> list[tuple[int, str]]:
    """(base_lsn, absolute path) for every segment, ascending by base_lsn.
    Unrelated files in the directory are ignored."""
    try:
        names = os.listdir(directory)
    except FileNotFoundError:
        return []
    out = []
    for name in names:
        base = base_lsn_of(name)
        if base is not None:
            out.append((base, os.path.join(directory, name)))
    out.sort()
    return out


def scan_segment(path: str) -> tuple[list[tuple[int, int, bytes]], int, int]:
    """Parse one segment file.

    Returns ``(records, valid_end, file_size)`` — ``valid_end < file_size``
    marks a torn tail (see :func:`hashgraph_tpu.wal.format.scan_buffer`).
    Segments are bounded by the writer's rotation threshold, so reading one
    whole file at a time keeps recovery memory proportional to a single
    segment, not the log.
    """
    with open(path, "rb") as fh:
        data = fh.read()
    records, valid_end = F.scan_buffer(data)
    return records, valid_end, len(data)


def truncate_segment(path: str, valid_end: int) -> int:
    """Drop a torn tail in place; returns the number of bytes removed."""
    size = os.path.getsize(path)
    if valid_end >= size:
        return 0
    with open(path, "r+b") as fh:
        fh.truncate(valid_end)
        fh.flush()
        os.fsync(fh.fileno())
    return size - valid_end
