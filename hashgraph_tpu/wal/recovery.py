"""Crash recovery: scan a WAL directory and replay it into an engine.

Replay feeds records through the engine's EXISTING batch entry points —
``ingest_proposals`` / ``ingest_votes`` / ``ingest_columnar`` /
``ingest_columnar_multi`` — so recovered state runs the same validation
gauntlet as live traffic (signatures, chains, expiry, duplicate rejection,
round caps). A record that was rejected live is rejected identically on
replay; statuses are not errors, they are the log converging to the same
observable state the live engine had.

Torn-tail rule (ARIES-style): the scan accepts records up to the first bad
frame — short header, bad length, truncated body, or CRC mismatch — and
ignores everything after it. A torn tail can only exist in the ACTIVE
(last) segment of a clean history (sealed segments are fsynced at
rotation); if an EARLIER segment is torn, every later segment is
unreachable-after-corruption and replay stops there too, reporting the
dropped segments in the scan result rather than replaying around a hole
(log order is the correctness invariant — skipping a gap could replay a
vote before its proposal).
"""

from __future__ import annotations

from dataclasses import dataclass, field

import numpy as np

from ..errors import ConsensusError
from ..tracing import tracer as default_tracer
from ..wire import Proposal, Vote
from . import format as F
from .segment import list_segments, scan_segment


@dataclass
class WalScan:
    """Result of scanning a WAL directory (no engine involved)."""

    records: list  # [(lsn, kind, payload)] in log order
    last_lsn: int = 0
    watermark: int = 0  # max snapshot mark seen (0 = no snapshot)
    torn_path: str | None = None  # segment holding the first bad frame
    torn_bytes: int = 0  # bytes ignored after the first bad frame
    segments_dropped: int = 0  # later segments unreachable past a torn one

    @property
    def torn(self) -> bool:
        return self.torn_path is not None


@dataclass
class ReplayStats:
    """Result of replaying a scan into an engine."""

    records_total: int = 0  # records seen (incl. snapshot marks)
    records_applied: int = 0  # records dispatched into the engine
    records_skipped: int = 0  # covered by the watermark (snapshot holds them)
    votes_replayed: int = 0  # individual vote rows across all records
    proposals_replayed: int = 0
    last_lsn: int = 0
    watermark: int = 0
    errors: list = field(default_factory=list)  # (lsn, repr(exc)) decode faults
    # Torn-tail diagnostics, mirrored from the scan so recover() callers see
    # them without a separate scan: torn_path is the segment holding the
    # first bad frame; segments_dropped counts LATER segments that were
    # unreachable past it (nonzero = mid-log corruption, not a crash tail —
    # acknowledged records were lost and the embedder should be told).
    torn_path: "str | None" = None
    torn_bytes: int = 0
    segments_dropped: int = 0

    @property
    def torn(self) -> bool:
        return self.torn_path is not None


def _iter_intact(directory: str, meta: WalScan):
    """Yield each segment's intact records (one list per segment, so the
    caller holds at most one segment in memory), applying the torn-tail
    rule — stop after the first torn segment — and filling ``meta``'s
    torn/last_lsn/watermark fields as a side effect."""
    segments = list_segments(directory)
    for i, (_base, path) in enumerate(segments):
        records, valid_end, size = scan_segment(path)
        for lsn, kind, payload in records:
            if kind == F.KIND_SNAPSHOT:
                mark = F.decode_snapshot(payload)
                if mark > meta.watermark:
                    meta.watermark = mark
        if records:
            meta.last_lsn = records[-1][0]
        yield records
        if valid_end < size:
            meta.torn_path = path
            meta.torn_bytes = size - valid_end
            meta.segments_dropped = len(segments) - i - 1
            return


def scan(directory: str) -> WalScan:
    """Read every intact record in LSN order, applying the torn-tail rule.

    Materializes the whole surviving log; for replay of large logs prefer
    passing the directory path straight to :func:`replay`, which streams
    one segment at a time (the snapshot watermark is found on a cheap
    first pass, so covered records are decoded but never retained)."""
    result = WalScan(records=[])
    for records in _iter_intact(directory, result):
        result.records.extend(records)
    return result


def replay(
    source: "str | WalScan",
    engine,
    *,
    after_lsn: "int | None" = 0,
    tracer=None,
    on_record=None,
) -> ReplayStats:
    """Replay a WAL (directory path or a prior :func:`scan`) into ``engine``.

    ``after_lsn`` skips records the caller has already restored by other
    means — pass the snapshot watermark after ``load_from_storage``, or
    ``None`` to use the log's own latest watermark (that is what
    :meth:`DurableEngine.recover` does); the default ``0`` replays every
    surviving record into a fresh engine.

    A directory-path ``source`` is streamed one segment at a time, so
    recovery memory is bounded by a single segment, not the log
    (``after_lsn=None`` costs one extra metadata pass over the files to
    find the watermark first). A :class:`WalScan` source replays the
    already-materialized records.

    The engine will emit events for replayed transitions exactly as live
    traffic would; attach/subscribe the event bus AFTER recovery unless the
    embedder wants the replayed stream.

    ``on_record(lsn, kind)`` (optional) is invoked before each surviving
    record is applied — replay progress observation for long logs (a
    fleet supervisor reporting a recovering shard's position, or a test
    holding a replay mid-flight to assert other shards keep serving).
    Exceptions from the callback abort the replay.
    """
    tr = tracer if tracer is not None else default_tracer
    log_watermark = 0  # marks the probe saw beyond forward-reachable ones
    if isinstance(source, str):
        if after_lsn is None:
            after_lsn = log_watermark = latest_watermark(source)
        meta = WalScan(records=[])
        stats = ReplayStats()
        for records in _iter_intact(source, meta):
            for lsn, kind, payload in records:
                if on_record is not None:
                    on_record(lsn, kind)
                _replay_record(engine, lsn, kind, payload, after_lsn, stats, tr)
    else:
        meta = source
        if after_lsn is None:
            after_lsn = meta.watermark
        stats = ReplayStats()
        for lsn, kind, payload in meta.records:
            if on_record is not None:
                on_record(lsn, kind)
            _replay_record(engine, lsn, kind, payload, after_lsn, stats, tr)
    stats.last_lsn = meta.last_lsn
    stats.watermark = max(meta.watermark, log_watermark)
    stats.torn_path = meta.torn_path
    stats.torn_bytes = meta.torn_bytes
    stats.segments_dropped = meta.segments_dropped
    # Corruption is never silent: beyond the returned stats, emit counters
    # so an embedder watching tracing sees data loss without inspecting
    # every ReplayStats (nonzero dropped_segments/decode_errors means
    # acknowledged records could not be replayed — not a crash tail).
    if stats.torn_bytes:
        tr.count("wal.recover.torn_bytes", stats.torn_bytes)
    if stats.segments_dropped:
        tr.count("wal.recover.dropped_segments", stats.segments_dropped)
    if stats.errors:
        tr.count("wal.recover.decode_errors", len(stats.errors))
    return stats


def latest_watermark(directory: str) -> int:
    """Find the most recent snapshot watermark by scanning segments
    NEWEST-first and stopping at the first one holding a snapshot record —
    for a checkpointing node that is the active (or last sealed) segment,
    so recovery's watermark probe reads one or two files, not the log.

    A watermark found past a torn mid-log segment (which forward replay
    would drop) is still safe to honor: the snapshot covers every record
    ``lsn <= watermark`` regardless of whether the log bytes carrying the
    mark are forward-reachable."""
    for _base, path in reversed(list_segments(directory)):
        records, _, _ = scan_segment(path)
        marks = [
            F.decode_snapshot(payload)
            for _lsn, kind, payload in records
            if kind == F.KIND_SNAPSHOT
        ]
        if marks:
            return max(marks)
    return 0


def _replay_record(engine, lsn, kind, payload, after_lsn, stats, tr) -> None:
    stats.records_total += 1
    if kind == F.KIND_SNAPSHOT:
        return  # bookkeeping, not state
    if lsn <= after_lsn:
        stats.records_skipped += 1
        return
    apply_record(engine, kind, payload, stats, tracer=tr, lsn=lsn)


def apply_record(
    engine, kind: int, payload: bytes, stats: "ReplayStats | None" = None,
    *, tracer=None, lsn: int = 0,
) -> ReplayStats:
    """Dispatch ONE decoded WAL record through the engine's live batch
    entry points — the unit step of :func:`replay`, public so other
    consumers of the record stream (the state-sync tail,
    :mod:`hashgraph_tpu.sync.client`) apply records with identical
    semantics: validation runs exactly as live traffic, rejections settle
    as converged state, payload decode faults land in ``stats.errors``.
    Snapshot marks are bookkeeping and apply nothing."""
    if stats is None:
        stats = ReplayStats()
    tr = tracer if tracer is not None else default_tracer
    if kind == F.KIND_SNAPSHOT:
        return stats
    try:
        _apply(engine, kind, payload, stats)
    except ConsensusError:
        # Scalar entry points raise on rejection (process_incoming_vote
        # style); the live call raised the same way — state converged.
        pass
    except ValueError as exc:
        # Payload decode fault inside a CRC-valid record: surface it,
        # keep replaying (the frame layer guarantees record boundaries).
        stats.errors.append((lsn, repr(exc)))
        return stats
    stats.records_applied += 1
    tr.count("wal.recover.records")
    return stats


def read_tail(
    directory: str,
    after_lsn: int = 0,
    max_bytes: int = 4 * 1024 * 1024,
) -> "tuple[list[tuple[int, int, bytes]], bool]":
    """Read intact records with ``lsn > after_lsn`` in log order, bounded
    by ``max_bytes`` of payload — the serving side of WAL tailing
    (``OP_WAL_TAIL``). Returns ``(records, more)``: ``more`` is True when
    the budget stopped the read with further intact records available, so
    a caller loops with ``after_lsn`` advanced to the last served LSN
    until ``(few records, False)``.

    Sealed segments entirely below ``after_lsn`` are skipped by filename
    (their base LSNs bound their contents), so repeated tail polls on a
    long log do not rescan history. The torn-tail rule applies: records
    past the first bad frame are not served (a concurrent writer's
    in-flight append parses as a torn tail and is simply served on the
    next poll). LSN continuity of the result is the CLIENT's check —
    a gap here means compaction or mid-log corruption ate part of the
    suffix, and applying around it would reorder history."""
    records: list[tuple[int, int, bytes]] = []
    used = 0
    segments = list_segments(directory)
    for i, (base, path) in enumerate(segments):
        if i + 1 < len(segments) and segments[i + 1][0] - 1 <= after_lsn:
            continue  # sealed segment fully at or below the watermark
        seg_records, valid_end, size = scan_segment(path)
        for lsn, kind, payload in seg_records:
            if lsn <= after_lsn:
                continue
            if records and used + len(payload) > max_bytes:
                return records, True
            records.append((lsn, kind, payload))
            used += len(payload)
        if valid_end < size:
            break  # torn: later segments are unreachable-after-corruption
    return records, False


def _replay_columnar(engine, now, scopes, scope_idx, blob, offsets) -> None:
    """Re-apply a columnar record through the pre-validated columnar
    ingest, re-deriving gids from the wire bytes (fresh interning)."""
    votes = [
        Vote.decode(blob[offsets[i] : offsets[i + 1]])
        for i in range(len(offsets) - 1)
    ]
    pids = np.fromiter((v.proposal_id for v in votes), np.int64, len(votes))
    gids = np.fromiter(
        (engine.voter_gid(v.vote_owner) for v in votes), np.int64, len(votes)
    )
    values = np.fromiter((v.vote for v in votes), bool, len(votes))
    if len(scopes) > 1:
        engine.ingest_columnar_multi(
            scopes, scope_idx, pids, gids, values, now,
            wire_votes=(blob, offsets),
        )
    else:
        engine.ingest_columnar(
            scopes[0], pids, gids, values, now, wire_votes=(blob, offsets)
        )


def _apply(engine, kind: int, payload: bytes, stats: ReplayStats) -> None:
    if kind == F.KIND_PROPOSALS:
        now, items = F.decode_proposals(payload)
        decoded = [(scope, Proposal.decode(wire)) for scope, wire, _ in items]
        configs = [config for _, _, config in items]
        engine.ingest_proposals(decoded, now, configs=configs)
        stats.proposals_replayed += len(decoded)
    elif kind == F.KIND_DELIVER:
        # Same payload as KIND_PROPOSALS, different entry point: the
        # create-or-extend path is deterministic given engine state, so
        # replay re-derives the live run's exact suffix applications.
        now, items = F.decode_proposals(payload)
        decoded = [(scope, Proposal.decode(wire)) for scope, wire, _ in items]
        configs = [config for _, _, config in items]
        engine.deliver_proposals(decoded, now, configs=configs)
        stats.proposals_replayed += len(decoded)
    elif kind == F.KIND_VOTES:
        now, pre_validated, items = F.decode_votes(payload)
        decoded = [(scope, Vote.decode(wire)) for scope, wire in items]
        engine.ingest_votes(decoded, now, pre_validated=pre_validated)
        stats.votes_replayed += len(decoded)
    elif kind == F.KIND_COLUMNAR:
        now, scopes, scope_idx, blob, offsets = F.decode_columnar(payload)
        _replay_columnar(engine, now, scopes, scope_idx, blob, offsets)
        stats.votes_replayed += len(offsets) - 1
    elif kind == F.KIND_WIRE_COLUMNAR:
        # Same payload as KIND_COLUMNAR, replayed through the WIRE path:
        # the live call retained its chains wire-validated, so replay
        # must too — routing through plain columnar ingest would demote
        # ``wire_only`` and the recovered peer would silently drop the
        # cross-frame dangling-vote guard its non-crashed twins keep
        # (see format.KIND_WIRE_COLUMNAR). Only accepted rows were
        # logged, so crypto is skipped: a trusted prepass marks every
        # row verified — the KIND_COLUMNAR replay trust model, same WAL.
        from ..bridge import columnar as C
        from ..engine.engine import WireVotePrepass

        now, scopes, scope_idx, blob, offsets = F.decode_columnar(payload)
        offs = np.asarray(offsets, np.int64)
        n = len(offs) - 1
        cols, flags = C.parse_vote_columns(blob, offs)
        if bool(flags.all()) and hasattr(engine, "ingest_wire_columnar"):
            trusted = WireVotePrepass(
                np.zeros(n, np.int32),
                np.zeros(0, np.int64),
                lambda: [],
                buf=bytes(blob),
            )
            engine.ingest_wire_columnar(
                scopes,
                scope_idx if scope_idx is not None else np.zeros(n, np.int64),
                cols,
                np.frombuffer(blob, np.uint8),
                offs,
                now,
                _prepass=trusted,
            )
        else:  # pragma: no cover — live rows were canonical by construction
            _replay_columnar(engine, now, scopes, scope_idx, blob, offsets)
        stats.votes_replayed += n
    elif kind == F.KIND_SCOPE_CONFIG:
        mode, scope, config = F.decode_scope_config_record(payload)
        if mode == F.SCOPE_CONFIG_INITIALIZE:
            engine._initialize_scope(scope, config)
        elif mode == F.SCOPE_CONFIG_UPDATE:
            engine._update_scope_config(scope, config)
        else:
            engine.set_scope_config(scope, config)
    elif kind == F.KIND_SCOPE_DELETE:
        engine.delete_scopes(F.decode_scope_delete(payload))
    elif kind == F.KIND_TIMEOUT:
        scope, pid, now = F.decode_timeout(payload)
        engine.handle_consensus_timeout(scope, pid, now)
    elif kind == F.KIND_SWEEP:
        engine.sweep_timeouts(F.decode_sweep(payload))
    elif kind == F.KIND_LIFECYCLE:
        # Standalone tier sweep. Under recovery's replay mode the
        # engine's lifecycle hook is a no-op — the live run's TTL GC
        # arrives as the following KIND_GC record — so this replays the
        # call for engines replaying OUTSIDE replay mode (direct
        # replay() use, where live-path clock reconstruction makes the
        # policy re-derivable) and is otherwise inert.
        engine.lifecycle_sweep(F.decode_sweep(payload))
    elif kind == F.KIND_GC:
        # The live sweep's exact TTL-GC outcome (see format.KIND_GC):
        # applied verbatim, idempotent for keys a re-derived sweep
        # already collected.
        engine.gc_sessions(F.decode_gc(payload))
    else:
        raise ValueError(f"unknown WAL record kind {kind}")
