"""DurableEngine: write-ahead-logged wrapper around a consensus engine.

The library core performs no I/O by contract (the embedder owns
persistence); a crash between ``save_to_storage`` snapshots therefore loses
every proposal and vote ingested since the last snapshot. ``DurableEngine``
closes that window with the classic ARIES + Raft-snapshot recipe:

1. **Log before acknowledging.** Every mutating call appends one WAL record
   of the canonical wire bytes (network ingest logs BEFORE applying;
   locally-minted data — ``create_proposal`` / ``cast_vote``, whose bytes
   only exist after the engine builds them — and columnar batches — whose
   per-row accept/reject outcome only the engine knows, see
   :meth:`DurableEngine._log_columnar_accepted` — log after applying but
   before returning, so nothing unlogged is ever acknowledged).
2. **Replay the tail on restart.** :meth:`recover` loads the latest
   snapshot (if any) and replays every record past its watermark through
   the engine's own batch ingest paths — recovered traffic is validated
   exactly like live traffic.
3. **Compact behind snapshots.** :meth:`checkpoint` saves a snapshot,
   appends a watermark mark, and deletes every sealed segment the snapshot
   fully covers.

The wrapper exposes the full engine surface: mutators are intercepted and
logged; reads (and everything else) delegate to the wrapped engine
untouched. One wrapper-level lock serializes mutators so WAL order always
equals apply order across threads — acceptable because the engine itself is
coarse-locked by design.
"""

from __future__ import annotations

import os
import threading
import time

import numpy as np

from ..errors import StatusCode
from ..obs import WAL_CHECKPOINTS_TOTAL, WAL_RECOVER_SECONDS, flight_recorder
from ..obs import registry as default_registry
from ..scope_config import ScopeConfig, ScopeConfigBuilder
from ..wire import normalize_wire_votes
from . import format as F
from .recovery import ReplayStats, replay
from .writer import WalWriter


class DurableEngine:
    """Write-ahead-logged engine front-end.

    ``engine`` is typically a
    :class:`~hashgraph_tpu.engine.TpuConsensusEngine` but any object with
    the same surface works (the wrapper never imports the engine class, so
    this module stays jax-free). ``wal`` is a :class:`WalWriter` or a
    directory path (extra keyword arguments are forwarded to the writer —
    ``fsync_policy``, ``segment_bytes``, ...).
    """

    def __init__(self, engine, wal, *, record_budget: int = F.MAX_RECORD, **wal_kwargs):
        if isinstance(wal, (str, os.PathLike)):
            wal = WalWriter(wal, **wal_kwargs)
        elif wal_kwargs:
            raise ValueError(
                "wal_kwargs are only valid when wal is a directory path"
            )
        if record_budget <= 0 or record_budget > F.MAX_RECORD:
            raise ValueError("record_budget must be in (0, format.MAX_RECORD]")
        self._engine = engine
        self._wal = wal
        # Soft per-record payload budget: batches whose encoding would
        # cross it are split across multiple records (one engine apply,
        # several log records — replay applies them as consecutive smaller
        # batches, which is semantically identical because the engine's
        # batch semantics equal its sequential semantics at any batch
        # size). The writer independently enforces the hard MAX_RECORD cap.
        self._record_budget = record_budget
        self._ckpt_watermark = 0
        self._lock = threading.RLock()

    def _append_split(self, kind, items, encode, lead, sizeof) -> None:
        """Append ``encode(chunk)`` for consecutive chunks of ``items``,
        each chunk's payload (``lead`` header bytes + per-item ``sizeof``
        footprints) inside the record budget. Boundaries are chosen
        arithmetically so every byte is encoded exactly once — no trial
        encodes of oversized payloads. A single item over the budget is
        appended as-is (the writer raises if it also exceeds the hard cap —
        nothing is acked in that case). Splitting is invisible to replay:
        consecutive smaller batches are semantically identical because the
        engine's batch semantics equal its sequential semantics at any
        batch size."""
        budget = self._record_budget - F.BODY_LEAD_BYTES - lead
        chunk: list = []
        used = 0
        for item in items:
            size = sizeof(item)
            if chunk and used + size > budget:
                self._wal.append(kind, encode(chunk))
                chunk, used = [], 0
            chunk.append(item)
            used += size
        if chunk:
            self._wal.append(kind, encode(chunk))

    def _append_columnar_split(
        self, now, scopes, scope_idx, blob, offsets, kind=None
    ) -> None:
        """Columnar counterpart of :meth:`_append_split`: chunk the ROW
        range by walking the offsets (per-row footprint = wire bytes + one
        u32 offset entry + one u32 scope_idx entry when multi-scope),
        rebasing offsets and slicing scope_idx per chunk. Each chunk keeps
        the full scope list — only the rows are split."""
        if kind is None:
            kind = F.KIND_COLUMNAR
        multi = len(scopes) > 1
        # Fixed per-record lead: now + scope count + scopes + row count +
        # blob length prefix + the offsets array's extra (rows+1)th entry.
        lead = 8 + 4 + sum(len(F.encode_scope(s)) for s in scopes) + 4 + 4 + 4
        budget = self._record_budget - F.BODY_LEAD_BYTES - lead
        per_row_fixed = 8 if multi else 4
        count = len(offsets) - 1
        start = 0
        while start < count:
            end, used = start, 0
            while end < count:
                row = per_row_fixed + int(offsets[end + 1] - offsets[end])
                if end > start and used + row > budget:
                    break
                used += row
                end += 1
            lo, hi = int(offsets[start]), int(offsets[end])
            self._wal.append(
                kind,
                F.encode_columnar(
                    now,
                    scopes,
                    scope_idx[start:end] if multi else None,
                    blob[lo:hi],
                    offsets[start : end + 1] - lo,
                ),
            )
            start = end

    def _log_columnar_accepted(
        self, now, scopes, scope_idx, blob, offsets, statuses, kind=None
    ) -> None:
        """Log the rows the engine ACCEPTED (status OK) out of an applied
        columnar batch. Columnar records are logged after the apply, before
        the ack, because only the engine knows which rows it tallied: the
        live call trusts the caller's interned gid column (stale gids are
        dropped by the liveness check), while replay must re-derive gids
        from the wire bytes — fresh interning that would ACCEPT a row the
        live engine rejected. Logging only tallied rows keeps the recovered
        engine observably identical. A crash between apply and log loses an
        unacknowledged batch — same contract as the locally-minted paths."""
        ok = np.asarray(statuses, np.int64) == int(StatusCode.OK)
        if not ok.any():
            return
        if ok.all():
            self._append_columnar_split(
                now, scopes, scope_idx, blob, offsets, kind=kind
            )
            return
        keep = np.flatnonzero(ok)
        lens = (offsets[1:] - offsets[:-1])[keep]
        new_offsets = np.zeros(len(keep) + 1, np.int64)
        np.cumsum(lens, out=new_offsets[1:])
        new_blob = b"".join(
            blob[int(offsets[i]) : int(offsets[i + 1])] for i in keep
        )
        idx = None if scope_idx is None else np.asarray(scope_idx)[keep]
        self._append_columnar_split(
            now, scopes, idx, new_blob, new_offsets, kind=kind
        )

    # ── Accessors ──────────────────────────────────────────────────────

    @property
    def engine(self):
        return self._engine

    @property
    def wal(self) -> WalWriter:
        return self._wal

    def close(self) -> None:
        self._wal.close()

    def abandon(self) -> None:
        """Simulated ``kill -9`` (see :meth:`WalWriter.abandon`): release
        the WAL's handles and flock without the close-path fsync, so a
        chaos harness can restart this identity from the surviving log
        in-process. The wrapped engine object is left as-is — a crashed
        process's memory is simply gone; callers drop their reference."""
        self._wal.abandon()

    def __enter__(self) -> "DurableEngine":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    def __getattr__(self, name):
        # Reads and anything else not intercepted delegate to the engine.
        return getattr(self._engine, name)

    def explain_decision(self, scope, proposal_id) -> dict:
        """Engine decision provenance plus this peer's durability
        position: the WAL LSN watermark at readout time (every record at
        or below ``last_lsn`` survives a crash under the configured fsync
        policy) and the last checkpoint watermark (records at or below it
        are also covered by a snapshot)."""
        out = self._engine.explain_decision(scope, proposal_id)
        out["wal"] = self._wal_overlay()
        return out

    def capture_consistent(self, capture):
        """Run ``capture(engine, watermark)`` under the mutator lock and
        return its result: the callback observes a frozen engine whose
        state reflects exactly the records with ``lsn <= watermark``
        (mutators and the capture serialize on the same lock, so nothing
        can land between reading the LSN and reading the state). This is
        the consistency primitive state-sync snapshot builds ride on
        (:func:`hashgraph_tpu.sync.snapshot.build_snapshot`); the capture
        should be read-only and brief — writes stall for its duration."""
        with self._lock:
            return capture(self._engine, self._wal.last_lsn)

    def health_report(self, now=None) -> dict:
        """Engine health snapshot (scorecards / evidence / watchdog /
        alerts) plus this peer's durability position — same overlay as
        :meth:`explain_decision`, so an operator reading one health blob
        also knows what a crash right now would and would not lose."""
        out = self._engine.health_report(now)
        out["wal"] = self._wal_overlay()
        return out

    def _wal_overlay(self) -> dict:
        return {
            "last_lsn": self._wal.last_lsn,
            "checkpoint_watermark": self._ckpt_watermark,
            "fsync_policy": self._wal.fsync_policy,
        }

    # ── Recovery ───────────────────────────────────────────────────────

    def recover(
        self,
        storage=None,
        *,
        after_lsn: "int | None" = None,
        on_record=None,
    ) -> ReplayStats:
        """Rebuild the wrapped engine from the WAL (and optionally a
        snapshot): with ``storage``, loads it first and replays only records
        the snapshot does not cover; without, replays every surviving
        record from the start of the log. If compaction ever ran, records
        before the watermark no longer exist on disk, so the snapshot
        ``storage`` is required to recover them.

        By default a loaded ``storage`` is assumed to be the LATEST
        checkpoint, and replay skips up to the log's most recent snapshot
        mark. If you keep older snapshots too, that assumption is unsafe —
        recovering an older (or empty) storage under it would silently skip
        acknowledged records the snapshot does not actually contain. For
        that case persist :attr:`last_checkpoint_watermark` alongside each
        snapshot and pass it back here as ``after_lsn``: replay then skips
        exactly the records that snapshot covers. (Over-replay is safe — a
        watermark older than the snapshot just re-ingests records the
        engine rejects as duplicates — so when unsure, pass a smaller
        ``after_lsn``.)

        ``on_record(lsn, kind)`` forwards to
        :func:`~hashgraph_tpu.wal.recovery.replay` — replay-progress
        observation for long logs (a fleet supervisor reporting a
        recovering shard's position)."""
        with self._lock:
            start = time.perf_counter()
            # Replay-mode metrics gate (engines without one — this module
            # is duck-typed — just replay unguarded): replayed decisions
            # were made before the crash, so they must not feed the
            # decision-latency histogram or re-count as fresh decisions.
            set_mode = getattr(self._engine, "set_replay_mode", None)
            if set_mode is not None:
                set_mode(True)
            try:
                if storage is None:
                    stats = replay(
                        self._wal.directory,
                        self._engine,
                        after_lsn=0 if after_lsn is None else after_lsn,
                        on_record=on_record,
                    )
                else:
                    self._engine.load_from_storage(storage)
                    # after_lsn=None: skip records the latest snapshot
                    # covers (replay finds the watermark on a first
                    # metadata pass and streams the tail one segment at a
                    # time).
                    stats = replay(
                        self._wal.directory,
                        self._engine,
                        after_lsn=after_lsn,
                        on_record=on_record,
                    )
            finally:
                if set_mode is not None:
                    set_mode(False)
            duration = time.perf_counter() - start
            default_registry.histogram(WAL_RECOVER_SECONDS).observe(duration)
            flight_recorder.record(
                "wal.recover",
                directory=self._wal.directory,
                records=stats.records_applied,
                errors=len(stats.errors),
                segments_dropped=stats.segments_dropped,
                seconds=round(duration, 6),
            )
            return stats

    # ── Proposal lifecycle ─────────────────────────────────────────────

    # Conservative upper bound on everything a single-proposal record adds
    # beyond the request's variable-length fields (wire varints/tags, the
    # consensus-config override, counts, framing).
    _MINT_SLACK = 1024

    def _ensure_mintable(self, scope, request) -> None:
        """Reject a create request whose logged record could exceed the
        hard MAX_RECORD cap BEFORE the engine mints anything. The minted
        wire bytes only exist after the engine builds them, so the
        locally-minted paths log after applying — an unloggable request
        must therefore fail before the apply, or the live engine would
        hold state recovery can never reproduce."""
        bound = (
            len(request.payload)
            + len(request.name.encode("utf-8"))
            + len(request.proposal_owner)
            + len(F.encode_scope(scope))
            + self._MINT_SLACK
        )
        if F.BODY_LEAD_BYTES + bound > F.MAX_RECORD:
            raise ValueError(
                f"proposal payload too large to log durably: the WAL record "
                f"could exceed MAX_RECORD ({F.MAX_RECORD} bytes)"
            )

    def create_proposal(self, scope, request, now, config=None):
        with self._lock:
            self._ensure_mintable(scope, request)
            proposal = self._engine.create_proposal(scope, request, now, config)
            self._wal.append(
                F.KIND_PROPOSALS,
                F.encode_proposals(now, [(scope, proposal.encode(), config)]),
            )
            return proposal

    def create_proposals(self, scope, requests, now, config=None):
        with self._lock:
            for request in requests:
                self._ensure_mintable(scope, request)
            proposals = self._engine.create_proposals(scope, requests, now, config)
            self._append_split(
                F.KIND_PROPOSALS,
                [(scope, p.encode(), config) for p in proposals],
                lambda items: F.encode_proposals(now, items),
                F.PROPOSALS_LEAD_BYTES,
                F.sizeof_proposal_item,
            )
            return proposals

    def create_proposals_multi(self, items, now, config=None):
        with self._lock:
            for scope, requests in items:
                for request in requests:
                    self._ensure_mintable(scope, request)
            out = self._engine.create_proposals_multi(items, now, config)
            flat = [
                (scope, p.encode(), config)
                for (scope, _), proposals in zip(items, out)
                for p in proposals
            ]
            self._append_split(
                F.KIND_PROPOSALS,
                flat,
                lambda its: F.encode_proposals(now, its),
                F.PROPOSALS_LEAD_BYTES,
                F.sizeof_proposal_item,
            )
            return out

    def process_incoming_proposal(self, scope, proposal, now, config=None):
        with self._lock:
            self._wal.append(
                F.KIND_PROPOSALS,
                F.encode_proposals(now, [(scope, proposal.encode(), config)]),
            )
            self._engine.process_incoming_proposal(scope, proposal, now, config)

    def ingest_proposals(self, items, now, configs=None):
        with self._lock:
            self._append_split(
                F.KIND_PROPOSALS,
                [
                    (
                        scope,
                        proposal.encode(),
                        configs[i] if configs is not None else None,
                    )
                    for i, (scope, proposal) in enumerate(items)
                ],
                lambda its: F.encode_proposals(now, its),
                F.PROPOSALS_LEAD_BYTES,
                F.sizeof_proposal_item,
            )
            return self._engine.ingest_proposals(items, now, configs=configs)

    def deliver_proposal(self, scope, proposal, now, config=None):
        with self._lock:
            self._wal.append(
                F.KIND_DELIVER,
                F.encode_proposals(now, [(scope, proposal.encode(), config)]),
            )
            return self._engine.deliver_proposal(scope, proposal, now, config)

    def deliver_proposals(self, items, now, configs=None):
        """Create-or-extend gossip delivery, logged under KIND_DELIVER so
        replay re-runs the watermark path (a KIND_PROPOSALS record would
        replay as plain ingest and silently DROP the suffix votes an
        extension applied live). Record splitting is safe because
        deliver_proposals processes items strictly in order — a batch
        call is definitionally equivalent to the same deliveries made as
        consecutive smaller batches (the engine documents that guarantee
        as load-bearing for exactly this splitting)."""
        with self._lock:
            self._append_split(
                F.KIND_DELIVER,
                [
                    (
                        scope,
                        proposal.encode(),
                        configs[i] if configs is not None else None,
                    )
                    for i, (scope, proposal) in enumerate(items)
                ],
                lambda its: F.encode_proposals(now, its),
                F.PROPOSALS_LEAD_BYTES,
                F.sizeof_proposal_item,
            )
            return self._engine.deliver_proposals(items, now, configs=configs)

    # ── Voting ─────────────────────────────────────────────────────────

    def cast_vote(self, scope, proposal_id, choice, now):
        with self._lock:
            vote = self._engine.cast_vote(scope, proposal_id, choice, now)
            # Locally built and signed by this engine's own signer — replay
            # skips re-validation exactly as the live apply did.
            self._wal.append(
                F.KIND_VOTES,
                F.encode_votes(now, True, [(scope, vote.encode())]),
            )
            return vote

    def cast_vote_and_get_proposal(self, scope, proposal_id, choice, now):
        with self._lock:
            self.cast_vote(scope, proposal_id, choice, now)
            return self._engine.get_proposal(scope, proposal_id)

    def process_incoming_vote(self, scope, vote, now):
        with self._lock:
            self._wal.append(
                F.KIND_VOTES, F.encode_votes(now, False, [(scope, vote.encode())])
            )
            self._engine.process_incoming_vote(scope, vote, now)

    def ingest_votes(self, items, now, pre_validated=False):
        with self._lock:
            self._append_split(
                F.KIND_VOTES,
                [(scope, vote.encode()) for scope, vote in items],
                lambda its: F.encode_votes(now, pre_validated, its),
                F.VOTES_LEAD_BYTES,
                F.sizeof_vote_item,
            )
            return self._engine.ingest_votes(items, now, pre_validated=pre_validated)

    def ingest_votes_pipelined(self, batches, now, pre_validated=False):
        """Durable :meth:`TpuConsensusEngine.ingest_votes_pipelined`: one
        KIND_VOTES record per batch, all logged IN ORDER before any batch
        applies (log-before-ack at the granularity of the whole pipelined
        call — statuses are not returned until every batch applied, so a
        crash replays exactly the batch sequence the caller would have
        been acked for, and replay runs them as plain sequential
        ingest_votes calls, which the pipelined path is result-identical
        to by contract)."""
        with self._lock:
            batches = [list(b) for b in batches]
            for items in batches:
                self._append_split(
                    F.KIND_VOTES,
                    [(scope, vote.encode()) for scope, vote in items],
                    lambda its: F.encode_votes(now, pre_validated, its),
                    F.VOTES_LEAD_BYTES,
                    F.sizeof_vote_item,
                )
            return self._engine.ingest_votes_pipelined(
                batches, now, pre_validated=pre_validated
            )

    def ingest_columnar(
        self,
        scope,
        proposal_ids,
        voter_gids,
        values,
        now,
        max_depth=8,
        wire_votes=None,
    ):
        if wire_votes is None:
            raise ValueError(
                "durable columnar ingest requires wire_votes: without the "
                "canonical vote bytes the batch cannot be logged or replayed "
                "(gid interning is process-local)"
            )
        with self._lock:
            blob, offsets = normalize_wire_votes(wire_votes, len(proposal_ids))
            statuses = self._engine.ingest_columnar(
                scope,
                proposal_ids,
                voter_gids,
                values,
                now,
                max_depth=max_depth,
                wire_votes=(blob, offsets),
            )
            self._log_columnar_accepted(
                now, [scope], None, blob, offsets, statuses
            )
            return statuses

    def ingest_columnar_multi(
        self,
        scopes,
        scope_idx,
        proposal_ids,
        voter_gids,
        values,
        now,
        max_depth=8,
        wire_votes=None,
    ):
        if wire_votes is None:
            raise ValueError(
                "durable columnar ingest requires wire_votes: without the "
                "canonical vote bytes the batch cannot be logged or replayed "
                "(gid interning is process-local)"
            )
        with self._lock:
            blob, offsets = normalize_wire_votes(wire_votes, len(proposal_ids))
            idx = None if len(scopes) <= 1 else np.asarray(scope_idx)
            statuses = self._engine.ingest_columnar_multi(
                scopes,
                scope_idx,
                proposal_ids,
                voter_gids,
                values,
                now,
                max_depth=max_depth,
                wire_votes=(blob, offsets),
            )
            self._log_columnar_accepted(
                now, list(scopes), idx, blob, offsets, statuses
            )
            return statuses

    def ingest_wire_columnar(
        self,
        scopes,
        scope_idx,
        cols,
        data,
        offsets,
        now,
        max_depth=8,
        stage_seconds=None,
        _prepass=None,
    ):
        """Durable wire-columnar ingest (the bridge's OP_VOTE_BATCH fast
        path): apply-validated rows log as a KIND_WIRE_COLUMNAR record of
        their verbatim wire bytes — same accepted-rows-only discipline as
        :meth:`ingest_columnar_multi`, logged after the apply, before the
        ack, but the kind byte routes replay back through
        ``ingest_wire_columnar`` (crypto skipped) so a recovered peer
        keeps wire-validated retention and the cross-frame dangling-vote
        guard its non-crashed twins have (see format.KIND_WIRE_COLUMNAR).
        The WAL blob doubles as the engine's working copy (``_buf``) —
        one ``tobytes()`` per frame across the whole durable path."""
        with self._lock:
            blob = (
                _prepass.buf if _prepass is not None and _prepass.buf is not None
                else data.tobytes() if hasattr(data, "tobytes")
                else bytes(data)
            )
            statuses = self._engine.ingest_wire_columnar(
                scopes,
                scope_idx,
                cols,
                data,
                offsets,
                now,
                max_depth=max_depth,
                stage_seconds=stage_seconds,
                _prepass=_prepass,
                _buf=blob,
            )
            offs = np.asarray(offsets, np.int64)
            idx = None if len(scopes) <= 1 else np.asarray(scope_idx)
            self._log_columnar_accepted(
                now, list(scopes), idx, blob, offs, statuses,
                kind=F.KIND_WIRE_COLUMNAR,
            )
            return statuses

    # ── Timeouts ───────────────────────────────────────────────────────

    def handle_consensus_timeout(self, scope, proposal_id, now):
        with self._lock:
            # Log first: the call mutates (and emits) even when it raises
            # InsufficientVotesAtTimeout; replay re-raises identically.
            self._wal.append(
                F.KIND_TIMEOUT, F.encode_timeout(scope, proposal_id, now)
            )
            return self._engine.handle_consensus_timeout(scope, proposal_id, now)

    def sweep_timeouts(self, now):
        """Timeout sweep + tier lifecycle, logged in two parts: the
        KIND_SWEEP record (before the apply — the timeout half replays
        deterministically from persisted expiries) and, when the
        lifecycle hook garbage-collected anything, a KIND_GC record of
        the exact keys (after the apply, before the ack — the TTL
        decision rides idle clocks a snapshot restore does not carry, so
        replay applies the logged outcome instead of re-deriving the
        policy; see format.KIND_GC). A crash between apply and GC-log
        merely leaves the collected sessions to be re-collected by the
        recovered engine's next sweep."""
        with self._lock:
            self._wal.append(F.KIND_SWEEP, F.encode_sweep(now))
            sink: list = []
            out = self._engine.sweep_timeouts(now, _gc_sink=sink)
            if sink:
                self._wal.append(F.KIND_GC, F.encode_gc(sink))
            return out

    def lifecycle_sweep(self, now):
        """Standalone tier sweep, logged like :meth:`sweep_timeouts`'s
        lifecycle half (KIND_LIFECYCLE + the KIND_GC outcome): its TTL
        GC is semantic — demoted sessions past ``evict_decided_after``
        cease to exist — so an unlogged call would let a crash resurrect
        sessions the live engine already dropped. ``demote_session``
        stays unlogged by design — demotion is cache management, and
        recovery rebuilding a demoted session as live is
        fingerprint-identical."""
        with self._lock:
            self._wal.append(F.KIND_LIFECYCLE, F.encode_lifecycle(now))
            sink: list = []
            out = self._engine.lifecycle_sweep(now, _gc_sink=sink)
            if sink:
                self._wal.append(F.KIND_GC, F.encode_gc(sink))
            return out

    # ── Scope config ───────────────────────────────────────────────────

    def scope(self, scope):
        """Fluent builder bound to THIS wrapper, so the terminal
        initialize/update calls are logged (the engine's own builder would
        bypass the WAL)."""
        from ..service import ScopeConfigBuilderWrapper

        existing = self._engine.get_scope_config(scope)
        builder = (
            ScopeConfigBuilder.from_existing(existing)
            if existing is not None
            else ScopeConfigBuilder()
        )
        return ScopeConfigBuilderWrapper(self, scope, builder)

    def set_scope_config(self, scope, config: ScopeConfig) -> None:
        self._scope_config_op(F.SCOPE_CONFIG_SET, scope, config)

    def _initialize_scope(self, scope, config: ScopeConfig) -> None:
        self._scope_config_op(F.SCOPE_CONFIG_INITIALIZE, scope, config)

    def _update_scope_config(self, scope, config: ScopeConfig) -> None:
        self._scope_config_op(F.SCOPE_CONFIG_UPDATE, scope, config)

    def _scope_config_op(self, mode: int, scope, config: ScopeConfig) -> None:
        apply = {
            F.SCOPE_CONFIG_SET: self._engine.set_scope_config,
            F.SCOPE_CONFIG_INITIALIZE: self._engine._initialize_scope,
            F.SCOPE_CONFIG_UPDATE: self._engine._update_scope_config,
        }[mode]
        with self._lock:
            self._wal.append(
                F.KIND_SCOPE_CONFIG,
                F.encode_scope_config_record(mode, scope, config),
            )
            apply(scope, config)

    def delete_scope(self, scope) -> None:
        self.delete_scopes([scope])

    def delete_scopes(self, scopes) -> None:
        with self._lock:
            self._wal.append(F.KIND_SCOPE_DELETE, F.encode_scope_delete(list(scopes)))
            self._engine.delete_scopes(list(scopes))

    # ── Snapshot + compaction ──────────────────────────────────────────

    @property
    def last_checkpoint_watermark(self) -> int:
        """Watermark of the most recent save_to_storage/checkpoint in this
        process (0 = none yet). Embedders keeping more than the latest
        snapshot should persist it alongside each one and hand it back to
        :meth:`recover` as ``after_lsn``."""
        return self._ckpt_watermark

    def save_to_storage(self, storage) -> int:
        """Snapshot every tracked session into ``storage`` and append a
        snapshot watermark: records up to the pre-snapshot LSN are now
        covered and eligible for compaction. The watermark is readable as
        :attr:`last_checkpoint_watermark` until the next checkpoint."""
        count, _ = self._save_and_mark(storage)
        return count

    def checkpoint(self, storage, compact: bool = True) -> int:
        """save_to_storage + (optionally) drop every segment the new
        snapshot fully covers. Returns the number of sessions saved.

        ``compact=True`` is only safe when ``storage`` persists
        SYNCHRONOUSLY — by the time ``save_to_storage`` returns, the
        snapshot must survive a crash. Compaction deletes the only other
        copy of the covered records; if the backend buffers (writes its
        snapshot file later), a crash in that window loses acknowledged
        records unrecoverably, even under ``fsync_policy="always"``. For a
        buffering backend use the two-phase form: ``checkpoint(storage,
        compact=False)``, make the snapshot durable, then
        ``wal.compact(last_checkpoint_watermark)``."""
        count, watermark = self._save_and_mark(storage)
        if compact:
            self._wal.compact(watermark)
        return count

    def compact(self) -> int:
        """Second phase of the two-phase checkpoint for BUFFERING storage
        backends: drop every sealed segment the most recent checkpoint
        covers. The documented safe flow is ``checkpoint(storage,
        compact=False)`` → make the snapshot durable → ``compact()``; this
        method is that last step as one safe call (it compacts to
        :attr:`last_checkpoint_watermark`, never beyond what a snapshot in
        this process actually covered). Raises if no checkpoint ran yet —
        compacting without one would delete the only copy of acknowledged
        records. Returns the number of segments removed. A crash in the
        window between the phases is safe in both orders: snapshot durable
        but not compacted merely re-replays covered records (duplicate
        rejection converges), and the un-compacted log still covers a
        snapshot that never became durable."""
        with self._lock:
            if self._ckpt_watermark <= 0:
                raise ValueError(
                    "no checkpoint in this process: call "
                    "checkpoint(storage, compact=False) first, make the "
                    "snapshot durable, then compact()"
                )
            return self._wal.compact(self._ckpt_watermark)

    def load_from_storage(self, storage) -> int:
        """Delegates without logging: a bulk restore is snapshot-shaped
        state, not traffic — callers restoring a crashed node should use
        :meth:`recover`, which also replays the WAL tail."""
        with self._lock:
            return self._engine.load_from_storage(storage)

    def _save_and_mark(self, storage) -> tuple[int, int]:
        with self._lock:
            count = self._engine.save_to_storage(storage)
            default_registry.counter(WAL_CHECKPOINTS_TOTAL).inc()
            flight_recorder.record("wal.checkpoint", sessions=count)
            # Everything logged before the save is inside the snapshot
            # (mutators and the save both run under this lock). Sealing the
            # active segment first puts the whole covered history into
            # sealed segments, so a following compact() can drop ALL of it;
            # the mark itself lands in the fresh active segment.
            watermark = self._wal.last_lsn
            self._wal.rotate()
            self._wal.append_snapshot_mark(watermark)
            self._ckpt_watermark = watermark
            return count, watermark
