"""Consensus session: per-proposal state machine and its configuration.

Mirrors the reference engine (reference: src/session.rs): a session tracks a
proposal from creation through vote collection to a terminal state, enforcing
round caps (Gossipsub fixed 2-round vs P2P dynamic ceil(2n/3)) and running the
decision kernel after every mutation. This scalar implementation is the oracle
for the dense TPU pool in hashgraph_tpu.models.
"""

from __future__ import annotations

import enum
from dataclasses import dataclass, field

from .errors import (
    DuplicateVote,
    ConsensusNotReached,
    MaxRoundsExceeded,
    SessionNotActive,
)
from .protocol import (
    COMPUTE_CHAIN,
    calculate_max_rounds,
    decide,
    validate_proposal,
    validate_proposal_timestamp,
    validate_threshold,
    validate_timeout,
    validate_vote,
    validate_vote_chain,
)
from .scope_config import NetworkType, ScopeConfig
from .types import STILL_ACTIVE, SessionTransition
from .wire import Proposal, Vote

_U32_MAX = 0xFFFFFFFF


@dataclass(frozen=True)
class ConsensusConfig:
    """Per-session configuration (reference: src/session.rs:27-44).

    ``max_rounds == 0`` with ``use_gossipsub_rounds == False`` triggers the
    dynamic P2P cap ceil(2n/3).
    """

    consensus_threshold: float = 2.0 / 3.0
    consensus_timeout: float = 60.0
    max_rounds: int = 2
    use_gossipsub_rounds: bool = True
    liveness_criteria: bool = True

    @classmethod
    def from_scope_config(cls, config: ScopeConfig) -> "ConsensusConfig":
        """reference: src/session.rs:52-68"""
        if config.network_type == NetworkType.GOSSIPSUB:
            max_rounds = (
                config.max_rounds_override if config.max_rounds_override is not None else 2
            )
            use_gossipsub_rounds = True
        else:
            max_rounds = (
                config.max_rounds_override if config.max_rounds_override is not None else 0
            )
            use_gossipsub_rounds = False
        return cls(
            consensus_threshold=config.default_consensus_threshold,
            consensus_timeout=config.default_timeout,
            max_rounds=max_rounds,
            use_gossipsub_rounds=use_gossipsub_rounds,
            liveness_criteria=config.default_liveness_criteria_yes,
        )

    @classmethod
    def p2p(cls) -> "ConsensusConfig":
        """Dynamic ceil(2n/3) round cap (reference: src/session.rs:73-75)."""
        return cls.from_scope_config(ScopeConfig.from_network_type(NetworkType.P2P))

    @classmethod
    def gossipsub(cls) -> "ConsensusConfig":
        """Fixed 2-round flow (reference: src/session.rs:78-80)."""
        return cls.from_scope_config(ScopeConfig.from_network_type(NetworkType.GOSSIPSUB))

    def with_timeout(self, consensus_timeout: float) -> "ConsensusConfig":
        validate_timeout(consensus_timeout)
        return ConsensusConfig(
            consensus_threshold=self.consensus_threshold,
            consensus_timeout=consensus_timeout,
            max_rounds=self.max_rounds,
            use_gossipsub_rounds=self.use_gossipsub_rounds,
            liveness_criteria=self.liveness_criteria,
        )

    def with_threshold(self, consensus_threshold: float) -> "ConsensusConfig":
        validate_threshold(consensus_threshold)
        return ConsensusConfig(
            consensus_threshold=consensus_threshold,
            consensus_timeout=self.consensus_timeout,
            max_rounds=self.max_rounds,
            use_gossipsub_rounds=self.use_gossipsub_rounds,
            liveness_criteria=self.liveness_criteria,
        )

    def with_liveness_criteria(self, liveness_criteria: bool) -> "ConsensusConfig":
        return ConsensusConfig(
            consensus_threshold=self.consensus_threshold,
            consensus_timeout=self.consensus_timeout,
            max_rounds=self.max_rounds,
            use_gossipsub_rounds=self.use_gossipsub_rounds,
            liveness_criteria=liveness_criteria,
        )

    def max_round_limit(self, expected_voters_count: int) -> int:
        """reference: src/session.rs:120-128"""
        if self.use_gossipsub_rounds:
            return self.max_rounds
        if self.max_rounds == 0:
            return calculate_max_rounds(expected_voters_count, self.consensus_threshold)
        return self.max_rounds


class ConsensusStateKind(enum.Enum):
    ACTIVE = "active"
    CONSENSUS_REACHED = "consensus_reached"
    FAILED = "failed"


@dataclass(frozen=True)
class ConsensusState:
    """Session state (reference: src/session.rs:156-164)."""

    kind: ConsensusStateKind
    result: bool | None = None  # set iff kind == CONSENSUS_REACHED

    @classmethod
    def active(cls) -> "ConsensusState":
        return cls(ConsensusStateKind.ACTIVE)

    @classmethod
    def reached(cls, result: bool) -> "ConsensusState":
        return cls(ConsensusStateKind.CONSENSUS_REACHED, result)

    @classmethod
    def failed(cls) -> "ConsensusState":
        return cls(ConsensusStateKind.FAILED)

    @property
    def is_active(self) -> bool:
        return self.kind == ConsensusStateKind.ACTIVE

    @property
    def is_reached(self) -> bool:
        return self.kind == ConsensusStateKind.CONSENSUS_REACHED

    @property
    def is_failed(self) -> bool:
        return self.kind == ConsensusStateKind.FAILED


@dataclass
class ConsensusSession:
    """Per-proposal lifecycle tracker (reference: src/session.rs:166-178).

    ``tallies`` is TPU-framework-specific: owner -> yes/no records applied
    through the columnar path (:meth:`add_tally`), which deliberately
    carries no Vote objects. They count toward decisions and duplicate
    detection exactly like votes, but are absent from the proposal's
    embedded chain — the documented columnar trade-off (PARITY.md)."""

    proposal: Proposal
    state: ConsensusState
    votes: dict[bytes, Vote]  # vote_owner -> Vote, one vote per participant
    created_at: int
    config: ConsensusConfig
    tallies: dict[bytes, bool] = field(default_factory=dict)

    def clone(self) -> "ConsensusSession":
        return ConsensusSession(
            proposal=self.proposal.clone(),
            state=self.state,
            votes={k: v.clone() for k, v in self.votes.items()},
            created_at=self.created_at,
            config=self.config,
            tallies=dict(self.tallies),
        )

    @classmethod
    def _new(cls, proposal: Proposal, config: ConsensusConfig, now: int) -> "ConsensusSession":
        return cls(
            proposal=proposal,
            state=ConsensusState.active(),
            votes={},
            created_at=now,
            config=config,
        )

    @classmethod
    def from_proposal(
        cls,
        proposal: Proposal,
        scheme,
        config: ConsensusConfig,
        now: int,
        sig_verdicts=None,
        chain_error=COMPUTE_CHAIN,
        computed_hashes=None,
    ) -> tuple["ConsensusSession", SessionTransition]:
        """Validate a (possibly vote-carrying) proposal and build a session,
        replaying embedded votes from a clean round-1 state
        (reference: src/session.rs:198-221). ``sig_verdicts``/``chain_error``
        /``computed_hashes`` inject batched-path results (see
        protocol.validate_proposal)."""
        validate_proposal(
            proposal,
            scheme,
            now,
            sig_verdicts=sig_verdicts,
            chain_error=chain_error,
            computed_hashes=computed_hashes,
        )

        existing_votes = [v.clone() for v in proposal.votes]
        clean_proposal = proposal.clone()
        clean_proposal.votes = []
        clean_proposal.round = 1

        session = cls._new(clean_proposal, config, now)
        transition = session.initialize_with_votes(
            existing_votes,
            scheme,
            proposal.expiration_timestamp,
            proposal.timestamp,
            now,
            sig_verdicts=sig_verdicts,
            chain_error=chain_error,
            computed_hashes=computed_hashes,
        )
        return session, transition

    def add_vote(self, vote: Vote, now: int) -> SessionTransition:
        """Add a single (already-validated) vote
        (reference: src/session.rs:225-249). Check order is load-bearing:
        expiry -> round limit -> duplicate -> insert -> round update ->
        consensus."""
        if self.state.is_reached:
            return SessionTransition.consensus_reached(self.state.result)
        if not self.state.is_active:
            raise SessionNotActive()

        validate_proposal_timestamp(self.proposal.expiration_timestamp, now)
        self._check_round_limit(1)
        if vote.vote_owner in self.votes or vote.vote_owner in self.tallies:
            raise DuplicateVote()
        self.votes[vote.vote_owner] = vote.clone()
        self.proposal.votes.append(vote.clone())
        self._update_round(1)
        return self._check_consensus()

    def add_tally(self, owner: bytes, value: bool, now: int) -> SessionTransition:
        """Columnar analogue of :meth:`add_vote`: record one validated
        yes/no choice for an owner WITHOUT materializing a Vote object or
        touching the proposal's embedded chain. Same check order, round
        bookkeeping, and decision semantics as add_vote — this is what the
        device pool does per lane, expressed on the scalar substrate (used
        for host-spilled sessions on the columnar ingest path)."""
        if self.state.is_reached:
            return SessionTransition.consensus_reached(self.state.result)
        if not self.state.is_active:
            raise SessionNotActive()

        validate_proposal_timestamp(self.proposal.expiration_timestamp, now)
        self._check_round_limit(1)
        if owner in self.votes or owner in self.tallies:
            raise DuplicateVote()
        self.tallies[owner] = value
        self._update_round(1)
        return self._check_consensus()

    def initialize_with_votes(
        self,
        votes: list[Vote],
        scheme,
        expiration_timestamp: int,
        creation_time: int,
        now: int,
        sig_verdicts=None,
        chain_error=COMPUTE_CHAIN,
        computed_hashes=None,
    ) -> SessionTransition:
        """Batch-initialize: validate everything, then add atomically
        (reference: src/session.rs:253-298)."""
        if not self.state.is_active:
            raise SessionNotActive()

        validate_proposal_timestamp(expiration_timestamp, now)

        if not votes:
            return STILL_ACTIVE

        seen_owners: set[bytes] = set()
        for vote in votes:
            if vote.vote_owner in seen_owners:
                raise DuplicateVote()
            seen_owners.add(vote.vote_owner)

        # Distinct voters bound the batch size (reference: src/session.rs:277-282).
        if len(votes) > self.proposal.expected_voters_count:
            self.state = ConsensusState.failed()
            raise MaxRoundsExceeded()

        if chain_error is COMPUTE_CHAIN:
            validate_vote_chain(votes)
        elif chain_error is not None:
            raise chain_error
        for i, vote in enumerate(votes):
            validate_vote(
                vote,
                scheme,
                expiration_timestamp,
                creation_time,
                now,
                sig_verdict=sig_verdicts[i] if sig_verdicts is not None else None,
                computed_hash=(
                    computed_hashes[i] if computed_hashes is not None else None
                ),
            )

        self._check_round_limit(len(votes))
        self._update_round(len(votes))

        for vote in votes:
            self.votes[vote.vote_owner] = vote.clone()
            self.proposal.votes.append(vote)

        return self._check_consensus()

    def _check_round_limit(self, vote_count: int) -> None:
        """Round-cap projection (reference: src/session.rs:306-344).
        On violation the session transitions to Failed before raising."""
        if vote_count > self.proposal.expected_voters_count:
            self.state = ConsensusState.failed()
            raise MaxRoundsExceeded()

        if self.config.use_gossipsub_rounds:
            # Round 1 = proposal; ANY votes move (and keep) the session in round 2.
            if self.proposal.round == 2 or (self.proposal.round == 1 and vote_count > 0):
                projected_value = 2
            else:
                projected_value = self.proposal.round
        else:
            # P2P: current votes = round - 1; each new vote increments.
            current_votes = max(self.proposal.round - 1, 0)
            projected_value = min(current_votes + vote_count, _U32_MAX)

        if projected_value > self.config.max_round_limit(self.proposal.expected_voters_count):
            self.state = ConsensusState.failed()
            raise MaxRoundsExceeded()

    def _update_round(self, vote_count: int) -> None:
        """reference: src/session.rs:351-366"""
        if self.config.use_gossipsub_rounds:
            if self.proposal.round == 1 and vote_count > 0:
                self.proposal.round = 2
        else:
            self.proposal.round = min(self.proposal.round + vote_count, _U32_MAX)

    def tally_counts(self) -> tuple[int, int]:
        """(yes, total) over the combined participant set — votes plus
        columnar tallies, each owner in exactly one. The single source of
        the counts both :meth:`decide_now` and the engine's
        ``explain_decision`` report, so the provenance readout can never
        drift from the kernel input."""
        yes = sum(1 for v in self.votes.values() if v.vote) + sum(
            1 for t in self.tallies.values() if t
        )
        return yes, len(self.votes) + len(self.tallies)

    def decide_now(self, is_timeout: bool) -> bool | None:
        """Run the decision kernel over votes + columnar tallies (the
        combined participant set — each owner appears in exactly one)."""
        yes, total = self.tally_counts()
        return decide(
            yes,
            total,
            self.proposal.expected_voters_count,
            self.config.consensus_threshold,
            self.proposal.liveness_criteria_yes,
            is_timeout,
        )

    def _check_consensus(self) -> SessionTransition:
        """Run the decision kernel with is_timeout=False
        (reference: src/session.rs:372-387)."""
        result = self.decide_now(False)
        if result is not None:
            self.state = ConsensusState.reached(result)
            return SessionTransition.consensus_reached(result)
        self.state = ConsensusState.active()
        return STILL_ACTIVE

    def is_active(self) -> bool:
        return self.state.is_active

    def get_consensus_result(self) -> bool:
        """reference: src/session.rs:398-404"""
        if self.state.is_reached:
            return self.state.result
        raise ConsensusNotReached()
