"""ConsensusService: the single entry point for consensus operations.

One service instance is one peer's view (reference: src/service.rs:21-29): it
holds the storage handle, event bus, and that peer's signer. Multi-peer setups
build one service per peer, optionally sharing storage and event bus. The
library performs no I/O: the application supplies transport (calling the
``process_incoming_*`` methods on receipt), timers (calling
``handle_consensus_timeout``), and the clock (every method takes ``now`` in
seconds since the Unix epoch).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Generic, Hashable, TypeVar

from .errors import (
    ProposalAlreadyExist,
    InsufficientVotesAtTimeout,
    ScopeNotFound,
    SessionNotFound,
    UserAlreadyVoted,
)
from .events import BroadcastEventBus, ConsensusEventBus
from .protocol import (
    build_vote,
    calculate_consensus_result,
    regenerate_until_unique,
    validate_proposal_timestamp,
    validate_vote,
)
from .scope_config import NetworkType, ScopeConfig, ScopeConfigBuilder
from .session import ConsensusConfig, ConsensusSession, ConsensusState
from .signing import ConsensusSignatureScheme, EthereumConsensusSigner
from .storage import ConsensusStorage, InMemoryConsensusStorage
from .types import (
    ConsensusEvent,
    ConsensusFailedEvent,
    ConsensusReached,
    CreateProposalRequest,
    SessionTransition,
)
from .wire import Proposal, Vote

Scope = TypeVar("Scope", bound=Hashable)

DEFAULT_MAX_SESSIONS_PER_SCOPE = 10  # reference: src/service.rs:89-90


@dataclass
class ConsensusStats:
    """Aggregate per-scope counters (reference: src/service_stats.rs:10-19)."""

    total_sessions: int = 0
    active_sessions: int = 0
    failed_sessions: int = 0
    consensus_reached: int = 0


class ConsensusService(Generic[Scope]):
    """The main consensus service (reference: src/service.rs:39-51).

    Generic over the scope key type; storage / event-bus / signer backends are
    injected. The signer instance signs this peer's outgoing votes; the
    signer's *class* verifies incoming ones.
    """

    def __init__(
        self,
        storage: ConsensusStorage[Scope],
        event_bus: ConsensusEventBus[Scope],
        signer: ConsensusSignatureScheme,
        max_sessions_per_scope: int = DEFAULT_MAX_SESSIONS_PER_SCOPE,
    ):
        self._storage = storage
        self._event_bus = event_bus
        self._signer = signer
        self._max_sessions_per_scope = max_sessions_per_scope

    @classmethod
    def new_with_components(
        cls,
        storage: ConsensusStorage[Scope],
        event_bus: ConsensusEventBus[Scope],
        signer: ConsensusSignatureScheme,
        max_sessions_per_scope: int = DEFAULT_MAX_SESSIONS_PER_SCOPE,
    ) -> "ConsensusService[Scope]":
        """Constructor matching the reference's generic ctor name
        (reference: src/service.rs:126-139)."""
        return cls(storage, event_bus, signer, max_sessions_per_scope)

    @classmethod
    def default_service(
        cls,
        signer: ConsensusSignatureScheme | None = None,
        max_sessions_per_scope: int = DEFAULT_MAX_SESSIONS_PER_SCOPE,
    ) -> "ConsensusService":
        """Ready-to-use service: in-memory storage, broadcast events,
        Ethereum signer (reference: src/service.rs:77-109,
        DefaultConsensusService)."""
        return cls(
            InMemoryConsensusStorage(),
            BroadcastEventBus(),
            signer if signer is not None else EthereumConsensusSigner.random(),
            max_sessions_per_scope,
        )

    @classmethod
    def new(cls, signer: ConsensusSignatureScheme) -> "ConsensusService":
        """Default-backends ctor under the reference's name
        (reference: src/service.rs:86-91)."""
        return cls.default_service(signer)

    @classmethod
    def new_with_max_sessions(
        cls, signer: ConsensusSignatureScheme, max_sessions_per_scope: int
    ) -> "ConsensusService":
        """reference: src/service.rs:99-109"""
        return cls.default_service(signer, max_sessions_per_scope)

    # ── Accessors (reference: src/service.rs:141-164) ──────────────────

    def storage(self) -> ConsensusStorage[Scope]:
        return self._storage

    def event_bus(self) -> ConsensusEventBus[Scope]:
        return self._event_bus

    def signer(self) -> ConsensusSignatureScheme:
        return self._signer

    @property
    def _scheme(self) -> type[ConsensusSignatureScheme]:
        return type(self._signer)

    # ── Consensus operations (reference: src/service.rs:166-373) ──────

    def create_proposal(
        self, scope: Scope, request: CreateProposalRequest, now: int
    ) -> Proposal:
        """Create a proposal and start its voting session
        (reference: src/service.rs:183-190). The application must schedule
        ``handle_consensus_timeout`` itself."""
        return self.create_proposal_with_config(scope, request, None, now)

    def create_proposal_with_config(
        self,
        scope: Scope,
        request: CreateProposalRequest,
        config: ConsensusConfig | None,
        now: int,
    ) -> Proposal:
        """reference: src/service.rs:195-209"""
        proposal = request.into_proposal(now)
        regenerate_until_unique(
            proposal,
            lambda pid: self._storage.get_session(scope, pid) is not None,
        )
        resolved = self._resolve_config(scope, config, proposal)
        session, _ = ConsensusSession.from_proposal(
            proposal.clone(), self._scheme, resolved, now
        )
        self._storage.save_session(scope, session)
        self._trim_scope_sessions(scope)
        return proposal

    def cast_vote(self, scope: Scope, proposal_id: int, choice: bool, now: int) -> Vote:
        """Sign and chain a vote by this peer (reference: src/service.rs:216-237).
        The returned vote is ready for network propagation."""
        session = self._get_session(scope, proposal_id)
        validate_proposal_timestamp(session.proposal.expiration_timestamp, now)

        if self._signer.identity() in session.votes:
            raise UserAlreadyVoted()

        vote = build_vote(session.proposal, choice, self._signer, now)
        transition = self._storage.update_session(
            scope, proposal_id, lambda s: s.add_vote(vote, now)
        )
        self._handle_transition(scope, proposal_id, transition, now)
        return vote

    def cast_vote_and_get_proposal(
        self, scope: Scope, proposal_id: int, choice: bool, now: int
    ) -> Proposal:
        """Cast and return the updated proposal for immediate gossip
        (reference: src/service.rs:243-253)."""
        self.cast_vote(scope, proposal_id, choice, now)
        return self._get_session(scope, proposal_id).proposal

    def process_incoming_proposal(self, scope: Scope, proposal: Proposal, now: int) -> None:
        """Validate and store a proposal delivered by the network layer
        (reference: src/service.rs:263-279)."""
        if self._storage.get_session(scope, proposal.proposal_id) is not None:
            raise ProposalAlreadyExist()
        config = self._resolve_config(scope, None, proposal)
        session, transition = ConsensusSession.from_proposal(
            proposal, self._scheme, config, now
        )
        # Event before save, as in the reference (src/service.rs:275-277).
        self._handle_transition(scope, session.proposal.proposal_id, transition, now)
        self._storage.save_session(scope, session)
        self._trim_scope_sessions(scope)

    def process_incoming_vote(self, scope: Scope, vote: Vote, now: int) -> None:
        """Validate and apply a network-delivered vote
        (reference: src/service.rs:286-305)."""
        session = self._get_session(scope, vote.proposal_id)
        validate_vote(
            vote,
            self._scheme,
            session.proposal.expiration_timestamp,
            session.proposal.timestamp,
            now,
        )
        proposal_id = vote.proposal_id
        transition = self._storage.update_session(
            scope, proposal_id, lambda s: s.add_vote(vote, now)
        )
        self._handle_transition(scope, proposal_id, transition, now)

    def handle_consensus_timeout(self, scope: Scope, proposal_id: int, now: int) -> bool:
        """Run the timeout decision: silent peers join the quorum under the
        liveness flag (reference: src/service.rs:323-373). Idempotent for
        already-decided sessions. Raises InsufficientVotesAtTimeout (after
        emitting ConsensusFailed) when no result is determinable."""

        def mutator(session: ConsensusSession) -> bool | None:
            if session.state.is_reached:
                return session.state.result
            result = calculate_consensus_result(
                session.votes,
                session.proposal.expected_voters_count,
                session.config.consensus_threshold,
                session.proposal.liveness_criteria_yes,
                True,
            )
            if result is not None:
                session.state = ConsensusState.reached(result)
                return result
            session.state = ConsensusState.failed()
            return None

        result = self._storage.update_session(scope, proposal_id, mutator)
        if result is not None:
            self._emit_event(
                scope, ConsensusReached(proposal_id=proposal_id, result=result, timestamp=now)
            )
            return result
        self._emit_event(scope, ConsensusFailedEvent(proposal_id=proposal_id, timestamp=now))
        raise InsufficientVotesAtTimeout()

    # ── Scope management (reference: src/service.rs:375-438) ───────────

    def scope(self, scope: Scope) -> "ScopeConfigBuilderWrapper[Scope]":
        """Fluent builder for scope configuration::

            service.scope("s").with_network_type(NetworkType.P2P) \\
                   .with_threshold(0.75).initialize()
        """
        existing = self._storage.get_scope_config(scope)
        builder = (
            ScopeConfigBuilder.from_existing(existing)
            if existing is not None
            else ScopeConfigBuilder()
        )
        return ScopeConfigBuilderWrapper(self, scope, builder)

    def _initialize_scope(self, scope: Scope, config: ScopeConfig) -> None:
        config.validate()
        self._storage.set_scope_config(scope, config)

    def _update_scope_config(self, scope: Scope, config: ScopeConfig) -> None:
        def updater(existing: ScopeConfig) -> None:
            existing.network_type = config.network_type
            existing.default_consensus_threshold = config.default_consensus_threshold
            existing.default_timeout = config.default_timeout
            existing.default_liveness_criteria_yes = config.default_liveness_criteria_yes
            existing.max_rounds_override = config.max_rounds_override
            existing.demote_after = config.demote_after
            existing.evict_decided_after = config.evict_decided_after
            existing.decide_p99_ms = config.decide_p99_ms
            existing.timeout_min = config.timeout_min
            existing.timeout_max = config.timeout_max

        self._storage.update_scope_config(scope, updater)

    # ── Config resolution (reference: src/service.rs:440-484) ──────────

    def _resolve_config(
        self,
        scope: Scope,
        proposal_override: ConsensusConfig | None,
        proposal: Proposal | None,
    ) -> ConsensusConfig:
        """Priority: explicit override > scope config > gossipsub default;
        then proposal-field overrides (timeout from expiration window unless
        explicitly overridden; liveness always from the proposal)."""
        has_explicit_override = proposal_override is not None
        if proposal_override is not None:
            base_config = proposal_override
        else:
            scope_config = self._storage.get_scope_config(scope)
            if scope_config is not None:
                base_config = ConsensusConfig.from_scope_config(scope_config)
            else:
                base_config = ConsensusConfig.gossipsub()

        if proposal is None:
            return base_config

        if has_explicit_override:
            timeout_seconds = base_config.consensus_timeout
        elif proposal.expiration_timestamp > proposal.timestamp:
            timeout_seconds = float(proposal.expiration_timestamp - proposal.timestamp)
        else:
            timeout_seconds = base_config.consensus_timeout

        return ConsensusConfig(
            consensus_threshold=base_config.consensus_threshold,
            consensus_timeout=timeout_seconds,
            max_rounds=base_config.max_rounds,
            use_gossipsub_rounds=base_config.use_gossipsub_rounds,
            liveness_criteria=proposal.liveness_criteria_yes,
        )

    # ── Internals (reference: src/service.rs:486-555) ──────────────────

    def _get_session(self, scope: Scope, proposal_id: int) -> ConsensusSession:
        session = self._storage.get_session(scope, proposal_id)
        if session is None:
            raise SessionNotFound()
        return session

    def _trim_scope_sessions(self, scope: Scope) -> None:
        """Silent LRU-by-created_at eviction beyond the per-scope cap
        (reference: src/service.rs:512-522)."""

        def mutator(sessions: list[ConsensusSession]) -> None:
            if len(sessions) <= self._max_sessions_per_scope:
                return
            sessions.sort(key=lambda s: s.created_at, reverse=True)
            del sessions[self._max_sessions_per_scope :]

        self._storage.update_scope_sessions(scope, mutator)

    def _list_scope_sessions(self, scope: Scope) -> list[ConsensusSession]:
        sessions = self._storage.list_scope_sessions(scope)
        if sessions is None:
            raise ScopeNotFound()
        return sessions

    def _handle_transition(
        self, scope: Scope, proposal_id: int, transition: SessionTransition, now: int
    ) -> None:
        if transition.is_reached:
            self._emit_event(
                scope,
                ConsensusReached(
                    proposal_id=proposal_id, result=transition.reached, timestamp=now
                ),
            )

    def _emit_event(self, scope: Scope, event: ConsensusEvent) -> None:
        self._event_bus.publish(scope, event)

    # ── Stats (reference: src/service_stats.rs:32-59) ──────────────────

    def get_scope_stats(self, scope: Scope) -> ConsensusStats:
        """Counters for monitoring; zeros for unknown scopes."""
        try:
            sessions = self._list_scope_sessions(scope)
        except ScopeNotFound:
            return ConsensusStats()
        return ConsensusStats(
            total_sessions=len(sessions),
            active_sessions=sum(1 for s in sessions if s.is_active()),
            failed_sessions=sum(1 for s in sessions if s.state.is_failed),
            consensus_reached=sum(1 for s in sessions if s.state.is_reached),
        )


class ScopeConfigBuilderWrapper(Generic[Scope]):
    """Builder bound to a service+scope with terminal ``initialize``/``update``
    (reference: src/service.rs:558-668)."""

    def __init__(
        self,
        service: ConsensusService[Scope],
        scope: Scope,
        builder: ScopeConfigBuilder,
    ):
        self._service = service
        self._scope = scope
        self._builder = builder

    def with_network_type(self, network_type: NetworkType) -> "ScopeConfigBuilderWrapper[Scope]":
        self._builder.with_network_type(network_type)
        return self

    def with_threshold(self, threshold: float) -> "ScopeConfigBuilderWrapper[Scope]":
        self._builder.with_threshold(threshold)
        return self

    def with_timeout(self, timeout_seconds: float) -> "ScopeConfigBuilderWrapper[Scope]":
        self._builder.with_timeout(timeout_seconds)
        return self

    def with_liveness_criteria(self, liveness_criteria_yes: bool) -> "ScopeConfigBuilderWrapper[Scope]":
        self._builder.with_liveness_criteria(liveness_criteria_yes)
        return self

    def with_max_rounds(self, max_rounds: int | None) -> "ScopeConfigBuilderWrapper[Scope]":
        self._builder.with_max_rounds(max_rounds)
        return self

    def with_demote_after(self, seconds: float | None) -> "ScopeConfigBuilderWrapper[Scope]":
        self._builder.with_demote_after(seconds)
        return self

    def with_evict_decided_after(self, seconds: float | None) -> "ScopeConfigBuilderWrapper[Scope]":
        self._builder.with_evict_decided_after(seconds)
        return self

    def with_decide_p99_ms(self, ms: float | None) -> "ScopeConfigBuilderWrapper[Scope]":
        self._builder.with_decide_p99_ms(ms)
        return self

    def with_timeout_bounds(
        self, timeout_min: float | None, timeout_max: float | None
    ) -> "ScopeConfigBuilderWrapper[Scope]":
        self._builder.with_timeout_bounds(timeout_min, timeout_max)
        return self

    def p2p_preset(self) -> "ScopeConfigBuilderWrapper[Scope]":
        self._builder.p2p_preset()
        return self

    def gossipsub_preset(self) -> "ScopeConfigBuilderWrapper[Scope]":
        self._builder.gossipsub_preset()
        return self

    def strict_consensus(self) -> "ScopeConfigBuilderWrapper[Scope]":
        self._builder.strict_consensus()
        return self

    def fast_consensus(self) -> "ScopeConfigBuilderWrapper[Scope]":
        self._builder.fast_consensus()
        return self

    def with_network_defaults(self, network_type: NetworkType) -> "ScopeConfigBuilderWrapper[Scope]":
        self._builder.with_network_defaults(network_type)
        return self

    def initialize(self) -> None:
        """Persist as the scope's configuration (validated)."""
        self._service._initialize_scope(self._scope, self._builder.build())

    def update(self) -> None:
        """Overwrite the existing scope configuration (validated)."""
        self._service._update_scope_config(self._scope, self._builder.build())

    def get_config(self) -> ScopeConfig:
        return self._builder.get_config()
