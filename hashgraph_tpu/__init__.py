"""hashgraph_tpu — a TPU-native hashgraph-style binary consensus framework.

A brand-new JAX/XLA implementation with the capabilities of the reference
Rust library vacp2p/hashgraph-like-consensus (mounted read-only during
development; see SURVEY.md): binary yes/no decisions among n peers via signed
hashgraph vote chains, ceil(2n/3) quorum math, Gossipsub/P2P round semantics,
silent-peer liveness at timeout, scoped multi-tenant sessions, and pluggable
storage / event-bus / signature-scheme backends.

The consensus engine state lives as dense per-proposal tensors evaluated by
vmapped/sharded XLA kernels (hashgraph_tpu.ops / .models / .parallel); vote
hashing and ECDSA verification run on the host (hashgraph_tpu.signing,
optionally accelerated by the native C++ runtime). The scalar Python layer in
this package is the bit-exactness oracle the device kernels are validated
against.
"""

from .errors import (
    ConsensusError,
    ConsensusFailed,
    ConsensusNotReached,
    ConsensusSchemeError,
    DuplicateVote,
    EmptySignature,
    EmptyVoteHash,
    EmptyVoteOwner,
    InsufficientVotesAtTimeout,
    InvalidConsensusThreshold,
    InvalidExpectedVotersCount,
    InvalidMaxRounds,
    InvalidTimeout,
    InvalidVoteHash,
    InvalidVoteSignature,
    InvalidVoteTimestamp,
    MaxRoundsExceeded,
    ParentHashMismatch,
    ProposalAlreadyExist,
    ProposalExpired,
    ReceivedHashMismatch,
    ScopeNotFound,
    SessionNotActive,
    SessionNotFound,
    StatusCode,
    TimestampOlderThanCreationTime,
    UserAlreadyVoted,
    VoteExpired,
    VoteProposalIdMismatch,
)
from .protocol import (
    build_vote,
    calculate_consensus_result,
    compute_vote_hash,
    has_sufficient_votes,
    validate_proposal,
    validate_vote_chain,
)
from .events import BroadcastEventBus, ConsensusEventBus, EventReceiver
from .scope_config import NetworkType, ScopeConfig, ScopeConfigBuilder
from .service import ConsensusService, ConsensusStats, ScopeConfigBuilderWrapper
from .session import ConsensusConfig, ConsensusSession, ConsensusState
from .signing import (
    ConsensusSignatureScheme,
    Ed25519ConsensusSigner,
    Ed25519DeviceConsensusSigner,
    EthereumConsensusSigner,
    StubConsensusSigner,
)
from .storage import ConsensusStorage, InMemoryConsensusStorage
from .types import (
    ConsensusFailedEvent,
    ConsensusReached,
    CreateProposalRequest,
    SessionTransition,
)
from .obs import FlightRecorder, MetricsRegistry, MetricsSidecar
from .obs import flight_recorder, registry as metrics_registry
from .obs.health import AlertRule, EvidenceRecord, HealthMonitor
from .obs import health_monitor
from .obs.trace import TraceContext, trace_store
from .wal import DurableEngine, WalWriter
from .wire import Proposal, Vote

__version__ = "0.1.0"

__all__ = [
    "Proposal",
    "Vote",
    "DurableEngine",
    "WalWriter",
    "MetricsRegistry",
    "MetricsSidecar",
    "FlightRecorder",
    "TraceContext",
    "AlertRule",
    "EvidenceRecord",
    "HealthMonitor",
    "health_monitor",
    "metrics_registry",
    "flight_recorder",
    "trace_store",
    "ConsensusService",
    "ConsensusStats",
    "ConsensusConfig",
    "ConsensusSession",
    "ConsensusState",
    "ConsensusStorage",
    "InMemoryConsensusStorage",
    "ConsensusEventBus",
    "BroadcastEventBus",
    "EventReceiver",
    "NetworkType",
    "ScopeConfig",
    "ScopeConfigBuilder",
    "ScopeConfigBuilderWrapper",
    "CreateProposalRequest",
    "ConsensusReached",
    "ConsensusFailedEvent",
    "SessionTransition",
    "ConsensusSignatureScheme",
    "Ed25519ConsensusSigner",
    "Ed25519DeviceConsensusSigner",
    "EthereumConsensusSigner",
    "StubConsensusSigner",
    "build_vote",
    "compute_vote_hash",
    "validate_proposal",
    "validate_vote_chain",
    "calculate_consensus_result",
    "has_sufficient_votes",
    "StatusCode",
    "ConsensusError",
    "ConsensusFailed",
    "ConsensusNotReached",
    "ConsensusSchemeError",
    "DuplicateVote",
    "EmptySignature",
    "EmptyVoteHash",
    "EmptyVoteOwner",
    "InsufficientVotesAtTimeout",
    "InvalidConsensusThreshold",
    "InvalidExpectedVotersCount",
    "InvalidMaxRounds",
    "InvalidTimeout",
    "InvalidVoteHash",
    "InvalidVoteSignature",
    "InvalidVoteTimestamp",
    "MaxRoundsExceeded",
    "ParentHashMismatch",
    "ProposalAlreadyExist",
    "ProposalExpired",
    "ReceivedHashMismatch",
    "ScopeNotFound",
    "SessionNotActive",
    "SessionNotFound",
    "TimestampOlderThanCreationTime",
    "UserAlreadyVoted",
    "VoteExpired",
    "VoteProposalIdMismatch",
]
