"""Storage abstraction and default in-memory implementation.

The storage trait is the persistence/checkpoint abstraction of the framework
(reference: src/storage.rs:23-181): implement it against a durable backend for
crash recovery; sessions are also reconstructible from wire proposals via
``ConsensusSession.from_proposal``. The TPU engine in
:mod:`hashgraph_tpu.engine` exposes this same interface backed by dense device
tensors, with host storage remaining the source of truth.

Value semantics mirror the reference: reads return cloned sessions; mutations
go through closure-based ``update_session`` under the write lock.
"""

from __future__ import annotations

import threading
from typing import Callable, Generic, Hashable, Iterator, TypeVar

from .errors import ConsensusFailed, ConsensusNotReached, SessionNotFound
from .scope_config import ScopeConfig
from .session import ConsensusConfig, ConsensusSession
from .wire import Proposal

Scope = TypeVar("Scope", bound=Hashable)


class ConsensusStorage(Generic[Scope]):
    """Interface for storing and retrieving consensus sessions.

    Subclass to persist to a database or other backend. The scope is the
    partition key for all data. Derived query helpers are implemented on top
    of the primitives — override only for backend-side acceleration
    (reference: src/storage.rs:99-181).
    """

    # ── Primitives (13) ────────────────────────────────────────────────

    def save_session(self, scope: Scope, session: ConsensusSession) -> None:
        """Insert or overwrite by proposal_id (reference: src/storage.rs:28)."""
        raise NotImplementedError

    def get_session(self, scope: Scope, proposal_id: int) -> ConsensusSession | None:
        raise NotImplementedError

    def remove_session(self, scope: Scope, proposal_id: int) -> ConsensusSession | None:
        raise NotImplementedError

    def list_scope_sessions(self, scope: Scope) -> list[ConsensusSession] | None:
        """All sessions in a scope, or None if the scope doesn't exist."""
        raise NotImplementedError

    def stream_scope_sessions(self, scope: Scope) -> Iterator[ConsensusSession]:
        """Iterate sessions one at a time (reference: src/storage.rs:51-54)."""
        raise NotImplementedError

    def replace_scope_sessions(self, scope: Scope, sessions: list[ConsensusSession]) -> None:
        raise NotImplementedError

    def list_scopes(self) -> list[Scope] | None:
        raise NotImplementedError

    def update_session(
        self,
        scope: Scope,
        proposal_id: int,
        mutator: Callable[[ConsensusSession], object],
    ) -> object:
        """Apply a mutation atomically; raises SessionNotFound if absent."""
        raise NotImplementedError

    def update_scope_sessions(
        self, scope: Scope, mutator: Callable[[list[ConsensusSession]], None]
    ) -> None:
        raise NotImplementedError

    def get_scope_config(self, scope: Scope) -> ScopeConfig | None:
        raise NotImplementedError

    def set_scope_config(self, scope: Scope, config: ScopeConfig) -> None:
        raise NotImplementedError

    def delete_scope(self, scope: Scope) -> None:
        """Remove all data for a scope — sessions, config, everything
        (reference: src/storage.rs:87-92)."""
        raise NotImplementedError

    def update_scope_config(
        self, scope: Scope, updater: Callable[[ScopeConfig], None]
    ) -> None:
        raise NotImplementedError

    # ── Derived query helpers (reference: src/storage.rs:104-181) ──────

    def get_consensus_result(self, scope: Scope, proposal_id: int) -> bool:
        session = self.get_session(scope, proposal_id)
        if session is None:
            raise SessionNotFound()
        if session.state.is_reached:
            return session.state.result
        if session.state.is_failed:
            raise ConsensusFailed()
        raise ConsensusNotReached()

    def get_proposal(self, scope: Scope, proposal_id: int) -> Proposal:
        session = self.get_session(scope, proposal_id)
        if session is None:
            raise SessionNotFound()
        return session.proposal

    def get_proposal_config(self, scope: Scope, proposal_id: int) -> ConsensusConfig:
        session = self.get_session(scope, proposal_id)
        if session is None:
            raise SessionNotFound()
        return session.config

    def get_active_proposals(self, scope: Scope) -> list[Proposal]:
        sessions = self.list_scope_sessions(scope) or []
        return [s.proposal for s in sessions if s.is_active()]

    def get_reached_proposals(self, scope: Scope) -> dict[int, bool]:
        sessions = self.list_scope_sessions(scope) or []
        return {
            s.proposal.proposal_id: s.state.result
            for s in sessions
            if s.state.is_reached
        }


class InMemoryConsensusStorage(ConsensusStorage[Scope]):
    """In-RAM storage keyed scope -> proposal_id -> session
    (reference: src/storage.rs:188-376). Thread-safe via an RLock; reads
    return clones so callers never alias stored state."""

    def __init__(self):
        self._lock = threading.RLock()
        self._sessions: dict[Scope, dict[int, ConsensusSession]] = {}
        self._scope_configs: dict[Scope, ScopeConfig] = {}

    def save_session(self, scope: Scope, session: ConsensusSession) -> None:
        with self._lock:
            self._sessions.setdefault(scope, {})[session.proposal.proposal_id] = (
                session.clone()
            )

    def get_session(self, scope: Scope, proposal_id: int) -> ConsensusSession | None:
        with self._lock:
            session = self._sessions.get(scope, {}).get(proposal_id)
            return session.clone() if session is not None else None

    def remove_session(self, scope: Scope, proposal_id: int) -> ConsensusSession | None:
        with self._lock:
            scope_sessions = self._sessions.get(scope)
            if scope_sessions is None:
                return None
            return scope_sessions.pop(proposal_id, None)

    def list_scope_sessions(self, scope: Scope) -> list[ConsensusSession] | None:
        with self._lock:
            scope_sessions = self._sessions.get(scope)
            if scope_sessions is None:
                return None
            return [s.clone() for s in scope_sessions.values()]

    def stream_scope_sessions(self, scope: Scope) -> Iterator[ConsensusSession]:
        # Snapshot under the lock, yield outside it (the reference's impl
        # equally materializes a Vec before iterating, src/storage.rs:266-276).
        with self._lock:
            snapshot = [s.clone() for s in self._sessions.get(scope, {}).values()]
        return iter(snapshot)

    def replace_scope_sessions(self, scope: Scope, sessions: list[ConsensusSession]) -> None:
        with self._lock:
            self._sessions[scope] = {
                s.proposal.proposal_id: s.clone() for s in sessions
            }

    def list_scopes(self) -> list[Scope] | None:
        with self._lock:
            scopes = list(self._sessions.keys())
        return scopes or None

    def update_session(
        self,
        scope: Scope,
        proposal_id: int,
        mutator: Callable[[ConsensusSession], object],
    ) -> object:
        with self._lock:
            session = self._sessions.get(scope, {}).get(proposal_id)
            if session is None:
                raise SessionNotFound()
            return mutator(session)

    def update_scope_sessions(
        self, scope: Scope, mutator: Callable[[list[ConsensusSession]], None]
    ) -> None:
        """Materialize -> mutate -> write back; dropping the last session
        removes the scope entry (reference: src/storage.rs:320-342)."""
        with self._lock:
            scope_sessions = self._sessions.setdefault(scope, {})
            sessions_list = list(scope_sessions.values())
            mutator(sessions_list)
            if not sessions_list:
                del self._sessions[scope]
                return
            self._sessions[scope] = {
                s.proposal.proposal_id: s for s in sessions_list
            }

    def get_scope_config(self, scope: Scope) -> ScopeConfig | None:
        with self._lock:
            config = self._scope_configs.get(scope)
            return config.clone() if config is not None else None

    def set_scope_config(self, scope: Scope, config: ScopeConfig) -> None:
        config.validate()
        with self._lock:
            self._scope_configs[scope] = config.clone()

    def delete_scope(self, scope: Scope) -> None:
        with self._lock:
            self._sessions.pop(scope, None)
            self._scope_configs.pop(scope, None)

    def update_scope_config(
        self, scope: Scope, updater: Callable[[ScopeConfig], None]
    ) -> None:
        """Create-default-then-mutate, validating after
        (reference: src/storage.rs:366-375)."""
        with self._lock:
            config = self._scope_configs.setdefault(scope, ScopeConfig())
            updater(config)
            config.validate()
