"""Scope-level configuration: per-scope defaults every proposal inherits.

Mirrors the reference semantics (reference: src/scope_config.rs): a scope
holds a network type (Gossipsub/P2P round presets — these are round-semantics
presets, not transports), a default threshold/timeout/liveness, and an
optional max-rounds override. Timeouts are float seconds (the reference uses
``Duration``).
"""

from __future__ import annotations

import enum
from dataclasses import dataclass

from .errors import InvalidMaxRounds
from .protocol import validate_threshold, validate_timeout

DEFAULT_TIMEOUT_SECONDS = 60.0  # reference: src/scope_config.rs:13


class NetworkType(enum.Enum):
    """Round/vote semantics preset (reference: src/scope_config.rs:17-23)."""

    GOSSIPSUB = "gossipsub"  # 2 rounds, all votes land in round 2
    P2P = "p2p"  # dynamic ceil(2n/3) cap, each vote increments the round


@dataclass
class ScopeConfig:
    """Per-scope defaults (reference: src/scope_config.rs:30-53).

    ``demote_after`` / ``evict_decided_after`` are TPU-framework-specific
    storage-tiering policies with no reference analogue (the reference's
    only lifecycle is ``delete_scope``, src/storage.rs:92 — see PARITY.md):
    ``demote_after`` seconds of inactivity move a session out of its
    device slot / host record into the compact demoted tier (it pages
    back transparently on any touch), and ``evict_decided_after`` seconds
    after a session's deciding activity garbage-collect decided/failed
    sessions outright. Both default to None = never (reference
    behavior).

    ``decide_p99_ms`` is the scope's declarative latency SLO (also
    embedder-layer, no reference analogue): the p99 decision-latency
    objective in milliseconds. Decisions slower than this count against
    the scope's error budget in the SLO engine
    (:mod:`hashgraph_tpu.obs.slo`) — sustained breaching fires a
    multi-window burn-rate alert and an incident dump. None (the
    default) = best-effort scope, tracked but never alerting.

    ``timeout_min`` / ``timeout_max`` bound the ADAPTIVE consensus
    timeout (also embedder-layer — the reference's timer contract at
    src/lib.rs:15-34 is static and embedder-supplied): when BOTH are
    set, the engine learns a per-scope timeout between them —
    PBFT-style multiplicative backoff each time a consensus timeout
    actually fires, decay toward the SLO engine's observed decision
    p99 on every successful (vote-driven) decision. Both None (the
    default) = static ``default_timeout``, exactly the reference
    behavior. Timeouts remain embedder-driven calls, so adaptivity is
    WAL-replay-safe: the learner is advisory, in-memory, and paused
    during replay."""

    network_type: NetworkType = NetworkType.GOSSIPSUB
    default_consensus_threshold: float = 2.0 / 3.0
    default_timeout: float = DEFAULT_TIMEOUT_SECONDS
    default_liveness_criteria_yes: bool = True
    max_rounds_override: int | None = None
    demote_after: float | None = None
    evict_decided_after: float | None = None
    decide_p99_ms: float | None = None
    timeout_min: float | None = None
    timeout_max: float | None = None

    def validate(self) -> None:
        """reference: src/scope_config.rs:57-69 — Some(0) override is only
        legal for P2P (it triggers dynamic calculation). Negative overrides
        are unrepresentable in the reference's u32 and rejected here."""
        validate_threshold(self.default_consensus_threshold)
        validate_timeout(self.default_timeout)
        if self.max_rounds_override is not None:
            if self.max_rounds_override < 0:
                raise InvalidMaxRounds()
            if (
                self.max_rounds_override == 0
                and self.network_type == NetworkType.GOSSIPSUB
            ):
                raise InvalidMaxRounds()
        for ttl in (self.demote_after, self.evict_decided_after):
            if ttl is not None and not ttl > 0:
                raise ValueError("tier TTLs must be positive seconds (or None)")
        if self.decide_p99_ms is not None and not self.decide_p99_ms > 0:
            raise ValueError(
                "decide_p99_ms must be positive milliseconds (or None)"
            )
        for bound in (self.timeout_min, self.timeout_max):
            if bound is not None and not bound > 0:
                raise ValueError(
                    "timeout bounds must be positive seconds (or None)"
                )
        if (self.timeout_min is None) != (self.timeout_max is None):
            raise ValueError(
                "timeout_min and timeout_max must be set together "
                "(adaptivity needs both bounds)"
            )
        if (
            self.timeout_min is not None
            and self.timeout_max is not None
            and self.timeout_min > self.timeout_max
        ):
            raise ValueError("timeout_min must not exceed timeout_max")

    def adaptive_timeout_enabled(self) -> bool:
        """True when this scope opted into the learned timeout."""
        return self.timeout_min is not None and self.timeout_max is not None

    def clone(self) -> "ScopeConfig":
        return ScopeConfig(
            network_type=self.network_type,
            default_consensus_threshold=self.default_consensus_threshold,
            default_timeout=self.default_timeout,
            default_liveness_criteria_yes=self.default_liveness_criteria_yes,
            max_rounds_override=self.max_rounds_override,
            demote_after=self.demote_after,
            evict_decided_after=self.evict_decided_after,
            decide_p99_ms=self.decide_p99_ms,
            timeout_min=self.timeout_min,
            timeout_max=self.timeout_max,
        )

    @classmethod
    def from_network_type(cls, network_type: NetworkType) -> "ScopeConfig":
        """reference: src/scope_config.rs:72-91 — both presets share the
        2/3 threshold, 60s timeout, liveness=True defaults."""
        return cls(network_type=network_type)


class ScopeConfigBuilder:
    """Fluent builder with presets (reference: src/scope_config.rs:93-204)."""

    def __init__(self, config: ScopeConfig | None = None):
        self._config = config.clone() if config is not None else ScopeConfig()

    @classmethod
    def from_existing(cls, config: ScopeConfig) -> "ScopeConfigBuilder":
        return cls(config)

    def with_network_type(self, network_type: NetworkType) -> "ScopeConfigBuilder":
        self._config.network_type = network_type
        return self

    def with_threshold(self, threshold: float) -> "ScopeConfigBuilder":
        self._config.default_consensus_threshold = threshold
        return self

    def with_timeout(self, timeout_seconds: float) -> "ScopeConfigBuilder":
        self._config.default_timeout = timeout_seconds
        return self

    def with_liveness_criteria(self, liveness_criteria_yes: bool) -> "ScopeConfigBuilder":
        self._config.default_liveness_criteria_yes = liveness_criteria_yes
        return self

    def with_max_rounds(self, max_rounds: int | None) -> "ScopeConfigBuilder":
        self._config.max_rounds_override = max_rounds
        return self

    def with_demote_after(self, seconds: float | None) -> "ScopeConfigBuilder":
        """Idle/decided sessions demote to the compact tier after this
        many seconds of inactivity (None = never; tiering off)."""
        self._config.demote_after = seconds
        return self

    def with_evict_decided_after(
        self, seconds: float | None
    ) -> "ScopeConfigBuilder":
        """Decided/failed sessions are garbage-collected outright this
        many seconds after their deciding activity (None = never)."""
        self._config.evict_decided_after = seconds
        return self

    def with_decide_p99_ms(self, ms: float | None) -> "ScopeConfigBuilder":
        """Declare the scope's p99 decision-latency SLO in milliseconds
        (None = best-effort; tracked in the SLO engine, never alerting)."""
        self._config.decide_p99_ms = ms
        return self

    def with_timeout_bounds(
        self, timeout_min: float | None, timeout_max: float | None
    ) -> "ScopeConfigBuilder":
        """Opt the scope into the ADAPTIVE consensus timeout, clamped to
        ``[timeout_min, timeout_max]`` seconds (both None = static
        ``default_timeout``, the reference behavior)."""
        self._config.timeout_min = timeout_min
        self._config.timeout_max = timeout_max
        return self

    def p2p_preset(self) -> "ScopeConfigBuilder":
        """reference: src/scope_config.rs:140-147"""
        self._config = ScopeConfig(network_type=NetworkType.P2P)
        return self

    def gossipsub_preset(self) -> "ScopeConfigBuilder":
        """reference: src/scope_config.rs:150-157"""
        self._config = ScopeConfig(network_type=NetworkType.GOSSIPSUB)
        return self

    def strict_consensus(self) -> "ScopeConfigBuilder":
        """Higher threshold = 0.9 (reference: src/scope_config.rs:160-163)."""
        self._config.default_consensus_threshold = 0.9
        return self

    def fast_consensus(self) -> "ScopeConfigBuilder":
        """Lower threshold = 0.6, 30s timeout (reference: src/scope_config.rs:166-170)."""
        self._config.default_consensus_threshold = 0.6
        self._config.default_timeout = 30.0
        return self

    def with_network_defaults(self, network_type: NetworkType) -> "ScopeConfigBuilder":
        """Reset network/threshold/timeout to the preset, preserving liveness
        and max-rounds override (reference: src/scope_config.rs:173-187)."""
        self._config.network_type = network_type
        self._config.default_consensus_threshold = 2.0 / 3.0
        self._config.default_timeout = DEFAULT_TIMEOUT_SECONDS
        return self

    def validate(self) -> None:
        self._config.validate()

    def build(self) -> ScopeConfig:
        self.validate()
        return self._config.clone()

    def get_config(self) -> ScopeConfig:
        return self._config.clone()
