"""hashgraph_tpu.obs — the production observability layer.

Four pieces, layered on (not replacing) the opt-in tracer in
:mod:`hashgraph_tpu.tracing`:

- :class:`MetricsRegistry` (``registry`` is the process-wide default):
  always-on counters / gauges / log-bucketed histograms cheap enough for
  per-batch hot paths;
- per-proposal lifecycle timelines (:mod:`.timeline`), recorded by
  ``TpuConsensusEngine`` and feeding the decision-latency histogram;
- exposition: Prometheus text rendering (:mod:`.prometheus`), an HTTP
  ``/metrics`` + ``/healthz`` sidecar (:mod:`.http`), and the bridge's
  ``GET_METRICS`` opcode;
- the always-on :class:`FlightRecorder` (``flight_recorder`` is the
  process-wide ring), auto-dumped as JSONL on engine faults and bridge
  dispatch exceptions;
- distributed causal tracing (:mod:`.trace`): traceparent-style
  :class:`TraceContext` carried on bridge frames and gossip bytes, the
  bounded process-wide :data:`trace_store` of context-tagged spans
  (:func:`observed_span` feeds it whenever a context is active), Chrome
  trace-event / Perfetto export, and :func:`merge_traces` stitching N
  peers' dumps into one causal timeline. Decision provenance on top:
  ``TpuConsensusEngine.explain_decision`` and the bridge ``OP_EXPLAIN``.

Well-known families (all on the default registry):

==============================================  =========  ==================
family                                          type       source
==============================================  =========  ==================
hashgraph_decision_latency_seconds              histogram  engine (create→decide wall time)
hashgraph_ingest_batch_size                     histogram  engine (votes per ingest call)
hashgraph_verify_batch_seconds                  histogram  engine (signature batch verify)
hashgraph_chain_kernel_seconds                  histogram  engine (device chain validation)
hashgraph_device_ingest_seconds                 histogram  engine (device vote dispatch)
wal_fsync_seconds                               histogram  WAL writer (per fsync syscall)
wal_recover_seconds                             histogram  DurableEngine.recover
hashgraph_live_proposals                        gauge      engines (tracked sessions)
hashgraph_vote_table_occupancy                  gauge      engines (claimed pool slots)
hashgraph_tier_{demoted_sessions,bytes}         gauge      engines (demoted-tier population / bytes)
hashgraph_tier_{demotions,promotions,gc}_total  counter    engine tier lifecycle traffic
wal_segment_count / wal_segment_bytes           gauge      WAL writers (live log footprint)
hashgraph_chain_suffix_length                   histogram  engine (votes applied per watermark extension)
hashgraph_votes_{total,accepted_total}          counter    engine ingest paths
hashgraph_proposals_created_total               counter    engine registration
hashgraph_decisions_total                       counter    engine transitions
hashgraph_timeouts_fired_total                  counter    engine timeout paths
hashgraph_verify_cache_{hits,misses,negative_hits,evictions}_total  counter  VerifiedVoteCache (memoized admission)
hashgraph_verified_signatures_total (+ {scheme=...})  counter    engine verify prepass (cache hits excluded)
hashgraph_verify_pool_queue_depth               gauge      native verify-pool backlog (scrape-time)
hashgraph_device_verify_{batches,signatures}_total  counter  crypto_device backend (batches / sigs dispatched)
hashgraph_device_verify_fallbacks_total         counter    crypto_device backend (host blame escalations)
hashgraph_device_verify_seconds                 histogram  crypto_device backend (end-to-end batch verify)
bridge_requests_total / bridge_errors_total     counter    bridge dispatch loop
flight_dumps_total                              counter    flight recorder dump sites
wal_checkpoints_total                           counter    DurableEngine checkpoints
hashgraph_alerts_total (+ {rule=...})           counter    health alert rule rising edges
hashgraph_equivocations_total                   counter    health evidence log (double-signs)
hashgraph_fork_redeliveries_total               counter    health evidence log (watermark forks)
hashgraph_truncation_redeliveries_total         counter    health scorecards (lagging chains)
hashgraph_expired_gossip_total                  counter    health scorecards (stale redeliveries)
hashgraph_{tracked_peers,evidence_records}      gauge      default health monitor
hashgraph_stale_peers                           gauge      liveness watchdog
hashgraph_phi (+ {peer=...})                    gauge      φ-accrual suspicion, worst peer (scrape-time)
hashgraph_liveness_suspects                     gauge      peers past the phi threshold (scrape-time)
hashgraph_liveness_heartbeats_total             counter    health monitor (admission heartbeats observed)
hashgraph_liveness_suspicion_edges_total        counter    health monitor (phi rising edges)
hashgraph_jax_live_buffer_bytes                 gauge      live JAX array bytes (scrape-time)
hashgraph_jax_compile_cache_{hits,misses}_total  counter   persistent XLA compile cache
hashgraph_sync_chunks_sent_total                counter    bridge sync source (snapshot chunks served)
hashgraph_sync_chunks_received_total            counter    CatchUpClient (snapshot chunks verified)
hashgraph_sync_tail_records_total               counter    CatchUpClient (WAL tail records applied)
hashgraph_sync_catchup_seconds                  histogram  CatchUpClient (end-to-end catch-up)
hashgraph_gossip_frames_sent_total              counter    gossip transport (multiplexed frames out)
hashgraph_gossip_frames_shed_total              counter    gossip transport (backpressure sheds)
hashgraph_gossip_frames_deferred_total          counter    gossip node (typed STATUS_RETRY_AFTER deferrals)
hashgraph_gossip_drain_pressure                 gauge      gossip send-queue saturation 0..1 (scrape-time)
hashgraph_bridge_retry_after_total              counter    bridge admission control (overload answers sent)
hashgraph_gossip_votes_coalesced_total          counter    vote coalescer (votes packed into batch frames)
hashgraph_gossip_send_queue_bytes               gauge      gossip transport send queues (scrape-time)
hashgraph_gossip_inflight_requests              gauge      gossip transport unanswered requests (scrape-time)
hashgraph_gossip_anti_entropy_rounds_total      counter    GossipNode anti-entropy rounds
hashgraph_gossip_anti_entropy_sessions_total    counter    GossipNode sessions pushed by anti-entropy
hashgraph_gossip_catchup_escalations_total      counter    GossipNode escalations to CatchUpClient
hashgraph_slo_breaches_total                    counter    SLO engine (decisions over their scope objective)
hashgraph_slo_alerts_total                      counter    SLO engine (burn-rate alert rising edges)
hashgraph_slo_alerts_firing                     gauge      SLO engine (objectives currently alerting)
hashgraph_slo_decision_p99_seconds (+ {scope=...}/{shard=...})  gauge  SLO engine (fast-window p99)
hashgraph_slo_burn_rate (+ {scope=...,window=...})  gauge   SLO engine (max fast-window burn rate)
hashgraph_slo_incidents_total                   counter    incident capture (dumps written)
hashgraph_bridge_wire_{columnar,fallback}_frames_total  counter  wire ingest (frames per decode path)
hashgraph_bridge_wire_{decode,crypto,apply}_seconds_total  counter  wire ingest (per-stage busy seconds)
hashgraph_bridge_wire_device_dispatches_total   counter    wire ingest (fused device calls issued)
hashgraph_bridge_wire_apply_rows_total          counter    wire ingest (vote rows riding dispatches)
hashgraph_bridge_shm_rings_attached_total       counter    bridge shm lane attachments
hashgraph_reactor_{windows,rows}_total          counter    apply reactor (windows flushed / rows ridden)
hashgraph_reactor_flush_{rows,bytes,deadline,now_change,forced}_total  counter  apply reactor flush reasons
hashgraph_reactor_window_occupancy              histogram  apply reactor (frames merged per window)
hashgraph_reactor_rows_per_dispatch             histogram  apply reactor (rows per fused dispatch)
hashgraph_profile_{samples,dropped}_total       counter    continuous profiler (stacks sampled / cap drops)
hashgraph_profile_overhead_seconds_total        counter    continuous profiler (self-measured sampling cost)
==============================================  =========  ==================

The table above is machine-readable: :func:`documented_families` parses it
(brace expansion, ``/`` alternatives, ``(+ ...)`` labelled-variant notes
stripped) and ``examples/metrics_smoke.py`` asserts every listed family is
eagerly installed — documentation drift from the registry is a test
failure, not a silent lie.
"""

from __future__ import annotations

import contextlib
import functools
import re
import time

from .flight import FlightRecorder, flight_recorder
from .accrual import PhiAccrual, phi_from_deviation
from .health import (
    ALERTS_TOTAL,
    EQUIVOCATIONS_TOTAL,
    EVIDENCE_RECORDS,
    EXPIRED_GOSSIP_TOTAL,
    FORK_REDELIVERIES_TOTAL,
    LIVENESS_HEARTBEATS_TOTAL,
    LIVENESS_SUSPECTS,
    LIVENESS_SUSPICION_EDGES_TOTAL,
    PHI,
    STALE_PEERS,
    TRACKED_PEERS,
    TRUNCATION_REDELIVERIES_TOTAL,
    AlertRule,
    EvidenceRecord,
    HealthMonitor,
    PeerScorecard,
)
from .http import MetricsSidecar
from .attribution import attribution_report, report_from_stage_totals
from .profiler import (
    PROFILE_DROPPED_TOTAL,
    PROFILE_OVERHEAD_SECONDS_TOTAL,
    PROFILE_SAMPLES_TOTAL,
    ContinuousProfiler,
    parse_collapsed,
    profiler_enabled,
    thread_role,
)
from .registry import (
    DEFAULT_SIZE_BUCKETS,
    DEFAULT_TIME_BUCKETS,
    Counter,
    Gauge,
    GaugeHandle,
    Histogram,
    Info,
    MetricsRegistry,
    log_buckets,
)
from .slo import (
    SLO_ALERTS_FIRING,
    SLO_ALERTS_TOTAL,
    SLO_BREACHES_TOTAL,
    SLO_BURN_RATE,
    SLO_DECISION_P99_SECONDS,
    SLO_INCIDENTS_TOTAL,
    IncidentCapture,
    SloEngine,
    WindowedHistogram,
)
from .timeline import ProposalTimeline, TimelineStore
from .trace import (
    TraceContext,
    TraceSpan,
    TraceStore,
    attach_trace,
    current_context,
    extract_trace,
    merge_traces,
    trace_store,
    use_context,
)

# ── Well-known family names ────────────────────────────────────────────

DECISION_LATENCY = "hashgraph_decision_latency_seconds"
INGEST_BATCH_SIZE = "hashgraph_ingest_batch_size"
VERIFY_BATCH_SECONDS = "hashgraph_verify_batch_seconds"
CHAIN_KERNEL_SECONDS = "hashgraph_chain_kernel_seconds"
DEVICE_INGEST_SECONDS = "hashgraph_device_ingest_seconds"
WAL_FSYNC_SECONDS = "wal_fsync_seconds"
WAL_RECOVER_SECONDS = "wal_recover_seconds"

LIVE_PROPOSALS = "hashgraph_live_proposals"
VOTE_TABLE_OCCUPANCY = "hashgraph_vote_table_occupancy"
WAL_SEGMENT_COUNT = "wal_segment_count"
WAL_SEGMENT_BYTES = "wal_segment_bytes"

# Tiered session lifecycle (engine demote/demand-page/GC): demoted-tier
# population + serialized bytes (scrape-time gauges over every live
# engine), and the demotion/promotion/GC traffic counters.
TIER_DEMOTED_SESSIONS = "hashgraph_tier_demoted_sessions"
TIER_BYTES = "hashgraph_tier_bytes"
TIER_DEMOTIONS_TOTAL = "hashgraph_tier_demotions_total"
TIER_PROMOTIONS_TOTAL = "hashgraph_tier_promotions_total"
TIER_GC_TOTAL = "hashgraph_tier_gc_total"

CHAIN_SUFFIX_LENGTH = "hashgraph_chain_suffix_length"

VOTES_TOTAL = "hashgraph_votes_total"
VOTES_ACCEPTED_TOTAL = "hashgraph_votes_accepted_total"
PROPOSALS_CREATED_TOTAL = "hashgraph_proposals_created_total"
DECISIONS_TOTAL = "hashgraph_decisions_total"
TIMEOUTS_FIRED_TOTAL = "hashgraph_timeouts_fired_total"
BRIDGE_REQUESTS_TOTAL = "bridge_requests_total"
BRIDGE_ERRORS_TOTAL = "bridge_errors_total"
FLIGHT_DUMPS_TOTAL = "flight_dumps_total"
WAL_CHECKPOINTS_TOTAL = "wal_checkpoints_total"
VERIFY_CACHE_HITS_TOTAL = "hashgraph_verify_cache_hits_total"
VERIFY_CACHE_MISSES_TOTAL = "hashgraph_verify_cache_misses_total"
VERIFY_CACHE_NEGATIVE_HITS_TOTAL = "hashgraph_verify_cache_negative_hits_total"
VERIFY_CACHE_EVICTIONS_TOTAL = "hashgraph_verify_cache_evictions_total"
# Signatures handed to a scheme's (batch) verify — cache hits excluded.
# Engines add a per-scheme labelled variant, e.g.
# hashgraph_verified_signatures_total{scheme="Ed25519ConsensusSigner"}.
VERIFIED_SIGNATURES_TOTAL = "hashgraph_verified_signatures_total"
# Native verify-pool tasks queued + running, sampled at scrape time.
VERIFY_POOL_QUEUE_DEPTH = "hashgraph_verify_pool_queue_depth"
# Device-resident Ed25519 batch verification (crypto_device.backend):
# batches/signatures dispatched to the device pipeline, host-blame
# escalations after a failed linear combination, and end-to-end batch
# wall time (decompress + SHA-512 + MSM + any blame pass).
DEVICE_VERIFY_BATCHES_TOTAL = "hashgraph_device_verify_batches_total"
DEVICE_VERIFY_SIGNATURES_TOTAL = "hashgraph_device_verify_signatures_total"
DEVICE_VERIFY_FALLBACKS_TOTAL = "hashgraph_device_verify_fallbacks_total"
DEVICE_VERIFY_SECONDS = "hashgraph_device_verify_seconds"
BUILD_INFO = "hashgraph_build_info"
# Device/XLA telemetry (providers installed by install_jax_telemetry —
# called from engine construction so obs itself stays jax-free).
JAX_LIVE_BUFFER_BYTES = "hashgraph_jax_live_buffer_bytes"
JAX_COMPILE_CACHE_HITS_TOTAL = "hashgraph_jax_compile_cache_hits_total"
JAX_COMPILE_CACHE_MISSES_TOTAL = "hashgraph_jax_compile_cache_misses_total"

# Scope-sharded fleet (parallel.fleet): shard-count gauges, the router's
# per-shard vote counter (fleets add labelled variants, e.g.
# hashgraph_fleet_routed_votes_total{shard="shard-0"}), and the
# fleet-wide sweep latency.
FLEET_SHARDS = "hashgraph_fleet_shards"
FLEET_SHARDS_RECOVERING = "hashgraph_fleet_shards_recovering"
FLEET_ROUTED_VOTES_TOTAL = "hashgraph_fleet_routed_votes_total"
FLEET_SWEEP_SECONDS = "hashgraph_fleet_sweep_seconds"

# Federated fleet (parallel.federation): live host count seen by each
# participant, votes routed to remotely-owned scopes over the gossip
# fabric, shard migrations completed, and end-to-end migration wall time
# (freeze -> snapshot+tail adopt -> placement flip -> tail replay).
FEDERATION_HOSTS = "hashgraph_federation_hosts"
FEDERATION_REMOTE_ROUTED_VOTES_TOTAL = (
    "hashgraph_federation_remote_routed_votes_total"
)
FEDERATION_MIGRATIONS_TOTAL = "hashgraph_federation_migrations_total"
FEDERATION_MIGRATION_SECONDS = "hashgraph_federation_migration_seconds"

# State sync (sync.client / bridge sync opcodes): snapshot chunks served
# by the source, chunks received + WAL tail records applied by the
# joiner, and the end-to-end catch-up wall time.
SYNC_CHUNKS_SENT_TOTAL = "hashgraph_sync_chunks_sent_total"
SYNC_CHUNKS_RECEIVED_TOTAL = "hashgraph_sync_chunks_received_total"
SYNC_TAIL_RECORDS_TOTAL = "hashgraph_sync_tail_records_total"
SYNC_CATCHUP_SECONDS = "hashgraph_sync_catchup_seconds"

# Gossip fabric (gossip.transport / gossip.node): multiplexed frames
# sent and shed (backpressure), votes packed by the coalescer, live
# send-queue bytes + in-flight requests across every transport (provider
# gauges), anti-entropy rounds/sessions pushed, and catch-up escalations
# of far-behind peers to the state-sync path.
GOSSIP_FRAMES_SENT_TOTAL = "hashgraph_gossip_frames_sent_total"
GOSSIP_FRAMES_SHED_TOTAL = "hashgraph_gossip_frames_shed_total"
GOSSIP_VOTES_COALESCED_TOTAL = "hashgraph_gossip_votes_coalesced_total"
GOSSIP_SEND_QUEUE_BYTES = "hashgraph_gossip_send_queue_bytes"
GOSSIP_INFLIGHT_REQUESTS = "hashgraph_gossip_inflight_requests"
GOSSIP_ANTI_ENTROPY_ROUNDS_TOTAL = "hashgraph_gossip_anti_entropy_rounds_total"
GOSSIP_ANTI_ENTROPY_SESSIONS_TOTAL = (
    "hashgraph_gossip_anti_entropy_sessions_total"
)
GOSSIP_CATCHUP_ESCALATIONS_TOTAL = "hashgraph_gossip_catchup_escalations_total"
# Overload admission control (ISSUE 18): frames the gossip node deferred
# after a typed STATUS_RETRY_AFTER answer (server-computed backoff hint
# from lane/queue depth), the server-side count of those answers, and a
# scrape-time 0..1 saturation gauge over every transport's send queues —
# operators see drain pressure instead of inferring it from silence.
GOSSIP_FRAMES_DEFERRED_TOTAL = "hashgraph_gossip_frames_deferred_total"
GOSSIP_DRAIN_PRESSURE = "hashgraph_gossip_drain_pressure"
BRIDGE_RETRY_AFTER_TOTAL = "hashgraph_bridge_retry_after_total"

# Zero-copy wire ingest (bridge._op_vote_batch columnar fast path):
# frames taken by each path, shm ring attachments, and per-stage wall
# seconds (wire decode / crypto / device apply) — the attribution the
# gossip bench reads back over GET_METRICS so the residual gap between
# networked and in-process throughput stays explainable per stage.
WIRE_COLUMNAR_FRAMES_TOTAL = "hashgraph_bridge_wire_columnar_frames_total"
WIRE_FALLBACK_FRAMES_TOTAL = "hashgraph_bridge_wire_fallback_frames_total"
WIRE_DECODE_SECONDS_TOTAL = "hashgraph_bridge_wire_decode_seconds_total"
WIRE_CRYPTO_SECONDS_TOTAL = "hashgraph_bridge_wire_crypto_seconds_total"
WIRE_APPLY_SECONDS_TOTAL = "hashgraph_bridge_wire_apply_seconds_total"
SHM_RINGS_ATTACHED_TOTAL = "hashgraph_bridge_shm_rings_attached_total"
# Device-dispatch amortization (ISSUE 19): how many fused
# ingest_wire_columnar dispatches the bridge layer actually issued and
# how many vote rows rode them — the bench's votes_per_dispatch line is
# apply_rows / device_dispatches, measured, not asserted. Both paths
# (reactor on AND off) increment these at the engine-call site.
WIRE_DEVICE_DISPATCHES_TOTAL = "hashgraph_bridge_wire_device_dispatches_total"
WIRE_APPLY_ROWS_TOTAL = "hashgraph_bridge_wire_apply_rows_total"

# Apply reactor (ISSUE 19): the cross-connection continuous-batching
# scheduler on the wire path. Windows = fused dispatch units flushed;
# rows = vote rows that rode a window; the flush_* family breaks the
# flush decisions down by reason (the registry's counters are
# label-free, so "flushes_by_reason" is one counter per reason).
# Occupancy (frames merged per window) and rows-per-dispatch land on
# size-bucket histograms.
REACTOR_WINDOWS_TOTAL = "hashgraph_reactor_windows_total"
REACTOR_ROWS_TOTAL = "hashgraph_reactor_rows_total"
REACTOR_FLUSH_ROWS_TOTAL = "hashgraph_reactor_flush_rows_total"
REACTOR_FLUSH_BYTES_TOTAL = "hashgraph_reactor_flush_bytes_total"
REACTOR_FLUSH_DEADLINE_TOTAL = "hashgraph_reactor_flush_deadline_total"
REACTOR_FLUSH_NOW_CHANGE_TOTAL = "hashgraph_reactor_flush_now_change_total"
REACTOR_FLUSH_FORCED_TOTAL = "hashgraph_reactor_flush_forced_total"
REACTOR_WINDOW_OCCUPANCY = "hashgraph_reactor_window_occupancy"
REACTOR_ROWS_PER_DISPATCH = "hashgraph_reactor_rows_per_dispatch"

# Process-wide default registry (mirrors tracing.tracer's role).
registry = MetricsRegistry()


def _install_well_known(reg: MetricsRegistry) -> None:
    """Create the well-known families eagerly so a scrape sees them from
    process start (a dashboard query against an idle node must not 404)."""
    for name in (
        DECISION_LATENCY,
        VERIFY_BATCH_SECONDS,
        CHAIN_KERNEL_SECONDS,
        DEVICE_INGEST_SECONDS,
        WAL_FSYNC_SECONDS,
        WAL_RECOVER_SECONDS,
        FLEET_SWEEP_SECONDS,
        FEDERATION_MIGRATION_SECONDS,
        SYNC_CATCHUP_SECONDS,
        DEVICE_VERIFY_SECONDS,
    ):
        reg.histogram(name, DEFAULT_TIME_BUCKETS)
    reg.histogram(INGEST_BATCH_SIZE, DEFAULT_SIZE_BUCKETS)
    reg.histogram(CHAIN_SUFFIX_LENGTH, DEFAULT_SIZE_BUCKETS)
    reg.histogram(REACTOR_WINDOW_OCCUPANCY, DEFAULT_SIZE_BUCKETS)
    reg.histogram(REACTOR_ROWS_PER_DISPATCH, DEFAULT_SIZE_BUCKETS)
    for name in (
        LIVE_PROPOSALS,
        VOTE_TABLE_OCCUPANCY,
        TIER_DEMOTED_SESSIONS,
        TIER_BYTES,
        WAL_SEGMENT_COUNT,
        WAL_SEGMENT_BYTES,
        JAX_LIVE_BUFFER_BYTES,
        VERIFY_POOL_QUEUE_DEPTH,
        FLEET_SHARDS,
        FLEET_SHARDS_RECOVERING,
        FEDERATION_HOSTS,
        TRACKED_PEERS,
        EVIDENCE_RECORDS,
        STALE_PEERS,
        PHI,
        LIVENESS_SUSPECTS,
        GOSSIP_SEND_QUEUE_BYTES,
        GOSSIP_INFLIGHT_REQUESTS,
        GOSSIP_DRAIN_PRESSURE,
    ):
        reg.gauge(name)
    for name in (
        VOTES_TOTAL,
        VOTES_ACCEPTED_TOTAL,
        PROPOSALS_CREATED_TOTAL,
        DECISIONS_TOTAL,
        TIMEOUTS_FIRED_TOTAL,
        BRIDGE_REQUESTS_TOTAL,
        BRIDGE_ERRORS_TOTAL,
        FLIGHT_DUMPS_TOTAL,
        WAL_CHECKPOINTS_TOTAL,
        VERIFY_CACHE_HITS_TOTAL,
        VERIFY_CACHE_MISSES_TOTAL,
        VERIFY_CACHE_NEGATIVE_HITS_TOTAL,
        VERIFY_CACHE_EVICTIONS_TOTAL,
        VERIFIED_SIGNATURES_TOTAL,
        TIER_DEMOTIONS_TOTAL,
        TIER_PROMOTIONS_TOTAL,
        TIER_GC_TOTAL,
        DEVICE_VERIFY_BATCHES_TOTAL,
        DEVICE_VERIFY_SIGNATURES_TOTAL,
        DEVICE_VERIFY_FALLBACKS_TOTAL,
        ALERTS_TOTAL,
        EQUIVOCATIONS_TOTAL,
        FORK_REDELIVERIES_TOTAL,
        TRUNCATION_REDELIVERIES_TOTAL,
        EXPIRED_GOSSIP_TOTAL,
        JAX_COMPILE_CACHE_HITS_TOTAL,
        JAX_COMPILE_CACHE_MISSES_TOTAL,
        FLEET_ROUTED_VOTES_TOTAL,
        FEDERATION_REMOTE_ROUTED_VOTES_TOTAL,
        FEDERATION_MIGRATIONS_TOTAL,
        SYNC_CHUNKS_SENT_TOTAL,
        SYNC_CHUNKS_RECEIVED_TOTAL,
        SYNC_TAIL_RECORDS_TOTAL,
        GOSSIP_FRAMES_SENT_TOTAL,
        GOSSIP_FRAMES_SHED_TOTAL,
        GOSSIP_VOTES_COALESCED_TOTAL,
        GOSSIP_ANTI_ENTROPY_ROUNDS_TOTAL,
        GOSSIP_ANTI_ENTROPY_SESSIONS_TOTAL,
        GOSSIP_CATCHUP_ESCALATIONS_TOTAL,
        GOSSIP_FRAMES_DEFERRED_TOTAL,
        BRIDGE_RETRY_AFTER_TOTAL,
        LIVENESS_HEARTBEATS_TOTAL,
        LIVENESS_SUSPICION_EDGES_TOTAL,
        WIRE_COLUMNAR_FRAMES_TOTAL,
        WIRE_FALLBACK_FRAMES_TOTAL,
        WIRE_DECODE_SECONDS_TOTAL,
        WIRE_CRYPTO_SECONDS_TOTAL,
        WIRE_APPLY_SECONDS_TOTAL,
        WIRE_DEVICE_DISPATCHES_TOTAL,
        WIRE_APPLY_ROWS_TOTAL,
        REACTOR_WINDOWS_TOTAL,
        REACTOR_ROWS_TOTAL,
        REACTOR_FLUSH_ROWS_TOTAL,
        REACTOR_FLUSH_BYTES_TOTAL,
        REACTOR_FLUSH_DEADLINE_TOTAL,
        REACTOR_FLUSH_NOW_CHANGE_TOTAL,
        REACTOR_FLUSH_FORCED_TOTAL,
        SHM_RINGS_ATTACHED_TOTAL,
        SLO_BREACHES_TOTAL,
        SLO_ALERTS_TOTAL,
        SLO_INCIDENTS_TOTAL,
        PROFILE_SAMPLES_TOTAL,
        PROFILE_DROPPED_TOTAL,
        PROFILE_OVERHEAD_SECONDS_TOTAL,
    ):
        reg.counter(name)
    # SLO gauges with registered providers come from the SloEngine bound
    # to this registry (below, for the default); bare families still must
    # exist from process start so an idle scrape sees them.
    for name in (SLO_ALERTS_FIRING, SLO_DECISION_P99_SECONDS, SLO_BURN_RATE):
        reg.gauge(name)
    reg.info(BUILD_INFO).set(
        # Resolved at scrape time: the package version needs the top-level
        # package object (circular at obs import time), and naming the JAX
        # runtime backend must not be the thing that initializes it (obs —
        # and the WAL, which imports obs — stays jax-free).
        version=_pkg_version,
        jax=lambda: _dist_version("jax"),
        backend=_jax_backend,
    )


@functools.lru_cache(maxsize=None)
def _dist_version(dist: str) -> str:
    """Installed version of ``dist`` WITHOUT importing it
    (importlib.metadata reads dist-info only). Cached: the value cannot
    change within a process, and every scrape resolves the labels —
    Prometheus polling must not pay repeated sys.path metadata walks."""
    try:
        from importlib.metadata import version

        return version(dist)
    except Exception:
        return "unknown"


@functools.lru_cache(maxsize=None)
def _pkg_version() -> str:
    try:
        from importlib.metadata import version

        return version("hashgraph-tpu")
    except Exception:
        import sys

        pkg = sys.modules.get("hashgraph_tpu")
        return getattr(pkg, "__version__", "unknown") if pkg else "unknown"


def _jax_backend() -> str:
    import sys

    jax = sys.modules.get("jax")
    if jax is None:
        return "not-loaded"
    try:
        # Only NAME an already-initialized backend: default_backend()
        # would otherwise initialize the platform client on the scrape
        # thread (grabbing device memory, pinning the platform before a
        # later distributed/platform-config call).
        from jax._src import xla_bridge

        if not xla_bridge._backends:
            return "uninitialized"
        return jax.default_backend()
    except Exception:
        return "uninitialized"


_install_well_known(registry)
flight_recorder.dump_counter = registry.counter(FLIGHT_DUMPS_TOTAL)

# Process-wide SLO engine (mirrors ``registry``'s role): engines feed it
# one observation per decision via their timeline sink; its windowed
# quantile / burn-rate / alert state backs the ``hashgraph_slo_*``
# families above and the sidecar's ``/slo`` endpoint. Incident capture is
# armed by ``$HASHGRAPH_INCIDENT_DIR`` (unset = evidence capture off).
slo_engine = SloEngine(
    registry,
    capture=IncidentCapture(counter=registry.counter(SLO_INCIDENTS_TOTAL)),
)

# Process-wide continuous profiler (mirrors ``registry``'s role): dormant
# until something starts it — ``BridgeServer.start()`` under the
# ``$HASHGRAPH_TPU_PROFILE=1`` opt-in (profiler.maybe_start_default), or
# an embedder directly. Its sample summary rides every attribution
# report (``/profile``, ``OP_PROFILE``, incident bundles).
default_profiler = ContinuousProfiler(registry)


def documented_families() -> list[str]:
    """Family names parsed from this module's docstring table — the
    contract ``examples/metrics_smoke.py`` holds the registry to, so the
    table can never silently drift from what is actually installed.
    Handles ``prefix{a,b}suffix`` brace alternatives, ``a / b`` listings,
    and strips ``(+ ...)`` labelled-variant notes."""
    table = __doc__.split("Well-known families", 1)[1]
    names: set[str] = set()
    separators = 0
    for line in table.splitlines():
        if line.startswith("====="):
            separators += 1
            if separators >= 3:
                break
            continue
        if separators != 2 or not line.strip():
            continue
        cell = re.split(r"\s{2,}", line.strip())[0]
        cell = cell.split(" (+", 1)[0].strip()
        for part in cell.split(" / "):
            part = part.strip()
            m = re.match(r"^([\w:]*)\{([\w,]+)\}([\w:]*)$", part)
            if m:
                for alt in m.group(2).split(","):
                    names.add(m.group(1) + alt + m.group(3))
            elif part:
                names.add(part)
    return sorted(names)

# Process-wide default health monitor (mirrors ``registry``'s role):
# engines not given their own share this one, so a bridge server's
# co-hosted peers accumulate one fleet view; its anomaly counters and
# point-in-time gauges land on the default registry above.
health_monitor = HealthMonitor(registry=registry)
health_monitor.register_gauges(registry)


def _jax_live_buffer_bytes() -> int:
    """Bytes held by live JAX arrays — sampled at scrape time, and only
    when something else already initialized the runtime (naming device
    memory must never be the thing that grabs it; same discipline as
    ``_jax_backend``)."""
    import sys

    jax = sys.modules.get("jax")
    if jax is None:
        return 0
    try:
        from jax._src import xla_bridge

        if not xla_bridge._backends:
            return 0
        return sum(int(getattr(a, "nbytes", 0)) for a in jax.live_arrays())
    except Exception:
        return 0


registry.register_gauge(JAX_LIVE_BUFFER_BYTES, _jax_live_buffer_bytes)


def _verify_pool_queue_depth() -> int:
    """Native verify-pool backlog — sampled at scrape time, and ONLY
    when the runtime is already loaded: naming the gauge must never be
    the thing that compiles or dlopens the native library (same
    discipline as the JAX gauges above)."""
    import sys

    native = sys.modules.get("hashgraph_tpu.native")
    if native is None:
        return 0
    try:
        return native.pool_queue_depth_if_loaded()
    except Exception:
        return 0


registry.register_gauge(VERIFY_POOL_QUEUE_DEPTH, _verify_pool_queue_depth)

_jax_telemetry_installed = False


def install_jax_telemetry(reg: MetricsRegistry | None = None) -> bool:
    """Route JAX's persistent-compilation-cache monitoring events
    (``/jax/compilation_cache/cache_hits`` / ``cache_misses``) onto the
    registry's counters. Idempotent; returns True once installed. Called
    from engine construction (which imports JAX anyway) so this module
    stays importable without JAX and never forces the runtime up."""
    global _jax_telemetry_installed
    if _jax_telemetry_installed:
        return True
    target = reg if reg is not None else registry
    try:
        from jax import monitoring as jax_monitoring
    except Exception:
        return False
    hits = target.counter(JAX_COMPILE_CACHE_HITS_TOTAL)
    misses = target.counter(JAX_COMPILE_CACHE_MISSES_TOTAL)

    def _on_event(event: str, **kwargs) -> None:
        if "/compilation_cache/" not in event:
            return
        if event.endswith("cache_hits"):
            hits.inc()
        elif event.endswith("cache_misses"):
            misses.inc()

    try:
        jax_monitoring.register_event_listener(_on_event)
    except Exception:
        return False
    _jax_telemetry_installed = True
    return True


@contextlib.contextmanager
def observed_span(tracer, name: str, histogram: Histogram, **attrs):
    """Time a block into the observability layers: always observe the
    duration into ``histogram`` (registry, always on); record a tracer
    span when tracing is enabled; and when a distributed trace context is
    active (:func:`hashgraph_tpu.obs.trace.use_context`), record a
    context-tagged child span into :data:`trace_store` — this is how
    engine/bridge/WAL spans join a cross-peer causal trace without any
    per-site wiring. One perf_counter pair (plus one contextvar read)
    when nothing is listening — cheap enough for per-batch sites, which
    is where this is used."""
    start = time.perf_counter()
    try:
        yield
    finally:
        duration = time.perf_counter() - start
        histogram.observe(duration)
        if tracer.enabled:
            tracer.record_span(name, start, duration, attrs)
        ctx = current_context()
        if ctx is not None and trace_store.enabled:
            end = time.time()
            trace_store.record(
                name,
                ctx.child(),
                end - duration,
                duration,
                parent=ctx.span_id,
                attrs=attrs,
            )


__all__ = [
    "AlertRule",
    "ContinuousProfiler",
    "Counter",
    "EvidenceRecord",
    "FlightRecorder",
    "Gauge",
    "GaugeHandle",
    "HealthMonitor",
    "Histogram",
    "IncidentCapture",
    "Info",
    "MetricsRegistry",
    "MetricsSidecar",
    "PeerScorecard",
    "PhiAccrual",
    "ProposalTimeline",
    "SloEngine",
    "TimelineStore",
    "TraceContext",
    "TraceSpan",
    "TraceStore",
    "WindowedHistogram",
    "attach_trace",
    "attribution_report",
    "current_context",
    "default_profiler",
    "documented_families",
    "extract_trace",
    "flight_recorder",
    "health_monitor",
    "install_jax_telemetry",
    "log_buckets",
    "merge_traces",
    "observed_span",
    "parse_collapsed",
    "phi_from_deviation",
    "profiler_enabled",
    "registry",
    "report_from_stage_totals",
    "slo_engine",
    "thread_role",
    "trace_store",
    "use_context",
]
