"""Always-on flight recorder: a bounded ring of recent events, dumped as
JSONL when something faults, so a postmortem can see the 2 seconds before
the crash without anyone having enabled tracing first.

Design constraints, in order:

1. **Near-zero overhead.** ``record`` is one tuple build + one
   ``deque.append`` (a single C call, atomic under the GIL — no lock on
   the hot path). Callers record per *batch* / per *request*, never per
   vote.
2. **Bounded.** The deque's ``maxlen`` caps memory; old events fall off.
3. **Always on.** There is no enable switch — the whole point is that the
   evidence exists when the fault nobody predicted happens.

Dumps go to ``$HASHGRAPH_FLIGHT_DIR`` (default
``<tmpdir>/hashgraph-flight``) as one JSONL file per fault, rate-limited
so a crash loop cannot fill the disk. The engine's public-API wrapper and
the bridge's dispatch loop both dump automatically on unexpected
exceptions; embedders can call :meth:`FlightRecorder.dump` on their own
fault paths too.
"""

from __future__ import annotations

import itertools
import json
import os
import tempfile
import threading
import time
from collections import deque

DEFAULT_CAPACITY = 4096
_ENV_DIR = "HASHGRAPH_FLIGHT_DIR"


def default_dump_dir() -> str:
    return os.environ.get(_ENV_DIR) or os.path.join(
        tempfile.gettempdir(), "hashgraph-flight"
    )


class FlightRecorder:
    """Lock-free bounded event ring with throttled JSONL fault dumps."""

    def __init__(
        self,
        capacity: int = DEFAULT_CAPACITY,
        dump_dir: str | None = None,
        min_dump_interval: float = 1.0,
    ):
        if capacity <= 0:
            raise ValueError("capacity must be positive")
        self._ring: deque = deque(maxlen=capacity)
        self._dump_dir = dump_dir
        self._min_interval = min_dump_interval
        self._last_dump = 0.0
        self._dropped_dumps = 0
        # Dump-path serialization only — record() stays lock-free. The
        # sequence uniquifies filenames when two faults land in the same
        # millisecond (itertools.count is atomic under the GIL).
        self._dump_lock = threading.Lock()
        self._dump_seq = itertools.count()
        # Optional Counter wired by hashgraph_tpu.obs (kept injectable to
        # avoid a module cycle with the registry's default instance).
        self.dump_counter = None

    # ── Recording (hot path) ───────────────────────────────────────────

    def record(self, kind: str, **attrs) -> None:
        """Append one event. deque.append is a single atomic C call; the
        ring may be appended to from any thread without a lock."""
        self._ring.append((time.time(), kind, attrs or None))

    # ── Readout / dumping ──────────────────────────────────────────────

    def __len__(self) -> int:
        return len(self._ring)

    def events(self) -> list[tuple[float, str, dict | None]]:
        """Oldest-first copy of the ring (list(deque) is atomic)."""
        return list(self._ring)

    def dump(self, reason: str, path: str | None = None) -> str | None:
        """Write the ring as JSONL (one event per line, oldest first,
        preceded by a header line carrying the reason and pid). Returns the
        file path, or None when throttled (at most one dump per
        ``min_dump_interval`` seconds — a crash loop must not fill the
        disk) or when the filesystem refuses the write. An explicit
        ``path`` bypasses (and does not consume) the throttle window.

        Never raises: this runs on fault paths, and an unwritable dump
        directory must not replace the original exception with an OSError
        — best-effort evidence, never a second fault."""
        with self._dump_lock:
            if path is None:
                # Throttle bookkeeping only for automatic fault dumps; an
                # explicit-path dump (embedder asked) must not consume the
                # window and suppress the next real fault's dump.
                now = time.monotonic()
                if now - self._last_dump < self._min_interval:
                    self._dropped_dumps += 1
                    return None
                self._last_dump = now
        tmp = None
        try:
            if path is None:
                directory = self._dump_dir or default_dump_dir()
                os.makedirs(directory, exist_ok=True)
                path = os.path.join(
                    directory,
                    f"flight-{int(time.time() * 1000)}"
                    f"-{os.getpid()}-{next(self._dump_seq)}.jsonl",
                )
            events = self.events()
            tmp = f"{path}.{next(self._dump_seq)}.tmp"
            with open(tmp, "w") as fh:
                fh.write(
                    json.dumps(
                        {
                            "type": "flight_header",
                            "reason": reason,
                            "pid": os.getpid(),
                            "ts": time.time(),
                            "events": len(events),
                            "dumps_throttled": self._dropped_dumps,
                        }
                    )
                    + "\n"
                )
                for ts, kind, attrs in events:
                    entry = {"ts": ts, "kind": kind}
                    if attrs:
                        for key, value in attrs.items():
                            # An unserializable attr must not turn the dump
                            # itself into a second fault.
                            try:
                                json.dumps(value)
                            except (TypeError, ValueError):
                                value = repr(value)
                            entry[key] = value
                    fh.write(json.dumps(entry) + "\n")
            os.replace(tmp, path)  # a torn dump never shadows a good one
        except Exception:
            self._dropped_dumps += 1
            if tmp is not None:
                try:
                    os.unlink(tmp)
                except OSError:
                    pass
            return None
        if self.dump_counter is not None:
            self.dump_counter.inc()
        return path

    def clear(self) -> None:
        self._ring.clear()


# Process-wide recorder: the engine, WAL, and bridge all feed this one ring
# so a dump interleaves every subsystem's last events in time order.
flight_recorder = FlightRecorder()
