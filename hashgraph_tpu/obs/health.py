"""Consensus health observatory: peer scorecards, misbehavior evidence,
liveness watchdog, and threshold alert rules.

The reference contract pushes liveness, timers, and peer-set management
onto the embedder (reference: src/lib.rs:15-34); at fleet scale the
operator's question is not "how many invalid votes" but *which peer* is
producing them. This module turns the engine's per-signer signals — vote
admissions, invalid signatures, expired gossip, fork/truncation
redeliveries, equivocations — into an accountable health layer:

- :class:`PeerScorecard` — bounded rolling stats per signer identity with
  a derived grade (``healthy | suspect | faulty``). Time is the logical
  monotonic tick the embedder already supplies to every engine call (the
  library's no-clock contract): ``last_seen`` and staleness are measured
  in that clock, never the wall.
- :class:`EvidenceRecord` — when two validly-signed conflicting votes
  from one peer are observed (same scope/proposal, different value or
  chain position), or a redelivered chain forks before the validated
  watermark at a position whose divergent vote's signer also has a
  different accepted vote (the double-sign bar — positional divergence
  alone is honestly producible and never attributed), the signed byte
  pairs are retained instead of dropped.
  Evidence is *self-authenticating*: both sides carry the offender's own
  signature over their content, so any third party can verify the
  conflict offline without trusting this process (the BFT-accountability
  property — see PAPERS.md).
- a **liveness watchdog** — suspicion is φ-accrual-derived
  (:mod:`hashgraph_tpu.obs.accrual`): each peer's inter-arrival history
  on the logical clock yields a continuous ``phi`` level, and a peer
  crosses into ``suspect``/stale when ``phi >= phi_threshold``. The old
  binary bound stays as a back-compat floor: silence past
  ``max(stale_after, session timeout hint)`` still convicts even when
  the arrival history is too thin for phi to speak.
- :class:`AlertRule` — threshold rules over registry metrics and
  scorecards. Rising edges emit a structured ``health.alert`` event into
  the flight recorder and count on ``hashgraph_alerts_total`` plus a
  per-rule ``hashgraph_alerts_total{rule="..."}`` counter; firing
  critical rules flip the bridge's ``/healthz`` to 503 with
  machine-readable reasons.

One process-wide default monitor (``hashgraph_tpu.obs.health_monitor``,
mirroring the metrics registry's role) is shared by every engine that is
not given its own, so a bridge server's co-hosted peers accumulate one
fleet view; all methods are thread-safe behind the monitor's own lock
(engines call in under their engine lock, scrape threads call in with no
lock at all).
"""

from __future__ import annotations

import hashlib
import threading
from collections import deque
from dataclasses import dataclass

from .accrual import PhiAccrual
from .flight import flight_recorder
from .prometheus import _escape_label
from .registry import MetricsRegistry

# Well-known family names (re-exported by hashgraph_tpu.obs; defined here
# so this module never imports the package __init__ — same layering as
# flight.py).
ALERTS_TOTAL = "hashgraph_alerts_total"
EQUIVOCATIONS_TOTAL = "hashgraph_equivocations_total"
FORK_REDELIVERIES_TOTAL = "hashgraph_fork_redeliveries_total"
TRUNCATION_REDELIVERIES_TOTAL = "hashgraph_truncation_redeliveries_total"
EXPIRED_GOSSIP_TOTAL = "hashgraph_expired_gossip_total"
EVIDENCE_RECORDS = "hashgraph_evidence_records"
TRACKED_PEERS = "hashgraph_tracked_peers"
STALE_PEERS = "hashgraph_stale_peers"
# φ-accrual liveness families (ISSUE 18): the bare PHI gauge reports the
# worst (max) suspicion across tracked peers; per-peer labelled
# ``hashgraph_phi{peer="..."}`` variants are installed as peers appear
# (bounded — see _MAX_PHI_LABELS).
PHI = "hashgraph_phi"
LIVENESS_SUSPECTS = "hashgraph_liveness_suspects"
LIVENESS_HEARTBEATS_TOTAL = "hashgraph_liveness_heartbeats_total"
LIVENESS_SUSPICION_EDGES_TOTAL = "hashgraph_liveness_suspicion_edges_total"

# Cap on per-peer labelled phi gauges: registry families are permanent,
# so an open-membership fleet must not mint one per transient identity.
_MAX_PHI_LABELS = 128

DEFAULT_PHI_THRESHOLD = 8.0

GRADE_HEALTHY = "healthy"
GRADE_SUSPECT = "suspect"
GRADE_FAULTY = "faulty"
_GRADE_RANK = {GRADE_HEALTHY: 0, GRADE_SUSPECT: 1, GRADE_FAULTY: 2}

SEVERITY_WARNING = "warning"
SEVERITY_CRITICAL = "critical"

KIND_EQUIVOCATION = "equivocation"
KIND_FORK = "fork"


@dataclass(slots=True)
class PeerScorecard:
    """Rolling per-signer accounting. All timestamps are the embedder's
    logical ``now`` ticks (no-clock contract); counters are cumulative
    for the monitor's lifetime (rates live on the metrics registry)."""

    identity: bytes
    first_seen: int = 0
    last_seen: int = 0
    votes_admitted: int = 0
    invalid_signatures: int = 0
    expired_gossip: int = 0
    fork_redeliveries: int = 0
    truncation_redeliveries: int = 0
    equivocations: int = 0
    # Chain lag: how far behind the accepted head this peer's most recent
    # non-extending redelivery was (accepted length - delivered length).
    chain_lag: int = 0
    max_chain_lag: int = 0
    # Largest consensus_timeout (seconds of logical time) among the
    # sessions this peer voted on — the watchdog's per-peer staleness
    # threshold, per "the scope's timeout config".
    timeout_hint: float = 0.0
    # φ-accrual inter-arrival history (lazily created on first
    # admission) and the last phi-suspicion state the alert evaluator
    # saw (rising-edge detection for the suspicion-edges counter).
    accrual: PhiAccrual | None = None
    phi_suspect: bool = False

    def phi(self, now: int | None) -> float:
        """Current φ-accrual suspicion level (0.0 with no clock or no
        usable arrival history — a thin history must never convict)."""
        if self.accrual is None or now is None:
            return 0.0
        return self.accrual.phi(now)

    def as_dict(
        self,
        now: int | None,
        stale_after: float,
        phi_threshold: float | None = None,
    ) -> dict:
        threshold = max(stale_after, self.timeout_hint)
        phi = self.phi(now)
        stale = now is not None and (
            (now - self.last_seen) > threshold
            or (phi_threshold is not None and phi >= phi_threshold)
        )
        return {
            "grade": self.grade(now, stale_after, phi_threshold),
            "votes_admitted": self.votes_admitted,
            "invalid_signatures": self.invalid_signatures,
            "expired_gossip": self.expired_gossip,
            "fork_redeliveries": self.fork_redeliveries,
            "truncation_redeliveries": self.truncation_redeliveries,
            "equivocations": self.equivocations,
            "chain_lag": self.chain_lag,
            "max_chain_lag": self.max_chain_lag,
            "first_seen": self.first_seen,
            "last_seen": self.last_seen,
            "stale": stale,
            "stale_after": threshold,
            "phi": round(phi, 3),
            "phi_threshold": phi_threshold,
        }

    def grade(
        self,
        now: int | None,
        stale_after: float,
        phi_threshold: float | None = None,
    ) -> str:
        """``faulty``: signed, self-authenticating misbehavior
        (equivocation). ``suspect``: circumstantial anomalies — invalid
        signatures, divergent (forked) redeliveries, φ-accrual suspicion
        past ``phi_threshold``, or silence past the binary timeout
        threshold (the back-compat floor) — which an honest-but-broken
        relay can also produce. ``healthy`` otherwise. Suspicion is
        computed at read time, so a phi- or silence-driven conviction
        clears itself the moment the peer's heartbeats resume."""
        if self.equivocations > 0:
            return GRADE_FAULTY
        threshold = max(stale_after, self.timeout_hint)
        if (
            self.invalid_signatures > 0
            or self.fork_redeliveries > 0
            or (now is not None and (now - self.last_seen) > threshold)
            or (phi_threshold is not None and self.phi(now) >= phi_threshold)
        ):
            return GRADE_SUSPECT
        return GRADE_HEALTHY


@dataclass(slots=True)
class EvidenceRecord:
    """One retained misbehavior proof. ``vote_a``/``vote_b`` are the
    verbatim wire (protobuf) bytes of the two conflicting votes — each
    carries the offender's signature over its own content, so the record
    authenticates itself to any verifier holding the scheme.
    ``verified`` says whether BOTH signatures were checked by this
    process at capture time (equivocations: yes — both votes passed
    admission validation; fork captures: no — the watermark path settles
    forks crypto-free by design, the bytes are retained for offline
    audit)."""

    kind: str  # KIND_EQUIVOCATION | KIND_FORK
    offender: bytes
    scope: str
    proposal_id: int
    detected_at: int
    vote_a: bytes  # accepted / first-seen signed vote bytes
    vote_b: bytes  # conflicting signed vote bytes
    verified: bool = True

    def as_dict(self) -> dict:
        return {
            "kind": self.kind,
            "offender": self.offender.hex(),
            "scope": self.scope,
            "proposal_id": self.proposal_id,
            "detected_at": self.detected_at,
            "vote_a": self.vote_a.hex(),
            "vote_b": self.vote_b.hex(),
            "verified": self.verified,
        }

    def dedup_key(self) -> bytes:
        h = hashlib.sha256()
        h.update(self.kind.encode())
        h.update(self.vote_a)
        h.update(b"|")
        h.update(self.vote_b)
        return h.digest()


class AlertRule:
    """One named threshold rule. ``check(view)`` returns a list of
    machine-readable detail dicts (empty = not firing); ``view`` is the
    evaluation context built by :meth:`HealthMonitor.evaluate_alerts`
    with keys ``peers`` (identity-hex -> scorecard dict), ``evidence``
    (list of dicts), ``stale`` (list of identity hexes), ``now``
    (logical tick or None), and ``registry``."""

    def __init__(
        self,
        name: str,
        check,
        severity: str = SEVERITY_WARNING,
        description: str = "",
    ):
        if severity not in (SEVERITY_WARNING, SEVERITY_CRITICAL):
            raise ValueError("severity must be 'warning' or 'critical'")
        self.name = name
        self.check = check
        self.severity = severity
        self.description = description

    # ── Factories ──────────────────────────────────────────────────────

    @classmethod
    def grade_at_least(
        cls, name: str, grade: str, severity: str = SEVERITY_CRITICAL
    ) -> "AlertRule":
        """Fires per peer whose derived grade is at or past ``grade``."""
        rank = _GRADE_RANK[grade]

        def check(view) -> list[dict]:
            return [
                {"peer": hexid, "grade": card["grade"]}
                for hexid, card in view["peers"].items()
                if _GRADE_RANK[card["grade"]] >= rank
            ]

        return cls(name, check, severity, f"any peer graded >= {grade}")

    @classmethod
    def stale_peers(
        cls, name: str = "peer-stale", severity: str = SEVERITY_WARNING
    ) -> "AlertRule":
        """Fires when the liveness watchdog flags any peer silent past
        its timeout threshold."""

        def check(view) -> list[dict]:
            return [{"peer": hexid} for hexid in view["stale"]]

        return cls(name, check, severity, "watchdog-flagged silent peers")

    @classmethod
    def phi_suspects(
        cls, name: str = "peer-suspect-phi", severity: str = SEVERITY_WARNING
    ) -> "AlertRule":
        """Fires per peer whose φ-accrual suspicion is at or past the
        monitor's phi threshold (the continuous-confidence analogue of
        ``peer-stale`` — see hashgraph_tpu.obs.accrual)."""

        def check(view) -> list[dict]:
            return [
                {
                    "peer": hexid,
                    "phi": card["phi"],
                    "threshold": card["phi_threshold"],
                }
                for hexid, card in view["peers"].items()
                if card.get("phi_threshold") is not None
                and card.get("phi", 0.0) >= card["phi_threshold"]
            ]

        return cls(name, check, severity, "phi-accrual suspicion past threshold")

    @classmethod
    def counter_above(
        cls,
        name: str,
        family: str,
        threshold: float,
        severity: str = SEVERITY_WARNING,
    ) -> "AlertRule":
        """Fires while ``registry.counter(family).value > threshold``
        (use for cumulative anomaly counters, e.g. negative verify-cache
        hits or WAL decode errors)."""

        def check(view) -> list[dict]:
            value = view["registry"].counter(family).value
            if value > threshold:
                return [{"metric": family, "value": value, "threshold": threshold}]
            return []

        return cls(name, check, severity, f"{family} > {threshold}")

    @classmethod
    def gauge_above(
        cls,
        name: str,
        family: str,
        threshold: float,
        severity: str = SEVERITY_WARNING,
    ) -> "AlertRule":
        def check(view) -> list[dict]:
            value = view["registry"].gauge(family).value
            if value > threshold:
                return [{"metric": family, "value": value, "threshold": threshold}]
            return []

        return cls(name, check, severity, f"{family} > {threshold}")

    @classmethod
    def scorecard_field_above(
        cls,
        name: str,
        fieldname: str,
        threshold: float,
        severity: str = SEVERITY_WARNING,
    ) -> "AlertRule":
        """Fires per peer whose scorecard ``fieldname`` exceeds
        ``threshold`` (e.g. invalid_signatures > 3)."""

        def check(view) -> list[dict]:
            return [
                {
                    "peer": hexid,
                    "field": fieldname,
                    "value": card[fieldname],
                    "threshold": threshold,
                }
                for hexid, card in view["peers"].items()
                if card.get(fieldname, 0) > threshold
            ]

        return cls(name, check, severity, f"{fieldname} > {threshold} on any peer")


def default_rules() -> "list[AlertRule]":
    """The stock rule set: signed misbehavior is critical (flips
    ``/healthz`` to 503 — an equivocating co-hosted peer means this
    node's output can no longer be trusted blindly); circumstantial
    anomalies are warnings an operator reads off the health report."""
    return [
        AlertRule.grade_at_least("peer-faulty", GRADE_FAULTY, SEVERITY_CRITICAL),
        AlertRule.grade_at_least("peer-suspect", GRADE_SUSPECT, SEVERITY_WARNING),
        AlertRule.stale_peers("peer-stale", SEVERITY_WARNING),
        AlertRule.phi_suspects("peer-suspect-phi", SEVERITY_WARNING),
        AlertRule.scorecard_field_above(
            "invalid-signature-burst", "invalid_signatures", 3, SEVERITY_WARNING
        ),
    ]


@dataclass(slots=True)
class _AlertState:
    firing: bool = False
    events: int = 0


class HealthMonitor:
    """Bounded, thread-safe health store: scorecards + evidence +
    watchdog + alert rules. See the module docstring for the model.

    ``stale_after`` is the default staleness threshold in logical-time
    units; a peer's own threshold is ``max(stale_after, largest
    consensus_timeout among its sessions)``. ``registry`` receives the
    anomaly counters and (for the process-default monitor) the gauge
    providers; pass a fresh :class:`MetricsRegistry` in tests for
    isolation.
    """

    def __init__(
        self,
        max_peers: int = 4096,
        max_evidence: int = 256,
        stale_after: float = 60.0,
        rules: "list[AlertRule] | None" = None,
        registry: MetricsRegistry | None = None,
        phi_threshold: "float | None" = DEFAULT_PHI_THRESHOLD,
        phi_window: int = 64,
        phi_min_samples: int = 8,
    ):
        if max_peers <= 0 or max_evidence <= 0:
            raise ValueError("max_peers and max_evidence must be positive")
        self.stale_after = float(stale_after)
        # φ-accrual suspicion bar: ``None`` disables the accrual detector
        # entirely (pure binary-threshold watchdog — the A/B baseline and
        # the pre-ISSUE-18 behavior).
        self.phi_threshold = (
            float(phi_threshold) if phi_threshold is not None else None
        )
        self._phi_window = int(phi_window)
        self._phi_min_samples = int(phi_min_samples)
        self._max_peers = max_peers
        self._max_evidence = max_evidence
        self._lock = threading.Lock()
        # Plain dict, bounded by amortized least-recently-SEEN eviction
        # (``_evict_locked``). An LRU OrderedDict with per-touch
        # move_to_end would be strictly ordered but costs the admission
        # hot path a list-node splice per vote; last_seen already orders
        # the victims, so eviction sorts rarely instead.
        self._peers: "dict[bytes, PeerScorecard]" = {}
        self._evidence: "deque[EvidenceRecord]" = deque()
        self._evidence_keys: set[bytes] = set()
        self._rules: "list[AlertRule]" = (
            list(rules) if rules is not None else default_rules()
        )
        self._alert_state: dict[str, _AlertState] = {}
        # Highest logical tick ever observed — the watchdog's "current
        # time" when a caller (e.g. an HTTP scrape, which has no embedder
        # clock) cannot supply one.
        self.latest_now = 0
        # Registries whose gauges already sample this monitor (see
        # register_gauges — double registration would double-count).
        self._gauge_registries: set[int] = set()
        # Registries that receive per-peer labelled phi gauges (strong
        # refs — a monitor and its registries share a lifetime), plus the
        # identities already labelled (bounded by _MAX_PHI_LABELS).
        self._phi_registries: "list[MetricsRegistry]" = []
        self._phi_labelled: set[bytes] = set()
        self._registry = registry if registry is not None else MetricsRegistry()
        reg = self._registry
        self._m_alerts = reg.counter(ALERTS_TOTAL)
        self._m_equivocations = reg.counter(EQUIVOCATIONS_TOTAL)
        self._m_forks = reg.counter(FORK_REDELIVERIES_TOTAL)
        self._m_truncations = reg.counter(TRUNCATION_REDELIVERIES_TOTAL)
        self._m_expired = reg.counter(EXPIRED_GOSSIP_TOTAL)
        self._m_heartbeats = reg.counter(LIVENESS_HEARTBEATS_TOTAL)
        self._m_phi_edges = reg.counter(LIVENESS_SUSPICION_EDGES_TOTAL)

    # ── Recording (engine-facing; engines call under their own lock) ───

    def tick(self, now: int) -> None:
        """Advance the monitor's logical clock without attributing
        anything to a peer (timeout sweeps call this so the watchdog has
        a current tick even when vote traffic stops). Locked: two engines
        sharing one monitor must not interleave the check-then-act and
        regress the clock below an observed tick."""
        with self._lock:
            self._tick_locked(now)

    def _tick_locked(self, now: int) -> None:
        if now > self.latest_now:
            self.latest_now = now

    def _card(self, identity: bytes, now: int) -> PeerScorecard:
        """Fetch-or-create under the caller's lock hold; past the cap the
        least-recently-seen peers are evicted (amortized)."""
        card = self._peers.get(identity)
        if card is None:
            card = PeerScorecard(identity, first_seen=now, last_seen=now)
            self._peers[identity] = card
            if len(self._peers) > self._max_peers:
                self._evict_locked()
        return card

    def _evict_locked(self) -> None:
        """Drop the least-recently-seen ~eighth of the peer set (at
        least one): one O(n log n) sort every cap/8 insertions instead
        of ordered-dict maintenance on every admission."""
        victims = sorted(self._peers.values(), key=lambda c: c.last_seen)
        for card in victims[: max(1, self._max_peers // 8)]:
            del self._peers[card.identity]

    def note_admitted(
        self,
        counts: "dict[bytes, int]",
        now: int,
        timeout_hint: float = 0.0,
    ) -> None:
        """Batched admission accounting: ``counts`` maps signer identity
        to votes admitted this call (the engine aggregates per batch so
        the hot path pays one lock acquisition, not one per vote).
        ``timeout_hint`` is the sessions' consensus_timeout — it raises
        the peers' staleness thresholds to the scope's timeout config.
        This is THE hot recording path (every admitted vote lands here);
        the body is deliberately inlined flat — no per-peer helper
        calls."""
        if not counts:
            return
        max_peers = self._max_peers
        fresh: "list[bytes] | None" = None
        with self._lock:
            if now > self.latest_now:
                self.latest_now = now
            peers = self._peers
            for identity, n in counts.items():
                card = peers.get(identity)
                if card is None:
                    card = PeerScorecard(
                        identity, first_seen=now, last_seen=now
                    )
                    peers[identity] = card
                    if len(peers) > max_peers:
                        self._evict_locked()
                    if fresh is None:
                        fresh = [identity]
                    else:
                        fresh.append(identity)
                # φ-accrual heartbeat: one arrival observation per batch
                # tick (the accrual coalesces same-tick arrivals itself).
                accrual = card.accrual
                if accrual is None:
                    accrual = card.accrual = PhiAccrual(
                        window=self._phi_window,
                        min_samples=self._phi_min_samples,
                    )
                accrual.heartbeat(now)
                card.votes_admitted += n
                if now > card.last_seen:
                    card.last_seen = now
                if timeout_hint > card.timeout_hint:
                    card.timeout_hint = timeout_hint
        self._m_heartbeats.inc(len(counts))
        # Labelled phi gauges for first-seen peers are installed OUTSIDE
        # the monitor lock: register_gauge takes registry locks, and a
        # scrape-side provider takes this monitor's lock — never hold
        # both from the same side.
        if fresh is not None and self._phi_registries:
            for identity in fresh:
                self._install_phi_gauge(identity)

    def note_invalid_signature(self, identity: bytes, now: int) -> None:
        """A vote claiming ``identity`` failed signature admission. The
        identity is the *claimed* signer — a forger imitating an honest
        peer dirties that peer's scorecard (grade: suspect, never
        faulty), which is exactly the signal an operator wants: someone
        is sending bad bytes under this name."""
        with self._lock:
            self._tick_locked(now)
            self._card(identity, now).invalid_signatures += 1
        # No dedicated counter family: invalid signatures already count
        # on the verify-cache / engine status surfaces; the scorecard
        # carries the per-peer attribution.

    def note_expired(self, identity: bytes, now: int) -> None:
        """Expired gossip (stale proposal or vote) attributed to the
        chain's most recent signer — the closest accountable identity to
        the redelivery source the engine can see."""
        with self._lock:
            self._tick_locked(now)
            self._card(identity, now).expired_gossip += 1
        self._m_expired.inc()

    def note_truncation(self, identity: bytes, lag: int, now: int) -> None:
        """A redelivered chain shorter than the accepted watermark:
        ``lag`` = accepted length - delivered length (the peer's view is
        behind the head)."""
        with self._lock:
            self._tick_locked(now)
            card = self._card(identity, now)
            card.truncation_redeliveries += 1
            card.chain_lag = lag
            if lag > card.max_chain_lag:
                card.max_chain_lag = lag
        self._m_truncations.inc()

    def note_fork(
        self,
        scope,
        proposal_id: int,
        accepted_vote_bytes: bytes,
        conflicting_vote_bytes: bytes,
        offender: bytes,
        now: int,
    ) -> None:
        """A redelivered chain diverging from the accepted prefix before
        the validated watermark, where the divergent vote's owner ALSO
        has a different accepted vote in the session — the engine only
        reports forks that meet the double-sign bar, so
        ``accepted_vote_bytes``/``conflicting_vote_bytes`` are BOTH the
        offender's own signed votes (a positional divergence alone can be
        produced by honest loss/reorder and is never attributed). The
        conflicting vote's signature was NOT verified here (the watermark
        path settles forks crypto-free — PR 4's whole point); the
        retained byte pair is self-authenticating for offline audit, so
        the record is marked ``verified=False``."""
        record = EvidenceRecord(
            kind=KIND_FORK,
            offender=offender,
            scope=str(scope),
            proposal_id=proposal_id,
            detected_at=now,
            vote_a=accepted_vote_bytes,
            vote_b=conflicting_vote_bytes,
            verified=False,
        )
        with self._lock:
            self._tick_locked(now)
            self._card(offender, now).fork_redeliveries += 1
            added = self._retain(record)
        if added:
            self._m_forks.inc()
            flight_recorder.record(
                "health.fork",
                scope=record.scope,
                proposal_id=proposal_id,
                offender=offender.hex(),
            )

    def note_equivocation(
        self,
        scope,
        proposal_id: int,
        first_vote_bytes: bytes,
        second_vote_bytes: bytes,
        offender: bytes,
        now: int,
    ) -> None:
        """Two validly-signed conflicting votes from one peer on one
        (scope, proposal) — different value or chain position. Both sides
        passed signature admission in this process, so the evidence is
        recorded ``verified=True``."""
        record = EvidenceRecord(
            kind=KIND_EQUIVOCATION,
            offender=offender,
            scope=str(scope),
            proposal_id=proposal_id,
            detected_at=now,
            vote_a=first_vote_bytes,
            vote_b=second_vote_bytes,
            verified=True,
        )
        with self._lock:
            self._tick_locked(now)
            added = self._retain(record)
            if added:
                self._card(offender, now).equivocations += 1
        if added:
            self._m_equivocations.inc()
            flight_recorder.record(
                "health.equivocation",
                scope=record.scope,
                proposal_id=proposal_id,
                offender=offender.hex(),
            )

    def _retain(self, record: EvidenceRecord) -> bool:
        """Dedup + bound the evidence log (lock held). Gossip redelivers
        the same conflict over and over; one retained pair per distinct
        conflict is the accountable unit."""
        key = record.dedup_key()
        if key in self._evidence_keys:
            return False
        self._evidence.append(record)
        self._evidence_keys.add(key)
        while len(self._evidence) > self._max_evidence:
            old = self._evidence.popleft()
            self._evidence_keys.discard(old.dedup_key())
        return True

    # ── Readout ────────────────────────────────────────────────────────

    def scorecard(self, identity: bytes) -> dict | None:
        """One peer's scorecard dict (graded at the latest tick)."""
        with self._lock:
            card = self._peers.get(identity)
            if card is None:
                return None
            return card.as_dict(
                self.latest_now, self.stale_after, self.phi_threshold
            )

    def peer_count(self) -> int:
        with self._lock:
            return len(self._peers)

    def evidence_count(self) -> int:
        with self._lock:
            return len(self._evidence)

    def evidence(self) -> "list[dict]":
        with self._lock:
            return [record.as_dict() for record in self._evidence]

    def convicted_peers(
        self, now: int | None = None, min_grade: str = GRADE_SUSPECT
    ) -> "dict[str, dict]":
        """Peers this monitor currently grades at or past ``min_grade``
        (default: every non-healthy peer) — the accountability readout
        the chaos harness asserts against. Returns ``identity-hex ->
        {"grade", "evidence"}`` where ``evidence`` counts the retained
        records naming that peer as offender. A conviction is only as
        good as its evidence: ``faulty`` grades always carry verified
        self-authenticating records; ``suspect`` grades may rest on
        circumstantial counters (invalid signatures, forked or stale
        redeliveries) an operator weighs rather than slashing on."""
        rank = _GRADE_RANK[min_grade]
        with self._lock:
            tick = self.latest_now if now is None else now
            offenders: dict[bytes, int] = {}
            for record in self._evidence:
                offenders[record.offender] = offenders.get(record.offender, 0) + 1
            out: dict[str, dict] = {}
            for identity, card in self._peers.items():
                grade = card.grade(tick, self.stale_after, self.phi_threshold)
                if _GRADE_RANK[grade] >= rank:
                    out[identity.hex()] = {
                        "grade": grade,
                        "evidence": offenders.get(identity, 0),
                    }
            return out

    def watchdog(self, now: int | None = None) -> "list[str]":
        """Identity hexes of peers silent past their staleness threshold
        at tick ``now`` (default: the latest tick observed)."""
        with self._lock:
            return self._stale_locked(self.latest_now if now is None else now)

    def _stale_locked(self, now: int | None) -> "list[str]":
        if now is None:
            return []
        phi_threshold = self.phi_threshold
        out = []
        for identity, card in self._peers.items():
            if (now - card.last_seen) > max(
                self.stale_after, card.timeout_hint
            ) or (
                phi_threshold is not None
                and card.phi(now) >= phi_threshold
            ):
                out.append(identity.hex())
        return out

    def stale_count(self) -> int:
        with self._lock:
            return len(self._stale_locked(self.latest_now))

    # ── Alert rules ────────────────────────────────────────────────────

    def add_rule(self, rule: AlertRule) -> None:
        with self._lock:
            self._rules.append(rule)

    def rules(self) -> "list[AlertRule]":
        with self._lock:
            return list(self._rules)

    def evaluate_alerts(
        self, now: int | None = None, registry: MetricsRegistry | None = None
    ) -> "list[dict]":
        """Run every rule against the current state; returns the firing
        alerts as ``{"rule", "severity", "description", "details"}``
        dicts. Counting is edge-triggered per rule: the transition
        not-firing -> firing emits ONE ``health.alert`` flight event and
        one increment on ``hashgraph_alerts_total`` (+ the per-rule
        labelled counter) — a /healthz poll loop must not turn one
        standing condition into a counter ramp."""
        firing, _ = self._evaluate(now, registry)
        return firing

    def _evaluate(
        self, now: int | None, registry: MetricsRegistry | None
    ) -> "tuple[list[dict], dict]":
        """(firing alerts, rule-evaluation view). The view — serialized
        scorecards, evidence, stale set — is returned so snapshot() can
        reuse it instead of paying a second full serialization pass per
        readout."""
        reg = registry if registry is not None else self._registry
        phi_threshold = self.phi_threshold
        phi_edges = 0
        with self._lock:
            tick = self.latest_now if now is None else now
            if now is not None:
                self._tick_locked(now)
            peers_view: dict[str, dict] = {}
            for identity, card in self._peers.items():
                serialized = card.as_dict(
                    tick, self.stale_after, phi_threshold
                )
                peers_view[identity.hex()] = serialized
                # Rising-edge accounting for the suspicion-edges counter:
                # one increment per not-suspect -> suspect transition as
                # seen by the evaluator, never a ramp per poll.
                suspect_now = (
                    phi_threshold is not None
                    and serialized["phi"] >= phi_threshold
                )
                if suspect_now and not card.phi_suspect:
                    phi_edges += 1
                card.phi_suspect = suspect_now
            view = {
                "now": tick,
                "registry": reg,
                "peers": peers_view,
                "evidence": [record.as_dict() for record in self._evidence],
                "stale": self._stale_locked(tick),
            }
            rules = list(self._rules)
        if phi_edges:
            self._m_phi_edges.inc(phi_edges)
        firing: list[dict] = []
        edges: list[tuple[str, str, int]] = []
        for rule in rules:
            try:
                details = rule.check(view)
            except Exception:
                # A broken rule must not take the health surface down
                # with it (same contract as gauge providers).
                continue
            with self._lock:
                state = self._alert_state.setdefault(rule.name, _AlertState())
                if details:
                    if not state.firing:
                        state.firing = True
                        state.events += 1
                        edges.append((rule.name, rule.severity, len(details)))
                    firing.append(
                        {
                            "rule": rule.name,
                            "severity": rule.severity,
                            "description": rule.description,
                            "details": details,
                        }
                    )
                else:
                    state.firing = False
        for name, severity, count in edges:
            self._m_alerts.inc()
            # Label-escape the rule name (backslash, quote, newline):
            # add_rule accepts arbitrary names, and one unescaped quote
            # in a counter name would invalidate the ENTIRE /metrics
            # exposition, not just this sample.
            self._registry.counter(
                f'{ALERTS_TOTAL}{{rule="{_escape_label(name)}"}}'
            ).inc()
            flight_recorder.record(
                "health.alert", rule=name, severity=severity, details=count
            )
        return firing, view

    def snapshot(self, now: int | None = None) -> dict:
        """The full JSON-ready health report: scorecards (graded at
        ``now`` or the latest tick), evidence records, watchdog state,
        and the firing alerts. This is what ``OP_HEALTH`` serves and
        ``bench.py --health-out`` persists. The serialized state is the
        SAME view the rules just evaluated (one pass, one moment — the
        report can never show alerts disagreeing with the scorecards
        beside them)."""
        alerts, view = self._evaluate(now, None)
        with self._lock:
            rule_names = [rule.name for rule in self._rules]
            events_total = sum(s.events for s in self._alert_state.values())
        return {
            "now": view["now"],
            "peers": view["peers"],
            # Accountability digest: every peer graded past healthy in
            # THIS report (same view as the scorecards beside it). The
            # chaos harness's conviction asserts read this key; see
            # convicted_peers() for the evidence-weighted readout.
            "convicted": {
                hexid: card["grade"]
                for hexid, card in view["peers"].items()
                if card["grade"] != GRADE_HEALTHY
            },
            "evidence": view["evidence"],
            "watchdog": {
                "stale_peers": view["stale"],
                "stale_after_default": self.stale_after,
                "phi_threshold": self.phi_threshold,
            },
            "alerts": {
                "firing": alerts,
                "rules": rule_names,
                "events_total": events_total,
            },
        }

    def register_gauges(self, registry: MetricsRegistry) -> None:
        """Attach this monitor's point-in-time gauges (tracked peers,
        retained evidence, stale peers) to ``registry``, weakly bound so
        a dead monitor's contribution vanishes. Idempotent per registry:
        providers are additive across registrations, so registering the
        same monitor twice would otherwise double its contribution on
        every scrape."""
        with self._lock:
            if id(registry) in self._gauge_registries:
                return
            self._gauge_registries.add(id(registry))
            self._phi_registries.append(registry)
            known = list(self._peers)
        registry.register_gauge(TRACKED_PEERS, self.peer_count, owner=self)
        registry.register_gauge(EVIDENCE_RECORDS, self.evidence_count, owner=self)
        registry.register_gauge(STALE_PEERS, self.stale_count, owner=self)
        registry.register_gauge(PHI, self.max_phi, owner=self)
        registry.register_gauge(
            LIVENESS_SUSPECTS, self.phi_suspect_count, owner=self
        )
        # Peers seen before this registry attached still get their
        # labelled phi series (idempotent per identity via _phi_labelled).
        for identity in known:
            self._install_phi_gauge(identity)

    # ── φ-accrual readout (gauge providers + labelled installs) ────────

    def max_phi(self) -> float:
        """Worst (max) φ-accrual suspicion across tracked peers at the
        latest tick — the bare ``hashgraph_phi`` series."""
        with self._lock:
            tick = self.latest_now
            return max(
                (card.phi(tick) for card in self._peers.values()),
                default=0.0,
            )

    def phi_suspect_count(self) -> int:
        """Peers at or past the phi threshold right now (the
        ``hashgraph_liveness_suspects`` gauge)."""
        if self.phi_threshold is None:
            return 0
        with self._lock:
            tick = self.latest_now
            return sum(
                1
                for card in self._peers.values()
                if card.phi(tick) >= self.phi_threshold
            )

    def _phi_sample(self, identity: bytes) -> float:
        with self._lock:
            card = self._peers.get(identity)
            return card.phi(self.latest_now) if card is not None else 0.0

    def _install_phi_gauge(self, identity: bytes) -> None:
        """Mint ``hashgraph_phi{peer="<hex>"}`` on every attached
        registry for ``identity`` (bounded; families are permanent, so an
        evicted peer's series just reads 0.0). Never called with the
        monitor lock held — register_gauge takes registry locks."""
        with self._lock:
            if (
                identity in self._phi_labelled
                or len(self._phi_labelled) >= _MAX_PHI_LABELS
            ):
                return
            self._phi_labelled.add(identity)
            registries = list(self._phi_registries)
        name = f'{PHI}{{peer="{_escape_label(identity.hex())}"}}'
        for registry in registries:
            registry.register_gauge(
                name,
                lambda identity=identity: self._phi_sample(identity),
                owner=self,
            )

    def reset(self) -> None:
        """Drop every scorecard, evidence record, and alert edge (tests
        only — production monitors should live for the process)."""
        with self._lock:
            self._peers.clear()
            self._evidence.clear()
            self._evidence_keys.clear()
            self._alert_state.clear()
            self.latest_now = 0
            # Labelled phi installs stay (registry families are
            # permanent); the providers read 0.0 for unknown peers.
