"""Continuous in-process stack profiler (the always-on GWP loop).

Every perf round so far steered by a busy-share number computed after
the fact from stage counters; this module closes the loop the way
Google-Wide Profiling does (Ren et al., IEEE Micro 2010): a background
thread samples every Python thread's stack via ``sys._current_frames()``
at an adaptive rate, folds the samples into a bounded call-graph
aggregate keyed by *thread role*, and exports both collapsed-stack
(flamegraph) text and Chrome trace-event documents that merge onto the
same wall-clock axis as the Dapper-lineage spans in :mod:`.trace`.

Design constraints, in the repo's established idiom:

- **Opt-in like the reactor**: ``$HASHGRAPH_TPU_PROFILE=1`` arms the
  process-wide instance (``obs.default_profiler``); ``enabled = False``
  is the live kill switch (the ``bench.py profile-overhead`` A/B flips
  it), mirroring ``SloEngine.enabled``.
- **Self-measuring overhead**: each sampling tick times itself and
  adapts the rate between ``min_hz`` (~19 Hz) and ``max_hz`` (~97 Hz) —
  backing off when the EWMA of its own cost exceeds ``overhead_budget``
  (a fraction of wall time), speeding back up when well under it. The
  odd primes avoid lockstep with periodic work (a 20 Hz sampler over a
  20 Hz flusher samples the same instant forever).
- **Bounded**: the aggregate holds at most ``max_stacks`` distinct
  (role, stack) keys; novel stacks past the cap count into ``dropped``
  instead of growing memory. A small ring of recent samples backs the
  Perfetto timeline export.
- **Protocol-invisible**: sampling reads interpreter frames only — it
  never touches engine or bridge state, so the sim/chaos corpus is
  byte-identical with the profiler on (asserted in tests).

Thread roles come from the repo's thread-name prefixes (reader threads,
the serial-lane pipeline pool, the apply reactor, gossip loops, WAL
fsync). The native crypto pool's worker threads are C threads invisible
to ``sys._current_frames()`` — time spent *waiting* on them shows up
under the submitting role, which is the schedulable truth.

Metric families (on whatever registry the profiler is bound to):
``hashgraph_profile_samples_total`` (thread-stacks captured),
``hashgraph_profile_dropped_total`` (samples lost to the stack cap),
``hashgraph_profile_overhead_seconds_total`` (the sampler's own cost).
"""

from __future__ import annotations

import json
import os
import sys
import threading
import time
from collections import deque

PROFILE_SAMPLES_TOTAL = "hashgraph_profile_samples_total"
PROFILE_DROPPED_TOTAL = "hashgraph_profile_dropped_total"
PROFILE_OVERHEAD_SECONDS_TOTAL = "hashgraph_profile_overhead_seconds_total"

PROFILE_SCHEMA = "hashgraph.profile.v1"

_ENV_PROFILE = "HASHGRAPH_TPU_PROFILE"

# Thread-name prefix -> role. Longest-prefix wins, so order by
# specificity. These are the names the repo actually assigns:
# bridge connection readers, the bridge pipeline (serial-lane) pool,
# the apply reactor + its deadline flusher, gossip transport loops,
# WAL writers, and any future Python-side crypto pool.
_ROLE_PREFIXES: tuple[tuple[str, str], ...] = (
    ("bridge-reader", "reader"),
    ("bridge-shm", "reader"),
    ("bridge-pipeline", "serial-lane"),
    ("apply-reactor", "reactor"),
    ("reactor-flusher", "reactor"),
    ("crypto", "crypto-pool"),
    ("gossip", "gossip-loop"),
    ("wal", "wal-fsync"),
    ("MainThread", "main"),
)


def thread_role(name: str) -> str:
    """Role label for a thread name (prefix table above; unmatched
    threads fold under ``other`` so the aggregate stays total)."""
    for prefix, role in _ROLE_PREFIXES:
        if name.startswith(prefix):
            return role
    return "other"


def _frame_label(code) -> str:
    """``module.qualname`` for one frame — short enough for collapsed
    lines, unambiguous enough to find the function."""
    mod = os.path.splitext(os.path.basename(code.co_filename))[0]
    qual = getattr(code, "co_qualname", code.co_name)
    return f"{mod}.{qual}"


def parse_collapsed(text: str) -> dict:
    """Inverse of :meth:`ContinuousProfiler.collapsed`: ``{(role,
    (frame, ...)): samples}``. Round-tripping is a test invariant — the
    export must stay loadable by standard flamegraph tooling AND by us."""
    out: dict = {}
    for line in text.splitlines():
        line = line.strip()
        if not line:
            continue
        stack, _, count = line.rpartition(" ")
        parts = stack.split(";")
        key = (parts[0], tuple(parts[1:]))
        out[key] = out.get(key, 0) + int(count)
    return out


class ContinuousProfiler:
    """Adaptive-rate whole-process stack sampler with a bounded
    (role, stack) aggregate. See the module docstring for the contract;
    ``sample_once`` / ``_adapt`` are deliberately public-ish seams so
    tests drive the fold and the backoff deterministically instead of
    racing wall clocks."""

    def __init__(
        self,
        registry=None,
        *,
        min_hz: float = 19.0,
        max_hz: float = 97.0,
        overhead_budget: float = 0.01,
        max_stacks: int = 4096,
        max_depth: int = 64,
        recent_samples: int = 4096,
    ):
        if not (0 < min_hz <= max_hz):
            raise ValueError("need 0 < min_hz <= max_hz")
        self.min_hz = float(min_hz)
        self.max_hz = float(max_hz)
        self.overhead_budget = float(overhead_budget)
        self.max_stacks = int(max_stacks)
        self.max_depth = int(max_depth)
        self.enabled = True  # live kill switch (sampling skipped when off)
        self._interval = 1.0 / self.max_hz  # optimistic start; backs off
        self._overhead_frac = 0.0
        self._overhead_s = 0.0
        self._samples = 0
        self._dropped = 0
        self._stacks: dict = {}
        self._roles: dict = {}
        self._recent: deque = deque(maxlen=int(recent_samples))
        self._lock = threading.Lock()
        self._stop_event = threading.Event()
        self._thread: threading.Thread | None = None
        self._own_ident: int | None = None
        if registry is not None:
            self._samples_counter = registry.counter(PROFILE_SAMPLES_TOTAL)
            self._dropped_counter = registry.counter(PROFILE_DROPPED_TOTAL)
            self._overhead_counter = registry.counter(
                PROFILE_OVERHEAD_SECONDS_TOTAL
            )
        else:
            self._samples_counter = None
            self._dropped_counter = None
            self._overhead_counter = None

    # ── lifecycle ──────────────────────────────────────────────────────

    @property
    def running(self) -> bool:
        return self._thread is not None and self._thread.is_alive()

    @property
    def rate_hz(self) -> float:
        return 1.0 / self._interval

    def start(self) -> None:
        """Idempotent: a process has one sampling thread, many callers
        (every BridgeServer.start() under the env opt-in)."""
        if self.running:
            return
        self._stop_event.clear()
        self._thread = threading.Thread(
            target=self._loop, name="obs-profiler", daemon=True
        )
        self._thread.start()

    def stop(self) -> None:
        self._stop_event.set()
        thread = self._thread
        if thread is not None:
            thread.join(timeout=5)
        self._thread = None

    def _loop(self) -> None:
        self._own_ident = threading.get_ident()
        while not self._stop_event.wait(self._interval):
            if not self.enabled:
                continue
            t0 = time.perf_counter()
            try:
                self.sample_once()
            except Exception:
                # A sampler fault must never take the process (or even
                # the sampler) down — skip the tick, keep the cadence.
                continue
            self._adapt(time.perf_counter() - t0)

    # ── the sampling tick ──────────────────────────────────────────────

    def sample_once(self) -> int:
        """Capture one stack per live thread (self excluded) into the
        aggregate; returns the number of thread-stacks taken."""
        frames = sys._current_frames()
        names = {t.ident: t.name for t in threading.enumerate()}
        wall = time.time()
        taken = 0
        dropped = 0
        with self._lock:
            for ident, frame in frames.items():
                if ident == self._own_ident:
                    continue
                role = thread_role(names.get(ident, ""))
                stack = []
                f = frame
                while f is not None and len(stack) < self.max_depth:
                    stack.append(_frame_label(f.f_code))
                    f = f.f_back
                stack.reverse()  # collapsed format is root-first
                key = (role, tuple(stack))
                if key in self._stacks or len(self._stacks) < self.max_stacks:
                    self._stacks[key] = self._stacks.get(key, 0) + 1
                else:
                    dropped += 1
                self._roles[role] = self._roles.get(role, 0) + 1
                self._samples += 1
                taken += 1
                self._recent.append((wall, role, stack[-1] if stack else "?"))
            self._dropped += dropped
        if self._samples_counter is not None and taken:
            self._samples_counter.inc(taken)
        if self._dropped_counter is not None and dropped:
            self._dropped_counter.inc(dropped)
        return taken

    def _adapt(self, cost_s: float) -> None:
        """Fold one tick's measured cost into the overhead EWMA and move
        the rate: over budget -> back off toward ``min_hz``; well under
        (below half the budget) -> speed back up toward ``max_hz``."""
        self._overhead_s += cost_s
        if self._overhead_counter is not None and cost_s > 0:
            self._overhead_counter.inc(cost_s)
        frac = cost_s / self._interval if self._interval > 0 else 1.0
        self._overhead_frac = 0.7 * self._overhead_frac + 0.3 * frac
        hz = 1.0 / self._interval
        if self._overhead_frac > self.overhead_budget:
            hz = max(self.min_hz, hz * 0.6)
        elif self._overhead_frac < 0.5 * self.overhead_budget:
            hz = min(self.max_hz, hz * 1.2)
        self._interval = 1.0 / hz

    def reset(self) -> None:
        with self._lock:
            self._stacks.clear()
            self._roles.clear()
            self._recent.clear()
            self._samples = 0
            self._dropped = 0
            self._overhead_s = 0.0
            self._overhead_frac = 0.0

    # ── readouts ───────────────────────────────────────────────────────

    def snapshot(self) -> dict:
        """Machine-readable aggregate: totals, rate, per-role sample
        counts, and the (bounded) stack table sorted hottest-first."""
        with self._lock:
            stacks = [
                {"role": role, "frames": list(fr), "samples": n}
                for (role, fr), n in sorted(
                    self._stacks.items(), key=lambda kv: (-kv[1], kv[0])
                )
            ]
            return {
                "schema": PROFILE_SCHEMA,
                "enabled": bool(self.enabled),
                "running": self.running,
                "rate_hz": round(self.rate_hz, 2),
                "overhead_budget": self.overhead_budget,
                "samples": self._samples,
                "dropped": self._dropped,
                "overhead_seconds": round(self._overhead_s, 6),
                "roles": dict(sorted(self._roles.items())),
                "stacks": stacks,
            }

    def collapsed(self, snapshot: dict | None = None) -> str:
        """Collapsed-stack text (``role;root;...;leaf N`` per line) —
        the format ``flamegraph.pl`` / speedscope / inferno ingest
        directly. :func:`parse_collapsed` is the exact inverse."""
        snap = self.snapshot() if snapshot is None else snapshot
        lines = [
            ";".join([entry["role"], *entry["frames"]])
            + f" {entry['samples']}"
            for entry in snap["stacks"]
        ]
        return "\n".join(lines) + ("\n" if lines else "")

    def chrome_events(self) -> list[dict]:
        """The retained sample ring as Chrome trace-event instants: one
        synthetic pid 0 "profiler" process (real peers start at pid 1 in
        :func:`..trace.chrome_trace`), one thread row per role, each
        sample an instant at its wall-clock microsecond — so sampled
        stacks and causal spans line up on one Perfetto axis."""
        with self._lock:
            recent = list(self._recent)
        events: list[dict] = [
            {
                "ph": "M",
                "name": "process_name",
                "pid": 0,
                "tid": 0,
                "args": {"name": "profiler (sampled stacks)"},
            }
        ]
        tids: dict[str, int] = {}
        samples: list[dict] = []
        for wall, role, leaf in recent:
            tid = tids.setdefault(role, len(tids) + 1)
            samples.append(
                {
                    "ph": "i",
                    "name": leaf,
                    "pid": 0,
                    "tid": tid,
                    "ts": wall * 1e6,
                    "s": "t",
                    "args": {"role": role},
                }
            )
        for role, tid in tids.items():
            events.append(
                {
                    "ph": "M",
                    "name": "thread_name",
                    "pid": 0,
                    "tid": tid,
                    "args": {"name": f"role {role}"},
                }
            )
        events.extend(samples)
        return events

    def export_chrome(self, path: str | None = None, spans=None) -> dict:
        """One merged Chrome trace-event document: the trace store's
        spans (or ``spans``) plus this profiler's sampled timeline.
        Writes JSON to ``path`` when given; returns the document."""
        from .trace import chrome_trace, trace_store

        doc = chrome_trace(trace_store.spans() if spans is None else spans)
        doc.setdefault("traceEvents", []).extend(self.chrome_events())
        snap = self.snapshot()
        doc.setdefault("otherData", {})["profile"] = {
            "samples": snap["samples"],
            "dropped": snap["dropped"],
            "rate_hz": snap["rate_hz"],
            "overhead_seconds": snap["overhead_seconds"],
        }
        if path is not None:
            with open(path, "w") as fh:
                json.dump(doc, fh)
        return doc


def profiler_enabled(explicit: "bool | None" = None) -> bool:
    """The reactor's construction-default/escape-hatch contract: an
    explicit argument wins; otherwise ``$HASHGRAPH_TPU_PROFILE`` (``1``
    = on), defaulting to OFF — always-on sampling is an operator's
    opt-in, and the determinism suites gate it."""
    if explicit is not None:
        return bool(explicit)
    return os.environ.get(_ENV_PROFILE, "0") == "1"


def maybe_start_default() -> "ContinuousProfiler | None":
    """Start the process-wide profiler iff the env opt-in is set (called
    from ``BridgeServer.start()`` — every serving process gets the
    always-on loop without per-embedder wiring). Returns the running
    instance, or None when the opt-in is off."""
    if not profiler_enabled():
        return None
    from hashgraph_tpu import obs

    if not obs.default_profiler.running:
        obs.default_profiler.start()
    return obs.default_profiler
