"""AttributionReport — the round-11/19 busy-share math as a readout.

Round 11 found "device-apply is 66.8% of server busy time" and round 19
attacked it down to 50.9% — but both numbers were ad-hoc counter
arithmetic inside ``bench.py``. This module makes the per-component
wall-clock attribution a first-class, machine-readable report any
operator (or incident bundle) can pull:

- the wire-path stage counters
  (``hashgraph_bridge_wire_{decode,crypto,apply}_seconds_total``),
- the WAL fsync histogram (``wal_fsync_seconds`` sum/count),
- the reactor window/dispatch counters (fused dispatches, rows,
  flush-reason breakdown),
- and the continuous profiler's sampled per-role stack counts,

fused into one ``{"stages": {name: {"seconds", "share"}}}`` body whose
shares sum to 1.0 over the instrumented busy time. The report is served
three ways (same body each time): the ``OP_PROFILE`` bridge opcode, the
sidecar's ``/profile`` endpoint, and ``IncidentCapture``'s
``profile.json``; ``parallel.rollup.merge_profile_states`` federates
host-labelled reports into one fleet view.

``report_from_stage_totals`` accepts a bench ``stage_totals`` block
(the BENCH_*.json schema) so the BENCH_r19 device-apply share is
reproducible from the checked-in artifact — an acceptance test, not a
coincidence: both paths share ``_build_report``.
"""

from __future__ import annotations

ATTRIBUTION_SCHEMA = "hashgraph.attribution.v1"

# Instrumented busy-time components, in pipeline order. ``wal_fsync``
# rides the histogram rather than a *_seconds_total counter; everything
# shares one denominator so the shares are comparable across rounds.
STAGE_KEYS = ("wire_decode", "crypto", "device_apply", "wal_fsync")

_STAGE_COUNTERS = {
    "hashgraph_bridge_wire_decode_seconds_total": "wire_decode",
    "hashgraph_bridge_wire_crypto_seconds_total": "crypto",
    "hashgraph_bridge_wire_apply_seconds_total": "device_apply",
}
_WAL_FSYNC_HISTOGRAM = "wal_fsync_seconds"
_DISPATCHES = "hashgraph_bridge_wire_device_dispatches_total"
_APPLY_ROWS = "hashgraph_bridge_wire_apply_rows_total"
_REACTOR_COUNTERS = {
    "hashgraph_reactor_windows_total": "windows",
    "hashgraph_reactor_rows_total": "rows",
    "hashgraph_reactor_flush_rows_total": "flush_rows",
    "hashgraph_reactor_flush_bytes_total": "flush_bytes",
    "hashgraph_reactor_flush_deadline_total": "flush_deadline",
    "hashgraph_reactor_flush_now_change_total": "flush_now_change",
    "hashgraph_reactor_flush_forced_total": "flush_forced",
}


def _build_report(
    seconds: dict,
    *,
    dispatches: float = 0.0,
    apply_rows: float = 0.0,
    wal_fsyncs: int = 0,
    reactor: dict | None = None,
    samples: dict | None = None,
) -> dict:
    busy = sum(seconds.values())
    stages = {
        key: {
            "seconds": round(seconds.get(key, 0.0), 6),
            "share": round(seconds.get(key, 0.0) / busy, 4) if busy else 0.0,
        }
        for key in STAGE_KEYS
    }
    report = {
        "schema": ATTRIBUTION_SCHEMA,
        "busy_seconds": round(busy, 6),
        "stages": stages,
        "device": {
            "dispatches": dispatches,
            "apply_rows": apply_rows,
            # The round-19 amortization factor, measured not asserted.
            "votes_per_dispatch": (
                round(apply_rows / dispatches, 2) if dispatches else 0.0
            ),
        },
        "wal": {"fsyncs": wal_fsyncs},
    }
    if reactor is not None:
        report["reactor"] = reactor
    if samples is not None:
        report["samples"] = samples
    return report


def attribution_report(state: dict | None = None, profiler=None) -> dict:
    """The live process's attribution report. ``state`` defaults to the
    process registry's ``export_state()``; ``profiler`` defaults to the
    process-wide :data:`~hashgraph_tpu.obs.default_profiler` (its sample
    summary is included only when it has actually sampled — an idle
    profiler must not imply an empty profile means an idle process)."""
    if state is None:
        from hashgraph_tpu.obs import registry

        state = registry.export_state()
    counters = state.get("counters") or {}
    histograms = state.get("histograms") or {}

    seconds = {key: 0.0 for key in STAGE_KEYS}
    for family, key in _STAGE_COUNTERS.items():
        seconds[key] = float(counters.get(family, 0.0))
    wal_fsyncs = 0
    wal = histograms.get(_WAL_FSYNC_HISTOGRAM)
    if wal:
        seconds["wal_fsync"] = float(wal.get("sum", 0.0))
        wal_fsyncs = int(wal.get("count", 0))

    reactor = {
        key: float(counters.get(family, 0.0))
        for family, key in _REACTOR_COUNTERS.items()
    }

    if profiler is None:
        from hashgraph_tpu.obs import default_profiler

        profiler = default_profiler
    samples = None
    snap = profiler.snapshot() if profiler is not None else None
    if snap is not None and snap["samples"]:
        samples = {
            "total": snap["samples"],
            "dropped": snap["dropped"],
            "rate_hz": snap["rate_hz"],
            "overhead_seconds": snap["overhead_seconds"],
            "roles": snap["roles"],
        }

    return _build_report(
        seconds,
        dispatches=float(counters.get(_DISPATCHES, 0.0)),
        apply_rows=float(counters.get(_APPLY_ROWS, 0.0)),
        wal_fsyncs=wal_fsyncs,
        reactor=reactor,
        samples=samples,
    )


def report_from_stage_totals(totals: dict) -> dict:
    """Attribution report from a bench ``stage_totals`` block (the
    BENCH_*.json schema: ``wire_decode_s / crypto_s / device_apply_s``
    plus ``device_dispatches / apply_rows``). Shares from this path are
    formula-identical to the bench's ``apply_share`` — the BENCH_r19
    reproduction test holds the two to the same number."""
    seconds = {
        "wire_decode": float(totals.get("wire_decode_s", 0.0)),
        "crypto": float(totals.get("crypto_s", 0.0)),
        "device_apply": float(totals.get("device_apply_s", 0.0)),
        "wal_fsync": float(totals.get("wal_fsync_s", 0.0)),
    }
    return _build_report(
        seconds,
        dispatches=float(totals.get("device_dispatches", 0.0)),
        apply_rows=float(totals.get("apply_rows", 0.0)),
    )
