"""SLO engine: sliding-window decision-latency quantiles, multi-window
burn-rate alerting, and exemplar-linked incident capture.

The registry's :class:`~hashgraph_tpu.obs.registry.Histogram` is
cumulative-forever — right for trend dashboards, useless for "is p99 over
objective *right now*". This module adds the time dimension:

- :class:`WindowedHistogram` — a sliding-window sketch over the SAME
  log-spaced bucket bounds the registry uses. Observations land in fixed
  time slices (a bounded deque of count vectors); a windowed quantile
  sums the slices inside the window and interpolates with the shared
  :func:`~hashgraph_tpu.obs.registry.quantile_from`. Memory is bounded at
  ``ceil(max_age / slice_seconds)`` count vectors regardless of rate.
- :class:`SloEngine` — per-scope, per-shard, and global windowed
  trackers; declarative objectives arrive per decision (the engine reads
  ``ScopeConfig.decide_p99_ms``); *multi-window burn-rate* alerting in
  the Google-SRE style: the burn rate is (breaching fraction) / (error
  budget fraction), and an alert fires only when BOTH the fast (5m) and
  slow (1h) windows burn above threshold — the fast window gives low
  detection latency, the slow window suppresses blips — and clears when
  the fast window recovers. State is machine-readable (:meth:`SloEngine
  .state`, the ``/slo`` sidecar endpoint) and exported as
  ``hashgraph_slo_*`` families on the metrics registry.
- :class:`IncidentCapture` — when a decision breaches its objective or an
  alert fires, dump the correlated evidence (flight-recorder ring,
  ``trace_store`` spans as a Perfetto-loadable Chrome trace, breach
  metadata) into a bounded on-disk incident directory, cooled down per
  scope so a sustained breach storm produces one dump, not thousands.

Everything takes an injectable ``clock`` so the chaos sim drives it on
virtual time; the process-wide instance (``hashgraph_tpu.obs.slo_engine``)
runs on ``time.monotonic``.
"""

from __future__ import annotations

import json
import math
import os
import shutil
import threading
import time
from bisect import bisect_left
from collections import OrderedDict, deque

from .flight import flight_recorder
from .registry import DEFAULT_TIME_BUCKETS, quantile_from
from .trace import chrome_trace, trace_store

# ── Well-known SLO families (installed eagerly by hashgraph_tpu.obs) ───

SLO_BREACHES_TOTAL = "hashgraph_slo_breaches_total"
SLO_ALERTS_TOTAL = "hashgraph_slo_alerts_total"
SLO_ALERTS_FIRING = "hashgraph_slo_alerts_firing"
SLO_DECISION_P99_SECONDS = "hashgraph_slo_decision_p99_seconds"
SLO_BURN_RATE = "hashgraph_slo_burn_rate"
SLO_INCIDENTS_TOTAL = "hashgraph_slo_incidents_total"

DEFAULT_FAST_WINDOW = 300.0  # 5 minutes
DEFAULT_SLOW_WINDOW = 3600.0  # 1 hour
# Google SRE multi-window default: 14.4x burn consumes a 30-day budget in
# ~2 days — page-worthy, yet blips shorter than the fast window never fire.
DEFAULT_BURN_THRESHOLD = 14.4

_ENV_INCIDENT_DIR = "HASHGRAPH_INCIDENT_DIR"

_escape = (
    lambda v: str(v).replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")
)


class WindowedHistogram:
    """Sliding-window log-bucketed sketch. NOT self-locking — the owner
    (:class:`SloEngine`) serializes access; standalone users in tests may
    call it single-threaded."""

    __slots__ = ("bounds", "slice_seconds", "max_age", "_slices")

    def __init__(
        self,
        bounds: tuple[float, ...] = DEFAULT_TIME_BUCKETS,
        slice_seconds: float = 10.0,
        max_age: float = DEFAULT_SLOW_WINDOW,
    ):
        if slice_seconds <= 0 or max_age <= slice_seconds:
            raise ValueError("need 0 < slice_seconds < max_age")
        self.bounds = tuple(float(b) for b in bounds)
        self.slice_seconds = float(slice_seconds)
        self.max_age = float(max_age)
        # Each slice: [slice_start, counts(len(bounds)+1), total, breaching].
        # Only slices that saw traffic exist; the deque stays time-ordered.
        self._slices: deque = deque()

    def _prune(self, now: float) -> None:
        horizon = now - self.max_age
        slices = self._slices
        while slices and slices[0][0] + self.slice_seconds <= horizon:
            slices.popleft()

    def observe(self, value: float, now: float, breaching: bool = False) -> None:
        start = math.floor(now / self.slice_seconds) * self.slice_seconds
        slices = self._slices
        if not slices or slices[-1][0] != start:
            self._prune(now)
            slices.append([start, [0] * (len(self.bounds) + 1), 0, 0])
        cur = slices[-1]
        cur[1][bisect_left(self.bounds, value)] += 1
        cur[2] += 1
        if breaching:
            cur[3] += 1

    def window_counts(
        self, window: float, now: float
    ) -> tuple[list[int], int, int]:
        """(bucket counts, total, breaching) summed over slices whose span
        intersects ``[now - window, now]``."""
        horizon = now - window
        counts = [0] * (len(self.bounds) + 1)
        total = breaching = 0
        for start, slice_counts, n, b in self._slices:
            if start + self.slice_seconds <= horizon:
                continue
            for i, c in enumerate(slice_counts):
                if c:
                    counts[i] += c
            total += n
            breaching += b
        return counts, total, breaching

    def quantile(self, q: float, window: float, now: float) -> float:
        counts, total, _ = self.window_counts(window, now)
        return quantile_from(self.bounds, counts, total, q)

    def summary(self, window: float, now: float) -> dict:
        counts, total, breaching = self.window_counts(window, now)
        return {
            "count": total,
            "breaching": breaching,
            "p50": quantile_from(self.bounds, counts, total, 0.5),
            "p95": quantile_from(self.bounds, counts, total, 0.95),
            "p99": quantile_from(self.bounds, counts, total, 0.99),
        }


class _ScopeTracker:
    __slots__ = (
        "window",
        "objective_s",
        "breaches",
        "alerts_total",
        "alert_firing",
        "alert_since",
    )

    def __init__(self, window: WindowedHistogram):
        self.window = window
        self.objective_s: float | None = None
        self.breaches = 0
        self.alerts_total = 0
        self.alert_firing = False
        self.alert_since: float | None = None


class IncidentCapture:
    """Bounded on-disk incident dumps linking an SLO breach to its causal
    evidence. Each incident directory holds:

    - ``incident.json`` — reason, scope/shard, breach latency vs
      objective, the breaching decision's trace id, span/event counts;
    - ``flight.jsonl`` — the process flight-recorder ring at capture time
      (explicit-path dump, so the fault-dump throttle is not consumed);
    - ``trace.json`` — ``trace_store`` spans as a Chrome trace-event
      document (Perfetto / chrome://tracing open it directly), filtered
      to the breaching trace id when its spans are still in the store;
    - ``profile.json`` — the wall-clock attribution report
      (:func:`~hashgraph_tpu.obs.attribution.attribution_report`):
      per-stage busy shares plus the continuous profiler's sampled
      per-role stack counts — *what the process was doing* when the
      objective broke, not just the breaching trace.

    Bounded two ways: newest ``max_incidents`` directories are kept
    (oldest pruned), and a per-scope ``cooldown_s`` collapses a breach
    storm into one dump. ``root=None`` (and no ``$HASHGRAPH_INCIDENT_DIR``)
    disables capture entirely."""

    def __init__(
        self,
        root: str | None = None,
        *,
        max_incidents: int = 16,
        cooldown_s: float = 60.0,
        clock=time.monotonic,
        counter=None,
    ):
        self.root = root if root is not None else os.environ.get(_ENV_INCIDENT_DIR)
        self.max_incidents = max_incidents
        self.cooldown_s = cooldown_s
        self._clock = clock
        self._lock = threading.Lock()
        self._last: dict[str, float] = {}
        self._seq = 0
        self.counter = counter

    @property
    def enabled(self) -> bool:
        return self.root is not None

    def capture(
        self,
        reason: str,
        *,
        scope=None,
        shard: str | None = None,
        trace_hex: str | None = None,
        latency_s: float | None = None,
        objective_s: float | None = None,
        detail: dict | None = None,
    ) -> str | None:
        """Dump one incident; returns its directory (None when disabled,
        cooled down, or the filesystem refuses — capture is best-effort
        evidence on what is effectively a fault path, never a second
        fault)."""
        if self.root is None:
            return None
        key = str(scope)
        with self._lock:
            now = self._clock()
            last = self._last.get(key)
            if last is not None and now - last < self.cooldown_s:
                return None
            self._last[key] = now
            self._seq += 1
            seq = self._seq
        path = os.path.join(self.root, f"incident-{seq:06d}-{reason}")
        try:
            os.makedirs(path, exist_ok=True)
            flight_recorder.dump(reason, path=os.path.join(path, "flight.jsonl"))
            spans = []
            if trace_hex:
                try:
                    spans = trace_store.spans(trace_id=bytes.fromhex(trace_hex))
                except ValueError:
                    spans = []
            if not spans:
                # The breaching trace already aged out of the bounded
                # store (or none was bound): keep the whole store — a
                # partial causal picture beats an empty file.
                spans = trace_store.spans()
            doc = chrome_trace(spans)
            doc.setdefault("otherData", {})["incident"] = reason
            with open(os.path.join(path, "trace.json"), "w") as fh:
                json.dump(doc, fh)
            try:
                # Additive evidence: a failing attribution read must not
                # cost the flight/trace dumps already on disk.
                from .attribution import attribution_report

                with open(os.path.join(path, "profile.json"), "w") as fh:
                    json.dump(attribution_report(), fh, indent=2)
            except Exception:
                pass
            meta = {
                "reason": reason,
                "scope": key if scope is not None else None,
                "shard": shard,
                "trace_id": trace_hex,
                "latency_s": latency_s,
                "objective_s": objective_s,
                "spans": len(spans),
                "flight_events": len(flight_recorder),
                "wall_ts": time.time(),
            }
            if detail:
                meta["detail"] = detail
            with open(os.path.join(path, "incident.json"), "w") as fh:
                json.dump(meta, fh, indent=2)
            self._gc()
        except Exception:
            return None
        if self.counter is not None:
            self.counter.inc()
        return path

    def incidents(self) -> list[str]:
        """Sorted incident directory names currently on disk (oldest
        first — the capture sequence is embedded in the name)."""
        if self.root is None or not os.path.isdir(self.root):
            return []
        return sorted(
            d
            for d in os.listdir(self.root)
            if d.startswith("incident-")
            and os.path.isdir(os.path.join(self.root, d))
        )

    def _gc(self) -> None:
        names = self.incidents()
        for stale in names[: max(0, len(names) - self.max_incidents)]:
            shutil.rmtree(os.path.join(self.root, stale), ignore_errors=True)


class SloEngine:
    """Windowed decision-latency tracking + multi-window burn-rate alerts.

    ``observe`` is the one hot entry point (called once per *decision*,
    under the caller's engine lock): it files the latency into the
    global, per-shard, and per-scope windowed sketches, applies the
    scope's objective if one was declared, and evaluates the alert state
    machine. Scope trackers live in a bounded LRU (a churn bench mints
    millions of scopes; unbounded per-scope state would be a leak) —
    scopes with declared objectives are pinned and never evicted.

    ``enabled=False`` short-circuits ``observe`` before any lock — the
    kill switch the SLO-overhead A/B in ``bench.py`` flips."""

    def __init__(
        self,
        registry=None,
        *,
        clock=time.monotonic,
        fast_window: float = DEFAULT_FAST_WINDOW,
        slow_window: float = DEFAULT_SLOW_WINDOW,
        burn_threshold: float = DEFAULT_BURN_THRESHOLD,
        target_quantile: float = 0.99,
        slice_seconds: float = 10.0,
        max_scopes: int = 256,
        capture: IncidentCapture | None = None,
    ):
        if not 0.0 < target_quantile < 1.0:
            raise ValueError("target_quantile must be in (0, 1)")
        if fast_window >= slow_window:
            raise ValueError("fast_window must be shorter than slow_window")
        self.enabled = True
        self.fast_window = float(fast_window)
        self.slow_window = float(slow_window)
        self.burn_threshold = float(burn_threshold)
        self.target_quantile = float(target_quantile)
        # Error budget: the fraction of decisions ALLOWED over objective
        # (1% for a p99 objective). burn = breaching_fraction / budget.
        self.budget_fraction = 1.0 - target_quantile
        self.slice_seconds = float(slice_seconds)
        self.max_scopes = max_scopes
        self._clock = clock
        self._lock = threading.Lock()
        self._global = self._new_window()
        self._shards: dict[str, WindowedHistogram] = {}
        self._scopes: "OrderedDict[str, _ScopeTracker]" = OrderedDict()
        self.capture = capture
        self._registry = registry
        self._m_breaches = None
        self._m_alerts = None
        self._shard_gauges: set[str] = set()
        self._scope_gauges: set[str] = set()
        if registry is not None:
            self._m_breaches = registry.counter(SLO_BREACHES_TOTAL)
            self._m_alerts = registry.counter(SLO_ALERTS_TOTAL)
            registry.register_gauge(
                SLO_ALERTS_FIRING, self._alerts_firing_count, owner=self
            )
            registry.register_gauge(
                SLO_DECISION_P99_SECONDS,
                lambda: self._global_p99(),
                owner=self,
            )
            registry.register_gauge(
                SLO_BURN_RATE, lambda: self._max_burn(), owner=self
            )

    def _new_window(self) -> WindowedHistogram:
        return WindowedHistogram(
            DEFAULT_TIME_BUCKETS, self.slice_seconds, self.slow_window
        )

    # ── Hot path ───────────────────────────────────────────────────────

    def observe(
        self,
        scope,
        latency_s: float,
        *,
        shard: str | None = None,
        objective_s: float | None = None,
        trace_hex: str | None = None,
        now: float | None = None,
    ) -> None:
        """File one decision latency. ``objective_s`` is the scope's
        declared SLO threshold (``ScopeConfig.decide_p99_ms / 1000``) or
        None for best-effort scopes (tracked, never alerting)."""
        if not self.enabled:
            return
        if now is None:
            now = self._clock()
        key = str(scope)
        breaching = objective_s is not None and latency_s > objective_s
        fired = False
        with self._lock:
            self._global.observe(latency_s, now, breaching)
            if shard is not None:
                wh = self._shards.get(shard)
                if wh is None:
                    wh = self._shards.setdefault(shard, self._new_window())
                    self._install_shard_gauge(shard)
                wh.observe(latency_s, now, breaching)
            tracker = self._scopes.get(key)
            if tracker is None:
                tracker = _ScopeTracker(self._new_window())
                self._scopes[key] = tracker
                self._evict_scopes()
            else:
                self._scopes.move_to_end(key)
            if objective_s is not None:
                if tracker.objective_s is None:
                    self._install_scope_gauges(key)
                tracker.objective_s = objective_s
            tracker.window.observe(latency_s, now, breaching)
            if breaching:
                tracker.breaches += 1
                if self._m_breaches is not None:
                    self._m_breaches.inc()
            if tracker.objective_s is not None:
                fired = self._evaluate_alert(key, tracker, now)
        if self.capture is not None and (breaching or fired):
            self.capture.capture(
                "burn_rate_alert" if fired else "slo_breach",
                scope=scope,
                shard=shard,
                trace_hex=trace_hex,
                latency_s=latency_s,
                objective_s=objective_s,
            )

    def _evict_scopes(self) -> None:
        # Objective-carrying trackers are pinned: an operator declared an
        # SLO on them, so their alert state must survive scope churn.
        while len(self._scopes) > self.max_scopes:
            for key, tracker in self._scopes.items():
                if tracker.objective_s is None:
                    del self._scopes[key]
                    break
            else:
                break  # every tracker is pinned; accept the overshoot

    def _burn(self, tracker: _ScopeTracker, window: float, now: float) -> float:
        _, total, breaching = tracker.window.window_counts(window, now)
        if total == 0:
            return 0.0
        return (breaching / total) / self.budget_fraction

    def _evaluate_alert(
        self, key: str, tracker: _ScopeTracker, now: float
    ) -> bool:
        fast = self._burn(tracker, self.fast_window, now)
        if tracker.alert_firing:
            if fast < self.burn_threshold:
                tracker.alert_firing = False
                tracker.alert_since = None
            return False
        if fast < self.burn_threshold:
            return False
        slow = self._burn(tracker, self.slow_window, now)
        if slow < self.burn_threshold:
            return False
        tracker.alert_firing = True
        tracker.alert_since = now
        tracker.alerts_total += 1
        if self._m_alerts is not None:
            self._m_alerts.inc()
        return True

    # ── Gauges (scrape-time providers on labelled families) ────────────

    def _install_shard_gauge(self, shard: str) -> None:
        if self._registry is None or shard in self._shard_gauges:
            return
        self._shard_gauges.add(shard)
        name = f'{SLO_DECISION_P99_SECONDS}{{shard="{_escape(shard)}"}}'
        self._registry.register_gauge(
            name, lambda s=shard: self._shard_p99(s), owner=self
        )

    def _install_scope_gauges(self, key: str) -> None:
        # Only objective-carrying scopes get labelled families: those are
        # operator-declared and few; minting one per churned bench scope
        # would grow the registry without bound (families are permanent).
        if self._registry is None or key in self._scope_gauges:
            return
        self._scope_gauges.add(key)
        label = _escape(key)
        self._registry.register_gauge(
            f'{SLO_DECISION_P99_SECONDS}{{scope="{label}"}}',
            lambda k=key: self._scope_quantile(k),
            owner=self,
        )
        self._registry.register_gauge(
            f'{SLO_BURN_RATE}{{scope="{label}",window="fast"}}',
            lambda k=key: self._scope_burn(k, self.fast_window),
            owner=self,
        )
        self._registry.register_gauge(
            f'{SLO_BURN_RATE}{{scope="{label}",window="slow"}}',
            lambda k=key: self._scope_burn(k, self.slow_window),
            owner=self,
        )

    def _global_p99(self) -> float:
        with self._lock:
            return self._global.quantile(
                self.target_quantile, self.fast_window, self._clock()
            )

    def _shard_p99(self, shard: str) -> float:
        with self._lock:
            wh = self._shards.get(shard)
            if wh is None:
                return 0.0
            return wh.quantile(
                self.target_quantile, self.fast_window, self._clock()
            )

    def _scope_quantile(self, key: str) -> float:
        with self._lock:
            tracker = self._scopes.get(key)
            if tracker is None:
                return 0.0
            return tracker.window.quantile(
                self.target_quantile, self.fast_window, self._clock()
            )

    def observed_p99(self, scope, *, now: float | None = None) -> float:
        """Per-scope windowed decision-latency quantile in SECONDS (the
        engine's ``target_quantile`` over the fast window), 0.0 while the
        scope has no recent decisions. Public read for the adaptive
        consensus-timeout learner (:mod:`hashgraph_tpu.engine.adaptive`),
        which decays a scope's learned timeout toward this observation."""
        with self._lock:
            tracker = self._scopes.get(str(scope))
            if tracker is None:
                return 0.0
            if now is None:
                now = self._clock()
            return tracker.window.quantile(
                self.target_quantile, self.fast_window, now
            )

    def _scope_burn(self, key: str, window: float) -> float:
        with self._lock:
            tracker = self._scopes.get(key)
            if tracker is None:
                return 0.0
            return self._burn(tracker, window, self._clock())

    def _alerts_firing_count(self) -> int:
        with self._lock:
            return sum(1 for t in self._scopes.values() if t.alert_firing)

    def _max_burn(self) -> float:
        with self._lock:
            now = self._clock()
            return max(
                (
                    self._burn(t, self.fast_window, now)
                    for t in self._scopes.values()
                    if t.objective_s is not None
                ),
                default=0.0,
            )

    # ── Readout ────────────────────────────────────────────────────────

    def state(self, now: float | None = None) -> dict:
        """Machine-readable SLO state — the ``/slo`` endpoint's body and
        the ``slo`` block ``OP_METRICS_PULL`` ships per host."""
        if now is None:
            now = self._clock()
        with self._lock:
            scopes = {}
            alerting = []
            for key, t in self._scopes.items():
                entry = t.window.summary(self.fast_window, now)
                entry["objective_s"] = t.objective_s
                entry["breaches_total"] = t.breaches
                if t.objective_s is not None:
                    entry["burn_fast"] = self._burn(t, self.fast_window, now)
                    entry["burn_slow"] = self._burn(t, self.slow_window, now)
                    entry["alert_firing"] = t.alert_firing
                    entry["alerts_total"] = t.alerts_total
                    if t.alert_firing:
                        alerting.append(key)
                scopes[key] = entry
            out = {
                "enabled": self.enabled,
                "windows": {
                    "fast_s": self.fast_window,
                    "slow_s": self.slow_window,
                },
                "burn_threshold": self.burn_threshold,
                "target_quantile": self.target_quantile,
                "global": self._global.summary(self.fast_window, now),
                "shards": {
                    sid: wh.summary(self.fast_window, now)
                    for sid, wh in self._shards.items()
                },
                "scopes": scopes,
                "alerts_firing": alerting,
            }
        if self.capture is not None:
            out["incidents"] = self.capture.incidents()
            out["incident_dir"] = self.capture.root
        return out

    def reset(self) -> None:
        """Drop every tracker (tests/bench reps; families persist)."""
        with self._lock:
            self._global = self._new_window()
            self._shards.clear()
            self._scopes.clear()
