"""Stdlib-threaded HTTP sidecar: ``/metrics`` (Prometheus text format),
``/healthz`` (JSON liveness), ``/slo`` (machine-readable SLO /
burn-rate alert state), and ``/profile`` (wall-clock attribution +
sampled-stack summary) without any dependency beyond ``http.server``.

The sidecar is deliberately tiny: scrapes are infrequent (seconds apart)
and the render is a single registry walk, so a ThreadingHTTPServer on a
daemon thread is plenty. It binds loopback by default for the same reason
the bridge does — it is an in-machine surface; exposure is the embedder's
call (pass ``host="0.0.0.0"`` explicitly to take that decision).
"""

from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from .prometheus import CONTENT_TYPE


class MetricsSidecar:
    """Serve one registry over HTTP. ``health_fn`` (optional) returns the
    JSON body for ``/healthz``; a falsy ``"ok"`` key turns the status into
    503 so load balancers can act on it. ``slo_fn`` (optional) returns the
    JSON body for ``/slo`` — by default the process-wide
    :meth:`~hashgraph_tpu.obs.slo.SloEngine.state`; pass a merged-view
    callable (federation) to serve fleet-wide SLO state instead.
    ``render_fn`` (optional) overrides the ``/metrics`` text entirely —
    the federation's merged-scrape hook (one scrape, every host's
    families labelled ``host="..."`` plus fleet totals). ``profile_fn``
    (optional) returns the JSON body for ``/profile`` — by default the
    process's :func:`~hashgraph_tpu.obs.attribution.attribution_report`;
    pass a merged-view callable (federation) to serve the fleet rollup
    instead."""

    def __init__(
        self,
        registry,
        host: str = "127.0.0.1",
        port: int = 0,
        health_fn=None,
        slo_fn=None,
        render_fn=None,
        profile_fn=None,
    ):
        self._registry = registry
        self._host = host
        self._port = port
        self._health_fn = health_fn
        self._render_fn = render_fn
        if slo_fn is None:
            # Late import: obs/__init__ constructs the default SloEngine
            # after importing this module.
            def slo_fn():
                from . import slo_engine

                return slo_engine.state()

        self._slo_fn = slo_fn
        if profile_fn is None:
            # Same late-import discipline as slo_fn.
            def profile_fn():
                from .attribution import attribution_report

                return attribution_report()

        self._profile_fn = profile_fn
        self._server: ThreadingHTTPServer | None = None
        self._thread: threading.Thread | None = None

    @property
    def address(self) -> tuple[str, int]:
        if self._server is None:
            raise RuntimeError("sidecar not started")
        return self._server.server_address[:2]

    def start(self) -> tuple[str, int]:
        registry = self._registry
        health_fn = self._health_fn
        slo_fn = self._slo_fn
        render_fn = self._render_fn
        profile_fn = self._profile_fn

        class Handler(BaseHTTPRequestHandler):
            def do_GET(self):  # noqa: N802 (stdlib naming)
                if self.path.split("?", 1)[0] == "/metrics":
                    if render_fn is not None:
                        try:
                            text = render_fn()
                        except Exception as exc:
                            self._reply(
                                503, "text/plain", repr(exc).encode() + b"\n"
                            )
                            return
                    else:
                        text = registry.render_prometheus()
                    self._reply(200, CONTENT_TYPE, text.encode("utf-8"))
                elif self.path.split("?", 1)[0] == "/slo":
                    try:
                        payload = slo_fn()
                    except Exception as exc:
                        payload = {"error": repr(exc)}
                    self._reply(
                        200,
                        "application/json",
                        json.dumps(payload).encode("utf-8"),
                    )
                elif self.path.split("?", 1)[0] == "/profile":
                    try:
                        payload = profile_fn()
                    except Exception as exc:
                        payload = {"error": repr(exc)}
                    self._reply(
                        200,
                        "application/json",
                        json.dumps(payload).encode("utf-8"),
                    )
                elif self.path.split("?", 1)[0] == "/healthz":
                    payload = {"ok": True}
                    if health_fn is not None:
                        try:
                            payload = health_fn()
                        except Exception as exc:
                            payload = {"ok": False, "error": repr(exc)}
                    status = 200 if payload.get("ok", True) else 503
                    self._reply(
                        status,
                        "application/json",
                        json.dumps(payload).encode("utf-8"),
                    )
                else:
                    self._reply(404, "text/plain", b"not found\n")

            def _reply(self, status: int, ctype: str, body: bytes) -> None:
                self.send_response(status)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, *args):  # scrapes must not spam stderr
                pass

        self._server = ThreadingHTTPServer((self._host, self._port), Handler)
        self._server.daemon_threads = True
        self._thread = threading.Thread(
            target=self._server.serve_forever, daemon=True
        )
        self._thread.start()
        return self.address

    def stop(self) -> None:
        if self._server is not None:
            self._server.shutdown()
            self._server.server_close()
            self._server = None
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None
