"""φ-accrual failure suspicion (Hayashibara et al. 2004, PAPERS.md).

The reference contract leaves liveness to the embedder (reference:
src/lib.rs:15-34); the health watchdog's original answer was a binary
``stale_after`` threshold — one fixed silence bound for every peer, so a
slow-but-honest peer under partial synchrony is convicted exactly as
hard as a dead one. The φ-accrual detector replaces the binary verdict
with a *continuous suspicion level*:

    phi(now) = -log10( P(silence >= now - last_heartbeat) )

under a normal approximation of the peer's own observed inter-arrival
distribution. ``phi = 1`` means "this much silence happens ~10% of the
time for THIS peer", ``phi = 8`` means one in 10^8 — the operator picks
a threshold on *confidence*, not on seconds, and a peer with naturally
jittery arrivals earns a proportionally wider tolerance (the
Chandra–Toueg unreliable-failure-detector framing: suspicion may be
wrong, and must be cheap to revise — phi falls back toward zero the
moment a heartbeat lands).

Time is the embedder's logical clock (the library's no-clock contract):
heartbeats are vote-admission ticks, never wall time, so the detector is
deterministic in the chaos sim and WAL-replay-safe in production.

Numerics: the Gaussian tail is Q(x) = erfc(x/√2)/2; past the double-
precision underflow point the standard asymptotic expansion
Q(x) ≈ exp(-x²/2)/(x·√(2π)) keeps phi finite and monotone instead of
collapsing to -log10(0). Phi is clamped to ``max_phi`` — beyond ~10^-64
confidence there is no operational difference, and a bounded value keeps
gauges and JSON serializations sane.
"""

from __future__ import annotations

import math
from collections import deque

# Below this many observed inter-arrival samples the distribution is not
# trustworthy and phi reports 0.0 (never suspicious): a freshly-seen
# peer must not be convictable off two data points.
DEFAULT_MIN_SAMPLES = 8
DEFAULT_WINDOW = 64
DEFAULT_MAX_PHI = 64.0
# Variance floors: a metronome-regular peer (stddev -> 0) must not make
# one tick of lateness look like certain death. The effective stddev is
# max(observed, min_stddev, rel_stddev * mean).
DEFAULT_MIN_STDDEV = 0.5
DEFAULT_REL_STDDEV = 0.1

_SQRT2 = math.sqrt(2.0)
_LN10 = math.log(10.0)
_LOG_SQRT_2PI = 0.5 * math.log(2.0 * math.pi)


def phi_from_deviation(x: float, max_phi: float = DEFAULT_MAX_PHI) -> float:
    """phi for a silence ``x`` standard deviations past the mean.

    ``x <= 0`` (silence no longer than a typical interval) is never
    suspicious. The direct erfc evaluation is exact until the tail
    underflows double precision (~x > 37); past that the asymptotic
    expansion continues the same monotone curve in log space.
    """
    if x <= 0.0:
        return 0.0
    if x < 8.0:
        q = 0.5 * math.erfc(x / _SQRT2)
        if q > 0.0:
            return min(max_phi, -math.log10(q))
    # Q(x) ~ exp(-x^2/2) / (x * sqrt(2*pi)) for large x: phi in log10.
    ln_q = -(x * x) / 2.0 - math.log(x) - _LOG_SQRT_2PI
    return min(max_phi, -ln_q / _LN10)


class PhiAccrual:
    """Bounded inter-arrival history + phi readout for ONE peer.

    ``heartbeat(now)`` records an arrival on the logical clock (same-tick
    arrivals coalesce: a burst of votes in one batch is one liveness
    observation, not a window full of zero intervals that would poison
    the variance). ``phi(now)`` is the current suspicion level. All
    methods are O(1); the window keeps running sums so phi never walks
    the deque.
    """

    __slots__ = (
        "window",
        "min_samples",
        "min_stddev",
        "rel_stddev",
        "max_phi",
        "last_heartbeat",
        "_intervals",
        "_sum",
        "_sumsq",
    )

    def __init__(
        self,
        *,
        window: int = DEFAULT_WINDOW,
        min_samples: int = DEFAULT_MIN_SAMPLES,
        min_stddev: float = DEFAULT_MIN_STDDEV,
        rel_stddev: float = DEFAULT_REL_STDDEV,
        max_phi: float = DEFAULT_MAX_PHI,
    ):
        if window < 2:
            raise ValueError("window must hold at least 2 intervals")
        if min_samples < 2:
            raise ValueError("min_samples must be at least 2")
        self.window = window
        self.min_samples = min_samples
        self.min_stddev = float(min_stddev)
        self.rel_stddev = float(rel_stddev)
        self.max_phi = float(max_phi)
        self.last_heartbeat: float | None = None
        self._intervals: deque[float] = deque()
        self._sum = 0.0
        self._sumsq = 0.0

    def heartbeat(self, now: float) -> None:
        """One arrival at logical tick ``now``. Out-of-order or same-tick
        arrivals (interval <= 0) refresh nothing — the clock is
        monotone per the embedder contract, and a coalesced batch is one
        observation."""
        last = self.last_heartbeat
        if last is None:
            self.last_heartbeat = now
            return
        interval = now - last
        if interval <= 0.0:
            return
        self.last_heartbeat = now
        self._intervals.append(interval)
        self._sum += interval
        self._sumsq += interval * interval
        if len(self._intervals) > self.window:
            old = self._intervals.popleft()
            self._sum -= old
            self._sumsq -= old * old

    @property
    def sample_count(self) -> int:
        return len(self._intervals)

    def mean(self) -> float:
        n = len(self._intervals)
        return self._sum / n if n else 0.0

    def stddev(self) -> float:
        n = len(self._intervals)
        if n < 2:
            return 0.0
        var = (self._sumsq - self._sum * self._sum / n) / n
        # Running-sum cancellation can drift epsilon-negative.
        return math.sqrt(var) if var > 0.0 else 0.0

    def phi(self, now: float) -> float:
        """Suspicion level at ``now``: 0.0 while the history is too thin
        (min_samples) or the silence is within a typical interval;
        monotone non-decreasing in silence after that."""
        if (
            self.last_heartbeat is None
            or len(self._intervals) < self.min_samples
        ):
            return 0.0
        silence = now - self.last_heartbeat
        if silence <= 0.0:
            return 0.0
        mean = self.mean()
        stddev = max(
            self.stddev(), self.min_stddev, self.rel_stddev * mean
        )
        return phi_from_deviation((silence - mean) / stddev, self.max_phi)

    def reset(self) -> None:
        self.last_heartbeat = None
        self._intervals.clear()
        self._sum = 0.0
        self._sumsq = 0.0
