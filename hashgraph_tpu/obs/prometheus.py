"""Prometheus text-format exposition (version 0.0.4) for a MetricsRegistry.

One render pass walks the registry snapshot-free: counters and gauges are
single samples; histograms expose the standard ``_bucket{le=...}`` /
``_sum`` / ``_count`` triplet with CUMULATIVE bucket counts ending at
``+Inf``. Family names are sanitized to the Prometheus grammar (dots and
dashes become underscores) so tracer-style dotted names render scrapeable.
"""

from __future__ import annotations

import math
import re

CONTENT_TYPE = "text/plain; version=0.0.4; charset=utf-8"

_NAME_OK = re.compile(r"^[a-zA-Z_:][a-zA-Z0-9_:]*$")
_BAD_CHARS = re.compile(r"[^a-zA-Z0-9_:]")


def sanitize(name: str) -> str:
    if _NAME_OK.match(name):
        return name
    cleaned = _BAD_CHARS.sub("_", name)
    if not cleaned or not _NAME_OK.match(cleaned[0]):
        cleaned = "_" + cleaned
    return cleaned


def _fmt(value: float) -> str:
    if value != value:  # NaN
        return "NaN"
    if value == math.inf:
        return "+Inf"
    if value == -math.inf:
        return "-Inf"
    if isinstance(value, int) or float(value).is_integer():
        return str(int(value))
    return repr(float(value))


def _escape_label(value: str) -> str:
    return value.replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")


def render(registry) -> str:
    lines: list[str] = []
    with registry._lock:
        counters = sorted(registry._counters.values(), key=lambda c: c.name)
        gauges = sorted(registry._gauges.values(), key=lambda g: g.name)
        histograms = sorted(registry._histograms.values(), key=lambda h: h.name)
        infos = sorted(registry._infos.values(), key=lambda i: i.name)
    for i in infos:
        name = sanitize(i.name)
        labels = ",".join(
            f'{sanitize(k)}="{_escape_label(v)}"'
            for k, v in sorted(i.labels().items())
        )
        lines.append(f"# TYPE {name} gauge")
        lines.append(f"{name}{{{labels}}} 1")
    # Counters may carry a pre-labelled name (``family{rule="x"}``, the
    # health layer's per-rule alert counters): the base name is sanitized,
    # the label block passes through verbatim, and the TYPE line is
    # emitted once per base — the sort above keeps a family's labelled
    # samples adjacent to the bare one, as the text format requires.
    prev_base = None
    for c in counters:
        base, brace, labels = c.name.partition("{")
        name = sanitize(base)
        if name != prev_base:
            lines.append(f"# TYPE {name} counter")
            prev_base = name
        lines.append(f"{name}{brace}{labels} {_fmt(c.value)}")
    for g in gauges:
        name = sanitize(g.name)
        lines.append(f"# TYPE {name} gauge")
        lines.append(f"{name} {_fmt(g.value)}")
    for h in histograms:
        name = sanitize(h.name)
        # One locked copy per histogram: bucket/sum/count must describe
        # the same moment (the format requires +Inf == count).
        buckets, h_sum, h_count = h.exposition()
        lines.append(f"# TYPE {name} histogram")
        for bound, cumulative in buckets:
            lines.append(f'{name}_bucket{{le="{_fmt(bound)}"}} {cumulative}')
        lines.append(f"{name}_sum {_fmt(h_sum)}")
        lines.append(f"{name}_count {h_count}")
    return "\n".join(lines) + "\n"
