"""Prometheus text-format exposition (version 0.0.4) for a MetricsRegistry.

One render pass walks the registry snapshot-free: counters and gauges are
single samples; histograms expose the standard ``_bucket{le=...}`` /
``_sum`` / ``_count`` triplet with CUMULATIVE bucket counts ending at
``+Inf``. Family names are sanitized to the Prometheus grammar (dots and
dashes become underscores) so tracer-style dotted names render scrapeable.

Counters, gauges and histograms may all carry a pre-labelled name
(``family{host="h1"}``): the base name is sanitized, the label block
passes through verbatim, and the TYPE line is emitted once per base.
Histogram buckets that recorded an exemplar render an OpenMetrics-style
suffix (`` # {trace_id="..."} value ts``) so a scrape links each latency
band to a concrete distributed trace.

:func:`render_state` renders the same text from an exported (or
fleet-merged) registry state dict — the one code path both the live
``/metrics`` surface and the federation's merged scrape go through.
"""

from __future__ import annotations

import math
import re

CONTENT_TYPE = "text/plain; version=0.0.4; charset=utf-8"

_NAME_OK = re.compile(r"^[a-zA-Z_:][a-zA-Z0-9_:]*$")
_BAD_CHARS = re.compile(r"[^a-zA-Z0-9_:]")


def sanitize(name: str) -> str:
    if _NAME_OK.match(name):
        return name
    cleaned = _BAD_CHARS.sub("_", name)
    if not cleaned or not _NAME_OK.match(cleaned[0]):
        cleaned = "_" + cleaned
    return cleaned


def _fmt(value: float) -> str:
    if value != value:  # NaN
        return "NaN"
    if value == math.inf:
        return "+Inf"
    if value == -math.inf:
        return "-Inf"
    if isinstance(value, int) or float(value).is_integer():
        return str(int(value))
    return repr(float(value))


def _escape_label(value: str) -> str:
    return value.replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")


def _split_labels(name: str) -> tuple[str, str]:
    """``family{a="b"}`` -> (sanitized base, inner label text or "")."""
    base, brace, rest = name.partition("{")
    return sanitize(base), rest[:-1] if brace and rest.endswith("}") else ""


def _exemplar_suffix(exemplar) -> str:
    """OpenMetrics-style exemplar: `` # {trace_id="..."} value ts``."""
    value, trace_id, ts = exemplar
    return (
        f' # {{trace_id="{_escape_label(str(trace_id))}"}}'
        f" {_fmt(float(value))} {float(ts):.3f}"
    )


def _histogram_lines(
    lines: list[str],
    name: str,
    labels: str,
    buckets,
    h_sum: float,
    h_count: int,
    exemplars=None,
) -> None:
    """Emit one histogram's sample lines. ``buckets`` is the cumulative
    (bound, count) list ending at +Inf; ``labels`` is the inner label
    text (without braces) prepended to each sample's label set."""
    prefix = f"{labels}," if labels else ""
    suffix = f"{{{labels}}}" if labels else ""
    for idx, (bound, cumulative) in enumerate(buckets):
        line = f'{name}_bucket{{{prefix}le="{_fmt(bound)}"}} {cumulative}'
        if exemplars and idx in exemplars:
            line += _exemplar_suffix(exemplars[idx])
        lines.append(line)
    lines.append(f"{name}_sum{suffix} {_fmt(h_sum)}")
    lines.append(f"{name}_count{suffix} {h_count}")


def _cumulative(bounds, counts) -> list[tuple[float, int]]:
    out = []
    running = 0
    for bound, n in zip(bounds, counts):
        running += n
        out.append((bound, running))
    out.append((math.inf, running + counts[len(bounds)]))
    return out


def render(registry) -> str:
    lines: list[str] = []
    with registry._lock:
        counters = sorted(registry._counters.values(), key=lambda c: c.name)
        gauges = sorted(registry._gauges.values(), key=lambda g: g.name)
        histograms = sorted(registry._histograms.values(), key=lambda h: h.name)
        infos = sorted(registry._infos.values(), key=lambda i: i.name)
    for i in infos:
        name = sanitize(i.name)
        labels = ",".join(
            f'{sanitize(k)}="{_escape_label(v)}"'
            for k, v in sorted(i.labels().items())
        )
        lines.append(f"# TYPE {name} gauge")
        lines.append(f"{name}{{{labels}}} 1")
    # Counters may carry a pre-labelled name (``family{rule="x"}``, the
    # health layer's per-rule alert counters): the base name is sanitized,
    # the label block passes through verbatim, and the TYPE line is
    # emitted once per base — the sort above keeps a family's labelled
    # samples adjacent to the bare one, as the text format requires.
    prev_base = None
    for c in counters:
        base, brace, labels = c.name.partition("{")
        name = sanitize(base)
        if name != prev_base:
            lines.append(f"# TYPE {name} counter")
            prev_base = name
        lines.append(f"{name}{brace}{labels} {_fmt(c.value)}")
    prev_base = None
    for g in gauges:
        base, brace, labels = g.name.partition("{")
        name = sanitize(base)
        if name != prev_base:
            lines.append(f"# TYPE {name} gauge")
            prev_base = name
        lines.append(f"{name}{brace}{labels} {_fmt(g.value)}")
    prev_base = None
    for h in histograms:
        name, labels = _split_labels(h.name)
        # One locked copy per histogram: bucket/sum/count must describe
        # the same moment (the format requires +Inf == count).
        buckets, h_sum, h_count = h.exposition()
        if name != prev_base:
            lines.append(f"# TYPE {name} histogram")
            prev_base = name
        _histogram_lines(
            lines, name, labels, buckets, h_sum, h_count, h.exemplars()
        )
    return "\n".join(lines) + "\n"


def render_state(state: dict) -> str:
    """Render an exported registry state (:meth:`MetricsRegistry
    .export_state`) — or a fleet-merged one from
    :func:`hashgraph_tpu.parallel.rollup.merge_metric_states` — in the
    same text format :func:`render` produces from live instruments."""
    lines: list[str] = []
    prev_base = None
    for iname in sorted(state.get("infos", {})):
        name, pre = _split_labels(iname)
        labels = ",".join(
            f'{sanitize(k)}="{_escape_label(str(v))}"'
            for k, v in sorted(state["infos"][iname].items())
        )
        if pre:
            labels = f"{pre},{labels}" if labels else pre
        if name != prev_base:
            lines.append(f"# TYPE {name} gauge")
            prev_base = name
        lines.append(f"{name}{{{labels}}} 1")
    for kind, type_name in (("counters", "counter"), ("gauges", "gauge")):
        prev_base = None
        for raw in sorted(state.get(kind, {})):
            base, brace, labels = raw.partition("{")
            name = sanitize(base)
            if name != prev_base:
                lines.append(f"# TYPE {name} {type_name}")
                prev_base = name
            lines.append(f"{name}{brace}{labels} {_fmt(state[kind][raw])}")
    prev_base = None
    for raw in sorted(state.get("histograms", {})):
        h = state["histograms"][raw]
        name, labels = _split_labels(raw)
        if name != prev_base:
            lines.append(f"# TYPE {name} histogram")
            prev_base = name
        exemplars = {
            int(i): tuple(v) for i, v in (h.get("exemplars") or {}).items()
        }
        _histogram_lines(
            lines,
            name,
            labels,
            _cumulative(h["bounds"], h["counts"]),
            h["sum"],
            h["count"],
            exemplars,
        )
    return "\n".join(lines) + "\n"


_EXEMPLAR_RE = re.compile(
    r'\s#\s\{trace_id="(?P<trace>[^"]*)"\}\s(?P<value>\S+)\s(?P<ts>\S+)$'
)


def parse_exemplars(text: str) -> dict[str, list[dict]]:
    """Parse the OpenMetrics-style exemplar suffixes out of rendered text:
    {family_bucket_sample_name: [{"le", "trace_id", "value", "ts"}]} —
    the round-trip half the exemplar tests (and incident tooling that
    only holds a scrape) use to recover trace links from plain text."""
    out: dict[str, list[dict]] = {}
    for line in text.splitlines():
        if line.startswith("#") or " # " not in line:
            continue
        m = _EXEMPLAR_RE.search(line)
        if m is None:
            continue
        sample = line[: m.start()].rsplit(" ", 1)[0]
        name, _, labeltext = sample.partition("{")
        le = None
        for part in labeltext.rstrip("}").split(","):
            k, _, v = part.partition("=")
            if k == "le":
                le = v.strip('"')
        out.setdefault(name, []).append(
            {
                "le": le,
                "trace_id": m.group("trace"),
                "value": float(m.group("value")),
                "ts": float(m.group("ts")),
            }
        )
    return out
