"""Distributed causal tracing: trace context, span store, Perfetto export.

PR 2 made a single node legible; this module makes the *fleet* legible.
One proposal's life — ``create_proposal`` on peer A, gossip, votes on
peers B..N, quorum, ``decided`` — becomes one causally-stitched trace:

- :class:`TraceContext` is a compact traceparent-style identity
  (16-byte ``trace_id``, 8-byte ``span_id``, 1-byte flags) minted at
  ``create_proposal`` and carried with the proposal wherever it travels:
  as an optional trailing field on bridge frames
  (:mod:`hashgraph_tpu.bridge.protocol`) and as an unknown-but-skippable
  protobuf field appended to gossiped ``Proposal``/``Vote`` bytes
  (:func:`attach_trace` / :func:`extract_trace` — peers built without
  tracing decode the message identically, proto3 unknown-field rules).
- The active context rides a :mod:`contextvars` variable
  (:func:`use_context` / :func:`current_context`); every span recorded
  through :func:`hashgraph_tpu.obs.observed_span` while a context is
  active lands in the process-wide :data:`trace_store` tagged with it —
  engine, bridge, and WAL spans alike.
- :class:`TraceStore` is bounded (a rolling window: past capacity the
  OLDEST spans are evicted and counted) and exports two ways: JSON-lines
  per peer
  (:meth:`TraceStore.export_jsonl`) and Chrome trace-event JSON
  (:meth:`TraceStore.export_chrome`) that Perfetto / ``chrome://tracing``
  open directly. :func:`merge_traces` stitches N peers' JSONL dumps into
  one causal timeline (one Perfetto "process" per peer, spans of one
  proposal share a ``trace_id`` row).

Correlating with device traces: capture a ``jax.profiler`` trace around
the same window (:func:`hashgraph_tpu.tracing.device_profile`) and open
both files in Perfetto — host spans carry wall-clock microsecond
timestamps, so the engine's ``device_ingest`` spans line up with the XLA
timeline of the same dispatch.

Decision provenance (the "why was this decided" readout built on these
contexts) lives in ``TpuConsensusEngine.explain_decision`` and the
bridge's ``OP_EXPLAIN`` opcode.
"""

from __future__ import annotations

import contextlib
import contextvars
import json
import os
import random
import threading
import time
from collections import deque
from dataclasses import dataclass, field

__all__ = [
    "TRACE_WIRE_BYTES",
    "TraceContext",
    "TraceSpan",
    "TraceStore",
    "attach_trace",
    "chrome_trace",
    "current_context",
    "extract_trace",
    "load_spans_jsonl",
    "merge_traces",
    "trace_store",
    "use_context",
]

# Wire footprint of one context: 16-byte trace_id + 8-byte span_id + flags.
TRACE_WIRE_BYTES = 25

# Protobuf field number used by attach_trace: far above the schema's
# 10..28 range, chosen so the 2-byte tag survives any plausible schema
# growth. Decoders that don't know it skip it (proto3 unknown fields),
# which is the whole backward-compatibility story.
TRACE_FIELD_NUMBER = 2047
_TRACE_TAG = (TRACE_FIELD_NUMBER << 3) | 2  # length-delimited

# Trace/span ids need collision resistance, not crypto strength — and id
# generation sits on the create_proposal path, so it must not consume
# os.urandom per call (the engine's deterministic-pid machinery draws
# from urandom; tracing sharing that stream would perturb it). One
# urandom seed at import, a private PRNG + lock afterwards.
_ID_RNG = random.Random(os.urandom(16))
_ID_LOCK = threading.Lock()


def _random_ids() -> tuple[bytes, bytes]:
    with _ID_LOCK:
        bits = _ID_RNG.getrandbits(192)
    return (bits >> 64).to_bytes(16, "big"), (bits & ((1 << 64) - 1)).to_bytes(
        8, "big"
    )


@dataclass(frozen=True, slots=True)
class TraceContext:
    """W3C-traceparent-shaped identity for one causal trace.

    ``trace_id`` names the whole multi-peer story (one per proposal);
    ``span_id`` names the position in it that new work should parent to.
    Immutable: propagation mints children (:meth:`child`), never mutates.
    """

    trace_id: bytes  # 16 bytes
    span_id: bytes  # 8 bytes
    flags: int = 1  # bit 0: sampled

    @classmethod
    def generate(cls) -> "TraceContext":
        trace_id, span_id = _random_ids()
        return cls(trace_id, span_id)

    def child(self) -> "TraceContext":
        """Same trace, fresh span identity — what a peer mints when it
        continues work it received from the wire."""
        return TraceContext(self.trace_id, _random_ids()[1], self.flags)

    # ── Compact binary form (bridge frames, gossip field) ──────────────

    def to_wire(self) -> bytes:
        return self.trace_id + self.span_id + bytes([self.flags & 0xFF])

    @classmethod
    def from_wire(cls, raw: bytes) -> "TraceContext":
        if len(raw) != TRACE_WIRE_BYTES:
            raise ValueError(
                f"trace context must be {TRACE_WIRE_BYTES} bytes, got {len(raw)}"
            )
        return cls(bytes(raw[:16]), bytes(raw[16:24]), raw[24])

    # ── Text form (logs, HTTP headers, explain output) ─────────────────

    def to_traceparent(self) -> str:
        return f"00-{self.trace_id.hex()}-{self.span_id.hex()}-{self.flags:02x}"

    @classmethod
    def from_traceparent(cls, header: str) -> "TraceContext":
        parts = header.strip().split("-")
        if len(parts) != 4 or parts[0] != "00":
            raise ValueError(f"unsupported traceparent: {header!r}")
        trace_id = bytes.fromhex(parts[1])
        span_id = bytes.fromhex(parts[2])
        if len(trace_id) != 16 or len(span_id) != 8:
            raise ValueError(f"bad traceparent field widths: {header!r}")
        return cls(trace_id, span_id, int(parts[3], 16))


# ── Ambient context propagation ────────────────────────────────────────

_ACTIVE: contextvars.ContextVar[TraceContext | None] = contextvars.ContextVar(
    "hashgraph_trace_context", default=None
)


def current_context() -> TraceContext | None:
    """The trace context active on this thread/task, or None."""
    return _ACTIVE.get()


@contextlib.contextmanager
def use_context(ctx: TraceContext | None):
    """Activate ``ctx`` for the block (None = no-op, so wire-parsing call
    sites can pass whatever they decoded without branching)."""
    if ctx is None:
        yield
        return
    token = _ACTIVE.set(ctx)
    try:
        yield
    finally:
        _ACTIVE.reset(token)


# ── Span records and the bounded store ─────────────────────────────────


@dataclass(slots=True)
class TraceSpan:
    """One completed, context-tagged span (or instant event)."""

    name: str
    trace_id: bytes
    span_id: bytes
    parent_id: bytes | None
    start: float  # wall epoch seconds (cross-peer mergeable)
    duration: float  # seconds; 0.0 for instants
    peer: str
    kind: str = "span"  # "span" | "instant"
    attrs: dict = field(default_factory=dict)

    def as_dict(self) -> dict:
        return {
            "type": "span",
            "name": self.name,
            "trace_id": self.trace_id.hex(),
            "span_id": self.span_id.hex(),
            "parent_id": self.parent_id.hex() if self.parent_id else None,
            "start": self.start,
            "duration": self.duration,
            "peer": self.peer,
            "kind": self.kind,
            **({"attrs": self.attrs} if self.attrs else {}),
        }

    @classmethod
    def from_dict(cls, d: dict) -> "TraceSpan":
        return cls(
            name=d["name"],
            trace_id=bytes.fromhex(d["trace_id"]),
            span_id=bytes.fromhex(d["span_id"]),
            parent_id=bytes.fromhex(d["parent_id"]) if d.get("parent_id") else None,
            start=float(d["start"]),
            duration=float(d.get("duration", 0.0)),
            peer=str(d.get("peer", "?")),
            kind=str(d.get("kind", "span")),
            attrs=dict(d.get("attrs", {})),
        )


class TraceStore:
    """Bounded, thread-safe store of context-tagged spans.

    Always on by default (the per-record cost is one lock + one deque
    append; spans only arrive when a trace context is active or a
    proposal lifecycle stamps its bound context), bounded at ``capacity``
    as a ROLLING window — past the cap the oldest span is evicted per
    new one (flight-recorder semantics: a long-running server always
    holds the most recent spans, so an incident trace requested months
    in is still captured) and evictions are counted in :attr:`dropped`;
    :meth:`export_chrome` embeds that count so a truncated capture never
    reads as a complete one. ``peer`` labels which node recorded a span:
    the store default is the process, engines override with their signer
    identity so one process hosting many bridge peers still attributes
    spans per peer.
    """

    def __init__(self, capacity: int = 65536, peer: str | None = None):
        self.enabled = True
        self.capacity = capacity
        self.peer = peer if peer is not None else f"proc:{os.getpid()}"
        self.dropped = 0
        self._spans: deque[TraceSpan] = deque(maxlen=capacity)
        self._lock = threading.Lock()

    def set_peer(self, peer: str) -> None:
        self.peer = peer

    # ── Recording ──────────────────────────────────────────────────────

    def record(
        self,
        name: str,
        ctx: TraceContext,
        start: float,
        duration: float,
        *,
        parent: bytes | None = None,
        peer: str | None = None,
        kind: str = "span",
        attrs: dict | None = None,
    ) -> None:
        """Store one completed span. ``ctx.span_id`` IS the span's own
        identity (mint a :meth:`TraceContext.child` per span); ``parent``
        is the causal predecessor's span_id, if known."""
        if not self.enabled:
            return
        span = TraceSpan(
            name,
            ctx.trace_id,
            ctx.span_id,
            parent,
            start,
            duration,
            peer if peer is not None else self.peer,
            kind,
            attrs if attrs is not None else {},
        )
        with self._lock:
            if len(self._spans) == self.capacity:
                self.dropped += 1  # maxlen deque evicts the oldest
            self._spans.append(span)

    def instant(
        self,
        name: str,
        ctx: TraceContext,
        ts: float | None = None,
        *,
        peer: str | None = None,
        attrs: dict | None = None,
    ) -> None:
        """Zero-duration marker on ``ctx``'s own span row (vote applied,
        decided, timeout fired)."""
        self.record(
            name,
            ctx,
            ts if ts is not None else time.time(),
            0.0,
            parent=None,
            peer=peer,
            kind="instant",
            attrs=attrs,
        )

    # ── Readout / export ───────────────────────────────────────────────

    def spans(
        self, *, peer: str | None = None, trace_id: bytes | None = None
    ) -> list[TraceSpan]:
        with self._lock:
            out = list(self._spans)
        if peer is not None:
            out = [s for s in out if s.peer == peer]
        if trace_id is not None:
            out = [s for s in out if s.trace_id == trace_id]
        return out

    def clear(self) -> None:
        with self._lock:
            self._spans.clear()
            self.dropped = 0

    def export_jsonl(self, path: str, *, peer: str | None = None) -> int:
        """Atomically write spans (optionally one peer's only) as JSON
        lines — the per-peer dump format :func:`merge_traces` stitches.
        A leading ``store`` metadata line carries the eviction count so a
        truncated capture stays visibly incomplete after merging. Returns
        the number of spans written."""
        spans = self.spans(peer=peer)
        head = json.dumps(
            {"type": "store", "peer": self.peer, "dropped": self.dropped}
        )
        _atomic_write(
            path,
            head + "\n" + "".join(json.dumps(s.as_dict()) + "\n" for s in spans),
        )
        return len(spans)

    def export_chrome(self, path: str, *, peer: str | None = None) -> int:
        """Atomically write a Chrome trace-event JSON file (Perfetto /
        chrome://tracing open it directly). Returns the event count; a
        nonzero store drop count is embedded as ``otherData`` so a capped
        capture is visibly incomplete."""
        spans = self.spans(peer=peer)
        doc = chrome_trace(spans)
        if self.dropped:
            doc["otherData"] = {"dropped_spans": self.dropped}
        _atomic_write(path, json.dumps(doc))
        return len(doc["traceEvents"])


# Process-wide default store (mirrors tracing.tracer / obs.registry).
trace_store = TraceStore()


def _atomic_write(path: str, text: str) -> None:
    # One crash-safe text-export implementation for the whole tracing
    # stack (temp file + umask-widened mode + os.replace).
    from ..tracing import atomic_write_text

    atomic_write_text(path, text)


# ── Chrome trace-event rendering and cross-peer stitching ──────────────


def chrome_trace(spans: list[TraceSpan]) -> dict:
    """Render spans as a Chrome trace-event document: one Perfetto
    "process" per peer (metadata-named), one thread row per trace_id so a
    proposal's causal chain reads left-to-right on a single line, spans as
    complete ("X") events and instants as instant ("i") events. Timestamps
    are wall-clock microseconds, so documents from different peers (or a
    concurrent ``jax.profiler`` device capture) line up on one axis."""
    peer_pids: dict[str, int] = {}
    for s in spans:
        peer_pids.setdefault(s.peer, len(peer_pids) + 1)
    events: list[dict] = []
    for peer, pid in peer_pids.items():
        events.append(
            {
                "ph": "M",
                "name": "process_name",
                "pid": pid,
                "tid": 0,
                "args": {"name": f"peer {peer}"},
            }
        )
    for s in sorted(spans, key=lambda s: (s.start, s.duration)):
        # 48 bits of the trace_id: well inside JSON-safe integer range,
        # collision odds negligible for any store-sized trace population
        # (a 10^6 space would birthday-collide around ~1.2k traces).
        tid = int.from_bytes(s.trace_id[:6], "big")
        args = {
            "trace_id": s.trace_id.hex(),
            "span_id": s.span_id.hex(),
            **({"parent_id": s.parent_id.hex()} if s.parent_id else {}),
            **s.attrs,
        }
        event = {
            "name": s.name,
            "cat": "consensus",
            "pid": peer_pids[s.peer],
            "tid": tid,
            "ts": s.start * 1e6,
            "args": args,
        }
        if s.kind == "instant":
            event["ph"] = "i"
            event["s"] = "t"  # thread-scoped marker
        else:
            event["ph"] = "X"
            event["dur"] = max(s.duration, 0.0) * 1e6
        events.append(event)
    return {"traceEvents": events, "displayTimeUnit": "ms"}


def _load_jsonl(path: str) -> tuple[list[TraceSpan], int]:
    """(spans, dropped-count) from one dump; unknown line types are
    skipped, so the files stay forward-extensible."""
    spans: list[TraceSpan] = []
    dropped = 0
    with open(path) as fh:
        for line in fh:
            line = line.strip()
            if not line:
                continue
            d = json.loads(line)
            if d.get("type") == "span":
                spans.append(TraceSpan.from_dict(d))
            elif d.get("type") == "store":
                dropped += int(d.get("dropped", 0))
    return spans, dropped


def load_spans_jsonl(path: str) -> list[TraceSpan]:
    """Read one peer's :meth:`TraceStore.export_jsonl` dump."""
    return _load_jsonl(path)[0]


def merge_traces(paths: list[str], out_path: str) -> dict:
    """Stitch N peers' JSONL span dumps into ONE Chrome trace-event file.

    Spans are merged, ordered by wall-clock start, and grouped by peer
    (Perfetto process) and trace_id (thread row) — so one proposal's
    spans from every peer land on the same row in causal order. Returns a
    summary: span/peer counts and per-trace span counts (hex trace_id →
    spans), which is also what ``examples/trace_smoke.py`` asserts on.
    """
    spans: list[TraceSpan] = []
    dropped = 0
    for path in paths:
        loaded, peer_dropped = _load_jsonl(path)
        spans.extend(loaded)
        dropped += peer_dropped
    spans.sort(key=lambda s: (s.start, s.duration))
    doc = chrome_trace(spans)
    if dropped:
        # Capped captures stay visibly incomplete in the merged view too.
        doc["otherData"] = {"dropped_spans": dropped}
    _atomic_write(out_path, json.dumps(doc))
    traces: dict[str, int] = {}
    for s in spans:
        key = s.trace_id.hex()
        traces[key] = traces.get(key, 0) + 1
    return {
        "spans": len(spans),
        "dropped": dropped,
        "peers": sorted({s.peer for s in spans}),
        "traces": traces,
        "out": out_path,
    }


# ── Gossip-envelope field: trace context inside protobuf bytes ─────────
# Varint primitives come from the wire codec — one protobuf
# implementation in the package, not two that can drift.


def attach_trace(message: bytes, ctx: TraceContext) -> bytes:
    """Append the trace context to encoded ``Proposal``/``Vote`` bytes as
    protobuf field :data:`TRACE_FIELD_NUMBER`.

    Backward compatible by construction: proto3 decoders (including this
    framework's and the reference's prost codec) skip unknown fields, so
    a peer built without tracing decodes the message identically — and
    signatures are unaffected because they cover the *decoded* signed
    fields re-encoded canonically, never the raw gossip bytes."""
    from ..wire import _encode_varint

    wire = ctx.to_wire()
    out = bytearray(message)
    _encode_varint(out, _TRACE_TAG)
    _encode_varint(out, len(wire))
    out += wire
    return bytes(out)


def extract_trace(message: bytes) -> TraceContext | None:
    """Scan encoded message bytes for an attached trace context (None when
    absent or malformed — gossip input is untrusted, so this never
    raises on junk)."""
    from ..wire import _decode_varint

    pos = 0
    n = len(message)
    try:
        while pos < n:
            key, pos = _decode_varint(message, pos)
            field_number, wire_type = key >> 3, key & 7
            if wire_type == 2:
                length, pos = _decode_varint(message, pos)
                end = pos + length
                if end > n:
                    return None
                if field_number == TRACE_FIELD_NUMBER and length == TRACE_WIRE_BYTES:
                    return TraceContext.from_wire(message[pos:end])
                pos = end
            elif wire_type == 0:
                _, pos = _decode_varint(message, pos)
            elif wire_type == 1:
                pos += 8
            elif wire_type == 5:
                pos += 4
            else:
                return None
    except ValueError:
        return None
    return None
