"""MetricsRegistry: always-on counters, gauges, and log-bucketed histograms.

The tracer (:mod:`hashgraph_tpu.tracing`) answers "what happened in this
run" — it is off by default and accumulates unbounded span lists for
offline analysis. This registry answers the production questions a consensus
service gets asked continuously ("what is p99 decision latency", "how many
WAL segments exist right now") and is therefore ALWAYS on, with bounded
state (a histogram is a fixed bucket array) and per-instrument cost small
enough for hot paths that run once per *batch* (never per vote):

- :class:`Counter` — monotonically increasing int, one lock-protected add;
- :class:`Gauge` — last-set value and/or registered provider callables
  (weakly referenced, so a dead engine's gauges vanish instead of freezing
  at their last value); multiple providers sum, which is what you want when
  several engines/WAL writers coexist in one process;
- :class:`Histogram` — log-spaced bucket bounds chosen at construction
  (``log_buckets``), observation is one bisect + one add under a lock;
  quantiles are estimated by log-linear interpolation inside the bucket.

Families are created lazily on first use and live for the process; name
them like Prometheus families (``wal_fsync_seconds``,
``hashgraph_decision_latency_seconds``) because
:mod:`hashgraph_tpu.obs.prometheus` renders them verbatim.
"""

from __future__ import annotations

import math
import threading
import time
import weakref
from bisect import bisect_left


def quantile_from(
    bounds: tuple[float, ...], counts: list[int], total: int, q: float
) -> float:
    """Estimate the q-quantile (0 < q < 1) of a log-bucketed count vector
    by log-linear interpolation within the containing bucket. 0.0 when
    empty; the last finite bound when the quantile falls in the +Inf
    bucket. Shared by :class:`Histogram` and the SLO engine's windowed
    sketches (:mod:`hashgraph_tpu.obs.slo`), which reuse these buckets."""
    if total == 0:
        return 0.0
    rank = q * total
    running = 0.0
    for i, n in enumerate(counts):
        if n == 0:
            continue
        if running + n >= rank:
            if i >= len(bounds):
                return bounds[-1]
            hi = bounds[i]
            lo = bounds[i - 1] if i > 0 else hi / 2.0
            frac = (rank - running) / n
            # Interpolate in log space — the buckets are log-spaced.
            return math.exp(
                math.log(lo) + frac * (math.log(hi) - math.log(lo))
            )
        running += n
    return bounds[-1]


def log_buckets(lo: float, hi: float, factor: float = 2.0) -> tuple[float, ...]:
    """Geometric bucket upper bounds from ``lo`` until ``hi`` is covered.
    The implicit final bucket is +Inf (everything above the last bound)."""
    if lo <= 0 or hi <= lo or factor <= 1.0:
        raise ValueError("need 0 < lo < hi and factor > 1")
    bounds = [lo]
    while bounds[-1] < hi:
        bounds.append(bounds[-1] * factor)
    return tuple(bounds)


class Counter:
    __slots__ = ("name", "_value", "_lock")

    def __init__(self, name: str):
        self.name = name
        self._value = 0
        self._lock = threading.Lock()

    def inc(self, n: int = 1) -> None:
        with self._lock:
            self._value += n

    @property
    def value(self) -> int:
        return self._value


class Gauge:
    """Point-in-time value. ``set`` stores a number; ``add_provider``
    registers a zero-arg callable sampled at read time (weakly referenced
    through ``owner`` when given, so the provider dies with its component).
    ``value`` is the stored number plus every live provider's sample —
    summation across providers is the aggregate a process-wide scrape
    wants (total live proposals across all engines, total WAL bytes across
    all writers)."""

    __slots__ = ("name", "_value", "_providers", "_lock")

    def __init__(self, name: str):
        self.name = name
        self._value = 0.0
        self._providers: list = []  # (weakref-to-owner-or-None, fn)
        self._lock = threading.Lock()

    def set(self, value: float) -> None:
        self._value = float(value)

    def add_provider(self, fn, owner=None) -> "GaugeHandle":
        ref = weakref.ref(owner) if owner is not None else None
        entry = (ref, fn)
        with self._lock:
            self._providers.append(entry)
        return GaugeHandle(self, entry)

    def _remove(self, entry) -> None:
        with self._lock:
            try:
                self._providers.remove(entry)
            except ValueError:
                pass

    @property
    def value(self) -> float:
        total = self._value
        dead = []
        with self._lock:
            providers = list(self._providers)
        for entry in providers:
            ref, fn = entry
            if ref is not None and ref() is None:
                dead.append(entry)
                continue
            try:
                total += float(fn())
            except Exception:
                # A provider raising (component mid-teardown) must not
                # poison the whole scrape.
                continue
        for entry in dead:
            self._remove(entry)
        return total


class Info:
    """Constant metadata family rendered as a labelled gauge with value 1
    (the Prometheus ``*_build_info`` convention). Label values may be
    strings or zero-arg callables — callables resolve at read time, so a
    label like the JAX runtime backend can be named lazily without the
    metrics layer forcing the runtime up."""

    __slots__ = ("name", "_labels", "_lock")

    def __init__(self, name: str):
        self.name = name
        self._labels: dict[str, object] = {}
        self._lock = threading.Lock()

    def set(self, **labels) -> None:
        with self._lock:
            self._labels.update(labels)

    def labels(self) -> dict[str, str]:
        """Resolved label set (callables invoked; a raising provider
        yields ``"error"`` rather than poisoning the scrape)."""
        with self._lock:
            items = list(self._labels.items())
        out: dict[str, str] = {}
        for key, value in items:
            if callable(value):
                try:
                    value = value()
                except Exception:
                    value = "error"
            out[key] = str(value)
        return out


class GaugeHandle:
    """Unregistration token for one gauge provider (components with an
    explicit close(), e.g. WalWriter, unregister there instead of waiting
    for GC)."""

    __slots__ = ("_gauge", "_entry")

    def __init__(self, gauge: Gauge, entry):
        self._gauge = gauge
        self._entry = entry

    def unregister(self) -> None:
        self._gauge._remove(self._entry)


# Default bounds: wide enough for microsecond fsyncs up to minute-scale
# decision latencies; 2x spacing keeps quantile error under ~41%-of-value
# worst case, plenty for dashboards and regression gates.
DEFAULT_TIME_BUCKETS = log_buckets(1e-6, 128.0)  # seconds
DEFAULT_SIZE_BUCKETS = log_buckets(1.0, 32 * 1024 * 1024)  # counts/bytes


class Histogram:
    """Fixed log-bucketed histogram. ``observe`` is one bisect + two adds
    under the instrument lock; there is no per-observation allocation.

    An observation may carry an OpenMetrics-style *exemplar* — a trace id
    correlating that one sample with its distributed trace. One exemplar
    is kept per bucket (latest wins), so a scrape can always link each
    latency band to a concrete causal trace; storage stays bounded at one
    small tuple per bucket, allocated lazily on the first exemplar."""

    __slots__ = ("name", "bounds", "_counts", "_sum", "_count", "_lock",
                 "_exemplars")

    def __init__(self, name: str, bounds: tuple[float, ...] = DEFAULT_TIME_BUCKETS):
        if not bounds or any(
            b2 <= b1 for b1, b2 in zip(bounds, bounds[1:])
        ):
            raise ValueError("bucket bounds must be strictly increasing")
        self.name = name
        self.bounds = tuple(float(b) for b in bounds)
        self._counts = [0] * (len(bounds) + 1)  # last = +Inf overflow
        self._sum = 0.0
        self._count = 0
        self._lock = threading.Lock()
        self._exemplars: dict[int, tuple[float, str, float]] | None = None

    def observe(self, value: float, exemplar: "str | None" = None) -> None:
        idx = bisect_left(self.bounds, value)
        with self._lock:
            self._counts[idx] += 1
            self._sum += value
            self._count += 1
            if exemplar is not None:
                if self._exemplars is None:
                    self._exemplars = {}
                self._exemplars[idx] = (float(value), exemplar, time.time())

    def exemplars(self) -> dict[int, tuple[float, str, float]]:
        """Per-bucket-index {idx: (value, trace_id, unix_ts)} — the latest
        exemplar observed into each bucket (empty until one is recorded)."""
        with self._lock:
            return dict(self._exemplars) if self._exemplars else {}

    @property
    def count(self) -> int:
        return self._count

    @property
    def sum(self) -> float:
        return self._sum

    def buckets(self) -> list[tuple[float, int]]:
        """CUMULATIVE (upper_bound, count) pairs, +Inf last — the
        Prometheus exposition shape."""
        return self.exposition()[0]

    def exposition(self) -> tuple[list[tuple[float, int]], float, int]:
        """(cumulative buckets, sum, count) from ONE locked copy, so a
        render never shows an +Inf bucket disagreeing with _count (the
        text format requires them equal)."""
        with self._lock:
            counts = list(self._counts)
            s, total = self._sum, self._count
        out = []
        running = 0
        for bound, n in zip(self.bounds, counts):
            running += n
            out.append((bound, running))
        out.append((math.inf, running + counts[-1]))
        return out, s, total

    def quantile(self, q: float) -> float:
        """Estimate the q-quantile (0 < q < 1) by log-linear interpolation
        within the containing bucket. 0.0 when empty; the last finite bound
        when the quantile falls in the +Inf bucket."""
        with self._lock:
            counts = list(self._counts)
            total = self._count
        return self._quantile_from(counts, total, q)

    def _quantile_from(self, counts: list[int], total: int, q: float) -> float:
        return quantile_from(self.bounds, counts, total, q)

    def snapshot(self) -> dict:
        # ONE locked copy: count/sum and every quantile must describe the
        # same moment even while observers keep writing.
        with self._lock:
            counts = list(self._counts)
            total, s = self._count, self._sum
        return {
            "count": total,
            "sum": s,
            "p50": self._quantile_from(counts, total, 0.5),
            "p90": self._quantile_from(counts, total, 0.9),
            "p99": self._quantile_from(counts, total, 0.99),
        }

    def export_state(self) -> dict:
        """Raw mergeable state (NON-cumulative per-bucket counts, bounds,
        sum, count, exemplars keyed by bucket index as strings) — the
        JSON-able shape ``OP_METRICS_PULL`` ships and
        ``parallel.rollup.merge_metric_states`` sums across hosts."""
        with self._lock:
            counts = list(self._counts)
            s, total = self._sum, self._count
            ex = dict(self._exemplars) if self._exemplars else {}
        return {
            "bounds": list(self.bounds),
            "counts": counts,
            "sum": s,
            "count": total,
            "exemplars": {str(i): list(v) for i, v in ex.items()},
        }


class MetricsRegistry:
    """Process-wide instrument directory. Families are created on first
    access and never removed (a scrape must see stable families); all
    accessors are thread-safe and idempotent."""

    def __init__(self):
        self._lock = threading.Lock()
        self._counters: dict[str, Counter] = {}
        self._gauges: dict[str, Gauge] = {}
        self._histograms: dict[str, Histogram] = {}
        self._infos: dict[str, Info] = {}

    # ── Family access ──────────────────────────────────────────────────

    def counter(self, name: str) -> Counter:
        c = self._counters.get(name)
        if c is None:
            with self._lock:
                c = self._counters.setdefault(name, Counter(name))
        return c

    def gauge(self, name: str) -> Gauge:
        g = self._gauges.get(name)
        if g is None:
            with self._lock:
                g = self._gauges.setdefault(name, Gauge(name))
        return g

    def histogram(
        self, name: str, bounds: tuple[float, ...] | None = None
    ) -> Histogram:
        h = self._histograms.get(name)
        if h is None:
            with self._lock:
                h = self._histograms.get(name)
                if h is None:
                    h = Histogram(
                        name, bounds if bounds is not None else DEFAULT_TIME_BUCKETS
                    )
                    self._histograms[name] = h
                    return h
        if bounds is not None and tuple(float(b) for b in bounds) != h.bounds:
            # Silently handing back an instrument with other buckets would
            # put observations in the wrong places with no error anywhere.
            raise ValueError(
                f"histogram {name!r} already exists with different bucket "
                f"bounds; a family's buckets are fixed at first creation"
            )
        return h

    def info(self, name: str) -> Info:
        i = self._infos.get(name)
        if i is None:
            with self._lock:
                i = self._infos.setdefault(name, Info(name))
        return i

    def register_gauge(self, name: str, fn, owner=None) -> GaugeHandle:
        """Attach a sampled-at-read provider to ``name`` (see
        :meth:`Gauge.add_provider`)."""
        return self.gauge(name).add_provider(fn, owner=owner)

    # ── Readout ────────────────────────────────────────────────────────

    def snapshot(self) -> dict:
        """JSON-ready state: counter values, gauge samples, histogram
        count/sum/quantiles. This is what ``bench.py --metrics-out``
        persists next to the throughput numbers."""
        with self._lock:
            counters = list(self._counters.values())
            gauges = list(self._gauges.values())
            histograms = list(self._histograms.values())
            infos = list(self._infos.values())
        return {
            "counters": {c.name: c.value for c in counters},
            "gauges": {g.name: g.value for g in gauges},
            "histograms": {h.name: h.snapshot() for h in histograms},
            "infos": {i.name: i.labels() for i in infos},
        }

    def export_state(self) -> dict:
        """One JSON-able frame of the whole registry: counter values,
        sampled gauge values, raw (mergeable) histogram buckets with
        exemplars, resolved info labels. This is what the bridge's
        ``OP_METRICS_PULL`` ships and what
        ``parallel.rollup.merge_metric_states`` merges into a fleet-wide
        view — unlike :meth:`snapshot`, nothing is pre-aggregated into
        quantiles, so sums across hosts stay exact."""
        with self._lock:
            counters = list(self._counters.values())
            gauges = list(self._gauges.values())
            histograms = list(self._histograms.values())
            infos = list(self._infos.values())
        return {
            "counters": {c.name: c.value for c in counters},
            "gauges": {g.name: g.value for g in gauges},
            "histograms": {h.name: h.export_state() for h in histograms},
            "infos": {i.name: i.labels() for i in infos},
        }

    def render_prometheus(self) -> str:
        from .prometheus import render

        return render(self)

    def reset(self) -> None:
        """Drop every family (tests only — production families should live
        for the process)."""
        with self._lock:
            self._counters.clear()
            self._gauges.clear()
            self._histograms.clear()
            self._infos.clear()
