"""Per-proposal lifecycle timelines: created → first_vote → quorum →
decided / timed_out.

The engine stamps each live session's milestones as they happen (wall
clock for latency math, the caller-supplied logical ``now`` for
correlation with application time), feeding the decision-latency histogram
at the moment a session leaves ACTIVE. Finished timelines move to a
bounded ring so a recently-churned proposal is still explainable after its
slot was recycled.

All mutation happens under the engine lock (the store is engine-private
state, like ``_records``); no internal locking is needed or attempted.
"""

from __future__ import annotations

from collections import deque
from dataclasses import dataclass

OUTCOME_YES = "yes"
OUTCOME_NO = "no"
OUTCOME_FAILED = "failed"


@dataclass(slots=True)
class ProposalTimeline:
    scope: object
    proposal_id: int
    created_at: int  # logical now
    created_wall: float  # time.monotonic()
    first_vote_at: int | None = None
    first_vote_wall: float | None = None
    # Quorum milestone: stamped when the session decides by votes (the
    # tally crossing its required-votes threshold IS the decision moment
    # in this engine); absent for timeout/round-cap outcomes, where no
    # quorum was ever reached.
    quorum_at: int | None = None
    decided_at: int | None = None
    decided_wall: float | None = None
    outcome: str | None = None  # yes / no / failed; None while active
    by_timeout: bool = False
    # True when the outcome arrived pre-decided (snapshot restore,
    # vote-carrying gossip): the wall stamps then measure load time, not a
    # decision this engine made, so no latency is derived or observed.
    pre_decided: bool = False
    # Hex trace id of the session's bound distributed-trace context
    # (stamped by the engine's _bind_trace when tracing is on): the
    # decision-latency observation carries it as an OpenMetrics exemplar,
    # and an SLO breach's incident dump filters trace_store to it.
    trace_hex: str | None = None

    def as_dict(self) -> dict:
        """Readout shape for embedders and the bridge: raw stamps plus the
        derived latencies dashboards actually plot."""
        out = {
            "scope": str(self.scope),
            "proposal_id": self.proposal_id,
            "created_at": self.created_at,
            "first_vote_at": self.first_vote_at,
            "quorum_at": self.quorum_at,
            "decided_at": self.decided_at,
            "outcome": self.outcome,
            "by_timeout": self.by_timeout,
            "pre_decided": self.pre_decided,
        }
        if self.first_vote_wall is not None:
            out["first_vote_latency_s"] = self.first_vote_wall - self.created_wall
        if self.decided_wall is not None and not self.pre_decided:
            out["decision_latency_s"] = self.decided_wall - self.created_wall
        return out


class TimelineStore:
    """Slot-keyed live timelines plus a bounded ring of finished ones.

    ``decision_histogram`` receives created→decided wall seconds once per
    session, exactly when the session leaves ACTIVE (vote quorum, round-cap
    failure, or timeout)."""

    def __init__(self, decision_histogram, completed_capacity: int = 1024):
        self._hist = decision_histogram
        # Optional SLO hook: called as slo_sink(timeline, latency_s) for
        # every latency this store observes (same gating as the histogram
        # — never for pre_decided/replay/unowned sessions). The engine
        # points this at the process SLO engine; keeping it a plain
        # callable keeps this module free of policy and lets the ~7
        # engine decided() call sites stay untouched.
        self.slo_sink = None
        self._live: dict[int, ProposalTimeline] = {}
        self._done: deque[ProposalTimeline] = deque()
        self._done_capacity = completed_capacity
        # (scope, proposal_id) -> most recent finished timeline: keeps
        # bridge/explain lookups O(1) under churn instead of scanning the
        # ring. Overwritten on pid reuse (most recent wins, matching the
        # old reverse scan); an entry dies when ITS timeline ages out of
        # the ring.
        self._done_index: dict[tuple, ProposalTimeline] = {}
        # WAL recovery replays pre-crash traffic through the live ingest
        # paths; with this flag set every decision is stamped pre_decided
        # (outcome recorded, no latency derived or observed) — replay
        # speed is not decision latency.
        self.replay_mode = False

    def _retire(self, tl: ProposalTimeline) -> None:
        """Move a finished timeline into the bounded ring + (scope, pid)
        index, evicting (and de-indexing) the oldest past capacity."""
        self._done.append(tl)
        self._done_index[(tl.scope, tl.proposal_id)] = tl
        while len(self._done) > self._done_capacity:
            old = self._done.popleft()
            key = (old.scope, old.proposal_id)
            if self._done_index.get(key) is old:
                del self._done_index[key]

    def created(self, slot: int, scope, proposal_id: int, now: int, wall: float) -> None:
        # A recycled slot whose previous tenant was never forgotten (should
        # not happen — delete/evict forget) still must not leak: retire it.
        prev = self._live.get(slot)
        if prev is not None:
            self._retire(prev)
        self._live[slot] = ProposalTimeline(scope, proposal_id, now, wall)

    def voted(self, slot: int, now: int, wall: float) -> None:
        tl = self._live.get(slot)
        if tl is not None and tl.first_vote_wall is None:
            tl.first_vote_at = now
            tl.first_vote_wall = wall

    def decided(
        self,
        slot: int,
        outcome: str,
        now: int,
        wall: float,
        by_timeout: bool = False,
        observe: bool = True,
        pre_decided: bool = False,
    ) -> None:
        """``pre_decided=True`` stamps the outcome without feeding the
        latency histogram and marks the timeline so the readout omits the
        derived latency too — for sessions that arrived already decided
        (snapshot restore, vote-carrying gossip), where the latency would
        be this engine's load time, not a decision time.
        ``observe=False`` suppresses only the histogram observation (used
        by multi-host engines for sessions another process owns, so a
        fleet-wide metrics sum counts each decision once)."""
        tl = self._live.get(slot)
        if tl is None or tl.outcome is not None:
            return  # untracked or already finalized (re-emits are idempotent)
        if self.replay_mode:
            pre_decided = True
        tl.decided_at = now
        tl.decided_wall = wall
        tl.outcome = outcome
        tl.by_timeout = by_timeout
        if not by_timeout and not pre_decided and outcome != OUTCOME_FAILED:
            tl.quorum_at = now  # vote quorum IS the decision moment
        if pre_decided:
            tl.pre_decided = True
        elif observe:
            latency = wall - tl.created_wall
            self._hist.observe(latency, exemplar=tl.trace_hex)
            if self.slo_sink is not None:
                self.slo_sink(tl, latency)

    def forget(self, slot: int) -> None:
        tl = self._live.pop(slot, None)
        if tl is not None:
            self._retire(tl)

    def get(self, slot: int) -> ProposalTimeline | None:
        return self._live.get(slot)

    def find(self, scope, proposal_id: int) -> ProposalTimeline | None:
        """Most recent finished timeline for (scope, proposal_id) — the
        fallback when the session's slot is already recycled. O(1) via the
        retire-time index (bridge-side lookups stay flat under churn)."""
        return self._done_index.get((scope, proposal_id))

    def live_count(self) -> int:
        return len(self._live)
