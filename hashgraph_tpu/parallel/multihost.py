"""Multi-host pool: the slot axis sharded across the devices of many
processes, with process-local data feeding.

Execution model (the scaling-book recipe applied to consensus):

- **Slot ownership follows device ownership.** The global pool's slot axis
  shards over the full mesh; each process owns the contiguous slot ranges
  of its addressable devices (`local_slot_range`).
- **Control plane is replicated.** Allocation, release, snapshot loads, and
  timeout sweeps must be invoked with identical arguments on every process
  (standard jax SPMD: same program, same global shapes). Host bookkeeping
  stays consistent because these ops are deterministic.
- **Data plane is process-local.** Each process ingests only votes for its
  own slots (the embedder's shard-aware relay forwards votes to the owning
  host — consensus state itself never crosses DCN). The routed batch is
  materialized per process via ``jax.make_array_from_process_local_data``:
  nobody ever holds the global batch, and readbacks pull only addressable
  shards. Per-dispatch grid shapes are agreed with one tiny allgather so
  every process compiles the same program.
- **Events are emitted by the owning process only** (ingest statuses and
  timeout transitions are returned for local slots), so a fleet of engine
  front-ends never double-publishes — asserted end-to-end by the 2-process
  ``TpuConsensusEngine``-on-``MultiHostPool`` test
  (tests/test_multihost.py::test_two_process_engine_on_multihost_pool),
  which drives the full engine surface: replicated control plane,
  local-only scalar + columnar ingest with agreed dispatch cadence,
  misrouted-vote rejection, collective timeouts and sweeps.
- **Signatures verify where votes arrive** (host CPU, native runtime), so
  adding hosts scales verification linearly with the fleet, independent of
  the TPU topology.

The 2-process CPU integration test (tests/test_multihost.py) spawns real
``jax.distributed`` processes and drives allocation, cross-process ingest,
psum stats, and the timeout sweep end-to-end.
"""

from __future__ import annotations

import numpy as np

import jax
from jax.experimental import multihost_utils

from .mesh import PROPOSAL_AXIS, consensus_mesh
from .sharded import ShardedPool

__all__ = [
    "initialize_distributed",
    "distributed_consensus_mesh",
    "local_slot_range",
    "agree_trace_context",
    "collectives_available",
    "is_collectives_gap",
    "COLLECTIVES_GAP_SIGNATURE",
    "MultiHostPool",
]


# The exact backend-gap signature raised by jaxlib CPU backends that
# implement no multi-process collectives (sharded computations across
# jax.distributed processes fail at dispatch with this message). It is
# BOTH the runtime capability probe's discriminator (see
# collectives_available) and the only failure the two subprocess
# integration tests in tests/test_multihost.py may skip on — anything
# else still fails them.
COLLECTIVES_GAP_SIGNATURE = (
    "Multiprocess computations aren't implemented on the CPU backend"
)


def is_collectives_gap(exc: "BaseException | str") -> bool:
    """Whether an exception (or its message) is the known CPU-backend
    multi-process collectives gap — the one condition under which the
    fleet demotes cross-host tallies from psum to fabric frames."""
    return COLLECTIVES_GAP_SIGNATURE in str(exc)


_collectives_probe: "bool | None" = None


def collectives_available(refresh: bool = False) -> bool:
    """Runtime capability probe: can this process run cross-process
    collectives?

    Single-process (no ``jax.distributed`` fleet): trivially True — every
    collective is an in-process reduction, which all backends implement.
    Multi-process: run ONE tiny allgather and catch the CPU-backend gap
    signature (:data:`COLLECTIVES_GAP_SIGNATURE`). This is the runtime
    analogue of what used to be a test-only skip-guard: the federation
    tally path consults it to pick real psum collectives where the
    backend supports them and the gossip fabric's ``OP_FLEET_TALLY``
    frames where it doesn't. Any OTHER failure re-raises — a real bug
    must not silently demote the tally path.

    Memoized (a backend cannot gain the capability mid-process);
    ``refresh=True`` re-probes."""
    global _collectives_probe
    if _collectives_probe is not None and not refresh:
        return _collectives_probe
    if jax.process_count() <= 1:
        _collectives_probe = True
        return True
    try:
        multihost_utils.process_allgather(np.ones(1, np.int32))
    except Exception as exc:
        if is_collectives_gap(exc):
            _collectives_probe = False
            return False
        raise
    _collectives_probe = True
    return True


def initialize_distributed(
    coordinator_address: str | None = None,
    num_processes: int | None = None,
    process_id: int | None = None,
) -> None:
    """Bring up jax.distributed for a multi-host deployment.

    On TPU pods the arguments auto-detect from the environment; pass them
    explicitly elsewhere. Call once per process before any jax computation.
    """
    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes,
        process_id=process_id,
    )


def distributed_consensus_mesh(axis_name: str = PROPOSAL_AXIS):
    """The 1-D consensus mesh spanning every device of every process."""
    return consensus_mesh(axis_name=axis_name)


def agree_trace_context(ctx=None):
    """Fleet-wide distributed-trace agreement: every process adopts
    process 0's trace context so the replicated control plane's spans
    (allocation, timeout sweeps) stitch into ONE causal trace instead of
    N disjoint ones.

    Collective — call with identical cadence on every process (like the
    pool's control-plane ops), typically right after minting a root
    context on process 0::

        ctx = agree_trace_context(TraceContext.generate())
        with use_context(ctx):
            engine.sweep_timeouts(now)   # spans share one trace_id fleet-wide

    ``ctx`` defaults to this process's ambient context
    (:func:`~hashgraph_tpu.obs.trace.current_context`); processes other
    than 0 may pass anything (or nothing) — process 0's value wins.
    Returns the agreed context, or None when process 0 had none.
    """
    from ..obs.trace import TRACE_WIRE_BYTES, TraceContext, current_context

    local = ctx if ctx is not None else current_context()
    wire = np.frombuffer(
        local.to_wire() if local is not None else bytes(TRACE_WIRE_BYTES),
        np.uint8,
    )
    gathered = np.asarray(multihost_utils.process_allgather(wire)).reshape(
        -1, TRACE_WIRE_BYTES
    )
    agreed = gathered[0].tobytes()
    if not any(agreed):
        return None
    return TraceContext.from_wire(agreed)


def local_slot_range(
    capacity_per_device: int, mesh=None
) -> tuple[int, int]:
    """The global slot interval owned by this process: [start, stop).

    With slots laid out contiguously per device in mesh order, a process
    owns the union of its addressable devices' ranges (contiguous on
    standard TPU topologies where local devices are consecutive in the
    mesh).
    """
    mesh = mesh if mesh is not None else distributed_consensus_mesh()
    start, stop = _local_device_span(mesh)
    return (start * capacity_per_device, stop * capacity_per_device)


def _local_device_span(mesh) -> tuple[int, int]:
    """[start, stop) positions of this process's devices in mesh order."""
    devices = list(mesh.devices.flat)
    local = [
        i for i, d in enumerate(devices) if d.process_index == jax.process_index()
    ]
    if not local:
        return (0, 0)
    start, stop = min(local), max(local) + 1
    if local != list(range(start, stop)):
        raise RuntimeError(
            "this process's devices are not contiguous in the mesh; "
            "reorder the mesh so slot ranges stay process-local"
        )
    return (start, stop)


class MultiHostPool(ShardedPool):
    """ShardedPool across the devices of many ``jax.distributed`` processes.

    Contract (module docstring has the full model):
    - control-plane calls (``allocate_batch``, ``release``, ``load_rows``,
      ``timeout``) are collective with IDENTICAL arguments on every process;
    - ``ingest_async``/``complete_all`` are collective in *cadence* (every
      process dispatches the same number of batches, empty ones included)
      but each process passes only votes for its own slots
      (``local_slot_range``); statuses/transitions come back for local
      votes/slots only, so each process emits events for what it owns;
    - per-dispatch grid shapes are agreed via one small allgather.
    """

    def __init__(self, capacity_per_device, voter_capacity, mesh=None):
        mesh = mesh if mesh is not None else distributed_consensus_mesh()
        # Span first: _init_device_arrays (called from the base ctor) needs
        # it to materialize process-local sections.
        self._dev_lo, self._dev_hi = _local_device_span(mesh)
        super().__init__(capacity_per_device, voter_capacity, mesh)

    def local_slots(self) -> tuple[int, int]:
        """The global slot interval [start, stop) this process owns."""
        return (
            self._dev_lo * self.local_capacity,
            self._dev_hi * self.local_capacity,
        )

    # ── Process-local materialization ─────────────────────────────────

    def _init_device_arrays(self) -> None:
        """Initial pool arrays built from process-local sections (a plain
        device_put cannot target other hosts' devices)."""
        from ..ops.decide import STATE_FREE

        p, v = self.capacity, self.voter_capacity
        self._state = self._put_batch(np.full(p, STATE_FREE, np.int32))
        self._yes = self._put_batch(np.zeros(p, np.int32))
        self._tot = self._put_batch(np.zeros(p, np.int32))
        self._vote_mask = self._put_batch(np.zeros((p, v), bool))
        self._vote_val = self._put_batch(np.zeros((p, v), bool))
        self._n = self._put_batch(np.zeros(p, np.int32))
        self._req = self._put_batch(np.zeros(p, np.int32))
        self._cap = self._put_batch(np.zeros(p, np.int32))
        self._gossip = self._put_batch(np.zeros(p, bool))
        self._liveness = self._put_batch(np.zeros(p, bool))

    def _put_batch(self, arr: np.ndarray) -> jax.Array:
        """Build the global [D*B, ...] device array from this process's
        section only — no host ever materializes another host's rows on
        device (`jax.make_array_from_process_local_data`)."""
        from jax.sharding import NamedSharding, PartitionSpec as P

        spec = P(self.axis) if arr.ndim == 1 else P(self.axis, None)
        sharding = NamedSharding(self.mesh, spec)
        rows_per_dev = arr.shape[0] // self.n_devices
        lo = self._dev_lo * rows_per_dev
        hi = self._dev_hi * rows_per_dev
        return jax.make_array_from_process_local_data(
            sharding, np.ascontiguousarray(arr[lo:hi]), arr.shape
        )

    @staticmethod
    def _local_block(garr) -> np.ndarray:
        """Assemble this process's contiguous section of a 1-D-sharded
        global array from its addressable shards (device order)."""
        shards = sorted(
            garr.addressable_shards,
            key=lambda s: s.index[0].start if s.index[0].start is not None else 0,
        )
        return np.concatenate([np.asarray(s.data) for s in shards], axis=0)

    # ── Data plane ─────────────────────────────────────────────────────

    def ingest_async(self, slots, lanes, values, now):
        """Collective dispatch; ``slots`` must all be process-local. Unlike
        the single-host pools an EMPTY batch still dispatches (the other
        processes' batches are part of the same global program) — the
        inherited grouped path dispatches unconditionally, preserving that.
        """
        from ..ops.ingest import group_batch

        slots = np.asarray(slots, np.int64)
        lo, hi = self.local_slots()
        if slots.size and not ((slots >= lo) & (slots < hi)).all():
            raise ValueError(
                f"ingest batch contains non-local slots (this process owns "
                f"[{lo}, {hi})); route votes to the owning host first"
            )
        uniq, row, col, depth = group_batch(slots)
        return self.ingest_async_grouped(
            uniq, row, col, depth, lanes, values, now
        )

    def _dispatch_ingest(self, slot_pack, grid_pack):
        return self._fleet_routed_ingest(
            slot_pack, grid_pack, self._sharded_ingest
        )

    def _dispatch_ingest_fresh(self, slot_pack, grid_pack, laneless=False):
        """Fleet closed-form ingest: same shape-agreement + routing as the
        scan dispatch (the caller — the engine — has already agreed
        fleet-wide that this call takes the fresh path; the laneless flag
        derives from voter_capacity, identical on every process)."""
        return self._fleet_routed_ingest(
            slot_pack,
            grid_pack,
            self._sharded_fresh_ingest_laneless
            if laneless
            else self._sharded_fresh_ingest,
        )

    def _fleet_routed_ingest(self, slot_pack, grid_pack, kernel):
        """Agree padded shapes across processes (every process must compile
        and run the same global program), then reuse the shared routing
        body with the agreed buckets and block-local row positions."""
        from ..engine.pool import _bucket

        s_count, depth = grid_pack.shape
        local_shape = np.array(
            [_bucket(s_count), _bucket(depth, floor=1)], np.int64
        )
        agreed = multihost_utils.process_allgather(local_shape)
        return self._routed_ingest(
            slot_pack,
            grid_pack,
            kernel,
            bucket_s=int(agreed[..., 0].max()),
            bucket_l=int(agreed[..., 1].max()),
            row_offset=self._dev_lo,
        )

    def complete_all(self, pendings):
        """Block on in-flight ingests, pulling only addressable shards
        (one device_get for all of them)."""
        shard_lists = []
        for pending in pendings:
            shards = sorted(
                pending.out.addressable_shards,
                key=lambda s: s.index[0].start
                if s.index[0].start is not None
                else 0,
            )
            shard_lists.append([s.data for s in shards])
        flat = jax.device_get([d for lst in shard_lists for d in lst])
        outs = []
        pos = 0
        for lst in shard_lists:
            outs.append(np.concatenate(flat[pos : pos + len(lst)], axis=0))
            pos += len(lst)
        return [
            self._finish(pending, out) for pending, out in zip(pendings, outs)
        ]

    def complete(self, pending):
        return self.complete_all([pending])[0]

    # ── Control plane ──────────────────────────────────────────────────

    def timeout(self, slots):
        """Collective (identical ``slots`` everywhere); returns only this
        process's slots — the owner emits the events. The host state mirror
        is synced for ALL requested slots (one small allgather), so
        ``state_of``/``state_counts`` — and any engine layered on top — stay
        truthful for non-local slots after a sweep."""
        if not slots:
            return []
        self._check_no_inflight("timeout")
        slot_arr = np.asarray(slots, np.int64)
        slot_grid, _, rows, bucket = self._route(slot_arr, [])
        self._state, row_state = self._sharded_timeout(
            self._state, self._yes, self._tot, self._n, self._req,
            self._liveness, self._put_batch(slot_grid),
        )
        local_block = self._local_block(row_state)
        lo_rows = self._dev_lo * bucket
        hi_rows = self._dev_hi * bucket
        local_states = np.full(len(slots), -1, np.int64)
        out = []
        for i, slot in enumerate(slots):
            r = int(rows[i])
            if lo_rows <= r < hi_rows:
                new_state = int(local_block[r - lo_rows])
                local_states[i] = new_state
                out.append((int(slot), new_state))
        # Every slot is local to exactly one process; max over the gathered
        # per-process vectors (-1 where non-local) recovers each slot's
        # owner-observed state on every process.
        gathered = multihost_utils.process_allgather(local_states)
        global_states = np.asarray(gathered).reshape(-1, len(slots)).max(axis=0)
        self._state_host[slot_arr] = global_states.astype(np.int32)
        return out

    def sync_states(self) -> None:
        """Refresh the host state mirror for non-local slots.

        Ingest transitions are observed owner-locally by design (zero DCN on
        the hot path), so remote slots' mirrored states lag until the next
        collective touch. This collective (identical cadence on every
        process; requires homogeneous per-process device counts) allgathers
        each process's local mirror block so ``state_of``/``state_counts``
        are globally exact at a quiesce/stats point."""
        self._check_no_inflight("sync_states")
        lo, hi = self.local_slots()
        gathered = np.asarray(
            multihost_utils.process_allgather(
                np.concatenate(
                    [np.array([lo], np.int64), self._state_host[lo:hi].astype(np.int64)]
                )
            )
        ).reshape(jax.process_count(), -1)
        for row in gathered:
            start = int(row[0])
            block = row[1:].astype(np.int32)
            self._state_host[start : start + len(block)] = block
