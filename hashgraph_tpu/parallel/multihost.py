"""Multi-host scaffolding: process initialization + the scale-out design.

Single-host multi-device is fully implemented (ShardedPool over a mesh,
validated on virtual 8-device meshes and the driver's multi-chip dry run).
This module holds the multi-host entry point and documents how the design
extends — it is scaffolding in the honest sense: initialization and mesh
construction work on any jax.distributed deployment, while the per-process
data-feeding path below is exercised only single-host in this repo.

Scale-out design (the scaling-book recipe applied to consensus):

- **Slot ownership follows device ownership.** The global pool's slot axis
  shards over the full mesh; each process owns the contiguous slot ranges of
  its addressable devices. The host-side router (`ShardedPool._route`)
  already computes per-device sections — multi-host, each process simply
  materializes only its own sections (`jax.make_array_from_process_local_data`)
  instead of the full batch.
- **Vote traffic is DCN-free by construction.** The embedder's transport
  (gossip) delivers votes to whichever host received them; a thin
  shard-aware relay forwards each vote to the process owning its proposal's
  slot — consensus state itself never crosses DCN. The only collective,
  the psum in `global_state_counts`, rides ICI within a slice and DCN
  across slices, and it is O(#states) per sweep.
- **Signatures verify where votes arrive** (host CPU, native runtime), so
  adding hosts scales verification linearly with the fleet, independent of
  the TPU topology.
"""

from __future__ import annotations

import jax

from .mesh import PROPOSAL_AXIS, consensus_mesh

__all__ = ["initialize_distributed", "distributed_consensus_mesh"]


def initialize_distributed(
    coordinator_address: str | None = None,
    num_processes: int | None = None,
    process_id: int | None = None,
) -> None:
    """Bring up jax.distributed for a multi-host deployment.

    On TPU pods the arguments auto-detect from the environment; pass them
    explicitly elsewhere. Call once per process before any jax computation.
    """
    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes,
        process_id=process_id,
    )


def distributed_consensus_mesh(axis_name: str = PROPOSAL_AXIS):
    """The 1-D consensus mesh spanning every device of every process."""
    return consensus_mesh(axis_name=axis_name)


def local_slot_range(
    capacity_per_device: int, mesh=None
) -> tuple[int, int]:
    """The global slot interval owned by this process: [start, stop).

    With slots laid out contiguously per device in mesh order, a process
    owns the union of its addressable devices' ranges (contiguous on
    standard TPU topologies where local devices are consecutive in the
    mesh).
    """
    mesh = mesh if mesh is not None else distributed_consensus_mesh()
    devices = list(mesh.devices.flat)
    local = [i for i, d in enumerate(devices) if d.process_index == jax.process_index()]
    if not local:
        return (0, 0)
    start, stop = min(local), max(local) + 1
    if local != list(range(start, stop)):
        raise RuntimeError(
            "this process's devices are not contiguous in the mesh; "
            "reorder the mesh so slot ranges stay process-local"
        )
    return (start * capacity_per_device, stop * capacity_per_device)
