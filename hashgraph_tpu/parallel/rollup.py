"""Shared rollups: one summation for every aggregate surface.

``TpuConsensusEngine.occupancy()`` defines the per-engine capacity
snapshot (live/device/spilled counts plus the demoted-tier counters).
Fleet and federation both need the fleet-wide sum, and before this
helper each hand-summed its own key set — a new engine key (like the
tier counters) could silently go missing from one aggregate. Now the
key set lives here once: extend ``OCCUPANCY_SUM_KEYS`` and every
aggregate surface (fleet totals, the federation adapter, bench
rollups) carries the new counter automatically.

The same discipline applies to cross-host METRIC federation:
:func:`merge_metric_states` is the ONE merge for ``OP_METRICS_PULL``
frames — fleet-wide totals plus per-host labelled breakdowns in the
registry's export-state schema, renderable by
``obs.prometheus.render_state`` — used by the federation driver's merged
``/metrics`` view and ``bench.py``'s fleet reports alike. A second
hand-sum anywhere means a new family can silently go missing from one
surface; add behavior here instead.
"""

from __future__ import annotations

# Engine occupancy keys that sum meaningfully across shards/hosts.
# (voter_capacity deliberately excluded: it is a per-pool geometry, not
# an additive capacity.)
OCCUPANCY_SUM_KEYS = (
    "live_sessions",
    "device_slots_used",
    "host_spilled",
    "capacity",
    "tier_sessions",
    "tier_bytes",
    "tier_demotions_total",
    "tier_promotions_total",
    "tier_gc_total",
)


def aggregate_occupancy(entries) -> dict:
    """Sum per-shard ``occupancy()`` entries into one capacity view.

    Shards that are mid-recovery or mid-migration report no counts (their
    entries carry ``recovering``/``migrating`` flags instead); they are
    skipped and surfaced as ``unavailable_shards`` so a rollup that hides
    half the fleet says so.
    """
    out = {key: 0 for key in OCCUPANCY_SUM_KEYS}
    unavailable = 0
    for entry in entries:
        if entry.get("recovering") or entry.get("migrating"):
            unavailable += 1
            continue
        for key in OCCUPANCY_SUM_KEYS:
            out[key] += entry.get(key, 0)
    out["unavailable_shards"] = unavailable
    return out


# ── Cross-host metric federation ───────────────────────────────────────


def _escape_label(value: str) -> str:
    return (
        str(value).replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")
    )


def with_label(name: str, key: str, value: str) -> str:
    """Insert ``key="value"`` into a (possibly pre-labelled) family name:
    ``f{a="b"}`` -> ``f{key="value",a="b"}``; ``f`` -> ``f{key="value"}``."""
    base, brace, rest = name.partition("{")
    label = f'{key}="{_escape_label(value)}"'
    if not brace:
        return f"{base}{{{label}}}"
    return f"{base}{{{label},{rest}"


def _merge_histograms(merged: dict, hist: dict) -> bool:
    """Sum ``hist`` into ``merged`` in place (export_state schema).
    Returns False — leaving ``merged`` untouched — when the bucket bounds
    disagree (two hosts on different builds); the per-host labelled
    series still carry the data, so nothing is lost, only un-summed."""
    if merged["bounds"] != hist["bounds"]:
        return False
    counts = merged["counts"]
    for i, c in enumerate(hist["counts"]):
        counts[i] += c
    merged["sum"] += hist["sum"]
    merged["count"] += hist["count"]
    for idx, ex in (hist.get("exemplars") or {}).items():
        # Keep the largest-valued exemplar per bucket: the outlier is the
        # trace a fleet-wide p99 investigation wants to open first.
        cur = merged["exemplars"].get(idx)
        if cur is None or ex[0] > cur[0]:
            merged["exemplars"][idx] = list(ex)
    return True


def merge_metric_states(frames) -> dict:
    """Merge ``OP_METRICS_PULL`` frames (``{"host": label, "state":
    <MetricsRegistry.export_state()>}``) into ONE registry-state dict:

    - every family appears re-labelled per host (``name{host="h1"}``),
      so a single scrape keeps the per-host breakdown;
    - counters/gauges/histograms additionally appear under their bare
      name as the fleet-wide sum (histograms only when every host agrees
      on bucket bounds);
    - infos stay per-host only — constant metadata does not sum.

    The result renders with ``obs.prometheus.render_state`` — the one
    merge + one renderer every fleet-wide surface (federation sidecar,
    ``bench.py`` fleet reports) goes through.
    """
    out = {"counters": {}, "gauges": {}, "histograms": {}, "infos": {}}
    skip_total: set = set()  # histogram families with mismatched bounds
    for frame in frames:
        host = str(frame.get("host", "unknown"))
        state = frame.get("state") or {}
        for kind in ("counters", "gauges"):
            for name, value in (state.get(kind) or {}).items():
                bucket = out[kind]
                bucket[with_label(name, "host", host)] = value
                bucket[name] = bucket.get(name, 0) + value
        for name, hist in (state.get("histograms") or {}).items():
            out["histograms"][with_label(name, "host", host)] = hist
            if name in skip_total:
                continue
            total = out["histograms"].get(name)
            if total is None:
                out["histograms"][name] = {
                    "bounds": list(hist["bounds"]),
                    "counts": list(hist["counts"]),
                    "sum": hist["sum"],
                    "count": hist["count"],
                    "exemplars": {
                        k: list(v)
                        for k, v in (hist.get("exemplars") or {}).items()
                    },
                }
            elif not _merge_histograms(total, hist):
                del out["histograms"][name]
                skip_total.add(name)
        for name, labels in (state.get("infos") or {}).items():
            out["infos"][with_label(name, "host", host)] = labels
    return out


def merge_slo_states(frames) -> dict:
    """Fleet ``/slo`` view from ``OP_METRICS_PULL`` frames: per-host SLO
    states keyed by host label, plus the fleet rollup a single pager
    needs — every firing alert as ``host/scope``, total windowed decision
    count, the worst per-host fast-window p99, and every incident dump.
    (True merged quantiles would need the raw windows, which stay
    host-local; the worst host's p99 is the conservative fleet answer.)"""
    hosts: dict = {}
    alerts: list = []
    incidents: list = []
    count = 0
    worst_p99 = 0.0
    for frame in frames:
        host = str(frame.get("host", "unknown"))
        slo = frame.get("slo") or {}
        hosts[host] = slo
        for scope in slo.get("alerts_firing", ()):  # noqa: B007
            alerts.append(f"{host}/{scope}")
        for inc in slo.get("incidents", ()):  # noqa: B007
            incidents.append(f"{host}/{inc}")
        overall = slo.get("global") or {}
        count += overall.get("count", 0)
        worst_p99 = max(worst_p99, overall.get("p99", 0.0))
    return {
        "hosts": hosts,
        "alerts_firing": alerts,
        "incidents": incidents,
        "global": {"count": count, "worst_p99": worst_p99},
    }


def merge_profile_states(frames) -> dict:
    """Fleet attribution view from ``OP_PROFILE`` frames (``{"host":
    label, "profile": <obs.attribution.attribution_report()>}``):
    per-host reports keyed by host label, plus the fleet rollup —
    per-stage busy seconds summed across hosts with shares recomputed
    over the fleet-wide denominator, device dispatch/row totals (and
    the fleet-wide amortization factor), and sample/role totals from
    every host's continuous profiler. The same discipline as
    :func:`merge_metric_states`: ONE merge, every fleet surface (the
    federation sidecar's ``/profile``, bench reports) goes through it."""
    from ..obs.attribution import STAGE_KEYS

    hosts: dict = {}
    seconds = {key: 0.0 for key in STAGE_KEYS}
    dispatches = 0.0
    apply_rows = 0.0
    wal_fsyncs = 0
    samples_total = 0
    samples_dropped = 0
    overhead_s = 0.0
    roles: dict = {}
    for frame in frames:
        host = str(frame.get("host", "unknown"))
        profile = frame.get("profile") or {}
        hosts[host] = profile
        for key, stage in (profile.get("stages") or {}).items():
            if key in seconds:
                seconds[key] += float(stage.get("seconds", 0.0))
        device = profile.get("device") or {}
        dispatches += float(device.get("dispatches", 0.0))
        apply_rows += float(device.get("apply_rows", 0.0))
        wal_fsyncs += int((profile.get("wal") or {}).get("fsyncs", 0))
        samples = profile.get("samples") or {}
        samples_total += int(samples.get("total", 0))
        samples_dropped += int(samples.get("dropped", 0))
        overhead_s += float(samples.get("overhead_seconds", 0.0))
        for role, n in (samples.get("roles") or {}).items():
            roles[role] = roles.get(role, 0) + int(n)
    busy = sum(seconds.values())
    return {
        "schema": "hashgraph.attribution.v1",
        "hosts": hosts,
        "busy_seconds": round(busy, 6),
        "stages": {
            key: {
                "seconds": round(seconds[key], 6),
                "share": round(seconds[key] / busy, 4) if busy else 0.0,
            }
            for key in STAGE_KEYS
        },
        "device": {
            "dispatches": dispatches,
            "apply_rows": apply_rows,
            "votes_per_dispatch": (
                round(apply_rows / dispatches, 2) if dispatches else 0.0
            ),
        },
        "wal": {"fsyncs": wal_fsyncs},
        "samples": {
            "total": samples_total,
            "dropped": samples_dropped,
            "overhead_seconds": round(overhead_s, 6),
            "roles": dict(sorted(roles.items())),
        },
    }
