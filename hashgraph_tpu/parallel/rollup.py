"""Shared occupancy rollup: one summation for every aggregate surface.

``TpuConsensusEngine.occupancy()`` defines the per-engine capacity
snapshot (live/device/spilled counts plus the demoted-tier counters).
Fleet and federation both need the fleet-wide sum, and before this
helper each hand-summed its own key set — a new engine key (like the
tier counters) could silently go missing from one aggregate. Now the
key set lives here once: extend ``OCCUPANCY_SUM_KEYS`` and every
aggregate surface (fleet totals, the federation adapter, bench
rollups) carries the new counter automatically.
"""

from __future__ import annotations

# Engine occupancy keys that sum meaningfully across shards/hosts.
# (voter_capacity deliberately excluded: it is a per-pool geometry, not
# an additive capacity.)
OCCUPANCY_SUM_KEYS = (
    "live_sessions",
    "device_slots_used",
    "host_spilled",
    "capacity",
    "tier_sessions",
    "tier_bytes",
    "tier_demotions_total",
    "tier_promotions_total",
    "tier_gc_total",
)


def aggregate_occupancy(entries) -> dict:
    """Sum per-shard ``occupancy()`` entries into one capacity view.

    Shards that are mid-recovery or mid-migration report no counts (their
    entries carry ``recovering``/``migrating`` flags instead); they are
    skipped and surfaced as ``unavailable_shards`` so a rollup that hides
    half the fleet says so.
    """
    out = {key: 0 for key in OCCUPANCY_SUM_KEYS}
    unavailable = 0
    for entry in entries:
        if entry.get("recovering") or entry.get("migrating"):
            unavailable += 1
            continue
        for key in OCCUPANCY_SUM_KEYS:
            out[key] += entry.get(key, 0)
    out["unavailable_shards"] = unavailable
    return out
