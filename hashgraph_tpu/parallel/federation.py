"""Federated multi-host fleet: (host, shard) placement, cross-host vote
routing over the gossip fabric, and live shard migration under traffic.

The scaling math for the north-star workload is ``hosts x shards x
per-shard throughput``, and before this module the repo only multiplied
the last two: :class:`~hashgraph_tpu.parallel.fleet.ConsensusFleet` is
single-process, and the gossip fabric moves votes between processes but
replicates rather than partitions. Federation composes the two proven
layers into one topology (the operational template of gossip-based BFT
deployments — Buchman et al., "The latest gossip on BFT consensus"):

- **Placement** is two-level rendezvous hashing with the fleet's
  pin-until-delete elasticity (:class:`FederationPlacement`): HRW over
  the host set picks the owning *host*, HRW over that host's homed
  shards picks the *shard*. Adding or removing a host remaps only that
  host's scopes; scopes with live state are pinned to their shard and
  never split — a pinned scope follows its shard even when the shard is
  re-homed onto another host.
- **A host** runs a :class:`FleetGroup`: the local
  :class:`ConsensusFleet` (one engine per device) fronted by ONE
  bridge peer whose engine is a :class:`FleetEngineAdapter` — the
  single-engine surface the wire expects, routed per scope to the
  owning shard. Coalesced ``OP_VOTE_BATCH`` frames land on the host's
  zero-copy columnar wire ingest, split per shard
  (:func:`hashgraph_tpu.bridge.columnar.pack_rows`) and dispatched
  concurrently.
- **Routing**: votes for a remotely-owned scope ride the gossip fabric
  (``GossipTransport`` + ``VoteCoalescer`` + ``OP_VOTE_BATCH``) to the
  owning host instead of erroring SESSION_NOT_FOUND. Fleet-wide
  ``state_counts`` aggregates via real cross-host collectives where the
  backend supports them (:func:`tally_path` consults
  :func:`~hashgraph_tpu.parallel.multihost.collectives_available`, the
  runtime promotion of what used to be a test skip-guard) and via the
  fabric's ``OP_FLEET_TALLY`` frames where it doesn't.
- **Live shard migration** (:func:`migrate_shard`): freeze the shard
  (routes raise the typed
  :class:`~hashgraph_tpu.parallel.fleet.ShardMigratingError` with a
  retry-after hint — votes back off, they are never dropped), snapshot
  at an exact WAL watermark (``DurableEngine.capture_consistent``
  behind the PR-8 sync wire format), re-home onto the adopting host via
  ``catch_up_shard`` (snapshot install + WAL tailing — Ongaro's
  snapshot-install/log-tail recipe), assert source/destination
  ``state_fingerprint`` equality, flip the placement atomically, replay
  the drained tail, retire the source. All under sustained traffic.
"""

from __future__ import annotations

import threading
import time
import weakref

import numpy as np

from ..errors import StatusCode
from ..obs import (
    FEDERATION_HOSTS,
    FEDERATION_MIGRATION_SECONDS,
    FEDERATION_MIGRATIONS_TOTAL,
    FEDERATION_REMOTE_ROUTED_VOTES_TOTAL,
    flight_recorder,
    slo_engine,
)
from ..obs import registry as default_registry
from .rollup import (
    aggregate_occupancy,
    merge_metric_states,
    merge_slo_states,
)
from .fleet import (
    ConsensusFleet,
    ShardMigratingError,
    _check_shard_ids,
    rendezvous_owner,
)

__all__ = [
    "FederationPlacement",
    "FleetEngineAdapter",
    "FleetGroup",
    "FederationDriver",
    "MigrationError",
    "migrate_shard",
    "tally_path",
    "ShardMigratingError",
]

_OK = int(StatusCode.OK)
_ALREADY = int(StatusCode.ALREADY_REACHED)
_NOT_FOUND = int(StatusCode.SESSION_NOT_FOUND)


class MigrationError(RuntimeError):
    """A shard migration failed integrity checks (the placement was NOT
    flipped; the source still owns the shard)."""


def _retry_hint(exc) -> float:
    """The retry-after seconds a STATUS_SHARD_MIGRATING response
    carries (the message tail); 1.0 when unparseable."""
    try:
        return float(str(exc).rsplit(":", 1)[-1].strip())
    except (ValueError, IndexError):
        return 1.0


def tally_path() -> str:
    """Which mechanism cross-host tallies ride on this process:
    ``"psum"`` when a multi-process jax fleet exists AND the backend
    implements cross-process collectives
    (:func:`~hashgraph_tpu.parallel.multihost.collectives_available`,
    the runtime capability probe), else ``"fabric"`` — summing each
    host's ``OP_FLEET_TALLY`` frame over the gossip fabric."""
    import jax

    from .multihost import collectives_available

    if jax.process_count() > 1 and collectives_available():
        return "psum"
    return "fabric"


# ── Two-level placement ────────────────────────────────────────────────


class FederationPlacement:
    """Deterministic (host, shard) assignment over an elastic host set.

    Level 1: rendezvous over the host ids picks the owning host.
    Level 2: rendezvous over the shards *currently homed* on that host
    picks the shard. Both levels use the fleet's keyed-blake2b HRW
    (:func:`~hashgraph_tpu.parallel.fleet.rendezvous_owner`) — stable
    across processes and restarts, and each level remaps minimally under
    membership changes (adding/removing a host perturbs only scopes
    whose level-1 argmax involves it).

    Scopes with live state are **pinned to their shard**
    (pin-until-delete, the fleet's discipline): a pin survives host
    membership changes AND shard re-homing, so a migration moves the
    pinned scopes with their shard and a membership change never splits
    a live scope. Every participant (hosts, drivers) constructing this
    placement from the same membership history computes identical
    assignments — the cross-process contract the restart-stability test
    pins down.

    Thread-safe; :meth:`migrate` flips a shard's home under the same
    lock every :meth:`owner` read takes, so there is NO window in which
    two hosts both own a scope (tested by the concurrent-flip test).
    """

    _CACHE_CAP = 65_536  # the ScopePlacement memo-bound precedent

    def __init__(self, hosts: "dict[str, list[str]]"):
        if not hosts:
            raise ValueError("placement needs at least one host")
        self._hosts: dict[str, list[str]] = {}
        self._home: dict[str, str] = {}
        for host_id, shard_ids in hosts.items():
            shard_ids = list(dict.fromkeys(shard_ids))
            _check_shard_ids([host_id])
            _check_shard_ids(shard_ids)
            for sid in shard_ids:
                if sid in self._home:
                    raise ValueError(f"shard {sid!r} homed on two hosts")
                self._home[sid] = host_id
            self._hosts[host_id] = shard_ids
        self._pins: dict = {}  # scope -> shard_id while the scope lives
        self._migrating: dict[str, float] = {}  # shard -> retry_after
        self._cache: dict = {}
        self._lock = threading.Lock()

    @classmethod
    def uniform(
        cls, host_ids: "list[str]", shards_per_host: int
    ) -> "FederationPlacement":
        """The standard topology: ``shards_per_host`` shards per host,
        named ``<host>:<k>`` — globally unique, and every participant
        that knows (host ids, shard count) reconstructs it identically."""
        return cls(
            {
                host: [f"{host}:{k}" for k in range(shards_per_host)]
                for host in host_ids
            }
        )

    # ── readouts ───────────────────────────────────────────────────────

    @property
    def host_ids(self) -> "list[str]":
        with self._lock:
            return list(self._hosts)

    @property
    def shard_ids(self) -> "list[str]":
        with self._lock:
            return list(self._home)

    def shards_of(self, host_id: str) -> "list[str]":
        with self._lock:
            return list(self._hosts[host_id])

    def host_of(self, shard_id: str) -> str:
        with self._lock:
            return self._home[shard_id]

    def owner(self, scope) -> "tuple[str, str]":
        """The (host, shard) owning ``scope`` — the pin when the scope
        is live, the two-level rendezvous otherwise."""
        with self._lock:
            pinned = self._pins.get(scope)
            if pinned is not None:
                return self._home[pinned], pinned
            owner = self._cache.get(scope)
            if owner is None:
                if len(self._cache) >= self._CACHE_CAP:
                    self._cache.clear()
                # Hosts that currently home no shards (everything
                # migrated away) own nothing — skip them at level 1.
                candidates = [h for h, s in self._hosts.items() if s]
                host = rendezvous_owner(scope, candidates)
                shard = rendezvous_owner(scope, self._hosts[host])
                owner = self._cache[scope] = (host, shard)
            return owner

    def migrating(self, shard_id: str) -> bool:
        with self._lock:
            return shard_id in self._migrating

    def retry_after(self, shard_id: str) -> float:
        with self._lock:
            return self._migrating.get(shard_id, 0.0)

    # ── pins (live scopes never split) ─────────────────────────────────

    def pin(self, scope, shard_id: str) -> None:
        """Pin a live scope to its shard. Taken at the scope's first
        mutating touch — the owning fleet takes the matching local pin on
        the same touch, and both sides computed the same HRW shard, so
        the pins coincide by construction."""
        with self._lock:
            if shard_id not in self._home:
                raise ValueError(f"unknown shard {shard_id!r}")
            self._pins.setdefault(scope, shard_id)

    def release(self, scope) -> None:
        """Release a deleted scope's pin (and memo entry)."""
        with self._lock:
            self._pins.pop(scope, None)
            self._cache.pop(scope, None)

    def pinned(self, scope):
        with self._lock:
            return self._pins.get(scope)

    def pins_of_shard(self, shard_id: str) -> list:
        with self._lock:
            return [s for s, sid in self._pins.items() if sid == shard_id]

    # ── elastic host membership ────────────────────────────────────────

    def add_host(self, host_id: str, shard_ids: "list[str]") -> None:
        """Scale-out: only scopes whose level-1 argmax moves to the new
        host remap (the rendezvous invariant); pinned scopes never move."""
        shard_ids = list(dict.fromkeys(shard_ids))
        _check_shard_ids([host_id])
        _check_shard_ids(shard_ids)
        with self._lock:
            if host_id in self._hosts:
                raise ValueError(f"host {host_id!r} already placed")
            for sid in shard_ids:
                if sid in self._home:
                    raise ValueError(f"shard {sid!r} homed on two hosts")
            self._hosts[host_id] = shard_ids
            for sid in shard_ids:
                self._home[sid] = host_id
            self._cache.clear()

    def remove_host(self, host_id: str, force: bool = False) -> None:
        """Scale-in: only the removed host's scopes remap. Refuses while
        the host still homes shards with pinned (live) scopes unless
        ``force`` — migrate them first (:func:`migrate_shard`)."""
        with self._lock:
            if host_id not in self._hosts:
                raise ValueError(f"host {host_id!r} not placed")
            if len(self._hosts) == 1:
                raise ValueError("cannot remove the last host")
            homed = self._hosts[host_id]
            pinned = [
                s for s, sid in self._pins.items() if sid in set(homed)
            ]
            if pinned and not force:
                raise ValueError(
                    f"host {host_id!r} still owns live scopes "
                    f"{pinned[:4]}...; migrate its shards or pass force=True"
                )
            for sid in homed:
                del self._home[sid]
                self._migrating.pop(sid, None)
            for scope in pinned:
                del self._pins[scope]
            del self._hosts[host_id]
            self._cache.clear()

    # ── migration flip ─────────────────────────────────────────────────

    def begin_migration(self, shard_id: str, retry_after: float = 1.0) -> None:
        """Mark a shard mid-migration. Routing layers consult
        :meth:`migrating` and raise/buffer instead of dispatching; the
        placement itself stays a pure lookup."""
        with self._lock:
            if shard_id not in self._home:
                raise ValueError(f"unknown shard {shard_id!r}")
            self._migrating[shard_id] = retry_after

    def complete_migration(self, shard_id: str, to_host: str) -> None:
        """Atomically re-home ``shard_id`` onto ``to_host`` and lift the
        freeze — one lock, so no reader ever observes dual ownership or
        an ownerless shard."""
        with self._lock:
            if to_host not in self._hosts:
                raise ValueError(f"unknown host {to_host!r}")
            from_host = self._home[shard_id]
            if from_host != to_host:
                self._hosts[from_host].remove(shard_id)
                self._hosts[to_host].append(shard_id)
                self._home[shard_id] = to_host
            self._migrating.pop(shard_id, None)
            self._cache.clear()

    def abort_migration(self, shard_id: str) -> None:
        with self._lock:
            self._migrating.pop(shard_id, None)


# ── The single-engine facade over a fleet ──────────────────────────────


class _MergedReceiver:
    """Round-robin try_recv over the per-shard event receivers — the
    bridge's OP_POLL_EVENTS drains one merged stream."""

    def __init__(self, receivers):
        self._receivers = receivers

    def try_recv(self):
        for receiver in self._receivers:
            item = receiver.try_recv()
            if item is not None:
                return item
        return None


class _MergedEventBus:
    def __init__(self, fleet: ConsensusFleet):
        self._fleet = fleet

    def subscribe(self) -> _MergedReceiver:
        # Snapshot of the shard set at subscribe time (the bridge
        # subscribes once, at peer registration); shards added later
        # surface events through their own engines' buses.
        return _MergedReceiver(
            [
                shard.engine.event_bus().subscribe()
                for shard in self._fleet._shards.values()
                if shard.engine is not None
            ]
        )


class FleetEngineAdapter:
    """One host's :class:`ConsensusFleet` presented as the single-engine
    surface the bridge wire expects: every opcode the federation uses —
    proposal lifecycle, coalesced ``OP_VOTE_BATCH`` (object path AND the
    zero-copy columnar path), ``OP_DELIVER_PROPOSALS``,
    ``OP_STATE_FINGERPRINT``, ``OP_FLEET_TALLY``, health — routes per
    scope to the owning shard through the fleet's batching router.

    Not named ``engine`` anywhere and carrying its own
    ``save_to_storage``: ``sync.state_fingerprint`` digests the UNION of
    the shards' canonical session/config frames (order-insensitive, so
    the per-shard interleaving is irrelevant).

    The adapter deliberately has no ``wire_verify_begin``: the bridge's
    reader-thread prepass is per-engine, and a fleet spans several — the
    per-shard crypto runs inside each shard's own
    ``ingest_wire_columnar`` on the concurrent dispatch instead."""

    def __init__(self, fleet: ConsensusFleet):
        self._fleet = fleet
        self._bus = _MergedEventBus(fleet)

    @property
    def fleet(self) -> ConsensusFleet:
        return self._fleet

    # Identity / infrastructure the bridge touches at registration.

    def signer(self):
        """The host's wire identity: shard 0's signer (proposal_owner on
        bridge-created proposals — any stable per-host identity serves)."""
        first = next(iter(self._fleet._shards.values()))
        return first.engine.signer()

    def event_bus(self):
        return self._bus

    def trace_context_of(self, scope, proposal_id):
        return self._fleet._engine_for(scope).trace_context_of(
            scope, proposal_id
        )

    # Control plane — scope-routed passthroughs (the fleet pins live
    # scopes to their shard on the first mutating touch).

    def create_proposal(self, scope, request, now, config=None):
        return self._fleet.create_proposal(scope, request, now, config)

    def create_proposals(self, scope, requests, now, config=None):
        return self._fleet.create_proposals(scope, requests, now, config)

    def cast_vote(self, scope, proposal_id, choice, now):
        return self._fleet.cast_vote(scope, proposal_id, choice, now)

    def process_incoming_proposal(self, scope, proposal, now, config=None):
        return self._fleet.process_incoming_proposal(
            scope, proposal, now, config
        )

    def process_incoming_vote(self, scope, vote, now) -> None:
        self._fleet.process_incoming_vote(scope, vote, now)

    def handle_consensus_timeout(self, scope, proposal_id, now):
        return self._fleet._engine_for(scope).handle_consensus_timeout(
            scope, proposal_id, now
        )

    def get_consensus_result(self, scope, proposal_id):
        return self._fleet.get_consensus_result(scope, proposal_id)

    def get_proposal(self, scope, proposal_id):
        return self._fleet.get_proposal(scope, proposal_id)

    def get_scope_stats(self, scope):
        return self._fleet.get_scope_stats(scope)

    def get_scope_config(self, scope):
        return self._fleet.get_scope_config(scope)

    def set_scope_config(self, scope, config) -> None:
        self._fleet.set_scope_config(scope, config)

    def delete_scope(self, scope) -> None:
        self._fleet.delete_scope(scope)

    def explain_decision(self, scope, proposal_id) -> dict:
        return self._fleet.explain_decision(scope, proposal_id)

    def voter_gid(self, scope, owner: bytes) -> int:
        return self._fleet.voter_gid(scope, owner)

    def sweep_timeouts(self, now):
        return self._fleet.sweep_timeouts(now)

    # Data plane.

    def ingest_votes(self, items, now, pre_validated: bool = False):
        return self._fleet.ingest_votes(items, now, pre_validated=pre_validated)

    def ingest_votes_pipelined(self, batches, now, pre_validated: bool = False):
        return self._fleet.ingest_votes_pipelined(
            batches, now, pre_validated=pre_validated
        )

    def deliver_proposals(self, items, now, configs=None):
        return self._fleet.deliver_proposals(items, now, configs=configs)

    def deliver_proposal(self, scope, proposal, now, config=None):
        return self._fleet.deliver_proposal(scope, proposal, now, config)

    def ingest_wire_columnar(
        self,
        scopes,
        scope_idx,
        cols,
        data,
        offsets,
        now,
        max_depth: int = 8,
        stage_seconds: "dict | None" = None,
        _prepass=None,
        _buf=None,
    ) -> np.ndarray:
        """The host's zero-copy wire ingest, split per owning shard:
        rows group by the fleet's placement, pack into contiguous
        per-shard column triples (``columnar.pack_rows`` — the same
        vectorized gather the bridge uses per peer), and land
        concurrently on each shard engine's own ``ingest_wire_columnar``
        (full validation, per-shard crypto batch). ``_prepass`` is
        ignored by design — see the class docstring."""
        from ..bridge import columnar as WC

        fleet = self._fleet
        scope_idx = np.asarray(scope_idx, np.int64)
        offsets = np.asarray(offsets, np.int64)
        batch = len(cols)
        statuses = np.full(batch, _NOT_FOUND, np.int32)
        groups, _ = fleet._group_scopes(scopes, unavailable_ok=False)
        stage_parts: "list[dict]" = []

        def dispatch(sid: str, members: list):
            ordinals = np.fromiter(
                (k for k, _ in members), np.int64, len(members)
            )
            local_of = np.full(len(scopes), -1, np.int64)
            local_of[ordinals] = np.arange(len(members))
            rows = np.nonzero(local_of[scope_idx] >= 0)[0]
            if rows.size == 0:
                return rows, np.empty(0, np.int32)
            if len(groups) == 1 and rows.size == batch:
                sub_data, sub_offsets, sub_cols = data, offsets, cols
            else:
                sub_data, sub_offsets, sub_cols = WC.pack_rows(
                    data, offsets, cols, rows
                )
            engine = fleet._live_engine(sid)
            fleet._note_routed(sid, int(rows.size))
            stage: dict = {}
            stage_parts.append(stage)
            sub = engine.ingest_wire_columnar(
                [scope for _, scope in members],
                local_of[scope_idx[rows]],
                sub_cols,
                sub_data,
                sub_offsets,
                now,
                max_depth=max_depth,
                stage_seconds=stage,
            )
            return rows, sub

        futures = [
            fleet._executor.submit(dispatch, sid, members)
            for sid, members in groups.items()
        ]
        for future in futures:
            rows, sub = future.result()
            statuses[rows] = sub
        if stage_seconds is not None:
            for stage in stage_parts:
                for key, value in stage.items():
                    stage_seconds[key] = stage_seconds.get(key, 0.0) + value
        return statuses

    # Tallies / fingerprints / health.

    def fleet_state_counts(self) -> "dict[int, int]":
        return self._fleet.fleet_state_counts()

    def save_to_storage(self, storage) -> int:
        """Union of the shards' canonical dumps (unwrapping durable
        wrappers, whose own save appends a checkpoint mark) — what
        ``sync.state_fingerprint`` digests for the whole host."""
        total = 0
        for shard in self._fleet._shards.values():
            engine = shard.engine
            if engine is None:
                continue
            target = getattr(engine, "engine", engine)
            total += target.save_to_storage(storage)
        return total

    def session_keys(self) -> list:
        return [
            key
            for shard in self._fleet._shards.values()
            if shard.engine is not None
            for key in shard.engine.session_keys()
        ]

    def occupancy(self) -> dict:
        """Aggregate capacity view (the per-shard breakdown lives on
        ``fleet.occupancy()``) — the shared rollup, so engine-level keys
        (tier counters included) can never drift from the fleet's."""
        return aggregate_occupancy(self._fleet.occupancy().values())

    def health_report(self, now=None) -> dict:
        return self._fleet.health_report(now)


# ── One host's stack ───────────────────────────────────────────────────


class _RemoteHost:
    __slots__ = ("host_id", "host", "port", "peer_id")

    def __init__(self, host_id: str, host: str, port: int, peer_id: int):
        self.host_id = host_id
        self.host = host
        self.port = port
        self.peer_id = peer_id


class FleetGroup:
    """One federation host: the local :class:`ConsensusFleet` fronted by
    a bridge server (ONE peer = the :class:`FleetEngineAdapter`), plus a
    gossip-fabric client side that forwards votes for remotely-owned
    scopes to their host.

    ``wal_root`` is REQUIRED: every shard must be durable so the host
    can serve a migrating shard's consistent snapshot + WAL tail to the
    adopting host (the PR-8 sync path ``export_shard`` exposes).

    The group (and any driver) derives its view of the topology from a
    :class:`FederationPlacement`; all participants must construct it
    from the same membership history (``FederationPlacement.uniform``
    from the same host list is the standard way)."""

    def __init__(
        self,
        host_id: str,
        signer_factory,
        *,
        placement: FederationPlacement,
        wal_root: str,
        n_shards: "int | None" = None,
        capacity_per_shard: int = 1024,
        voter_capacity: int = 64,
        max_sessions_per_scope: "int | None" = None,
        fsync_policy: str = "batch",
        port: int = 0,
        wire_columnar: "bool | None" = None,
        request_timeout: float = 30.0,
    ):
        import os

        self.host_id = host_id
        self.placement = placement
        shard_ids = placement.shards_of(host_id)
        if n_shards is not None and n_shards != len(shard_ids):
            raise ValueError(
                f"placement homes {len(shard_ids)} shards on {host_id!r}, "
                f"n_shards says {n_shards}"
            )
        self.fleet = ConsensusFleet(
            signer_factory,
            n_shards=len(shard_ids),
            shard_ids=shard_ids,
            capacity_per_shard=capacity_per_shard,
            voter_capacity=voter_capacity,
            max_sessions_per_scope=max_sessions_per_scope,
            wal_root=os.path.join(wal_root, host_id),
            fsync_policy=fsync_policy,
        )
        self.adapter = FleetEngineAdapter(self.fleet)
        self._request_timeout = request_timeout
        self._port = port
        self._wire_columnar = wire_columnar
        self._engine_slot: list = []
        self.server = None
        self.peer_id = 0
        self._transport = None
        self._remote: "dict[str, _RemoteHost]" = {}
        self._merged_sidecar = None
        self._lock = threading.Lock()
        ref_self = weakref.ref(self)
        default_registry.register_gauge(
            FEDERATION_HOSTS,
            lambda: (
                (len(g._remote) + 1) if (g := ref_self()) is not None else 0
            ),
            owner=self,
        )
        self._m_remote_routed = default_registry.counter(
            FEDERATION_REMOTE_ROUTED_VOTES_TOTAL
        )

    # ── lifecycle ──────────────────────────────────────────────────────

    def start(self) -> "tuple[str, int]":
        """Bind the bridge server, register the fleet adapter as its one
        peer, and return the listening address."""
        from ..bridge.server import BridgeServer
        from ..gossip.transport import GossipTransport
        from ..signing.stub import StubConsensusSigner

        self.server = BridgeServer(
            port=self._port,
            engine_factory=self._pop_engine,
            signer_factory=StubConsensusSigner,
            wire_columnar=self._wire_columnar,
            host_label=self.host_id,
        )
        self.server.start()
        self.peer_id = self._register(self.adapter)
        self._transport = GossipTransport()
        return self.server.address

    def _pop_engine(self, signer):
        # engine_factory seam: ADD_PEER on this server always follows a
        # _register() push (the federation server mints no default
        # engines — its peers are the fleet adapter and, transiently,
        # migrating shard engines).
        if not self._engine_slot:
            raise ValueError(
                "federation server peers are registered via FleetGroup"
            )
        return self._engine_slot.pop()

    def _register(self, engine) -> int:
        import hashlib as _hashlib

        from ..bridge import protocol as P

        key = _hashlib.sha256(
            f"federation:{self.host_id}:{len(self._engine_slot)}".encode()
            + str(time.monotonic_ns()).encode()
        ).digest()
        self._engine_slot.append(engine)
        status, out = self.server.dispatch_frame(
            P.OP_ADD_PEER, P.u8(32) + key
        )
        if status != P.STATUS_OK:
            raise RuntimeError(f"peer registration failed: status {status}")
        return P.Cursor(out).u32()

    @property
    def address(self) -> "tuple[str, int]":
        return self.server.address

    def connect(self, host_id: str, host: str, port: int, peer_id: int) -> None:
        """Join a remote host to the fabric (blocking HELLO): votes for
        scopes it owns will ride coalesced OP_VOTE_BATCH frames there."""
        self._transport.connect(host_id, host, port)
        with self._lock:
            self._remote[host_id] = _RemoteHost(host_id, host, port, peer_id)

    def close(self) -> None:
        if self._merged_sidecar is not None:
            self._merged_sidecar.stop()
            self._merged_sidecar = None
        if self._transport is not None:
            self._transport.close()
        if self.server is not None:
            self.server.stop()
        self.fleet.close()

    def __enter__(self) -> "FleetGroup":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    # ── routing (the federation data plane) ────────────────────────────

    def _route(self, scope) -> "tuple[str, str]":
        host, shard = self.placement.owner(scope)
        if self.placement.migrating(shard):
            raise ShardMigratingError(
                shard, self.placement.retry_after(shard)
            )
        return host, shard

    def owner_of(self, scope) -> "tuple[str, str]":
        return self.placement.owner(scope)

    def ingest_votes(self, items, now, pre_validated: bool = False) -> np.ndarray:
        """The federated :meth:`ConsensusFleet.ingest_votes`: locally
        owned rows land on the local fleet router; remotely owned rows
        ride ONE coalesced ``OP_VOTE_BATCH`` frame per owning host over
        the fabric (instead of erroring SESSION_NOT_FOUND), statuses
        stitched back in input order. Rows for a migrating shard raise
        :class:`ShardMigratingError` — back off ``retry_after`` and
        retry; nothing is dropped."""
        from ..bridge import protocol as P
        from ..bridge.client import BridgeError, parse_status_list
        from ..gossip.transport import ChannelBusy

        statuses = np.full(len(items), _NOT_FOUND, np.int32)
        local: list[int] = []
        remote: "dict[str, list[int]]" = {}
        for k, (scope, _vote) in enumerate(items):
            host, _shard = self._route(scope)
            if host == self.host_id:
                local.append(k)
            else:
                remote.setdefault(host, []).append(k)
        if local:
            sub = self.fleet.ingest_votes([items[k] for k in local], now)
            statuses[local] = sub
        for host, idxs in remote.items():
            info = self._remote.get(host)
            if info is None:
                raise KeyError(
                    f"scope owned by host {host!r} but it is not connected"
                )
            # One frame per (host, call): groups keyed by scope in input
            # order (order within a scope preserved — the chain rule).
            # Grouping REORDERS interleaved scopes' rows, so the frame's
            # flattened row order is recorded and statuses map back
            # through it — never positionally onto ``idxs``.
            grouped: "dict[str, list[tuple[int, bytes]]]" = {}
            for k in idxs:
                scope, vote = items[k]
                grouped.setdefault(scope, []).append((k, vote.encode()))
            frame_rows = [
                k for pairs in grouped.values() for k, _ in pairs
            ]
            payload = P.encode_vote_batch(
                now,
                [
                    (info.peer_id, scope, [blob for _, blob in pairs])
                    for scope, pairs in grouped.items()
                ],
            )
            deadline = time.monotonic() + self._request_timeout
            while True:
                try:
                    future = self._transport.request(
                        host, P.OP_VOTE_BATCH, payload
                    )
                    break
                except ChannelBusy:
                    if time.monotonic() >= deadline:
                        raise
                    time.sleep(0.002)
            try:
                sub = parse_status_list(
                    future.result(self._request_timeout)
                )
            except BridgeError as exc:
                if exc.status == P.STATUS_SHARD_MIGRATING:
                    # The remote froze the shard between our placement
                    # read and the dispatch: surface the same typed
                    # error a local freeze raises.
                    _h, shard = self.placement.owner(items[idxs[0]][0])
                    raise ShardMigratingError(
                        shard, _retry_hint(exc)
                    ) from exc
                raise
            statuses[frame_rows] = np.asarray(sub, np.int32)
            self._m_remote_routed.inc(len(idxs))
        return statuses

    def deliver_proposals(self, items, now) -> "list[int]":
        """Federated anti-entropy delivery: local items through the
        fleet's watermark path, remote items as one
        ``OP_DELIVER_PROPOSALS`` frame per owning host."""
        from ..bridge import protocol as P
        from ..bridge.client import parse_status_list

        statuses = [_NOT_FOUND] * len(items)
        local: list[int] = []
        remote: "dict[str, list[int]]" = {}
        for k, (scope, _proposal) in enumerate(items):
            host, _shard = self._route(scope)
            (local if host == self.host_id else
             remote.setdefault(host, [])).append(k)
        if local:
            sub = self.fleet.deliver_proposals(
                [items[k] for k in local], now
            )
            for k, code in zip(local, sub):
                statuses[k] = int(code)
        for host, idxs in remote.items():
            info = self._remote[host]
            payload = P.encode_deliver_proposals(
                info.peer_id,
                [(items[k][0], items[k][1].encode()) for k in idxs],
                now,
            )
            future = self._transport.request(
                host, P.OP_DELIVER_PROPOSALS, payload
            )
            sub = parse_status_list(future.result(self._request_timeout))
            for k, code in zip(idxs, sub):
                statuses[k] = int(code)
        return statuses

    # ── fleet-wide tallies across hosts ────────────────────────────────

    def federated_state_counts(self) -> "dict[int, int]":
        """The global slot-state histogram across every host: the local
        fleet's ONE-psum tally plus each remote host's, aggregated by
        the path :func:`tally_path` picked — real cross-host collectives
        where the backend implements them, ``OP_FLEET_TALLY`` fabric
        frames where it doesn't (this box)."""
        local = self.fleet.fleet_state_counts()
        if tally_path() == "psum":
            return self._psum_counts(local)
        total = dict(local)
        for host, counts in self._fabric_tallies().items():
            for code, count in counts.items():
                total[code] = total.get(code, 0) + count
        return total

    def _fabric_tallies(self) -> "dict[str, dict[int, int]]":
        from ..bridge import protocol as P

        out: "dict[str, dict[int, int]]" = {}
        with self._lock:
            remote = list(self._remote.values())
        futures = [
            (
                info.host_id,
                self._transport.request(
                    info.host_id, P.OP_FLEET_TALLY, P.u32(info.peer_id)
                ),
            )
            for info in remote
        ]
        for host_id, future in futures:
            out[host_id] = P.parse_fleet_tally(
                future.result(self._request_timeout)
            )
        return out

    @staticmethod
    def _psum_counts(local: "dict[int, int]") -> "dict[int, int]":
        """The collective arm: every jax.distributed process contributes
        its local count vector, one allgather+sum yields the global
        histogram (collective cadence — call on every process)."""
        from jax.experimental import multihost_utils

        codes = sorted(local)
        vec = np.asarray([local[c] for c in codes], np.int64)
        gathered = np.asarray(
            multihost_utils.process_allgather(vec)
        ).reshape(-1, len(codes))
        summed = gathered.sum(axis=0)
        return {code: int(n) for code, n in zip(codes, summed)}

    def state_fingerprint(self) -> str:
        from ..sync.snapshot import state_fingerprint

        return state_fingerprint(self.adapter)

    # ── metric federation (OP_METRICS_PULL frames + merged views) ──────

    def metrics_frame(self) -> dict:
        """This host's ``OP_METRICS_PULL`` frame, locally (no wire hop):
        the raw registry state + SLO state under the host's label — the
        same dict a remote puller would receive."""
        return {
            "host": self.host_id,
            "state": default_registry.export_state(),
            "slo": slo_engine.state(),
        }

    def federated_metric_frames(self) -> "list[dict]":
        """The local frame plus every connected host's, pulled over the
        fabric as single ``OP_METRICS_PULL`` frames."""
        import json

        from ..bridge import protocol as P

        with self._lock:
            remote = list(self._remote.values())
        futures = [
            self._transport.request(info.host_id, P.OP_METRICS_PULL, b"")
            for info in remote
        ]
        frames = [self.metrics_frame()]
        for future in futures:
            frames.append(
                json.loads(
                    future.result(self._request_timeout)
                    .blob()
                    .decode("utf-8")
                )
            )
        return frames

    def federated_metrics(self) -> dict:
        """Fleet-wide registry state: per-host labelled families + bare
        fleet totals, through the ONE shared merge
        (:func:`~hashgraph_tpu.parallel.rollup.merge_metric_states`)."""
        return merge_metric_states(self.federated_metric_frames())

    def federated_metrics_text(self) -> str:
        """The merged frames rendered in Prometheus text format — the
        body a fleet-wide ``/metrics`` scrape serves."""
        from ..obs.prometheus import render_state

        return render_state(self.federated_metrics())

    def federated_slo(self) -> dict:
        """Fleet-wide ``/slo`` view: per-host SLO states plus firing
        alerts/incidents qualified ``host/...``."""
        return merge_slo_states(self.federated_metric_frames())

    def serve_merged_metrics(
        self, host: str = "127.0.0.1", port: int = 0
    ) -> "tuple[str, int]":
        """Start a sidecar whose ``/metrics`` and ``/slo`` serve the
        MERGED fleet view (pulling every connected host per scrape).
        Returns the bound address; stopped by :meth:`close`."""
        from ..obs.http import MetricsSidecar

        self._merged_sidecar = MetricsSidecar(
            default_registry,
            host=host,
            port=port,
            render_fn=self.federated_metrics_text,
            slo_fn=self.federated_slo,
        )
        return self._merged_sidecar.start()

    # ── migration (source + destination halves) ────────────────────────

    def export_shard(
        self, shard_id: str, retry_after: float = 1.0
    ) -> "tuple[int, str]":
        """Source half, step 1: freeze the shard (routes raise the typed
        migrating error carrying ``retry_after`` — on the wire too, as
        the STATUS_SHARD_MIGRATING hint; the engine stays live) and
        register its durable engine as a bridge sync peer. Returns
        ``(peer_id, fingerprint)`` — the adopting host catches up from
        that peer and the orchestrator asserts fingerprint equality
        before flipping."""
        from ..sync.snapshot import state_fingerprint

        self.fleet.begin_migration(shard_id, retry_after)
        engine = self.fleet.shard(shard_id).engine
        if not hasattr(engine, "capture_consistent"):
            self.fleet.end_migration(shard_id)
            raise MigrationError(
                f"shard {shard_id!r} is not durable; migration ships a "
                "WAL-watermarked snapshot"
            )
        peer_id = self._register(engine)
        return peer_id, state_fingerprint(engine)

    def adopt_shard(
        self, shard_id: str, host: str, port: int, source_peer: int
    ) -> dict:
        """Destination half: add the shard to the local fleet, catch it
        up from the source peer (snapshot at the frozen WAL watermark +
        tail, one batched verify), and pin the migrated scopes to the
        adopted shard (they keep living where their sessions are,
        regardless of the local rendezvous). Returns the adoption report
        incl. the installed state's fingerprint."""
        from ..sync.snapshot import state_fingerprint

        self.fleet.add_shard(shard_id)
        try:
            self.fleet.catch_up_shard(shard_id, host, port, source_peer)
        except BaseException:
            self.fleet.remove_shard(shard_id, force=True)
            raise
        engine = self.fleet.shard(shard_id).engine
        keys = engine.session_keys()
        scopes = {scope for scope, _pid in keys}
        for scope in scopes:
            self.fleet.pin_scope(scope, shard_id)
        report = self.fleet.shard(shard_id).catchup_report
        return {
            "sessions": len(keys),
            "scopes": len(scopes),
            "fingerprint": state_fingerprint(engine),
            "votes_verified": (
                report.votes_verified if report is not None else 0
            ),
            "seconds": report.seconds if report is not None else 0.0,
        }

    def retire_shard(self, shard_id: str, peer_id: int) -> None:
        """Source half, final step (after the placement flipped): drop
        the temporary sync peer and remove the shard — its engine closes
        and its WAL flock releases; the state lives on the adopter. A
        host drained of its LAST shard keeps serving the wire (the
        federated placement routes nothing new to it)."""
        self.server.remove_peer(peer_id)
        self.fleet.remove_shard(shard_id, force=True, allow_empty=True)


# ── In-process migration orchestration ─────────────────────────────────


def migrate_shard(
    placement: FederationPlacement,
    groups: "dict[str, FleetGroup]",
    shard_id: str,
    to_host: str,
    *,
    retry_after: float = 1.0,
) -> dict:
    """Re-home ``shard_id`` onto ``to_host`` under traffic: freeze (typed
    retry-after for concurrent routes), snapshot+tail adopt, assert
    source/destination ``state_fingerprint`` equality, atomic placement
    flip, retire the source. Raises :class:`MigrationError` (placement
    unflipped, source unfrozen) on any integrity failure.

    This is the in-process orchestration (both groups in this process —
    tests, smoke topologies). The multi-host bench drives the same
    halves over the host runners' control channels with a
    :class:`FederationDriver` buffering the in-window tail."""
    from_host = placement.host_of(shard_id)
    if from_host == to_host:
        raise ValueError(f"shard {shard_id!r} already on {to_host!r}")
    src, dst = groups[from_host], groups[to_host]
    t0 = time.perf_counter()
    flight_recorder.record(
        "federation.migrate_start",
        shard=shard_id, source=from_host, target=to_host,
    )
    placement.begin_migration(shard_id, retry_after)
    peer_id = None
    try:
        peer_id, src_fingerprint = src.export_shard(shard_id, retry_after)
        host, port = src.address
        report = dst.adopt_shard(shard_id, host, port, peer_id)
        if report["fingerprint"] != src_fingerprint:
            dst.fleet.remove_shard(shard_id, force=True)
            raise MigrationError(
                f"shard {shard_id!r} fingerprint mismatch after adopt: "
                f"{src_fingerprint[:16]} != {report['fingerprint'][:16]}"
            )
        placement.complete_migration(shard_id, to_host)
    except BaseException:
        placement.abort_migration(shard_id)
        if peer_id is not None:
            try:
                src.server.remove_peer(peer_id)
            except ValueError:
                pass
        src.fleet.end_migration(shard_id)
        raise
    src.retire_shard(shard_id, peer_id)
    seconds = time.perf_counter() - t0
    default_registry.counter(FEDERATION_MIGRATIONS_TOTAL).inc()
    default_registry.histogram(FEDERATION_MIGRATION_SECONDS).observe(seconds)
    flight_recorder.record(
        "federation.migrate_finish",
        shard=shard_id, source=from_host, target=to_host,
        sessions=report["sessions"], seconds=round(seconds, 4),
    )
    return {
        "shard": shard_id,
        "from": from_host,
        "to": to_host,
        "seconds": round(seconds, 4),
        "sessions": report["sessions"],
        "scopes": report["scopes"],
        "fingerprint": report["fingerprint"],
    }


# ── The fabric-side driver (an embedder with no local fleet) ───────────


class FederationDriver:
    """Routes an embedder's outbound votes across the federation over
    the gossip fabric: per-scope (host, shard) ownership from the shared
    :class:`FederationPlacement`, coalesced pipelined ``OP_VOTE_BATCH``
    frames per owning host, bounded-queue backpressure with deferred
    resend (votes are NEVER dropped: a shed frame re-queues, a vote for
    a migrating shard buffers into that shard's tail and replays after
    the placement flip).

    This is ``bench.py fleet --hosts N``'s driver; it is also the shape
    of a stateless front-end tier routing user traffic into the
    federation."""

    def __init__(
        self,
        placement: FederationPlacement,
        *,
        flush_votes: int = 512,
        flush_bytes: int = 512 * 1024,
        flush_interval: float = 0.005,
        request_timeout: float = 60.0,
    ):
        from ..gossip.coalescer import VoteCoalescer
        from ..gossip.transport import GossipTransport

        self.placement = placement
        self._transport = GossipTransport()
        self._coalescer = VoteCoalescer(
            flush_votes=flush_votes,
            flush_bytes=flush_bytes,
            flush_interval=flush_interval,
        )
        self._timeout = request_timeout
        self._hosts: "dict[str, _RemoteHost]" = {}
        self._lock = threading.Lock()
        self._outstanding: list = []
        self._deferred: list = []  # shed frames awaiting a resend
        self._tail: "dict[str, list]" = {}  # shard -> buffered submits
        self._migration_t0: "dict[str, float]" = {}
        self._submitted = 0
        self._acked = 0
        self._rejected = 0
        self._reject_codes: "dict[int, int]" = {}
        ref_self = weakref.ref(self)
        default_registry.register_gauge(
            FEDERATION_HOSTS,
            lambda: len(d._hosts) if (d := ref_self()) is not None else 0,
            owner=self,
        )
        self._m_remote_routed = default_registry.counter(
            FEDERATION_REMOTE_ROUTED_VOTES_TOTAL
        )

    def connect(self, host_id: str, host: str, port: int, peer_id: int) -> None:
        self._transport.connect(host_id, host, port)
        with self._lock:
            self._hosts[host_id] = _RemoteHost(host_id, host, port, peer_id)

    def close(self) -> None:
        self._transport.close()

    def __enter__(self) -> "FederationDriver":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    # ── submission ─────────────────────────────────────────────────────

    def submit(self, scope: str, votes: "list[bytes]", now: int) -> str:
        """Coalesce one scope's signed votes toward the owning host.
        Returns ``"sent"`` (on the wire or windowed) or ``"buffered"``
        (owning shard mid-migration; replays on the flip)."""
        with self._lock:
            self._submitted += len(votes)
        return self._route_votes(scope, votes, now)

    def _route_votes(self, scope: str, votes: "list[bytes]", now: int) -> str:
        """Route without touching the submitted counter (shared by
        submit, tail replay, and failed-frame recovery)."""
        host, shard = self.placement.owner(scope)
        if self.placement.migrating(shard):
            with self._lock:
                self._tail.setdefault(shard, []).append(
                    (scope, list(votes), now)
                )
            # Close the window race: if the flip landed between our
            # migrating check and the append, complete_shard_migration
            # may already have popped (and replayed) the tail — our
            # entry would be orphaned. Re-check after the append: when
            # the freeze is gone, pop whatever is left and re-route it
            # ourselves (the appended votes are the newest, so order
            # per scope still holds).
            if not self.placement.migrating(shard):
                with self._lock:
                    entries = self._tail.pop(shard, None)
                if entries:
                    for late_scope, late_votes, late_now in entries:
                        self._route_votes(late_scope, late_votes, late_now)
            return "buffered"
        info = self._hosts[host]
        for vote in votes:
            ready = self._coalescer.add(host, info.peer_id, scope, vote, now)
            if ready is not None:
                self._send(host, ready[0])
        self._m_remote_routed.inc(len(votes))
        return "sent"

    def _send(self, host: str, payload) -> None:
        from ..bridge import protocol as P

        future = self._transport.try_request(host, P.OP_VOTE_BATCH, payload)
        if future is None:
            with self._lock:  # shed: bounded, deferred — never dropped
                self._deferred.append((host, payload))
            return
        with self._lock:
            self._outstanding.append((future, payload))
            backlog = len(self._outstanding)
        if backlog > 64:
            self._reap()

    def pump(self) -> None:
        """Close due coalescer windows, resend deferred frames, reap
        completed responses — call on the driving loop's cadence."""
        for host in self._coalescer.due():
            ready = self._coalescer.flush(host)
            if ready is not None:
                self._send(host, ready[0])
        self._resend_deferred()
        self._reap()

    def _resend_deferred(self) -> None:
        from ..bridge import protocol as P

        with self._lock:
            deferred, self._deferred = self._deferred, []
        for host, payload in deferred:
            future = self._transport.try_request(
                host, P.OP_VOTE_BATCH, payload
            )
            if future is None:
                with self._lock:
                    self._deferred.append((host, payload))
            else:
                with self._lock:
                    self._outstanding.append((future, payload))

    def _recover_frame(self, payload) -> None:
        """A frame the server refused whole (shard frozen mid-flight,
        connection lost): decode it back to (scope, votes) groups and
        re-route every row under the CURRENT placement — frozen-shard
        scopes buffer into the migration tail, the rest re-coalesce to
        their (possibly new) owner. The refusal is all-or-nothing on the
        server (grouping raises before any shard dispatches), so a
        recovery never double-applies."""
        from ..bridge import protocol as P

        body = payload if isinstance(payload, bytes) else b"".join(payload)
        now, groups = P.decode_vote_batch(P.Cursor(body))
        for _peer_id, scope, votes in groups:
            self._route_votes(scope, list(votes), now)

    def _harvest(self, future, payload, budget: "float | None") -> None:
        from ..bridge.client import (
            BridgeConnectionLost,
            BridgeError,
            parse_status_list,
        )

        try:
            statuses = parse_status_list(
                future.result(budget if budget is not None else 0)
            )
        except (BridgeError, BridgeConnectionLost, TimeoutError, OSError):
            self._recover_frame(payload)
            return
        acked = sum(1 for c in statuses if c in (_OK, _ALREADY))
        with self._lock:
            self._acked += acked
            self._rejected += len(statuses) - acked
            for code in statuses:
                if code not in (_OK, _ALREADY):
                    self._reject_codes[code] = (
                        self._reject_codes.get(code, 0) + 1
                    )

    def _reap(self) -> None:
        with self._lock:
            # ONE done() probe per entry: a future resolving between a
            # "done" pass and a "not done" pass would land in neither
            # list and its frame's tallies would vanish unharvested.
            done: list = []
            remaining: list = []
            for entry in self._outstanding:
                (done if entry[0].done() else remaining).append(entry)
            self._outstanding = remaining
        for future, payload in done:
            self._harvest(future, payload, None)

    def drain(self, timeout: float = 60.0) -> dict:
        """Flush everything (windows, deferred resends) and await every
        in-flight frame; returns cumulative delivery counts since the
        last drain. ``acked == submitted - buffered`` (with zero
        rejected) is the zero-loss criterion the bench asserts."""
        deadline = time.monotonic() + timeout
        while True:
            for host in list(self._hosts):
                ready = self._coalescer.flush(host)
                if ready is not None:
                    self._send(host, ready[0])
            self._resend_deferred()
            with self._lock:
                outstanding, self._outstanding = self._outstanding, []
                idle = not self._deferred and not outstanding
            for future, payload in outstanding:
                self._harvest(
                    future, payload, max(0.0, deadline - time.monotonic())
                )
            with self._lock:
                # Recovery may have re-coalesced rows: loop until no
                # frame is pending anywhere (windows, deferred, wire).
                pending = bool(self._deferred) or bool(self._outstanding)
                pending = pending or any(
                    self._coalescer.pending(h) for h in self._hosts
                )
            if idle and not pending:
                break
            if time.monotonic() >= deadline:
                raise TimeoutError("frames still pending at drain deadline")
            time.sleep(0.002)
        with self._lock:
            buffered = sum(
                len(votes)
                for entries in self._tail.values()
                for _s, votes, _n in entries
            )
            report = {
                "submitted": self._submitted,
                "acked": self._acked,
                "rejected": self._rejected,
                "reject_codes": dict(self._reject_codes),
                "buffered": buffered,
            }
            self._submitted = self._acked = self._rejected = 0
            self._reject_codes = {}
        return report

    # ── fabric readouts ────────────────────────────────────────────────

    def fleet_tally(self) -> "dict[int, int]":
        """Federation-wide state histogram over the fabric (the driver
        has no local fleet, so it always sums OP_FLEET_TALLY frames)."""
        from ..bridge import protocol as P

        with self._lock:
            hosts = list(self._hosts.values())
        futures = [
            (
                info.host_id,
                self._transport.request(
                    info.host_id, P.OP_FLEET_TALLY, P.u32(info.peer_id)
                ),
            )
            for info in hosts
        ]
        total: "dict[int, int]" = {}
        for _hid, future in futures:
            for code, count in P.parse_fleet_tally(
                future.result(self._timeout)
            ).items():
                total[code] = total.get(code, 0) + count
        return total

    def state_fingerprint(self, host_id: str) -> str:
        from ..bridge import protocol as P

        info = self._hosts[host_id]
        future = self._transport.request(
            host_id, P.OP_STATE_FINGERPRINT, P.u32(info.peer_id)
        )
        return future.result(self._timeout).string()

    def pull_metric_frames(self) -> "list[dict]":
        """One ``OP_METRICS_PULL`` frame per connected host (the driver
        has no local fleet, so every frame comes over the fabric)."""
        import json

        from ..bridge import protocol as P

        with self._lock:
            hosts = list(self._hosts)
        futures = [
            self._transport.request(host, P.OP_METRICS_PULL, b"")
            for host in hosts
        ]
        return [
            json.loads(f.result(self._timeout).blob().decode("utf-8"))
            for f in futures
        ]

    def merged_metrics(self) -> dict:
        """Fleet-wide registry state through the ONE shared merge."""
        return merge_metric_states(self.pull_metric_frames())

    def merged_metrics_text(self) -> str:
        from ..obs.prometheus import render_state

        return render_state(self.merged_metrics())

    def merged_slo(self) -> dict:
        return merge_slo_states(self.pull_metric_frames())

    # ── migration window (the driver's half of a live migration) ───────

    def _quiesce_inflight(self, timeout: float) -> None:
        """Resolve every frame that was on the wire (or shed-deferred)
        at call time: each completes normally or refuses typed, and
        refused frames recover — during a migration freeze, straight
        into the shard's tail, in send order. New traffic keeps flowing
        while this waits; frames sent after the snapshot cannot contain
        a frozen scope's votes (submits buffer those)."""
        deadline = time.monotonic() + timeout
        while True:
            self._resend_deferred()
            with self._lock:
                if not self._deferred:
                    break
            if time.monotonic() >= deadline:
                raise TimeoutError("deferred frames could not be resent")
            time.sleep(0.002)
        with self._lock:
            snapshot = [f for f, _p in self._outstanding]
        for future in snapshot:
            try:
                future.result(max(0.0, deadline - time.monotonic()))
            except Exception:
                pass  # _harvest routes the failure (recovery) below
        self._reap()

    def begin_shard_migration(
        self,
        shard_id: str,
        retry_after: float = 1.0,
        quiesce_timeout: float = 30.0,
    ) -> None:
        """Open the migration window and DRAIN the shard's router
        queue, oldest first:

        1. subsequent submits for the shard's scopes buffer into its
           tail (never sent, never dropped);
        2. frames already on the wire resolve — ones the source refuses
           (``STATUS_SHARD_MIGRATING``) recover into the tail;
        3. the shard's votes still waiting in open coalescer windows
           move into the tail behind them.

        The tail therefore holds every unacked vote of the shard's
        scopes in submission order; :meth:`complete_shard_migration`
        replays it to the new owner after the flip."""
        self.placement.begin_migration(shard_id, retry_after)
        with self._lock:
            self._tail.setdefault(shard_id, [])
            self._migration_t0[shard_id] = time.perf_counter()
        flight_recorder.record(
            "federation.migrate_start",
            shard=shard_id, source=self.placement.host_of(shard_id),
        )
        self._quiesce_inflight(quiesce_timeout)

        def owned(scope) -> bool:
            return self.placement.owner(scope)[1] == shard_id

        for host in list(self._hosts):
            for _peer, scope, votes, wnow in self._coalescer.extract(
                host, owned
            ):
                with self._lock:
                    self._tail[shard_id].append((scope, votes, wnow))

    def complete_shard_migration(self, shard_id: str, to_host: str) -> dict:
        """Flip the placement and replay the buffered tail to the new
        owner. Returns {seconds, tail_votes}."""
        self.placement.complete_migration(shard_id, to_host)
        with self._lock:
            entries = self._tail.pop(shard_id, [])
            t0 = self._migration_t0.pop(shard_id, None)
        tail_votes = 0
        for scope, votes, now in entries:
            # Replay without re-counting: the tail was counted as
            # submitted when it buffered.
            self._route_votes(scope, votes, now)
            tail_votes += len(votes)
        seconds = (
            time.perf_counter() - t0 if t0 is not None else 0.0
        )
        default_registry.counter(FEDERATION_MIGRATIONS_TOTAL).inc()
        default_registry.histogram(FEDERATION_MIGRATION_SECONDS).observe(
            seconds
        )
        flight_recorder.record(
            "federation.migrate_finish",
            shard=shard_id, target=to_host,
            tail_votes=tail_votes, seconds=round(seconds, 4),
        )
        return {"seconds": round(seconds, 4), "tail_votes": tail_votes}

    def abort_shard_migration(self, shard_id: str) -> None:
        """Lift the freeze without flipping; the tail replays to the
        ORIGINAL owner."""
        self.placement.abort_migration(shard_id)
        with self._lock:
            entries = self._tail.pop(shard_id, [])
            self._migration_t0.pop(shard_id, None)
        for scope, votes, now in entries:
            self._route_votes(scope, votes, now)
