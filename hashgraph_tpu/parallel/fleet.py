"""Scope-sharded consensus fleet: N independent engines over N devices,
one logical service.

Hashgraph-style virtual voting has no cross-scope dataflow (the reference
partitions all state by scope — src/storage.rs:188-194 — and every
decision reads only its own session's chain), so the fleet's unit of
sharding is the *scope*: every scope lives entirely on one shard, each
shard is a full :class:`~hashgraph_tpu.engine.TpuConsensusEngine` whose
pool is pinned to its own device, and the only fleet-wide communication
is one ``psum`` per stats/sweep readout. This is the data-parallel SPMD
recipe (shard the batch axis, collective-reduce the tallies) applied one
level above :class:`~hashgraph_tpu.parallel.sharded.ShardedPool`: the
pool shards *slots* of one engine across a mesh; the fleet shards
*scopes* across engines, so host-side work (crypto, resolution, event
emission) scales with the shard count too — the multiplier the ROADMAP's
"millions of users" arithmetic needs (N shards × per-shard throughput).

Placement is rendezvous (highest-random-weight) hashing over the live
shard set: ``owner(scope) = argmax_s H(s, scope)`` with a keyed blake2b
digest. Deterministic across processes and restarts (no dependence on
Python's randomized ``hash()``), and *minimally disruptive* under elastic
membership — adding a shard steals only the scopes that now hash to it;
removing a shard reassigns only that shard's scopes (every other scope's
argmax is unchanged). Scopes with live state are additionally *pinned* to
their current shard so a membership change never silently splits an
existing scope's sessions; pins release on ``delete_scope`` (migration of
live scopes is the state-sync item, ROADMAP 4).

Each shard carries its own WAL (``wal_root/<shard-id>``) and its own
:class:`~hashgraph_tpu.obs.health.HealthMonitor`, so one shard's
crash-recovery replay (``set_replay_mode`` gating and all) stalls only
its own slice of traffic: the router keeps dispatching to every other
shard while a recovering shard replays, and routes to the recovering
shard either raise :class:`ShardRecoveringError` or report
``SESSION_NOT_FOUND`` (the multihost misroute convention — "owned
elsewhere right now, retry/route"), caller's choice.
"""

from __future__ import annotations

import hashlib
import os
import threading
import time
import weakref
from concurrent.futures import ThreadPoolExecutor
from functools import partial

import numpy as np

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..errors import StatusCode
from ..obs import (
    FLEET_ROUTED_VOTES_TOTAL,
    FLEET_SHARDS,
    FLEET_SHARDS_RECOVERING,
    FLEET_SWEEP_SECONDS,
)
from ..obs import registry as default_registry
from ..obs.health import HealthMonitor
from ..obs.prometheus import _escape_label
from .mesh import PROPOSAL_AXIS
from .sharded import ShardedPool

__all__ = [
    "rendezvous_owner",
    "ScopePlacement",
    "FleetShard",
    "ConsensusFleet",
    "ShardRecoveringError",
    "ShardMigratingError",
]


class ShardRecoveringError(RuntimeError):
    """The scope's owning shard is mid-recovery (WAL replay in flight)."""

    def __init__(self, shard_id: str):
        super().__init__(
            f"shard {shard_id!r} is recovering; its scopes are briefly "
            "unavailable (other shards keep serving)"
        )
        self.shard_id = shard_id


class ShardMigratingError(ShardRecoveringError):
    """The scope's owning shard is mid-migration to another host.

    A subclass of :class:`ShardRecoveringError` so existing
    unavailability handling keeps working; ``retry_after`` carries the
    migration orchestrator's hint of when routes resume on the new
    owner — callers back off and retry instead of dropping votes (the
    federation driver buffers them as the migration tail)."""

    def __init__(self, shard_id: str, retry_after: float = 1.0):
        RuntimeError.__init__(
            self,
            f"shard {shard_id!r} is migrating; its scopes resume on the "
            f"new owner in ~{retry_after:.1f}s (retry with backoff)",
        )
        self.shard_id = shard_id
        self.retry_after = retry_after


# ── Placement ──────────────────────────────────────────────────────────


def _scope_bytes(scope) -> bytes:
    """Canonical cross-process bytes for a scope id (the multihost pid
    discipline: a default object repr embeds a memory address and would
    de-sync placement between peers)."""
    from ..engine.engine import _canonical_scope_bytes

    return _canonical_scope_bytes(scope)


def _weight(shard_id: str, scope_bytes: bytes) -> int:
    """HRW weight of (shard, scope): keyed blake2b, 64-bit. The shard id
    is the *key* (domain separation), the scope is the message — stable
    across processes, restarts, and shard-set membership changes."""
    return int.from_bytes(
        hashlib.blake2b(
            scope_bytes, digest_size=8, key=shard_id.encode()[:64]
        ).digest(),
        "big",
    )


def _check_shard_ids(shard_ids) -> None:
    """blake2b keys cap at 64 bytes; a longer shard id would silently
    truncate, giving two ids with a shared 64-byte prefix IDENTICAL
    weights for every scope — one of them would never own anything and
    removing the other would remap every scope at once. Reject outright."""
    for sid in shard_ids:
        if len(sid.encode()) > 64:
            raise ValueError(
                f"shard id {sid!r} exceeds 64 bytes; rendezvous weights "
                "key on the id and would silently truncate"
            )


def rendezvous_owner(scope, shard_ids) -> str:
    """The shard owning ``scope`` under rendezvous hashing: the highest
    64-bit keyed digest wins (ties — a 2^-64 event — break on shard id, so
    the choice is still total and deterministic). Adding/removing a shard
    perturbs only the scopes whose argmax involves that shard: the
    rendezvous invariant the placement property tests pin down."""
    if not shard_ids:
        raise ValueError("rendezvous over an empty shard set")
    _check_shard_ids(shard_ids)
    sb = _scope_bytes(scope)
    return max(shard_ids, key=lambda sid: (_weight(sid, sb), sid))


class ScopePlacement:
    """Deterministic scope→shard assignment over an elastic shard set.

    Thread-safe; memoizes owner lookups per scope and drops the memo on
    membership changes (rendezvous recomputation is cheap but the router
    probes it per batch row group)."""

    def __init__(self, shard_ids):
        self._ids = list(dict.fromkeys(shard_ids))
        if not self._ids:
            raise ValueError("placement needs at least one shard")
        _check_shard_ids(self._ids)
        self._cache: dict = {}
        self._lock = threading.Lock()

    @property
    def shard_ids(self) -> list:
        return list(self._ids)

    # Memo bound: under scope churn (transient scope names, probed
    # candidates that never materialize) the memo would otherwise grow
    # one entry per scope id forever. Recomputation is cheap, so a full
    # reset at the cap beats LRU bookkeeping on the lookup hot path.
    _CACHE_CAP = 65_536

    def owner(self, scope) -> str:
        with self._lock:
            sid = self._cache.get(scope)
            if sid is None:
                if len(self._cache) >= self._CACHE_CAP:
                    self._cache.clear()
                sid = rendezvous_owner(scope, self._ids)
                self._cache[scope] = sid
            return sid

    def evict(self, scope) -> None:
        """Drop a scope's memo entry (fleet.delete_scope calls this —
        deleted scopes are never looked up again)."""
        with self._lock:
            self._cache.pop(scope, None)

    def add_shard(self, shard_id: str) -> None:
        _check_shard_ids([shard_id])
        with self._lock:
            if shard_id in self._ids:
                raise ValueError(f"shard {shard_id!r} already placed")
            self._ids.append(shard_id)
            self._cache.clear()

    def remove_shard(self, shard_id: str, allow_empty: bool = False) -> None:
        with self._lock:
            if shard_id not in self._ids:
                raise ValueError(f"shard {shard_id!r} not placed")
            if len(self._ids) == 1 and not allow_empty:
                # A standalone fleet with zero shards can route nothing;
                # only a federation host DRAINED by migration (its scopes
                # live on other hosts now) legitimately reaches empty.
                raise ValueError("cannot remove the last shard")
            self._ids.remove(shard_id)
            self._cache.clear()


# ── Shards ─────────────────────────────────────────────────────────────


class FleetShard:
    """One engine + device + WAL + health slice of the fleet."""

    def __init__(self, shard_id: str, device, engine, wal_dir=None, index=0):
        self.shard_id = shard_id
        self.device = device
        self.engine = engine  # TpuConsensusEngine or DurableEngine wrapper
        self.wal_dir = wal_dir
        # Construction-time signer index, pinned for the shard's lifetime:
        # recovery MUST rebuild with signer_factory(index) so a
        # deterministic factory reproduces the pre-crash identity even
        # after unrelated membership changes reshuffled dict positions.
        self.index = index
        self.lock = threading.RLock()
        self.recovering = False
        # Migration freeze: the engine stays LIVE (it serves the snapshot
        # + WAL tail the adopting host catches up from) but routes raise
        # ShardMigratingError until the placement flips and the shard is
        # retired (or end_migration aborts).
        self.migrating = False
        self.migration_retry_after = 1.0
        # Scopes pinned against lifecycle demote/GC for the freeze window.
        self.migration_pinned: set = set()
        self.recovery_error: "BaseException | None" = None
        self.votes_routed = 0  # rows this shard was handed by the router
        # Last WAL replay's ReplayStats (recover_shard) — surfaced in
        # occupancy()/health_report() so a fleet operator sees mid-log
        # corruption (torn bytes, dropped segments, decode errors)
        # without ssh'ing into the shard.
        self.recovery_stats = None
        # Last peer catch-up's CatchUpReport (catch_up_shard).
        self.catchup_report = None

    @property
    def available(self) -> bool:
        return (
            not self.recovering
            and not self.migrating
            and self.engine is not None
        )

    def health_report(self, now=None) -> dict:
        return self.engine.health_report(now)

    def pool(self):
        return self.engine.pool()


def _close_engine(engine) -> None:
    """Close a shard engine if it is closable (DurableEngine flushes its
    WAL and releases the directory flock; a bare TpuConsensusEngine has
    no close). Duck-typed on the bound ``close`` method — NOT on the
    ``wal`` property, whose value is a WalWriter instance and therefore
    never callable."""
    close = getattr(engine, "close", None)
    if callable(close):
        close()


def _shard_map():
    sm = getattr(jax, "shard_map", None)
    if sm is None:  # pre-graduation JAX
        from jax.experimental.shard_map import shard_map as sm
    return sm


class ConsensusFleet:
    """Production topology: a router over scope-sharded engines.

    ``signer_factory(shard_index) -> ConsensusSignatureScheme`` mints each
    shard's signer (deterministic factories make recovery rebuild the
    same identity). One shard per entry of ``devices`` (default: all
    local devices) unless ``n_shards`` overrides; with more shards than
    devices, shards round-robin over devices (the CPU smoke topology).

    Entry points mirror the engine surface; batch entry points are
    *routers*: rows group by owning shard, dispatch per shard on a thread
    pool (each engine carries its own lock, so shards proceed
    concurrently and device work overlaps), and statuses stitch back in
    input order. Per-shard crypto amortization is inherited wholesale:
    ``deliver_proposals`` keeps the validated-chain watermark per shard,
    ``ingest_votes_pipelined`` keeps the crypto/device double-buffering
    per shard.
    """

    def __init__(
        self,
        signer_factory,
        *,
        n_shards: int | None = None,
        devices=None,
        capacity_per_shard: int = 1024,
        voter_capacity: int = 64,
        max_sessions_per_scope: int | None = None,
        wal_root: "str | None" = None,
        fsync_policy: str = "batch",
        verify_cache="default",
        shard_ids=None,
    ):
        from ..engine import TpuConsensusEngine

        self._engine_cls = TpuConsensusEngine
        self._signer_factory = signer_factory
        self._devices = list(devices) if devices is not None else jax.devices()
        if n_shards is None:
            n_shards = len(shard_ids) if shard_ids else len(self._devices)
        if n_shards < 1:
            raise ValueError("fleet needs at least one shard")
        if shard_ids is None:
            shard_ids = [f"shard-{k}" for k in range(n_shards)]
        if len(shard_ids) != n_shards:
            raise ValueError("shard_ids must supply one id per shard")
        self._capacity_per_shard = capacity_per_shard
        self._voter_capacity = voter_capacity
        self._max_sessions = (
            max_sessions_per_scope
            if max_sessions_per_scope is not None
            else capacity_per_shard + 16
        )
        self._wal_root = wal_root
        self._fsync_policy = fsync_policy
        self._verify_cache = verify_cache
        self._lock = threading.RLock()  # membership + pin map only
        self._shards: dict[str, FleetShard] = {}
        self._pins: dict = {}  # scope -> shard_id while scope has state
        for k, sid in enumerate(shard_ids):
            self._shards[sid] = self._build_shard(
                sid, self._devices[k % len(self._devices)], k
            )
        # Monotonic signer-index allocator: indices are never reused, so
        # an added shard can never mint an identity a removed (or live)
        # shard already holds under a deterministic factory.
        self._next_index = len(shard_ids)
        self.placement = ScopePlacement(shard_ids)
        # Router concurrency: one worker per shard on real accelerators
        # (dispatch threads mostly wait on device execution), capped at
        # the core count on CPU where shards share the host substrate and
        # extra threads only add GIL/scheduler contention.
        platform = getattr(self._devices[0], "platform", "cpu")
        workers = (
            len(shard_ids)
            if platform != "cpu"
            else min(len(shard_ids), os.cpu_count() or 2)
        )
        self._executor = ThreadPoolExecutor(
            max_workers=max(2, workers), thread_name_prefix="fleet"
        )
        self._tally_cache = None  # (mesh, sharding, jitted psum) or None
        # Fleet observability: shard-count gauges ride the process-wide
        # registry (weakly owned — a dropped fleet's series vanish), the
        # routed-votes counter splits per shard for dashboards.
        self.metrics = default_registry
        ref_self = weakref.ref(self)
        self.metrics.register_gauge(
            FLEET_SHARDS,
            lambda: len(f._shards) if (f := ref_self()) is not None else 0,
            owner=self,
        )
        self.metrics.register_gauge(
            FLEET_SHARDS_RECOVERING,
            lambda: (
                sum(1 for s in f._shards.values() if s.recovering)
                if (f := ref_self()) is not None
                else 0
            ),
            owner=self,
        )
        self._m_routed = self.metrics.counter(FLEET_ROUTED_VOTES_TOTAL)
        self._m_routed_shard = {
            sid: self.metrics.counter(
                f'{FLEET_ROUTED_VOTES_TOTAL}{{shard="{_escape_label(sid)}"}}'
            )
            for sid in shard_ids
        }
        self._m_sweep = self.metrics.histogram(FLEET_SWEEP_SECONDS)

    # ── Construction / membership ──────────────────────────────────────

    def _build_shard(self, shard_id: str, device, index: int) -> FleetShard:
        mesh = Mesh(np.asarray([device]), (PROPOSAL_AXIS,))
        pool = ShardedPool(
            self._capacity_per_shard, self._voter_capacity, mesh
        )
        engine = self._engine_cls(
            self._signer_factory(index),
            pool=pool,
            max_sessions_per_scope=self._max_sessions,
            verify_cache=self._verify_cache,
            health_monitor=HealthMonitor(),
        )
        # SLO plane: decisions this shard's engine makes land in the
        # process SLO engine's per-shard sliding windows under this label
        # (hashgraph_slo_decision_p99_seconds{shard="..."}).
        engine._slo_shard = shard_id
        wal_dir = None
        if self._wal_root is not None:
            from ..wal import DurableEngine

            wal_dir = os.path.join(self._wal_root, shard_id)
            engine = DurableEngine(
                engine, wal_dir, fsync_policy=self._fsync_policy
            )
        return FleetShard(shard_id, device, engine, wal_dir, index=index)

    @property
    def shard_ids(self) -> list:
        return self.placement.shard_ids

    @property
    def n_shards(self) -> int:
        return len(self._shards)

    def shard(self, shard_id: str) -> FleetShard:
        return self._shards[shard_id]

    def add_shard(self, shard_id: "str | None" = None, device=None) -> str:
        """Elastic scale-out: new scopes that rendezvous-hash to the new
        shard land there; every existing scope's owner is unchanged
        (pins + the rendezvous invariant)."""
        with self._lock:
            index = self._next_index
            self._next_index += 1
            if shard_id is None:
                shard_id = f"shard-{index}"
            if device is None:
                device = self._devices[index % len(self._devices)]
            shard = self._build_shard(shard_id, device, index)
            self.placement.add_shard(shard_id)  # validates uniqueness
            self._shards[shard_id] = shard
            self._m_routed_shard[shard_id] = self.metrics.counter(
                f'{FLEET_ROUTED_VOTES_TOTAL}{{shard="{_escape_label(shard_id)}"}}'
            )
            self._tally_cache = None
            return shard_id

    def remove_shard(
        self, shard_id: str, force: bool = False, allow_empty: bool = False
    ) -> None:
        """Elastic scale-in. Refuses while the shard still owns pinned
        (live) scopes unless ``force`` — draining live scopes is the
        embedder's job (delete or snapshot-migrate them first).
        ``allow_empty`` permits removing the LAST shard: a federation
        host whose final shard migrated away serves nothing until a
        later ``add_shard`` (routes raise on the empty placement)."""
        with self._lock:
            pinned = [s for s, sid in self._pins.items() if sid == shard_id]
            if pinned and not force:
                raise ValueError(
                    f"shard {shard_id!r} still owns live scopes "
                    f"{pinned[:4]}...; drain them or pass force=True"
                )
            self.placement.remove_shard(shard_id, allow_empty=allow_empty)
            shard = self._shards.pop(shard_id)
            for s in pinned:
                del self._pins[s]
            _close_engine(shard.engine)
            self._tally_cache = None

    def close(self) -> None:
        self._executor.shutdown(wait=True)
        for shard in self._shards.values():
            _close_engine(shard.engine)

    def __enter__(self) -> "ConsensusFleet":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    # ── Routing ────────────────────────────────────────────────────────

    def owner_of(self, scope) -> str:
        with self._lock:
            pinned = self._pins.get(scope)
        return pinned if pinned is not None else self.placement.owner(scope)

    def _unavailable(self, sid: str) -> ShardRecoveringError:
        """The typed unavailability for routes to shard ``sid``:
        migrating shards carry the retry-after hint, everything else is
        the recovery error."""
        shard = self._shards[sid]
        if shard.migrating:
            return ShardMigratingError(sid, shard.migration_retry_after)
        return ShardRecoveringError(sid)

    def _shard_for(self, scope, pin: bool = False) -> FleetShard:
        sid = self.owner_of(scope)
        shard = self._shards[sid]
        if not shard.available:
            raise self._unavailable(sid)
        if pin:
            with self._lock:
                self._pins.setdefault(scope, sid)
        return shard

    def _engine_for(self, scope, pin: bool = False):
        return self._shard_for(scope, pin).engine

    def _live_engine(self, sid: str):
        """The shard's engine read ONCE — dispatch workers run after the
        grouping-time availability check, so a crash_shard landing in
        between must surface as the typed unavailability error, not an
        AttributeError on a None engine."""
        engine = self._shards[sid].engine
        if engine is None:
            raise self._unavailable(sid)
        return engine

    # Control plane — routed scope-granular passthroughs. Mutating entry
    # points pin the scope to its owner so elastic membership changes
    # never split a live scope's sessions across shards.

    def scope(self, scope):
        return self._engine_for(scope, pin=True).scope(scope)

    def set_scope_config(self, scope, config) -> None:
        self._engine_for(scope, pin=True).set_scope_config(scope, config)

    def get_scope_config(self, scope):
        return self._engine_for(scope).get_scope_config(scope)

    def create_proposal(self, scope, request, now, config=None):
        return self._engine_for(scope, pin=True).create_proposal(
            scope, request, now, config
        )

    def create_proposals(self, scope, requests, now, config=None):
        return self._engine_for(scope, pin=True).create_proposals(
            scope, requests, now, config
        )

    def process_incoming_proposal(self, scope, proposal, now, config=None):
        return self._engine_for(scope, pin=True).process_incoming_proposal(
            scope, proposal, now, config
        )

    def process_incoming_vote(self, scope, vote, now) -> None:
        self._engine_for(scope).process_incoming_vote(scope, vote, now)

    def cast_vote(self, scope, proposal_id, choice, now):
        return self._engine_for(scope).cast_vote(scope, proposal_id, choice, now)

    def voter_gid(self, scope, owner: bytes) -> int:
        """Interned voter id ON THE OWNING SHARD (gids are per-engine;
        columnar rows must carry the owner shard's interning)."""
        return self._engine_for(scope).voter_gid(owner)

    def get_proposal(self, scope, proposal_id):
        return self._engine_for(scope).get_proposal(scope, proposal_id)

    def get_consensus_result(self, scope, proposal_id):
        return self._engine_for(scope).get_consensus_result(scope, proposal_id)

    def get_scope_stats(self, scope):
        return self._engine_for(scope).get_scope_stats(scope)

    def explain_decision(self, scope, proposal_id) -> dict:
        return self._engine_for(scope).explain_decision(scope, proposal_id)

    def delete_scope(self, scope) -> None:
        self._engine_for(scope).delete_scope(scope)
        with self._lock:
            self._pins.pop(scope, None)
        self.placement.evict(scope)

    def event_bus_of(self, scope):
        return self._engine_for(scope).event_bus()

    # ── Data plane: the batching router ────────────────────────────────

    def _group_scopes(self, scopes, unavailable_ok: bool):
        """scope list -> {shard_id: [(ordinal, scope), ...]} plus the
        set of ordinals whose shard is unavailable (empty unless
        ``unavailable_ok``; otherwise the route raises)."""
        groups: dict[str, list] = {}
        down: set[int] = set()
        for k, scope in enumerate(scopes):
            sid = self.owner_of(scope)
            if not self._shards[sid].available:
                if not unavailable_ok:
                    raise self._unavailable(sid)
                down.add(k)
                continue
            groups.setdefault(sid, []).append((k, scope))
        return groups, down

    def _note_routed(self, sid: str, rows: int) -> None:
        shard = self._shards[sid]
        shard.votes_routed += rows
        self._m_routed.inc(rows)
        counter = self._m_routed_shard.get(sid)
        if counter is not None:
            counter.inc(rows)

    def ingest_columnar(
        self,
        scope,
        proposal_ids,
        voter_gids,
        values,
        now,
        max_depth: int = 8,
        wire_votes=None,
    ) -> np.ndarray:
        """Single-scope columnar ingest on the owning shard."""
        shard = self._shard_for(scope)  # raises before anything counts
        self._note_routed(shard.shard_id, len(proposal_ids))
        return shard.engine.ingest_columnar(
            scope, proposal_ids, voter_gids, values, now,
            max_depth=max_depth, wire_votes=wire_votes,
        )

    def ingest_columnar_multi(
        self,
        scopes,
        scope_idx,
        proposal_ids,
        voter_gids,
        values,
        now,
        max_depth: int = 8,
        wire_votes=None,
        unavailable_ok: bool = False,
    ) -> np.ndarray:
        """THE fleet throughput path: a mixed-scope columnar batch split
        by owning shard and dispatched to every shard concurrently (one
        ``ingest_columnar_multi`` per shard on the fleet executor — each
        shard's device pipeline runs in parallel), statuses stitched back
        in input order.

        Rows for a recovering shard raise :class:`ShardRecoveringError`
        unless ``unavailable_ok``, in which case they report
        ``SESSION_NOT_FOUND`` (the multihost misroute convention: owned
        elsewhere right now — route again later).
        """
        proposal_ids = np.asarray(proposal_ids, np.int64)
        scope_idx = np.asarray(scope_idx, np.int64)
        voter_gids = np.asarray(voter_gids, np.int64)
        values = np.asarray(values, bool)
        batch = len(proposal_ids)
        statuses = np.full(batch, int(StatusCode.SESSION_NOT_FOUND), np.int32)
        groups, _ = self._group_scopes(scopes, unavailable_ok)
        wire_norm = None
        if wire_votes is not None:
            from ..wire import normalize_wire_votes

            wire_norm = normalize_wire_votes(wire_votes, batch)

        def dispatch(sid: str, members: list):
            ordinals = np.fromiter((k for k, _ in members), np.int64)
            local_of = np.full(len(scopes), -1, np.int64)
            local_of[ordinals] = np.arange(len(members))
            rows = np.nonzero(local_of[scope_idx] >= 0)[0]
            if rows.size == 0:
                return rows, np.empty(0, np.int32)
            sub_wire = None
            if wire_norm is not None:
                blob, offsets = wire_norm
                sub_wire = [
                    bytes(blob[offsets[r] : offsets[r + 1]]) for r in rows
                ]
            engine = self._live_engine(sid)
            self._note_routed(sid, int(rows.size))
            sub = engine.ingest_columnar_multi(
                [scope for _, scope in members],
                local_of[scope_idx[rows]],
                proposal_ids[rows],
                voter_gids[rows],
                values[rows],
                now,
                max_depth=max_depth,
                wire_votes=sub_wire,
            )
            return rows, sub

        futures = [
            self._executor.submit(dispatch, sid, members)
            for sid, members in groups.items()
        ]
        for future in futures:
            rows, sub = future.result()
            statuses[rows] = sub
        return statuses

    def ingest_votes(
        self, items, now, pre_validated: bool = False,
        unavailable_ok: bool = False,
    ) -> np.ndarray:
        """Routed :meth:`TpuConsensusEngine.ingest_votes`: items group by
        their scope's owning shard, shards ingest concurrently, statuses
        return in input order."""
        statuses = np.full(
            len(items), int(StatusCode.SESSION_NOT_FOUND), np.int32
        )
        groups: dict[str, list[int]] = {}
        for k, (scope, _) in enumerate(items):
            sid = self.owner_of(scope)
            if not self._shards[sid].available:
                if not unavailable_ok:
                    raise self._unavailable(sid)
                continue
            groups.setdefault(sid, []).append(k)

        def dispatch(sid: str, idxs: list[int]):
            engine = self._live_engine(sid)
            self._note_routed(sid, len(idxs))
            sub = engine.ingest_votes(
                [items[k] for k in idxs], now, pre_validated=pre_validated
            )
            return idxs, sub

        futures = [
            self._executor.submit(dispatch, sid, idxs)
            for sid, idxs in groups.items()
        ]
        for future in futures:
            idxs, sub = future.result()
            statuses[idxs] = sub
        return statuses

    def ingest_votes_pipelined(
        self, batches, now, pre_validated: bool = False
    ) -> "list[np.ndarray]":
        """Routed pipelined ingest: each shard runs its OWN
        crypto/device double-buffer over its slice of every batch (batch
        cadence preserved per shard, empty slices included), shards run
        concurrently, per-batch statuses stitch back in input order."""
        batches = [list(b) for b in batches]
        results = [
            np.full(len(b), int(StatusCode.SESSION_NOT_FOUND), np.int32)
            for b in batches
        ]
        per_shard: dict[str, list[list[int]]] = {}
        for b, items in enumerate(batches):
            for k, (scope, _) in enumerate(items):
                sid = self.owner_of(scope)
                if not self._shards[sid].available:
                    raise self._unavailable(sid)
                per_shard.setdefault(
                    sid, [[] for _ in batches]
                )[b].append(k)

        def dispatch(sid: str, slices: "list[list[int]]"):
            engine = self._live_engine(sid)
            self._note_routed(sid, sum(len(s) for s in slices))
            sub = engine.ingest_votes_pipelined(
                [[batches[b][k] for k in idxs]
                 for b, idxs in enumerate(slices)],
                now,
                pre_validated=pre_validated,
            )
            return slices, sub

        futures = [
            self._executor.submit(dispatch, sid, slices)
            for sid, slices in per_shard.items()
        ]
        for future in futures:
            slices, sub = future.result()
            for b, (idxs, st) in enumerate(zip(slices, sub)):
                results[b][idxs] = st
        return results

    def deliver_proposals(self, items, now, configs=None) -> "list[int]":
        """Routed gossip delivery: per-shard order preserved, so each
        shard's validated-chain watermark semantics are exactly the
        engine's (a batch equals the same deliveries one by one)."""
        if configs is not None and len(configs) != len(items):
            raise ValueError("configs must supply one entry per item")
        statuses = [int(StatusCode.SESSION_NOT_FOUND)] * len(items)
        groups: dict[str, list[int]] = {}
        for k, (scope, _) in enumerate(items):
            shard = self._shard_for(scope, pin=True)
            groups.setdefault(shard.shard_id, []).append(k)

        def dispatch(sid: str, idxs: list[int]):
            sub = self._live_engine(sid).deliver_proposals(
                [items[k] for k in idxs],
                now,
                configs=(
                    [configs[k] for k in idxs] if configs is not None else None
                ),
            )
            return idxs, sub

        futures = [
            self._executor.submit(dispatch, sid, idxs)
            for sid, idxs in groups.items()
        ]
        for future in futures:
            idxs, sub = future.result()
            for k, code in zip(idxs, sub):
                statuses[k] = int(code)
        return statuses

    def deliver_proposal(self, scope, proposal, now, config=None) -> int:
        return self.deliver_proposals(
            [(scope, proposal)], now,
            configs=[config] if config is not None else None,
        )[0]

    # ── Sweeps / tallies / health ──────────────────────────────────────

    def sweep_timeouts(self, now) -> list:
        """Fleet-wide timeout sweep: every AVAILABLE shard sweeps
        concurrently (a recovering shard's sweep is deferred to its
        recovery replay — its sessions are frozen with it), results
        concatenated. One fleet psum (:meth:`fleet_state_counts`) after
        the sweep gives the global histogram."""
        t0 = time.perf_counter()

        def sweep_one(sid: str):
            engine = self._shards[sid].engine
            # A shard crashed between the availability check and this
            # worker running is simply not swept this pass (its sessions
            # are frozen with it) — same as arriving one check earlier.
            return engine.sweep_timeouts(now) if engine is not None else []

        futures = [
            self._executor.submit(sweep_one, sid)
            for sid, shard in self._shards.items()
            if shard.available
        ]
        swept = [item for future in futures for item in future.result()]
        self._m_sweep.observe(time.perf_counter() - t0)
        return swept

    def _tally(self):
        """Cached (mesh, jitted psum) over the shard devices, or None
        when shards share devices (host fallback). One collective per
        readout: per-shard [1,5] count blocks assemble into a global
        [n,5] array sharded over the fleet mesh, and a single psum
        reduces it — the agree_trace_context pattern applied to state
        tallies."""
        if self._tally_cache is not None:
            return self._tally_cache
        devs = [s.device for s in self._shards.values()]
        if len(set(devs)) != len(devs) or len(devs) < 2:
            return None
        mesh = Mesh(np.asarray(devs), ("shard",))
        tally = jax.jit(
            _shard_map()(
                partial(jax.lax.psum, axis_name="shard"),
                mesh=mesh,
                in_specs=P("shard", None),
                out_specs=P(),
            )
        )
        self._tally_cache = (
            mesh, NamedSharding(mesh, P("shard", None)), tally
        )
        return self._tally_cache

    def fleet_state_counts(self) -> dict[int, int]:
        """Global slot-state histogram across every shard.

        Device path (each shard on its own device): each shard's pool
        computes its local 5-vector on its device, the vectors assemble
        into one sharded [n_shards, 5] array, and ONE psum over the fleet
        mesh reduces them — no per-shard host readback. Shards sharing a
        device (CPU smoke) fall back to summing host mirrors.
        """
        from ..ops.decide import (
            STATE_ACTIVE,
            STATE_FAILED,
            STATE_FREE,
            STATE_REACHED_NO,
            STATE_REACHED_YES,
        )

        codes = (
            STATE_FREE, STATE_ACTIVE, STATE_FAILED,
            STATE_REACHED_NO, STATE_REACHED_YES,
        )
        shards = [s for s in self._shards.values() if s.available]
        # A recovering shard's slots are frozen with it — the tally covers
        # the serving fleet (and a readout mid-recovery must not crash on
        # the crashed shard's dropped engine). The single psum needs every
        # mesh device's block, so any unavailable shard routes the readout
        # through the host fallback.
        tally = self._tally() if len(shards) == len(self._shards) else None
        if tally is None:
            total = {code: 0 for code in codes}
            for shard in shards:
                for code, count in shard.pool().state_counts().items():
                    total[code] = total.get(code, 0) + count
            return total
        mesh, sharding, reduce_fn = tally
        blocks = []
        for shard in shards:
            pool = shard.pool()
            local = pool._sharded_counts(pool._state)  # [5] on shard device
            blocks.append(jnp.reshape(local, (1, len(codes))))
        global_counts = jax.make_array_from_single_device_arrays(
            (len(blocks), len(codes)),
            sharding,
            [b.addressable_shards[0].data for b in blocks],
        )
        agg = np.asarray(reduce_fn(global_counts)).reshape(len(codes))
        return {code: int(c) for code, c in zip(codes, agg)}

    @staticmethod
    def _recovery_overlay(shard: FleetShard) -> dict:
        """Durability-provenance block for one shard's readouts: how its
        state was (re)built. ``wal_recover`` carries the last local
        replay's corruption counters (nonzero torn_bytes past the tail /
        dropped_segments / decode_errors = acknowledged records replay
        could not reproduce — the operator-visible mid-log-corruption
        signal); ``catch_up`` summarizes the last peer catch-up."""
        out: dict = {}
        stats = shard.recovery_stats
        if stats is not None:
            out["wal_recover"] = {
                "records_applied": stats.records_applied,
                "votes_replayed": stats.votes_replayed,
                "torn_bytes": stats.torn_bytes,
                "dropped_segments": stats.segments_dropped,
                "decode_errors": len(stats.errors),
            }
        report = shard.catchup_report
        if report is not None:
            out["catch_up"] = {
                "watermark": report.watermark,
                "sessions_installed": report.sessions_installed,
                "votes_verified": report.votes_verified,
                "tail_records": report.tail_records,
                "trust_snapshot": report.trust_snapshot,
                "seconds": report.seconds,
            }
        return out

    def occupancy(self) -> dict:
        """Per-shard breakdown: engine occupancy + per-device slot
        occupancy (the MULTICHIP artifact's per-device view), plus the
        shard's recovery provenance (see :meth:`_recovery_overlay`)."""
        out = {}
        for sid, shard in self._shards.items():
            if not shard.available:
                out[sid] = {
                    "recovering": shard.recovering or shard.engine is None,
                    "migrating": shard.migrating,
                    "recovery_error": (
                        repr(shard.recovery_error)
                        if shard.recovery_error is not None
                        else None
                    ),
                }
                continue
            entry = dict(shard.engine.occupancy())
            entry["device"] = str(shard.device)
            entry["votes_routed"] = shard.votes_routed
            entry["per_device_slots_used"] = (
                shard.pool().per_device_occupancy()
            )
            entry.update(self._recovery_overlay(shard))
            out[sid] = entry
        return out

    def occupancy_totals(self) -> dict:
        """Fleet-wide occupancy sum over the per-shard breakdown — the
        shared rollup (:mod:`hashgraph_tpu.parallel.rollup`), so the
        engine's keys (tier counters included) aggregate identically here
        and on the federation adapter."""
        from .rollup import aggregate_occupancy

        return aggregate_occupancy(self.occupancy().values())

    def health_report(self, now=None) -> dict:
        """Per-shard health (each shard carries a private monitor, so one
        noisy shard's evidence never pollutes another's scorecards); each
        serving shard's report also carries its recovery provenance
        (``wal_recover`` corruption counters / ``catch_up`` summary)."""
        out = {}
        for sid, shard in self._shards.items():
            if not shard.available:
                out[sid] = {
                    "recovering": shard.recovering or shard.engine is None,
                    "migrating": shard.migrating,
                    "recovery_error": (
                        repr(shard.recovery_error)
                        if shard.recovery_error is not None
                        else None
                    ),
                }
                continue
            report = dict(shard.health_report(now))
            report.update(self._recovery_overlay(shard))
            out[sid] = report
        return out

    # ── Migration freeze (re-homing onto another host) ─────────────────

    def begin_migration(
        self, shard_id: str, retry_after: float = 1.0
    ) -> None:
        """Freeze a shard for re-homing: the engine stays LIVE so the
        bridge can serve its consistent snapshot + WAL tail to the
        adopting host, but every route raises
        :class:`ShardMigratingError` (with ``retry_after`` as the
        caller's backoff hint) until :meth:`end_migration` aborts or
        ``remove_shard`` retires the shard after the placement flip."""
        shard = self._shards[shard_id]
        if shard.engine is None or shard.recovering:
            raise ValueError(f"shard {shard_id!r} is not serving")
        shard.migration_retry_after = retry_after
        shard.migrating = True
        # Freeze the tier too: pin every scope so no lifecycle sweep can
        # demote/GC state while its snapshot+tail is being adopted (the
        # fleet sweep already skips migrating shards; the pin also covers
        # embedders driving the shard engine's sweep directly).
        engine = getattr(shard.engine, "engine", shard.engine)
        pin = getattr(engine, "pin_scope", None)
        if pin is not None:
            pinned = {scope for scope, _ in engine.session_keys()}
            for scope in pinned:
                pin(scope)
            shard.migration_pinned = pinned

    def end_migration(self, shard_id: str) -> None:
        """Abort a migration freeze: the shard resumes serving locally
        (the placement never flipped, so no state moved)."""
        shard = self._shards[shard_id]
        shard.migrating = False
        engine = getattr(shard.engine, "engine", shard.engine)
        unpin = getattr(engine, "unpin_scope", None)
        if unpin is not None:
            for scope in getattr(shard, "migration_pinned", ()):
                unpin(scope)
            shard.migration_pinned = set()

    def pin_scope(self, scope, shard_id: str) -> None:
        """Pin ``scope`` to ``shard_id`` explicitly. The adopting side
        of a shard migration uses this to mirror the source fleet's
        live-scope pins: the migrated sessions live on the adopted shard
        regardless of where this fleet's own rendezvous would have
        placed them."""
        if shard_id not in self._shards:
            raise ValueError(f"unknown shard {shard_id!r}")
        with self._lock:
            self._pins[scope] = shard_id

    # ── Crash / recovery ───────────────────────────────────────────────

    def crash_shard(self, shard_id: str) -> None:
        """Simulate a shard engine crash: drop the in-memory engine and
        release its WAL (the surviving log is the recovery source). The
        shard routes as unavailable until :meth:`recover_shard` swaps a
        replayed engine back in; every other shard keeps serving."""
        if self._wal_root is None:
            raise ValueError("crash/recovery needs wal_root (nothing to replay)")
        shard = self._shards[shard_id]
        with shard.lock:
            shard.recovering = True
            if shard.engine is not None:
                # Close the writer so the fresh recovery writer can take
                # the directory flock; real crash durability (torn tails,
                # partial fsync) is the WAL suite's coverage.
                shard.engine.close()
            shard.engine = None

    def recover_shard(
        self,
        shard_id: str,
        background: bool = False,
        on_record=None,
    ):
        """Rebuild a crashed shard from its WAL: fresh engine on the same
        device, ``DurableEngine.recover()`` replay (``set_replay_mode``
        gating included), then swap in and resume routing. Only THIS
        shard's traffic waits; the router never blocks other shards on
        the replay (the non-stall contract, tested by
        tests/test_fleet.py::test_recovery_does_not_stall_other_shards).

        ``background=True`` runs the replay on a daemon thread and
        returns it (join for completion). A FAILED background replay
        never resolves silently: the exception is stored as
        ``shard.recovery_error`` (surfaced by :meth:`occupancy` and
        :meth:`health_report`), the shard stays unavailable, and
        ``recover_shard`` may be retried. Foreground mode re-raises.
        ``on_record(lsn, kind)`` forwards to
        :func:`hashgraph_tpu.wal.recovery.replay` for progress
        observation.
        """
        shard = self._shards[shard_id]

        def _recover():
            with shard.lock:
                shard.recovery_error = None
                try:
                    # Rebuild with the shard's CONSTRUCTION index (not
                    # its current dict position — membership changes
                    # reshuffle that): a deterministic signer_factory
                    # then reproduces the pre-crash identity exactly.
                    # Construction failures (held flock, device/signer
                    # errors) are captured too, not just replay failures.
                    fresh = self._build_shard(
                        shard_id, shard.device, shard.index
                    )
                    try:
                        stats = fresh.engine.recover(on_record=on_record)
                    except BaseException:
                        _close_engine(fresh.engine)  # release the dir
                        raise                        # flock for a retry
                except BaseException as exc:
                    shard.recovery_error = exc
                    raise
                shard.engine = fresh.engine
                shard.wal_dir = fresh.wal_dir
                shard.recovery_stats = stats
                shard.recovering = False

        if background:
            def _recover_guarded():
                try:
                    _recover()
                except BaseException:
                    # Already recorded on shard.recovery_error; don't let
                    # the daemon thread spray a traceback as the only
                    # signal. The shard stays unavailable by design.
                    pass

            thread = threading.Thread(
                target=_recover_guarded, name=f"recover-{shard_id}", daemon=True
            )
            thread.start()
            return thread
        _recover()
        return None

    def catch_up_shard(
        self,
        shard_id: str,
        host: str,
        port: int,
        source_peer: int,
        *,
        trust_snapshot: bool = False,
        background: bool = False,
        wipe_local_wal: bool = True,
    ):
        """Rebuild a shard FROM A PEER instead of its local WAL — the
        recovery path for a shard whose log is gone, corrupted, or too
        far behind to matter: a fresh engine on the shard's device
        catches up via :class:`~hashgraph_tpu.sync.CatchUpClient`
        (snapshot install with one batched verify pass, then WAL-tail
        the suffix) from ``source_peer`` on the bridge at
        ``(host, port)``, then swaps in and resumes routing. Like
        :meth:`recover_shard`, only THIS shard's traffic waits.

        ``wipe_local_wal`` (default) clears the shard's local WAL
        directory first: catch-up REPLACES local history, and appending
        post-catch-up traffic after stale pre-crash records would leave
        a log no future replay could interpret. The shard's new local
        WAL then covers only post-catch-up traffic — checkpoint the
        shard once it serves if it must survive its own crash without
        re-syncing (the snapshot install itself is not logged, by the
        ``DurableEngine.load_from_storage`` contract).

        ``trust_snapshot`` skips the snapshot's signature verification
        (operator-trusted sources only). ``background`` mirrors
        :meth:`recover_shard`: failures land on ``shard.recovery_error``
        and the shard stays unavailable for a retry. The installed
        state's provenance is surfaced as ``catch_up`` in
        :meth:`occupancy` / :meth:`health_report`.
        """
        import shutil

        from ..sync import CatchUpClient

        shard = self._shards[shard_id]
        with shard.lock:
            shard.recovering = True
            if shard.engine is not None:
                _close_engine(shard.engine)  # release the WAL flock
                shard.engine = None

        def _catch_up():
            with shard.lock:
                shard.recovery_error = None
                try:
                    if wipe_local_wal and shard.wal_dir is not None:
                        shutil.rmtree(shard.wal_dir, ignore_errors=True)
                    fresh = self._build_shard(
                        shard_id, shard.device, shard.index
                    )
                    try:
                        with CatchUpClient(host, port, source_peer) as client:
                            report = client.catch_up(
                                fresh.engine, trust_snapshot=trust_snapshot
                            )
                    except BaseException:
                        _close_engine(fresh.engine)  # release the dir
                        raise                        # flock for a retry
                except BaseException as exc:
                    shard.recovery_error = exc
                    raise
                shard.engine = fresh.engine
                shard.wal_dir = fresh.wal_dir
                shard.catchup_report = report
                shard.recovery_stats = None  # state is the peer's, not the log's
                shard.recovering = False

        if background:
            def _catch_up_guarded():
                try:
                    _catch_up()
                except BaseException:
                    pass  # recorded on shard.recovery_error, by design

            thread = threading.Thread(
                target=_catch_up_guarded,
                name=f"catchup-{shard_id}",
                daemon=True,
            )
            thread.start()
            return thread
        _catch_up()
        return None
