"""Device-mesh helpers for the sharded consensus pool.

The framework's unit of parallelism is the proposal: every proposal slot is
independent (no cross-proposal dataflow in the protocol — the reference
partitions state the same way by scope/proposal, src/storage.rs:188-194), so
the natural mesh is one axis over all devices with the slot axis sharded
across it. Collectives are needed only for global aggregation (stats), which
rides ICI as a psum.
"""

from __future__ import annotations

import jax
from jax.sharding import Mesh

PROPOSAL_AXIS = "p"


def consensus_mesh(
    n_devices: int | None = None, axis_name: str = PROPOSAL_AXIS
) -> Mesh:
    """A 1-D mesh over the first ``n_devices`` devices (default: all).

    On a v5e-8 this is the 8-chip ICI ring; under
    ``--xla_force_host_platform_device_count=N`` it is N virtual CPU devices
    (how tests and the driver's multi-chip dry run exercise the sharded path
    without TPU hardware).
    """
    devices = jax.devices()
    if n_devices is not None:
        if n_devices > len(devices):
            raise ValueError(
                f"requested {n_devices} devices, have {len(devices)}"
            )
        devices = devices[:n_devices]
    return Mesh(devices, (axis_name,))
