"""Multi-device parallelism: mesh construction + the sharded proposal pool.

The slot axis is the framework's data-parallel axis (proposals are
independent); voter lanes stay within a device (the per-proposal ``[V]``
vectors are small); the host-validate → device-tally split is the pipeline
axis. Collectives (psum over ICI) appear only in global aggregation.
"""

from .fleet import (
    ConsensusFleet,
    FleetShard,
    ScopePlacement,
    ShardRecoveringError,
    rendezvous_owner,
)
from .mesh import PROPOSAL_AXIS, consensus_mesh
from .multihost import (
    MultiHostPool,
    agree_trace_context,
    distributed_consensus_mesh,
    initialize_distributed,
    local_slot_range,
)
from .sharded import ShardedPool

__all__ = [
    "consensus_mesh",
    "ShardedPool",
    "MultiHostPool",
    "PROPOSAL_AXIS",
    "agree_trace_context",
    "initialize_distributed",
    "distributed_consensus_mesh",
    "local_slot_range",
    "ConsensusFleet",
    "FleetShard",
    "ScopePlacement",
    "ShardRecoveringError",
    "rendezvous_owner",
]
