"""Multi-device parallelism: mesh construction + the sharded proposal pool.

The slot axis is the framework's data-parallel axis (proposals are
independent); voter lanes stay within a device (the per-proposal ``[V]``
vectors are small); the host-validate → device-tally split is the pipeline
axis. Collectives (psum over ICI) appear only in global aggregation.
"""

from .federation import (
    FederationDriver,
    FederationPlacement,
    FleetEngineAdapter,
    FleetGroup,
    MigrationError,
    migrate_shard,
    tally_path,
)
from .fleet import (
    ConsensusFleet,
    FleetShard,
    ScopePlacement,
    ShardMigratingError,
    ShardRecoveringError,
    rendezvous_owner,
)
from .mesh import PROPOSAL_AXIS, consensus_mesh
from .multihost import (
    COLLECTIVES_GAP_SIGNATURE,
    MultiHostPool,
    agree_trace_context,
    collectives_available,
    distributed_consensus_mesh,
    initialize_distributed,
    is_collectives_gap,
    local_slot_range,
)
from .sharded import ShardedPool

__all__ = [
    "consensus_mesh",
    "ShardedPool",
    "MultiHostPool",
    "PROPOSAL_AXIS",
    "agree_trace_context",
    "initialize_distributed",
    "distributed_consensus_mesh",
    "local_slot_range",
    "collectives_available",
    "is_collectives_gap",
    "COLLECTIVES_GAP_SIGNATURE",
    "ConsensusFleet",
    "FleetShard",
    "ScopePlacement",
    "ShardRecoveringError",
    "ShardMigratingError",
    "rendezvous_owner",
    "FederationPlacement",
    "FleetEngineAdapter",
    "FleetGroup",
    "FederationDriver",
    "MigrationError",
    "migrate_shard",
    "tally_path",
]
