"""Multi-device proposal pool: slot axis sharded over a device mesh.

SPMD layout (the scaling-book recipe: pick a mesh, annotate shardings, let
XLA place the collectives):

- every ``[P]`` / ``[P, V]`` pool array is sharded on the slot axis across
  the 1-D ``consensus_mesh``; device ``d`` owns the contiguous slot range
  ``[d·P_local, (d+1)·P_local)``;
- batched mutations are routed on host: each device receives only its own
  slots' work as one ``[D·B, ...]`` array sharded on axis 0, with local slot
  ids — inside ``shard_map`` every device runs the *same single-device
  kernel body* (:mod:`hashgraph_tpu.ops`) on its block, embarrassingly
  parallel, zero collectives on the hot path;
- the only cross-device communication is ``psum`` for global stats
  (:meth:`ShardedPool.global_state_counts`), riding ICI;
- slot allocation round-robins across devices so load stays balanced.

The reference has no distributed runtime (deliberate no-I/O design,
src/lib.rs:15-27); this layer is the TPU-native equivalent of scaling the
embedder horizontally, with sessions partitioned exactly like the
scope-partitioned storage maps (src/storage.rs:192-193).
"""

from __future__ import annotations

from functools import partial

import numpy as np

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..ops.decide import (
    STATE_ACTIVE,
    STATE_FAILED,
    STATE_FREE,
    STATE_REACHED_NO,
    STATE_REACHED_YES,
    timeout_body,
)
from ..ops.ingest import (
    fresh_ingest_body,
    ingest_body,
    pack_slots,
    unpack_slots,
)
from .mesh import PROPOSAL_AXIS, consensus_mesh
from ..engine.pool import (
    ProposalPool,
    activate_body,
    load_body,
    release_body,
    _bucket,
    _pad1,
    _pad2,
    _pad_slot_ids,
)

__all__ = ["ShardedPool"]

_STATE_CODES = (
    STATE_FREE,
    STATE_ACTIVE,
    STATE_FAILED,
    STATE_REACHED_NO,
    STATE_REACHED_YES,
)


class ShardedPool(ProposalPool):
    """ProposalPool with its slot axis sharded over a device mesh.

    ``capacity_per_device`` slots live on each of the mesh's D devices
    (total capacity = D × capacity_per_device). The public API — and all
    host bookkeeping inherited from ProposalPool — is unchanged; only the
    ``_dispatch_*`` device hooks are replaced with shard_map versions.
    """


    def __init__(
        self,
        capacity_per_device: int,
        voter_capacity: int,
        mesh: Mesh | None = None,
    ):
        self.mesh = mesh if mesh is not None else consensus_mesh()
        self.axis = self.mesh.axis_names[0]
        self.n_devices = self.mesh.devices.size
        self.local_capacity = capacity_per_device
        self._build_sharded_kernels()
        super().__init__(capacity_per_device * self.n_devices, voter_capacity)
        # Round-robin free list across devices: pops yield device 0, 1, ...,
        # D-1, then wrap — keeps per-device load balanced as slots fill.
        order = [
            d * self.local_capacity + k
            for k in range(self.local_capacity)
            for d in range(self.n_devices)
        ]
        self._free = order[::-1]

    # ── Sharded array construction ─────────────────────────────────────

    def _init_device_arrays(self) -> None:
        p, v = self.capacity, self.voter_capacity
        s1 = NamedSharding(self.mesh, P(self.axis))
        s2 = NamedSharding(self.mesh, P(self.axis, None))
        self._state = jax.device_put(
            np.full(p, STATE_FREE, np.int32), s1
        )
        self._yes = jax.device_put(np.zeros(p, np.int32), s1)
        self._tot = jax.device_put(np.zeros(p, np.int32), s1)
        self._vote_mask = jax.device_put(np.zeros((p, v), bool), s2)
        self._vote_val = jax.device_put(np.zeros((p, v), bool), s2)
        self._n = jax.device_put(np.zeros(p, np.int32), s1)
        self._req = jax.device_put(np.zeros(p, np.int32), s1)
        self._cap = jax.device_put(np.zeros(p, np.int32), s1)
        self._gossip = jax.device_put(np.zeros(p, bool), s1)
        self._liveness = jax.device_put(np.zeros(p, bool), s1)

    def _build_sharded_kernels(self) -> None:
        mesh, axis = self.mesh, self.axis
        v1 = P(axis)  # [P] pool arrays and [D*B] routed batches
        v2 = P(axis, None)  # [P, V] pool arrays and [D*B, L] grids

        # jax.shard_map graduated from jax.experimental in newer JAX;
        # accept both spellings so the mesh path works across the
        # versions the fleet actually runs.
        shard_map = getattr(jax, "shard_map", None)
        if shard_map is None:  # pre-graduation JAX
            from jax.experimental.shard_map import shard_map
        sm = partial(shard_map, mesh=mesh)

        self._sharded_activate = jax.jit(
            sm(
                activate_body,
                in_specs=(v1, v1, v1, v2, v2, v1, v1, v1, v1, v1,
                          v1, v1, v1, v1, v1, v1),
                out_specs=(v1, v1, v1, v2, v2, v1, v1, v1, v1, v1),
            ),
            donate_argnums=tuple(range(10)),
        )
        self._sharded_load = jax.jit(
            sm(
                load_body,
                in_specs=(v1, v1, v1, v2, v2, v1, v1, v1, v1, v2, v2),
                out_specs=(v1, v1, v1, v2, v2),
            ),
            donate_argnums=(0, 1, 2, 3, 4),
        )
        self._sharded_release = jax.jit(
            sm(release_body, in_specs=(v1, v1), out_specs=v1),
            donate_argnums=(0,),
        )
        self._sharded_ingest = jax.jit(
            sm(
                ingest_body,
                in_specs=(v1, v1, v1, v2, v2, v1, v1, v1, v1, v1, v1, v2),
                out_specs=(v1, v1, v1, v2, v2, v2),
            ),
            donate_argnums=(0, 1, 2, 3, 4),
        )
        # Closed-form (scan-free) fresh ingest: pure per-shard elementwise
        # + cumsum work, zero collectives — shards exactly like the scan.
        self._sharded_fresh_ingest = jax.jit(
            sm(
                fresh_ingest_body,
                in_specs=(v1, v1, v1, v2, v2, v1, v1, v1, v1, v1, v1, v2),
                out_specs=(v1, v1, v1, v2, v2, v2),
            ),
            donate_argnums=(0, 1, 2, 3, 4),
        )
        self._sharded_fresh_ingest_laneless = jax.jit(
            sm(
                partial(fresh_ingest_body, laneless=True),
                in_specs=(v1, v1, v1, v2, v2, v1, v1, v1, v1, v1, v1, v2),
                out_specs=(v1, v1, v1, v2, v2, v2),
            ),
            donate_argnums=(0, 1, 2, 3, 4),
        )
        self._sharded_timeout = jax.jit(
            sm(
                timeout_body,
                in_specs=(v1, v1, v1, v1, v1, v1, v1),
                out_specs=(v1, v1),
            ),
            donate_argnums=(0,),
        )

        def _counts_block(state):
            local = jnp.stack(
                [jnp.sum(state == code) for code in _STATE_CODES]
            )
            return jax.lax.psum(local, axis)

        self._sharded_counts = jax.jit(
            sm(_counts_block, in_specs=(v1,), out_specs=P())
        )

    # ── Host-side routing ──────────────────────────────────────────────

    def _route(
        self,
        slots: np.ndarray,
        payloads: list[tuple[np.ndarray, object]],
        bucket: int | None = None,
    ) -> tuple[np.ndarray, list[np.ndarray], np.ndarray, int]:
        """Distribute per-slot work to the owning devices.

        Returns (slot_grid [D*B] of local ids with per-device sentinel,
        routed payload arrays [D*B, ...], flat positions [K] mapping input
        order -> routed row, bucket B). ``bucket`` overrides the local
        per-device row bucket (multi-host callers pass the fleet-agreed
        value so every process compiles the same shapes).
        """
        dev = slots // self.local_capacity
        local = (slots % self.local_capacity).astype(np.int32)
        counts = np.bincount(dev, minlength=self.n_devices)
        if bucket is None:
            bucket = _bucket(int(counts.max()) if len(slots) else 0)
        order = np.argsort(dev, kind="stable")
        within = np.empty(len(slots), np.int64)
        starts = np.cumsum(counts) - counts
        within[order] = np.arange(len(slots)) - starts[dev[order]]
        rows = dev * bucket + within  # [K] flat routed position

        slot_grid = np.full(self.n_devices * bucket, self.local_capacity, np.int32)
        slot_grid[rows] = local
        routed = []
        for payload, fill in payloads:
            shape = (self.n_devices * bucket,) + payload.shape[1:]
            out = np.full(shape, fill, payload.dtype)
            out[rows] = payload
            routed.append(out)
        return slot_grid, routed, rows, bucket

    def _put_batch(self, arr: np.ndarray) -> jax.Array:
        """Place a routed [D*B, ...] host array with its axis-0 sharding."""
        spec = P(self.axis) if arr.ndim == 1 else P(self.axis, None)
        return jax.device_put(arr, NamedSharding(self.mesh, spec))

    # ── Dispatch overrides ─────────────────────────────────────────────

    def _dispatch_activate(self, slots, n, req, cap, gossip, liveness) -> None:
        slot_grid, (n_g, req_g, cap_g, go_g, li_g), _, _ = self._route(
            slots.astype(np.int64),
            [(n, 0), (req, 0), (cap, 0), (gossip, False), (liveness, False)],
        )
        (
            self._state, self._yes, self._tot, self._vote_mask,
            self._vote_val, self._n, self._req, self._cap,
            self._gossip, self._liveness,
        ) = self._sharded_activate(
            self._state, self._yes, self._tot, self._vote_mask,
            self._vote_val, self._n, self._req, self._cap,
            self._gossip, self._liveness,
            self._put_batch(slot_grid),
            self._put_batch(n_g),
            self._put_batch(req_g),
            self._put_batch(cap_g),
            self._put_batch(go_g),
            self._put_batch(li_g),
        )

    def _dispatch_load(self, slots, state, yes, tot, mask_rows, val_rows) -> None:
        slot_grid, (st_g, y_g, t_g, m_g, v_g), _, _ = self._route(
            slots.astype(np.int64),
            [
                (state, 0),
                (yes, 0),
                (tot, 0),
                (mask_rows, False),
                (val_rows, False),
            ],
        )
        (
            self._state, self._yes, self._tot, self._vote_mask, self._vote_val,
        ) = self._sharded_load(
            self._state, self._yes, self._tot, self._vote_mask, self._vote_val,
            self._put_batch(slot_grid),
            self._put_batch(st_g),
            self._put_batch(y_g),
            self._put_batch(t_g),
            self._put_batch(m_g),
            self._put_batch(v_g),
        )

    def _dispatch_release(self, slots) -> None:
        slot_grid, _, _, _ = self._route(slots.astype(np.int64), [])
        self._state = self._sharded_release(
            self._state, self._put_batch(slot_grid)
        )

    def _dispatch_ingest(self, slot_pack, grid_pack):
        """Route the packed batch to owning devices; non-blocking. Returns
        (device out [D*B, L+1], row indexer recovering the S input rows)."""
        return self._routed_ingest(slot_pack, grid_pack, self._sharded_ingest)

    def _routed_ingest(
        self,
        slot_pack,
        grid_pack,
        kernel,
        bucket_s=None,
        bucket_l=None,
        row_offset=0,
    ):
        """Shared routing/repack body for the scan and closed-form ingest
        dispatches — one place owns the pad-sentinel/bucket contract.
        Multi-host callers pass fleet-agreed ``bucket_s``/``bucket_l`` (so
        every process compiles the same global program) and their device
        offset for block-local row positions."""
        s_count, depth = grid_pack.shape
        if bucket_l is None:
            bucket_l = _bucket(depth, floor=1)
        slots_g, expired = unpack_slots(slot_pack)
        local_pack = pack_slots(
            (slots_g % self.local_capacity).astype(np.int32), expired
        )
        _, (pack_g, grid_g), rows, bucket = self._route(
            slots_g.astype(np.int64),
            [
                (local_pack, self.local_capacity),
                (_pad2(grid_pack, s_count, bucket_l, grid_pack.dtype), 0),
            ],
            bucket=bucket_s,
        )
        (
            self._state, self._yes, self._tot, self._vote_mask,
            self._vote_val, out,
        ) = kernel(
            self._state, self._yes, self._tot, self._vote_mask,
            self._vote_val, self._n, self._req, self._cap,
            self._gossip, self._liveness,
            self._put_batch(pack_g),
            self._put_batch(grid_g),
        )
        return out, rows - row_offset * bucket

    def _dispatch_ingest_fresh(self, slot_pack, grid_pack, laneless=False):
        """Sharded closed-form ingest; same routing contract as
        :meth:`_dispatch_ingest`."""
        return self._routed_ingest(
            slot_pack,
            grid_pack,
            self._sharded_fresh_ingest_laneless
            if laneless
            else self._sharded_fresh_ingest,
        )

    def _dispatch_timeout(self, slots) -> np.ndarray:
        slot_grid, _, rows, _ = self._route(slots.astype(np.int64), [])
        self._state, row_state = self._sharded_timeout(
            self._state, self._yes, self._tot, self._n, self._req,
            self._liveness, self._put_batch(slot_grid),
        )
        return np.asarray(row_state)[rows]

    # ── Collectives ────────────────────────────────────────────────────

    def per_device_occupancy(self) -> list[int]:
        """Occupied (non-FREE) slots per mesh device, from the host state
        mirror — the per-device view the MULTICHIP artifact and the fleet
        bench's per-shard breakdown report. Device ``d`` owns the
        contiguous block ``[d·local_capacity, (d+1)·local_capacity)``."""
        blocks = self._state_host.reshape(self.n_devices, self.local_capacity)
        return (blocks != STATE_FREE).sum(axis=1).astype(int).tolist()

    def global_state_counts(self) -> dict[int, int]:
        """Device-side global histogram of slot states via psum over ICI
        (the all-reduce the host mirror makes redundant for small pools, but
        the scalable path for multi-host deployments where no single host
        sees every shard)."""
        counts = np.asarray(self._sharded_counts(self._state))
        return {code: int(c) for code, c in zip(_STATE_CODES, counts)}
