"""State-sync smoke check (the `make catchup-smoke` target).

Two in-process peers on a BridgeServer build a small signed history; two
fresh joiners then catch up over the wire — one via snapshot+tail
(CatchUpClient.catch_up: manifest, digest-checked chunks, one batched
chain/signature verify, atomic install, WAL-tail the suffix), one via
full WAL replay (CatchUpClient.full_replay) — and both must converge to
byte-identical engine state (sync.state_fingerprint equality) with the
source. A third joiner resumes an interrupted transfer from the same
CatchUpState. Exit code 0 and a final ``catchup-smoke OK`` line mean the
state-sync path works end to end.
"""

import os
import sys
import tempfile

sys.path.insert(0, ".")  # run from the repo root, as the Makefile does

from hashgraph_tpu.bridge.client import BridgeClient  # noqa: E402
from hashgraph_tpu.bridge.server import BridgeServer  # noqa: E402
from hashgraph_tpu.engine import TpuConsensusEngine  # noqa: E402
from hashgraph_tpu.obs import registry  # noqa: E402
from hashgraph_tpu.signing.ethereum import EthereumConsensusSigner  # noqa: E402
from hashgraph_tpu.sync import CatchUpClient, state_fingerprint  # noqa: E402

NOW = 1_700_000_000


def fresh_joiner() -> TpuConsensusEngine:
    return TpuConsensusEngine(
        EthereumConsensusSigner.random(), capacity=32, voter_capacity=8
    )


def main() -> int:
    with tempfile.TemporaryDirectory() as wal_dir:
        server = BridgeServer(
            capacity=32, voter_capacity=8, wal_dir=wal_dir, wal_fsync="off"
        )
        with server:
            host, port = server.address
            with BridgeClient(host, port) as client:
                source_peer, identity = client.add_peer(os.urandom(32))
                voters = [client.add_peer(os.urandom(32))[0] for _ in range(3)]
                # A small multi-proposal history: create, gossip, vote.
                for p in range(4):
                    pid, blob = client.create_proposal(
                        source_peer, "smoke", NOW, f"p{p}", b"payload", 4, 3_600
                    )
                    for vp in voters:
                        client.process_proposal(vp, "smoke", blob, NOW)
                        vote = client.cast_vote(vp, "smoke", pid, True, NOW + 1)
                        client.process_vote(source_peer, "smoke", vote, NOW + 1)
                source = server.durable_engine(identity)
                src_fp = state_fingerprint(source)

                # Snapshot + tail.
                joiner = fresh_joiner()
                with CatchUpClient(host, port, source_peer) as cu:
                    report = cu.catch_up(joiner, max_chunk_bytes=512)
                assert report.sessions_installed == 4, report
                assert report.votes_verified > 0, report
                assert state_fingerprint(joiner) == src_fp, "snapshot+tail diverged"

                # Full WAL replay must land on the same bytes.
                replayer = fresh_joiner()
                with CatchUpClient(host, port, source_peer) as cu:
                    replay = cu.full_replay(replayer)
                assert replay.tail_records > 0, replay
                assert state_fingerprint(replayer) == src_fp, "full replay diverged"

                # Interrupt mid-download, resume with the same state.
                resumer = fresh_joiner()
                cu = CatchUpClient(host, port, source_peer)
                manifest = cu._bridge.sync_manifest(source_peer, 512)
                cu.state.manifest = manifest
                cu.state.chunks[0] = cu._bridge.sync_chunk(
                    source_peer, manifest["snapshot_id"], 0
                )
                cu.close()  # "connection dropped" after one chunk
                with CatchUpClient(
                    host, port, source_peer, state=cu.state
                ) as cu2:
                    resumed = cu2.catch_up(resumer, max_chunk_bytes=512)
                assert resumed.resumed, resumed
                assert state_fingerprint(resumer) == src_fp, "resume diverged"

                # The sync metric families carry the traffic just driven.
                text = client.get_metrics()
                for family in (
                    "hashgraph_sync_chunks_sent_total",
                    "hashgraph_sync_chunks_received_total",
                    "hashgraph_sync_tail_records_total",
                    "hashgraph_sync_catchup_seconds_count",
                ):
                    assert family in text, f"missing {family} in metrics"
                sent = registry.counter("hashgraph_sync_chunks_sent_total").value
                assert sent > 0, "no chunks counted as sent"

    print("catchup-smoke OK")
    return 0


if __name__ == "__main__":
    sys.exit(main())
