"""Embedder bridge demo: drive the consensus engine from outside Python.

Starts a BridgeServer (one TpuConsensusEngine per added peer), then plays
both sides of the embedder boundary:
1. the Python reference client runs the 3-voter quick-start over TCP, and
2. if a C compiler is available, builds native/bridge_client.c and lets the
   C embedder run the same scenario — proving a non-Python process can
   create proposals, vote, ferry the reference-schema protobuf bytes
   between peers, and receive events.

Run: python examples/bridge_embedder.py
"""

import shutil
import subprocess
import sys
import tempfile

sys.path.insert(0, ".")

from hashgraph_tpu.bridge import BridgeClient, BridgeServer


def python_quickstart(host: str, port: int) -> None:
    now = 1_700_000_000
    with BridgeClient(host, port) as client:
        print(f"bridge protocol v{client.ping()}")
        peers = {}
        for name in ("alice", "bob", "carol"):
            peer_id, identity = client.add_peer()
            peers[name] = peer_id
            print(f"  {name}: peer {peer_id}, address 0x{identity.hex()}")

        pid, _ = client.create_proposal(
            peers["alice"], "demo", now, "genesis-upgrade", b"ship it", 3, 600
        )
        client.cast_vote(peers["alice"], "demo", pid, True, now + 1)
        proposal = client.get_proposal(peers["alice"], "demo", pid)
        for name in ("bob", "carol"):
            client.process_proposal(peers[name], "demo", proposal, now + 2)
        for i, name in enumerate(("bob", "carol")):
            vote = client.cast_vote(peers[name], "demo", pid, True, now + 3 + i)
            for other in ("alice", "bob", "carol"):
                if other != name:
                    client.process_vote(peers[other], "demo", vote, now + 4 + i)

        for name, peer in peers.items():
            result = client.get_result(peer, "demo", pid)
            events = client.poll_events(peer)
            print(f"  {name}: consensus={result}, {len(events)} event(s)")
            assert result is True
    print("python embedder: PASS")


def c_quickstart(host: str, port: int) -> None:
    cc = shutil.which("cc") or shutil.which("gcc")
    if cc is None:
        print("c embedder: skipped (no C compiler)")
        return
    with tempfile.TemporaryDirectory() as tmp:
        binary = f"{tmp}/bridge_demo"
        subprocess.run(
            [cc, "-O2", "-o", binary, "native/bridge_client.c"], check=True
        )
        out = subprocess.run(
            [binary, host, str(port)], capture_output=True, text=True, timeout=120
        )
        print(out.stdout.strip())
        assert out.returncode == 0 and "QUICKSTART PASS" in out.stdout
    print("c embedder: PASS")


def main() -> None:
    with BridgeServer(capacity=64, voter_capacity=8) as server:
        host, port = server.address
        print(f"bridge listening on {host}:{port}")
        python_quickstart(host, port)
        c_quickstart(host, port)


if __name__ == "__main__":
    main()
