"""Distributed-tracing smoke check (the `make trace-smoke` target).

Two bridge peers on one server drive a proposal to decision over the
wire with the trace context propagating as frame suffixes, then the
check asserts the whole tentpole end to end:

- both peers' engines bound contexts sharing ONE trace_id (cross-peer
  span stitching through the bridge protocol's optional suffix);
- per-peer JSONL dumps merge (``merge_traces``) into one Chrome
  trace-event file that Perfetto opens, with both peers present and the
  proposal's spans causally ordered (created on A before processed on B
  before decided);
- ``BridgeClient.explain`` returns the vote chain and quorum arithmetic
  matching the decided outcome, plus the same trace identity;
- a peer speaking the OLD wire (no trace suffix anywhere) still
  interoperates on the same server.

Exit code 0 and a final ``trace-smoke OK`` line mean the distributed
tracing path works end to end.
"""

import json
import os
import sys
import tempfile

sys.path.insert(0, ".")  # run from the repo root, as the Makefile does

from hashgraph_tpu.bridge.client import BridgeClient  # noqa: E402
from hashgraph_tpu.bridge.server import BridgeServer  # noqa: E402
from hashgraph_tpu.obs.trace import merge_traces, trace_store  # noqa: E402

NOW = 1_700_000_000


def main() -> int:
    trace_store.clear()
    with BridgeServer(capacity=16, voter_capacity=8) as server:
        host, port = server.address
        with BridgeClient(host, port) as alice, BridgeClient(host, port) as bob:
            a_peer, a_id = alice.add_peer(os.urandom(32))
            b_peer, b_id = bob.add_peer(os.urandom(32))
            a_label = "peer:" + a_id.hex()[:12]
            b_label = "peer:" + b_id.hex()[:12]

            # Proposal created on A; its bound trace context comes back on
            # the response suffix and travels with every gossiped byte.
            pid, proposal = alice.create_proposal(
                a_peer, "smoke", NOW, "trace-me", b"payload", 2, 600
            )
            ctx = alice.last_trace_context
            assert ctx is not None, "server did not bind a trace context"
            bob.process_proposal(b_peer, "smoke", proposal, NOW, trace=ctx)
            vote_a = alice.cast_vote(a_peer, "smoke", pid, True, NOW + 1)
            vote_b = bob.cast_vote(b_peer, "smoke", pid, True, NOW + 1)
            alice.process_vote(a_peer, "smoke", vote_b, NOW + 2, trace=ctx)
            bob.process_vote(b_peer, "smoke", vote_a, NOW + 2, trace=ctx)
            assert alice.get_result(a_peer, "smoke", pid) is True

            # EXPLAIN: quorum arithmetic must match the decided outcome
            # and carry the same trace identity.
            verdict = alice.explain(a_peer, "smoke", pid)
            quorum = verdict["quorum"]
            assert verdict["status"] == "reached" and verdict["result"] is True
            assert quorum["reached"] and quorum["recomputed_result"] is True
            assert quorum["yes"] >= quorum["required_votes"], quorum
            assert len(verdict["vote_chain"]) == 2, verdict["vote_chain"]
            assert verdict["trace"]["trace_id"] == ctx.trace_id.hex()

            # Old-wire interop: a third peer speaking the seed protocol
            # (no suffixes at all — explicit trace=None and no ambient
            # context) decides the same proposal on the same server.
            with BridgeClient(host, port) as carol:
                c_peer, _ = carol.add_peer()
                pid2, _ = carol.create_proposal(
                    c_peer, "old", NOW, "untraced", b"", 1, 600
                )
                carol.cast_vote(c_peer, "old", pid2, True, NOW + 1)
                assert carol.get_result(c_peer, "old", pid2) is True

    with tempfile.TemporaryDirectory() as tmp:
        # Per-peer dumps (what each node of a real fleet would ship) ...
        a_path = os.path.join(tmp, "alice.jsonl")
        b_path = os.path.join(tmp, "bob.jsonl")
        assert trace_store.export_jsonl(a_path, peer=a_label) > 0
        assert trace_store.export_jsonl(b_path, peer=b_label) > 0
        # ... stitched into ONE Chrome trace-event file.
        merged = os.path.join(tmp, "merged-trace.json")
        summary = merge_traces([a_path, b_path], merged)
        assert summary["peers"] == sorted([a_label, b_label]), summary
        assert summary["traces"].get(ctx.trace_id.hex(), 0) >= 2, summary

        with open(merged) as fh:
            doc = json.load(fh)
        events = [
            e
            for e in doc["traceEvents"]
            if e.get("args", {}).get("trace_id") == ctx.trace_id.hex()
        ]
        by_name = {}
        for e in events:
            by_name.setdefault(e["name"], e)
        created = by_name["consensus.create_proposal"]
        processed = by_name["consensus.process_proposal"]
        decided = by_name["consensus.decided"]
        # Causal order across peers on the shared wall clock.
        assert created["ts"] <= processed["ts"] <= decided["ts"], (
            created["ts"],
            processed["ts"],
            decided["ts"],
        )
        # Cross-peer parent link: B's process span parents into A's trace.
        assert processed["args"]["parent_id"] == ctx.span_id.hex()
        peer_pids = {e["pid"] for e in events}
        assert len(peer_pids) >= 2, "merged trace lost a peer"

    print("trace-smoke OK")
    return 0


if __name__ == "__main__":
    sys.exit(main())
