"""Federated observability smoke check (the `make federation-scrape-smoke`
target, CI's ``obs-smoke`` job).

Two federation hosts (``examples/federation_host.py`` — each a full
FleetGroup: scope-sharded ConsensusFleet behind a bridge server) run as
REAL OS processes, a :class:`~hashgraph_tpu.parallel.federation.
FederationDriver` drives one decision onto each host, and the smoke then
asserts the metric-federation plane end to end:

- ``OP_METRICS_PULL`` returns one frame per host (registry export +
  SLO state, stamped with the host label);
- the merged Prometheus view carries BOTH hosts' families labelled
  ``host="..."`` plus the bare fleet-total sums, including the
  decision-latency histogram the decisions above populated;
- the merged ``/slo`` rollup keys both hosts and counts the windowed
  decisions fleet-wide;
- an HTTP sidecar serving the MERGED views (``render_fn``/``slo_fn``
  hooks) scrapes identically over the wire — one scrape, every host.

Exit code 0 and a final ``federation-scrape-smoke OK`` line mean a
single pager's dashboard can watch the whole fleet through one endpoint.
"""

import json
import os
import subprocess
import sys
import urllib.request

sys.path.insert(0, ".")  # run from the repo root, as the Makefile does

NOW = 1_700_000_000
V_COUNT = 4
HOST_IDS = ["h0", "h1"]


def main() -> int:
    from hashgraph_tpu import build_vote
    from hashgraph_tpu.bridge.client import BridgeClient
    from hashgraph_tpu.obs import registry as default_registry
    from hashgraph_tpu.obs.http import MetricsSidecar
    from hashgraph_tpu.parallel.federation import (
        FederationDriver,
        FederationPlacement,
    )
    from hashgraph_tpu.signing.stub import StubConsensusSigner
    from hashgraph_tpu.wire import Proposal

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    runner = os.path.join(repo, "examples", "federation_host.py")
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    placement = FederationPlacement.uniform(HOST_IDS, 2)

    procs: "dict[str, subprocess.Popen]" = {}
    clients: "dict[str, BridgeClient]" = {}
    peer_ids: "dict[str, int]" = {}
    driver = None
    sidecar = None
    try:
        for host_id in HOST_IDS:
            procs[host_id] = subprocess.Popen(
                [sys.executable, runner,
                 "--host-id", host_id,
                 "--hosts", ",".join(HOST_IDS),
                 "--shards-per-host", "2",
                 "--capacity", "32",
                 "--voter-capacity", str(V_COUNT + 2)],
                stdin=subprocess.PIPE,
                stdout=subprocess.PIPE,
                env=env,
                cwd=repo,
            )
        driver = FederationDriver(placement)
        for host_id, proc in procs.items():
            line = proc.stdout.readline().decode()
            assert line.startswith("READY "), f"host runner said: {line!r}"
            _, port_s, peer_s = line.split()
            peer_ids[host_id] = int(peer_s)
            clients[host_id] = BridgeClient(
                "127.0.0.1", int(port_s), timeout=30.0
            )
            driver.connect(host_id, "127.0.0.1", int(port_s), int(peer_s))

        # One decision PER HOST so every host's decision-latency window
        # has something to report: pick scope names until each host owns
        # at least one, then drive its vote chain through the driver.
        scopes: "dict[str, str]" = {}
        i = 0
        while len(scopes) < len(HOST_IDS):
            scope = f"scrape-{i}"
            i += 1
            owner, _shard = placement.owner(scope)
            scopes.setdefault(owner, scope)
        signers = [StubConsensusSigner(os.urandom(20)) for _ in range(V_COUNT)]
        for owner, scope in scopes.items():
            _owner, shard = placement.owner(scope)
            pid, blob = clients[owner].create_proposal(
                peer_ids[owner], scope, NOW, "p", b"payload", V_COUNT, 3_600
            )
            placement.pin(scope, shard)
            proposal = Proposal.decode(blob)
            votes = []
            for signer in signers:
                vote = build_vote(proposal, True, signer, NOW + 1)
                proposal.votes.append(vote)
                votes.append(vote.encode())
            driver.submit(scope, votes, NOW + 1)
            driver.pump()
        report = driver.drain()
        assert report["acked"] == len(HOST_IDS) * V_COUNT, report

        # One OP_METRICS_PULL frame per host, each self-labelled.
        frames = driver.pull_metric_frames()
        assert sorted(f["host"] for f in frames) == HOST_IDS, frames

        merged_text = driver.merged_metrics_text()
        for host_id in HOST_IDS:
            assert f'host="{host_id}"' in merged_text, (
                f"merged scrape missing host label {host_id!r}"
            )
        assert "hashgraph_decision_latency_seconds_bucket" in merged_text
        # The bare (unlabelled) family is the fleet-total sum — it must
        # coexist with the per-host labelled series in one scrape.
        assert "\nbridge_requests_total " in merged_text, (
            "merged scrape missing the bare fleet-total series"
        )

        merged_slo = driver.merged_slo()
        assert sorted(merged_slo["hosts"]) == HOST_IDS, merged_slo
        assert merged_slo["global"]["count"] >= len(HOST_IDS), merged_slo
        assert merged_slo["alerts_firing"] == [], merged_slo

        # The same merged views over HTTP: the single endpoint a fleet
        # dashboard scrapes.
        sidecar = MetricsSidecar(
            default_registry,
            host="127.0.0.1",
            port=0,
            render_fn=driver.merged_metrics_text,
            slo_fn=driver.merged_slo,
        )
        mhost, mport = sidecar.start()
        with urllib.request.urlopen(
            f"http://{mhost}:{mport}/metrics", timeout=5
        ) as response:
            scraped = response.read().decode("utf-8")
        for host_id in HOST_IDS:
            assert f'host="{host_id}"' in scraped, host_id
        with urllib.request.urlopen(
            f"http://{mhost}:{mport}/slo", timeout=5
        ) as response:
            scraped_slo = json.loads(response.read())
        assert sorted(scraped_slo["hosts"]) == HOST_IDS, scraped_slo
    finally:
        if sidecar is not None:
            sidecar.stop()
        if driver is not None:
            driver.close()
        for client in clients.values():
            client.close()
        for proc in procs.values():
            try:
                proc.stdin.close()  # EOF = the runner's shutdown signal
                proc.wait(timeout=15)
            except Exception:
                proc.kill()

    print("federation-scrape-smoke OK")
    return 0


if __name__ == "__main__":
    sys.exit(main())
