"""README quick-start: one proposal, three voters, Gossipsub 2/3 quorum.

Run: python examples/quickstart.py
"""

import sys
import time

sys.path.insert(0, ".")

from hashgraph_tpu import (
    BroadcastEventBus,
    ConsensusService,
    CreateProposalRequest,
    InMemoryConsensusStorage,
    StubConsensusSigner,
    build_vote,
)


def main() -> None:
    # Three peers sharing storage + event bus (in-process simulation; a real
    # deployment gives each peer its own service and ferries wire bytes).
    storage, bus = InMemoryConsensusStorage(), BroadcastEventBus()
    alice = ConsensusService(storage, bus, StubConsensusSigner(b"A" * 20))
    bob = ConsensusService(storage, bus, StubConsensusSigner(b"B" * 20))
    events = bus.subscribe()

    now = int(time.time())
    proposal = alice.create_proposal(
        "deployments",
        CreateProposalRequest(
            name="ship-v2",
            payload=b"git:abc123",
            proposal_owner=alice.signer().identity(),
            expected_voters_count=3,
            expiration_timestamp=60,
            liveness_criteria_yes=True,
        ),
        now,
    )
    print(f"proposal {proposal.proposal_id}: {proposal.name!r}, 3 voters, 2/3 quorum")

    alice.cast_vote("deployments", proposal.proposal_id, True, now)
    print("alice voted YES ->", storage.get_session("deployments", proposal.proposal_id).state.kind.value)

    bob.cast_vote("deployments", proposal.proposal_id, True, now)
    scope, event = events.recv(timeout=1)
    print(f"bob voted YES   -> ConsensusReached(result={event.result}) in scope {scope!r}")

    # Carol's vote arrives after the decision: accepted as a no-op success.
    carol_vote = build_vote(
        storage.get_proposal("deployments", proposal.proposal_id),
        False,
        StubConsensusSigner(b"C" * 20),
        now,
    )
    alice.process_incoming_vote("deployments", carol_vote, now)
    print("carol voted NO  -> still ConsensusReached (idempotent)")


if __name__ == "__main__":
    main()
