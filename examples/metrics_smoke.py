"""Observability smoke check (the `make metrics-smoke` target).

Starts a BridgeServer with the HTTP metrics sidecar and a WAL directory,
drives one proposal to decision over the wire, then asserts:

- ``/metrics`` serves Prometheus text containing the well-known families
  (decision-latency histogram buckets, WAL fsync histogram, ingest batch
  size, bridge request counters);
- EVERY family documented in the :mod:`hashgraph_tpu.obs` docstring
  table is eagerly installed — a dashboard provisioned from the docs
  must never see a hole before traffic arrives;
- ``/healthz`` reports ok with the expected peer count;
- ``/slo`` serves the machine-readable SLO state (windowed decision
  quantiles, burn-rate alert list) and the decision driven above shows
  up in its global window;
- the ``GET_METRICS`` bridge opcode returns the same families over the
  wire protocol.

Exit code 0 and a final ``metrics-smoke OK`` line mean the scrape path
works end to end.
"""

import json
import os
import sys
import tempfile
import urllib.request

sys.path.insert(0, ".")  # run from the repo root, as the Makefile does

from hashgraph_tpu.bridge.client import BridgeClient  # noqa: E402
from hashgraph_tpu.bridge.server import BridgeServer  # noqa: E402

NOW = 1_700_000_000

REQUIRED_FAMILIES = [
    "hashgraph_decision_latency_seconds_bucket",
    "hashgraph_decision_latency_seconds_count",
    "hashgraph_ingest_batch_size_bucket",
    "wal_fsync_seconds_bucket",
    "wal_segment_count",
    "hashgraph_live_proposals",
    "bridge_requests_total",
    # Labelled info gauge: who/what is serving this scrape.
    "hashgraph_build_info{",
    # Consensus-health observatory families.
    "hashgraph_alerts_total",
    "hashgraph_equivocations_total",
    "hashgraph_fork_redeliveries_total",
    "hashgraph_tracked_peers",
    "hashgraph_stale_peers",
    "hashgraph_evidence_records",
    # Device/XLA telemetry: live buffer bytes sampled at scrape time,
    # persistent-compile-cache traffic via jax.monitoring events.
    "hashgraph_jax_live_buffer_bytes",
    "hashgraph_jax_compile_cache_hits_total",
    "hashgraph_jax_compile_cache_misses_total",
    # Verify-pool + scheme telemetry: the native pool's backlog gauge
    # (0 when the runtime is absent — the gauge must still exist), the
    # signatures-verified counter, and its per-scheme labelled variant
    # (registered at engine construction).
    "hashgraph_verify_pool_queue_depth",
    "hashgraph_verified_signatures_total",
    'hashgraph_verified_signatures_total{scheme="',
    # Device-resident batch verification (crypto_device): counters +
    # histogram exist from process start — a dashboard must see the
    # device families even on a host-verifying node (they read 0).
    "hashgraph_device_verify_batches_total",
    "hashgraph_device_verify_signatures_total",
    "hashgraph_device_verify_fallbacks_total",
    "hashgraph_device_verify_seconds_bucket",
    # State-sync families: snapshot chunks served/received, WAL tail
    # records applied, end-to-end catch-up seconds (histogram). Eagerly
    # installed so a dashboard sees them before the first catch-up; the
    # traffic itself is exercised by examples/catchup_smoke.py.
    "hashgraph_sync_chunks_sent_total",
    "hashgraph_sync_chunks_received_total",
    "hashgraph_sync_tail_records_total",
    "hashgraph_sync_catchup_seconds_bucket",
    # Tiered-session-lifecycle families: demoted-tier population/bytes
    # gauges plus demotion/promotion/GC counters. Eagerly installed — an
    # untier'd node's dashboard must still see them (at 0) before any
    # scope opts into TTL policies; the traffic is exercised by
    # `bench.py churn` and tests/test_tiering.py.
    "hashgraph_tier_demoted_sessions",
    "hashgraph_tier_bytes",
    "hashgraph_tier_demotions_total",
    "hashgraph_tier_promotions_total",
    "hashgraph_tier_gc_total",
    # Federated fleet families: hosts gauge, votes routed to remotely
    # owned scopes over the fabric, shard migrations + their wall time.
    # Eagerly installed — a single-host node's dashboard must still see
    # them (at 0) before the operator ever federates; the traffic is
    # exercised by `bench.py fleet --hosts 2` and tests/test_federation.py.
    "hashgraph_federation_hosts",
    "hashgraph_federation_remote_routed_votes_total",
    "hashgraph_federation_migrations_total",
    "hashgraph_federation_migration_seconds_bucket",
    # Liveness observatory: φ-accrual suspicion gauges (the bare family
    # reports the worst peer; the labelled per-peer variant appears as
    # peers are tracked — both voters above), suspect-count gauge, and
    # heartbeat/suspicion-edge counters.
    "hashgraph_phi",
    'hashgraph_phi{peer="',
    "hashgraph_liveness_suspects",
    "hashgraph_liveness_heartbeats_total",
    "hashgraph_liveness_suspicion_edges_total",
    # Overload admission control: typed RETRY_AFTER deferrals on both
    # fabrics plus the gossip drain-pressure gauge (0 on a healthy
    # smoke — the families must still exist).
    "hashgraph_gossip_frames_deferred_total",
    "hashgraph_gossip_drain_pressure",
    "hashgraph_bridge_retry_after_total",
    # SLO plane (hashgraph_tpu.obs.slo): breach/alert counters and the
    # windowed burn-rate gauges exist from process start; the labelled
    # per-scope/per-shard variants appear once objectives are declared.
    "hashgraph_slo_breaches_total",
    "hashgraph_slo_alerts_total",
    "hashgraph_slo_alerts_firing",
    "hashgraph_slo_burn_rate",
    "hashgraph_slo_incidents_total",
    # Wire-path stage attribution: per-stage wall-seconds counters plus
    # columnar/fallback frame counts — the raw inputs the attribution
    # report fuses. Eagerly installed at server construction.
    "hashgraph_bridge_wire_columnar_frames_total",
    "hashgraph_bridge_wire_fallback_frames_total",
    "hashgraph_bridge_wire_decode_seconds_total",
    "hashgraph_bridge_wire_crypto_seconds_total",
    "hashgraph_bridge_wire_apply_seconds_total",
    "hashgraph_bridge_wire_device_dispatches_total",
    "hashgraph_bridge_wire_apply_rows_total",
    "hashgraph_bridge_shm_rings_attached_total",
    # Cross-connection apply reactor: windowing/flush counters and the
    # occupancy / rows-per-dispatch histograms exist from process start
    # even when the reactor is off (they read 0 — a dashboard must not
    # see a hole on a serial-lane node).
    "hashgraph_reactor_windows_total",
    "hashgraph_reactor_rows_total",
    "hashgraph_reactor_flush_rows_total",
    "hashgraph_reactor_flush_bytes_total",
    "hashgraph_reactor_flush_deadline_total",
    "hashgraph_reactor_flush_now_change_total",
    "hashgraph_reactor_flush_forced_total",
    "hashgraph_reactor_window_occupancy_bucket",
    "hashgraph_reactor_rows_per_dispatch_bucket",
    # Continuous profiling plane: sample/drop counters and the sampler's
    # self-measured overhead seconds — present (at 0) even when the
    # profiler is parked, so the kill switch never hides the families.
    "hashgraph_profile_samples_total",
    "hashgraph_profile_dropped_total",
    "hashgraph_profile_overhead_seconds_total",
]


def main() -> int:
    with tempfile.TemporaryDirectory() as wal_dir:
        server = BridgeServer(
            capacity=16, voter_capacity=8, wal_dir=wal_dir,
            wal_fsync="always", metrics_port=0,
        )
        with server:
            host, port = server.address
            mhost, mport = server.metrics_address
            with BridgeClient(host, port) as alice, BridgeClient(host, port) as bob:
                a_peer, _ = alice.add_peer(os.urandom(32))
                b_peer, _ = bob.add_peer(os.urandom(32))
                pid, proposal = alice.create_proposal(
                    a_peer, "smoke", NOW, "p", b"payload", 2, 100
                )
                bob.process_proposal(b_peer, "smoke", proposal, NOW)
                vote_a = alice.cast_vote(a_peer, "smoke", pid, True, NOW)
                vote_b = bob.cast_vote(b_peer, "smoke", pid, True, NOW)
                alice.process_vote(a_peer, "smoke", vote_b, NOW)
                bob.process_vote(b_peer, "smoke", vote_a, NOW)
                assert alice.get_result(a_peer, "smoke", pid) is True

                # HTTP sidecar scrape.
                with urllib.request.urlopen(
                    f"http://{mhost}:{mport}/metrics", timeout=5
                ) as response:
                    text = response.read().decode("utf-8")
                missing = [f for f in REQUIRED_FAMILIES if f not in text]
                assert not missing, f"missing families in /metrics: {missing}"
                assert 'le="+Inf"' in text, "histogram missing +Inf bucket"

                # The obs/__init__ docstring table IS the contract: every
                # family it documents must be eagerly installed, so a
                # dashboard provisioned from the docs sees no holes even
                # before the matching subsystem carries traffic.
                from hashgraph_tpu.obs import documented_families

                documented = documented_families()
                assert documented, "documented_families() came back empty"
                undocumented_holes = [
                    f for f in documented if f not in text
                ]
                assert not undocumented_holes, (
                    f"documented families not eagerly installed: "
                    f"{undocumented_holes}"
                )
                build_line = next(
                    l for l in text.splitlines()
                    if l.startswith("hashgraph_build_info{")
                )
                for label in ("version=", "jax=", "backend="):
                    assert label in build_line, build_line
                # The bridge server imported and ran JAX, so the backend
                # label must name a real runtime, not a placeholder.
                assert 'backend="not-loaded"' not in build_line, build_line

                # The bridge ran real device ingest: the live-buffer
                # gauge must report actual resident bytes, not a dead 0.
                buffer_line = next(
                    l for l in text.splitlines()
                    if l.startswith("hashgraph_jax_live_buffer_bytes ")
                )
                assert float(buffer_line.split()[-1]) > 0, buffer_line

                with urllib.request.urlopen(
                    f"http://{mhost}:{mport}/healthz", timeout=5
                ) as response:
                    health = json.loads(response.read())
                assert health["ok"] and health["peers"] == 2, health
                # Enriched /healthz: the alerts array is always present
                # (machine-readable degradation reasons appear there and
                # in "reasons" when a critical rule fires).
                assert "alerts" in health, health

                # /slo: the machine-readable SLO plane. The decision we
                # just drove must appear in the global fast window, and
                # nothing alerts on a healthy smoke.
                with urllib.request.urlopen(
                    f"http://{mhost}:{mport}/slo", timeout=5
                ) as response:
                    slo = json.loads(response.read())
                assert slo["enabled"] is True, slo
                assert slo["global"]["count"] >= 1, slo["global"]
                assert slo["alerts_firing"] == [], slo["alerts_firing"]
                assert slo["burn_threshold"] > 0, slo

                # Consensus-health snapshot over the wire (OP_HEALTH):
                # both voters carry healthy scorecards.
                report = alice.health(a_peer, NOW + 1)
                assert report["wal"]["fsync_policy"] == "always", report["wal"]
                grades = {
                    card["grade"] for card in report["peers"].values()
                }
                assert grades == {"healthy"}, report["peers"]
                assert report["alerts"]["firing"] == [], report["alerts"]

                # Same families over the bridge wire (GET_METRICS opcode).
                wire_text = alice.get_metrics()
                missing = [f for f in REQUIRED_FAMILIES if f not in wire_text]
                assert not missing, f"missing families via GET_METRICS: {missing}"

    print("metrics-smoke OK")
    return 0


if __name__ == "__main__":
    sys.exit(main())
