"""Multi-peer gossip simulation: independent services, out-of-order delivery.

Five fully separate peers (own storage, own bus, own keys) exchange wire
bytes only — the pattern a real gossip transport implements
(reference: tests/network_gossip_tests.rs). The gossiped bytes carry a
distributed trace context as a skippable protobuf field
(:func:`hashgraph_tpu.obs.trace.attach_trace`): peers built without
tracing decode the exact same messages, peers built with it stitch every
delivery into one causal trace. Run: python examples/gossip_simulation.py
"""

import random
import sys
import time

sys.path.insert(0, ".")

from hashgraph_tpu import (
    ConsensusService,
    CreateProposalRequest,
    StubConsensusSigner,
    Proposal,
    Vote,
)
from hashgraph_tpu.obs.trace import (
    TraceContext,
    attach_trace,
    current_context,
    extract_trace,
    trace_store,
    use_context,
)

N_PEERS = 5


def main() -> None:
    rng = random.Random(42)
    peers = [
        ConsensusService.default_service(StubConsensusSigner(bytes([i + 1]) * 20))
        for i in range(N_PEERS)
    ]
    now = int(time.time())
    scope = "network"

    # Peer 0 creates and broadcasts the proposal as wire bytes, with the
    # root trace context attached to the gossiped message itself.
    proposal = peers[0].create_proposal(
        scope,
        CreateProposalRequest(
            name="elect-coordinator", payload=b"", proposal_owner=b"p0",
            expected_voters_count=N_PEERS, expiration_timestamp=60,
            liveness_criteria_yes=False,
        ),
        now,
    )
    root = TraceContext.generate()
    trace_store.record(
        "consensus.create_proposal", root, time.time(), 0.0, peer="peer-0",
        attrs={"proposal_id": proposal.proposal_id},
    )
    wire = attach_trace(proposal.encode(), root)
    for i, peer in enumerate(peers[1:], start=1):
        # Activate the context the bytes travelled with — the idiom a
        # receiving node wraps around its delivery handler (an engine, or
        # any observed_span-instrumented layer, would auto-tag its spans;
        # the scalar service records none, so the example stamps one).
        with use_context(extract_trace(wire)):
            peer.process_incoming_proposal(scope, Proposal.decode(wire), now)
            ctx = current_context()
            trace_store.record(
                "consensus.process_proposal", ctx.child(), time.time(), 0.0,
                parent=ctx.span_id, peer=f"peer-{i}",
            )
    print(f"proposal {proposal.proposal_id} delivered to {N_PEERS} peers")

    # Everyone votes (peer 1 dissents -> 4 YES of 5, quorum is ceil(10/3)=4);
    # votes gossip to all peers in RANDOM order, trace context attached.
    mailbox: list[bytes] = []
    for i, peer in enumerate(peers):
        vote = peer.cast_vote(scope, proposal.proposal_id, i != 1, now)
        mailbox.append(attach_trace(vote.encode(), root))
    rng.shuffle(mailbox)

    for raw in mailbox:
        vote = Vote.decode(raw)  # the trace field is skipped by decoders
        with use_context(extract_trace(raw)):
            ctx = current_context()
            for i, peer in enumerate(peers):
                if peer.signer().identity() == vote.vote_owner:
                    continue  # own vote already applied locally
                peer.process_incoming_vote(scope, vote.clone(), now)
                trace_store.record(
                    "consensus.process_vote", ctx.child(), time.time(), 0.0,
                    parent=ctx.span_id, peer=f"peer-{i}",
                )

    # All peers converge on the same result — and on the same trace.
    results = [
        peer.storage().get_consensus_result(scope, proposal.proposal_id)
        for peer in peers
    ]
    print("per-peer results:", results)
    assert len(set(results)) == 1, "peers diverged!"
    traced_peers = {s.peer for s in trace_store.spans(trace_id=root.trace_id)}
    assert len(traced_peers) == N_PEERS, traced_peers
    print(f"converged: consensus = {results[0]} (4 YES of {N_PEERS})")
    print(f"one trace ({root.trace_id.hex()[:16]}…) spans {len(traced_peers)} peers")


if __name__ == "__main__":
    main()
