"""Multi-peer gossip simulation: independent services, out-of-order delivery.

Five fully separate peers (own storage, own bus, own keys) exchange wire
bytes only — the pattern a real gossip transport implements
(reference: tests/network_gossip_tests.rs). Run: python examples/gossip_simulation.py
"""

import random
import sys
import time

sys.path.insert(0, ".")

from hashgraph_tpu import (
    ConsensusService,
    CreateProposalRequest,
    StubConsensusSigner,
    Proposal,
    Vote,
)

N_PEERS = 5


def main() -> None:
    rng = random.Random(42)
    peers = [
        ConsensusService.default_service(StubConsensusSigner(bytes([i + 1]) * 20))
        for i in range(N_PEERS)
    ]
    now = int(time.time())
    scope = "network"

    # Peer 0 creates and broadcasts the proposal as wire bytes.
    proposal = peers[0].create_proposal(
        scope,
        CreateProposalRequest(
            name="elect-coordinator", payload=b"", proposal_owner=b"p0",
            expected_voters_count=N_PEERS, expiration_timestamp=60,
            liveness_criteria_yes=False,
        ),
        now,
    )
    wire = proposal.encode()
    for peer in peers[1:]:
        peer.process_incoming_proposal(scope, Proposal.decode(wire), now)
    print(f"proposal {proposal.proposal_id} delivered to {N_PEERS} peers")

    # Everyone votes (peer 1 dissents -> 4 YES of 5, quorum is ceil(10/3)=4);
    # votes gossip to all peers in RANDOM order.
    mailbox: list[bytes] = []
    for i, peer in enumerate(peers):
        vote = peer.cast_vote(scope, proposal.proposal_id, i != 1, now)
        mailbox.append(vote.encode())
    rng.shuffle(mailbox)

    for raw in mailbox:
        vote = Vote.decode(raw)
        for i, peer in enumerate(peers):
            if peer.signer().identity() == vote.vote_owner:
                continue  # own vote already applied locally
            peer.process_incoming_vote(scope, vote.clone(), now)

    # All peers converge on the same result.
    results = [
        peer.storage().get_consensus_result(scope, proposal.proposal_id)
        for peer in peers
    ]
    print("per-peer results:", results)
    assert len(set(results)) == 1, "peers diverged!"
    print(f"converged: consensus = {results[0]} (4 YES of {N_PEERS})")


if __name__ == "__main__":
    main()
