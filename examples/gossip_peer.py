"""Standalone gossip peer: one BridgeServer in its own process.

The gossip fabric's point is throughput ACROSS processes — in-process
"peers" share one GIL, so an aggregate number measured there is really
one interpreter's ceiling. This runner hosts a bridge server (stub
scheme by default — transport benches measure the fabric, not host
crypto) as a real OS process:

    python examples/gossip_peer.py [--capacity N] [--voter-capacity N]
                                   [--scheme stub|ethereum|ed25519]
                                   [--reactor on|off|env]

It prints ``PORT <port>`` on stdout once listening, then serves until
stdin reaches EOF (the parent closing the pipe is the shutdown signal —
no PID files, no signals racing the accept loop). ``bench.py gossip``
spawns one of these per peer; it is also a handy way to run a real
multi-process fabric by hand.
"""

import argparse
import sys

sys.path.insert(0, ".")


def main() -> None:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--capacity", type=int, default=256)
    parser.add_argument("--voter-capacity", type=int, default=66)
    parser.add_argument(
        "--scheme", choices=("stub", "ethereum", "ed25519"), default="stub"
    )
    # Apply-reactor pin for A/B benches: "env" defers to the server's
    # HASHGRAPH_TPU_APPLY_REACTOR default; on/off override it so a
    # paired arm cannot be polluted by the environment.
    parser.add_argument("--reactor", choices=("on", "off", "env"), default="env")
    args = parser.parse_args()

    # Honor JAX_PLATFORMS even where a sitecustomize already imported
    # jax and pinned a different backend (the tests/conftest.py dance):
    # jax.config wins as long as no computation ran yet.
    import os

    platforms = os.environ.get("JAX_PLATFORMS")
    if platforms:
        try:
            import jax

            jax.config.update("jax_platforms", platforms)
        except (ImportError, RuntimeError):
            pass

    from hashgraph_tpu.bridge.server import BridgeServer

    if args.scheme == "stub":
        from hashgraph_tpu.signing.stub import StubConsensusSigner as scheme
    elif args.scheme == "ed25519":
        from hashgraph_tpu.signing.ed25519 import Ed25519ConsensusSigner as scheme
    else:
        from hashgraph_tpu.signing.ethereum import EthereumConsensusSigner as scheme

    server = BridgeServer(
        capacity=args.capacity,
        voter_capacity=args.voter_capacity,
        signer_factory=scheme,
        apply_reactor=(
            None if args.reactor == "env" else args.reactor == "on"
        ),
    )
    with server:
        _host, port = server.address
        print(f"PORT {port}", flush=True)
        # Serve until the parent closes our stdin.
        sys.stdin.buffer.read()


if __name__ == "__main__":
    main()
