"""TPU engine demo: 1,000 concurrent proposals decided in batched dispatches.

Run: python examples/batch_engine.py
(Works on CPU or TPU; uses the stub signature scheme for speed.)
"""

import sys
import time

sys.path.insert(0, ".")

from hashgraph_tpu import CreateProposalRequest, StubConsensusSigner, build_vote
from hashgraph_tpu.engine import TpuConsensusEngine
from hashgraph_tpu.tracing import Tracer


def main() -> None:
    engine = TpuConsensusEngine(
        StubConsensusSigner(b"E" * 20), capacity=1024, voter_capacity=8,
        max_sessions_per_scope=1000,
    )
    engine.tracer = Tracer(enabled=True)
    now = int(time.time())

    print("creating 1000 proposals (5 voters each, liveness=YES)...")
    pids = [
        engine.create_proposal(
            "fleet",
            CreateProposalRequest(
                name=f"job-{i}", payload=b"", proposal_owner=b"scheduler",
                expected_voters_count=5, expiration_timestamp=120,
                liveness_criteria_yes=True,
            ),
            now,
        ).proposal_id
        for i in range(1000)
    ]

    voters = [StubConsensusSigner(bytes([i + 1]) * 20) for i in range(4)]
    start = time.perf_counter()
    total = 0
    for voter in voters:
        batch = [
            ("fleet", build_vote(engine.get_proposal("fleet", pid), True, voter, now))
            for pid in pids
        ]
        statuses = engine.ingest_votes(batch, now, pre_validated=True)
        total += len(batch)
        decided = sum(1 for s in statuses if s == 28)  # ALREADY_REACHED
        print(f"  round: {len(batch)} votes dispatched ({decided} were post-decision)")
    elapsed = time.perf_counter() - start

    stats = engine.get_scope_stats("fleet")
    print(
        f"\n{total} votes in {elapsed:.2f}s "
        f"({total / elapsed:,.0f} votes/sec incl. host build_vote)"
    )
    print(
        f"sessions: {stats.total_sessions} total, "
        f"{stats.consensus_reached} reached, {stats.active_sessions} active"
    )
    print("tracer counters:", {
        k: v for k, v in engine.tracer.counters().items() if not k.startswith("span")
    })


if __name__ == "__main__":
    main()
