"""Standalone federation host: one FleetGroup in its own OS process.

The federation's point is throughput ACROSS hosts — in-process "hosts"
share one GIL, so an aggregate number measured there is one
interpreter's ceiling, not a fleet's. This runner hosts one
:class:`~hashgraph_tpu.parallel.federation.FleetGroup` (a scope-sharded
``ConsensusFleet`` fronted by a bridge server whose single peer is the
fleet adapter) as a real OS process:

    python examples/federation_host.py --host-id h0 --hosts h0,h1 \
        [--shards-per-host N] [--capacity N] [--voter-capacity N] \
        [--wal-root DIR]

Every participant passes the SAME ``--hosts`` list and shard count, so
each process reconstructs the identical two-level rendezvous placement.
It prints ``READY <port> <peer_id>`` once listening, then serves one
command per stdin line (one response line on stdout each) until EOF —
the parent closing the pipe is the shutdown signal:

    EXPORT <shard_id> [retry_after_seconds]
        Freeze the shard for migration (wire refusals carry the
        retry-after hint), register its durable engine as a sync peer
        -> ``EXPORTED <peer_id> <fingerprint>``
    ADOPT <shard_id> <host> <port> <peer_id>
        Catch the shard up from a source peer (snapshot at its frozen
        WAL watermark + tail) -> ``ADOPTED <sessions> <fingerprint>``
    FLIP <shard_id> <to_host>
        Re-home the shard in this host's placement (the driver sends it
        to every host after a successful adopt) -> ``FLIPPED``
    RETIRE <shard_id> <peer_id>
        Drop the migrated shard + its temporary sync peer -> ``RETIRED``
    TALLY
        Local fleet state counts -> ``TALLY <json>``
    SLOCFG <scope> <decide_p99_ms>
        Declare a decide-latency SLO objective on a scope of this
        host's fleet -> ``SLOCFG``  (the SLO engine starts alerting on
        it; ``OP_METRICS_PULL`` / the merged ``/slo`` view report it)
    SLOSET <0|1>
        Toggle the process-wide SLO engine (the overhead-A/B kill
        switch) -> ``SLOSET <0|1>``

``bench.py fleet --hosts N`` spawns one of these per host; it is also a
handy way to run a real multi-process federation by hand.
"""

import argparse
import json
import shlex
import sys
import tempfile

sys.path.insert(0, ".")


def main() -> None:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--host-id", required=True)
    parser.add_argument(
        "--hosts", required=True,
        help="comma-separated host ids, identical on every participant",
    )
    parser.add_argument("--shards-per-host", type=int, default=2)
    parser.add_argument("--capacity", type=int, default=256)
    parser.add_argument("--voter-capacity", type=int, default=66)
    parser.add_argument("--wal-root", default=None)
    args = parser.parse_args()

    # Honor JAX_PLATFORMS even where a sitecustomize already imported
    # jax and pinned a different backend (the gossip_peer.py dance).
    import os

    platforms = os.environ.get("JAX_PLATFORMS")
    if platforms:
        try:
            import jax

            jax.config.update("jax_platforms", platforms)
        except (ImportError, RuntimeError):
            pass

    from hashgraph_tpu.parallel.federation import (
        FederationPlacement,
        FleetGroup,
    )
    from hashgraph_tpu.signing.stub import StubConsensusSigner

    wal_root = args.wal_root or tempfile.mkdtemp(prefix="federation-wal-")
    placement = FederationPlacement.uniform(
        args.hosts.split(","), args.shards_per_host
    )
    group = FleetGroup(
        args.host_id,
        lambda k: StubConsensusSigner(
            args.host_id.encode().ljust(12, b"\0") + bytes([k + 1]) * 8
        ),
        placement=placement,
        wal_root=wal_root,
        capacity_per_shard=args.capacity,
        voter_capacity=args.voter_capacity,
    )
    _host, port = group.start()
    print(f"READY {port} {group.peer_id}", flush=True)

    try:
        for line in sys.stdin:
            parts = shlex.split(line)
            if not parts:
                continue
            command, rest = parts[0].upper(), parts[1:]
            try:
                if command == "EXPORT":
                    retry = float(rest[1]) if len(rest) > 1 else 1.0
                    peer_id, fingerprint = group.export_shard(
                        rest[0], retry
                    )
                    print(f"EXPORTED {peer_id} {fingerprint}", flush=True)
                elif command == "ADOPT":
                    shard_id, host, port_s, peer_s = rest
                    report = group.adopt_shard(
                        shard_id, host, int(port_s), int(peer_s)
                    )
                    print(
                        f"ADOPTED {report['sessions']} "
                        f"{report['fingerprint']}",
                        flush=True,
                    )
                elif command == "FLIP":
                    placement.complete_migration(rest[0], rest[1])
                    print("FLIPPED", flush=True)
                elif command == "RETIRE":
                    group.retire_shard(rest[0], int(rest[1]))
                    print("RETIRED", flush=True)
                elif command == "TALLY":
                    counts = group.fleet.fleet_state_counts()
                    print(
                        "TALLY "
                        + json.dumps({str(k): v for k, v in counts.items()}),
                        flush=True,
                    )
                elif command == "SLOCFG":
                    from hashgraph_tpu import ScopeConfigBuilder

                    group.fleet.set_scope_config(
                        rest[0],
                        ScopeConfigBuilder()
                        .with_decide_p99_ms(float(rest[1]))
                        .build(),
                    )
                    print("SLOCFG", flush=True)
                elif command == "SLOSET":
                    from hashgraph_tpu.obs import slo_engine

                    slo_engine.enabled = bool(int(rest[0]))
                    print(f"SLOSET {int(slo_engine.enabled)}", flush=True)
                else:
                    print(f"ERROR unknown command {command}", flush=True)
            except Exception as exc:  # one line per command, always
                print(f"ERROR {exc!r}", flush=True)
    finally:
        group.close()


if __name__ == "__main__":
    main()
